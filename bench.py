"""Benchmark harness (BASELINE.md config 1: T10I4D100K-style synthetic,
minSupport=0.01).

Prints ONE JSON line on stdout:
    {"metric": ..., "value": N, "unit": "txns/sec", "vs_baseline": N}

``vs_baseline`` is the speedup of this framework's mining phase over a
faithful numpy re-creation of the reference's candidate-space algorithm
(per-candidate Boolean bitmap AND + weighted sum — the hot loops at
FastApriori.scala:145,149-151,233-235) run on this same host: the
reference publishes no numbers of its own (BASELINE.md), so the reference
*algorithm* on identical data is the honest baseline.

Everything else (per-level detail, cold-start time) goes to stderr.
"""

from __future__ import annotations

import argparse
import json
import sys
import time

import numpy as np


def reference_style_mine(lines, min_support):
    """The reference's algorithm (replicated bitmap, per-candidate scans)
    with numpy doing each candidate's work — a faithful same-host stand-in
    for a Spark executor core."""
    from fastapriori_tpu.models.candidates import gen_candidates
    from fastapriori_tpu.preprocess import preprocess

    data = preprocess(lines, min_support, native=False)
    f = data.num_items
    t = data.total_count
    if f < 2 or t == 0:
        return [(frozenset((r,)), int(c)) for r, c in enumerate(data.item_counts)]

    # Vertical bitmap: one Boolean column per item (C5).
    cols = np.zeros((f, t), dtype=bool)
    for tid, basket in enumerate(data.baskets):
        cols[basket, tid] = True
    w = data.weights.astype(np.int64)

    out = []
    # C6: per-pair AND + weighted sum.
    pairs = []
    for i in range(f - 1):
        ci = cols[i]
        for j in range(i + 1, f):
            c = int(w[ci & cols[j]].sum())
            if c >= data.min_count:
                s = frozenset((i, j))
                pairs.append(s)
                out.append((s, c))
    k_items = pairs
    k = 3
    while len(k_items) >= k:
        cands = gen_candidates(k_items, f)
        level = []
        for prefix, exts in cands:
            common = cols[prefix[0]].copy()
            for p in prefix[1:]:
                common &= cols[p]
            ps = frozenset(prefix)
            for y in exts:
                c = int(w[common & cols[y]].sum())
                if c >= data.min_count:
                    level.append((ps | {y}, c))
        out.extend(level)
        k_items = [s for s, _ in level]
        k += 1
    out.extend(
        (frozenset((r,)), int(c)) for r, c in enumerate(data.item_counts)
    )
    return out


# Synthetic stand-ins for the BASELINE.md configs (shape parameters follow
# the public dataset statistics; data itself is generated — zero egress).
# style "quest" = IBM-Quest-like pattern pool (market baskets); "docs" =
# zipf marginals + planted head patterns (document corpora — quest-style
# data at 177 items/txn makes every popular pair co-occur and Apriori's
# output exponential, which real doc corpora don't do).
CONFIGS = {
    # name: (n_txns, n_items, avg_txn_len, min_support, style)
    "t10i4d100k": (100_000, 1_000, 10, 0.01, "quest"),
    "retail": (88_000, 16_000, 10, 0.005, "quest"),
    "kosarak": (990_000, 41_000, 8, 0.002, "quest"),
    "webdocs-small": (200_000, 50_000, 177, 0.1, "docs"),
    "webdocs": (1_700_000, 50_000, 177, 0.1, "docs"),
    # MovieLens-25M user->item baskets (BASELINE.md config 5): 162K users,
    # 59K movies, ~153 ratings/user; long-tail popularity like a doc corpus.
    # Pair with --workload recommend for the end-to-end rule pipeline.
    "movielens": (162_000, 59_000, 153, 0.1, "docs"),
    # Sparse long-tail clickstream shape (ISSUE 7): wide item axis, short
    # baskets, zipf popularity — the corpus class where the bitmap
    # engine's Gram/level matmuls run at 0.2-0.8% MFU (BENCH r3-r5) and
    # the vertical tid-lane engine (ops/vertical.py) is the win.  The
    # per-engine compare attach (--engine-compare / the orchestrated
    # record's engine_compare block) mines it under BOTH engines.
    "clickstream-sparse": (40_000, 4_000, 8, 0.0025, "docs"),
}


def gen_lines(args):
    """Generate the preset's transaction lines with its generator style."""
    from fastapriori_tpu.utils.datagen import (
        generate_doc_transactions,
        generate_transactions,
    )

    if args.style == "docs":
        return generate_doc_transactions(
            n_txns=args.n_txns,
            n_items=args.n_items,
            avg_txn_len=args.avg_len,
            seed=args.seed,
        )
    return generate_transactions(
        n_txns=args.n_txns,
        n_items=args.n_items,
        avg_txn_len=args.avg_len,
        seed=args.seed,
    )


def _phase_summary(records, cold_s=None):
    """Aggregate one warm run's metrics records into the per-phase dict
    the bench record carries (VERDICT r4 weak #1: the parsed record must
    be attributable — a 2x wall move must decompose into host-ingest vs
    device vs launch-floor terms).  Times are the MEDIAN warm run's."""
    ph = {"dispatches": 0}
    levels_ms = {}
    for r in records:
        ev = r.get("event")
        w = r.get("wall_ms", 0.0)
        if ev == "preprocess":
            ph["preprocess_s"] = round(w / 1e3, 3)
            for k in ("pass1_s", "pass2_s", "pack_s", "threads"):
                if k in r:
                    ph[k] = r[k]
        elif ev in ("bitmap_build", "bitmap_pack"):
            ph[ev + "_s"] = round(
                ph.get(ev + "_s", 0.0) + w / 1e3, 3
            )
            if r.get("pair_overlapped"):
                # The ingest-overlapped pair(+level-3) program launched
                # under this phase — count it HERE, not in the mining
                # loop (its "level" events carry dispatches=0).
                ph["ingest_dispatches"] = (
                    ph.get("ingest_dispatches", 0) + 1
                )
        elif ev == "pair_prepass":
            ph["pair_prepass_ms"] = round(w, 1)
            ph["dispatches"] += 1
        elif ev == "level":
            # Events carry their own dispatch count since r6 (0 for the
            # ingest-overlapped pair/level-3 fetches); older records
            # fall back to the legacy one-per-level constant.
            if r.get("k") == 2:
                ph["pair_ms"] = round(w, 1)
            else:
                levels_ms[str(r.get("k"))] = round(w, 1)
            ph["dispatches"] += int(r.get("dispatches", 1))
        elif ev == "tail_fuse":
            ph["tail_fuse_ms"] = round(w, 1)
            ph["dispatches"] += int(r.get("dispatches", 1))
        elif ev == "fused_mine":
            ph["fused_mine_ms"] = round(w, 1)
            ph["dispatches"] += int(r.get("dispatches", 1))
        elif ev == "counts_drain":
            # Mid-mine drains of the deferred count tensors (byte
            # budget): each is a real mining-loop dispatch.
            ph["drain_ms"] = round(ph.get("drain_ms", 0.0) + w, 1)
            ph["dispatches"] += int(r.get("dispatches", 1))
        elif ev == "counts_resolve":
            # Broken out SEPARATELY from the headline dispatch series:
            # r5's baseline of 9 was measured without the end-of-mine
            # resolve, so folding it into `dispatches` would reset the
            # round-over-round comparison — but it IS a real dispatch,
            # so it stays visible here.
            ph["counts_resolve_ms"] = round(w, 1)
            ph["resolve_dispatches"] = int(r.get("dispatches", 1))
        elif ev == "degraded":
            # A degraded run must be VISIBLY degraded in the record
            # (reliability/ledger.py): every silent fallback — Pallas
            # off, fused->level, int8 widen, cap-overflow retry, fetch
            # retries — lands here, not just in a slower wall figure.
            d = ph.setdefault("degraded", {})
            d[r.get("kind", "?")] = d.get(r.get("kind", "?"), 0) + 1
            if r.get("kind") == "cascade":
                # The unified escalation chain (reliability/watchdog.py):
                # the record file keeps the FULL ordered trail, so a run
                # that walked any chain is reconstructible step by step.
                ph.setdefault("cascade_trail", []).append(
                    {
                        k: r[k]
                        for k in ("chain", "frm", "to", "reason", "site")
                        if k in r
                    }
                )
    if levels_ms:
        ph["levels_ms"] = levels_ms
        ph["levels_total_ms"] = round(sum(levels_ms.values()), 1)
    _quorum_summary(ph)
    if cold_s is not None:
        # Cold-warm delta ~= compile + first-warm backend costs; with a
        # primed persistent compile cache this should be small — the
        # record proves whether the cache hit in THIS environment.
        ph["cold_s"] = round(cold_s, 3)
    return ph


def _quorum_summary(ph):
    """Surface fault-domain events as FIRST-CLASS phase fields (ISSUE
    12 satellite): a run whose consensus layer adopted a peer's
    degradation (quorum_adopt / mesh_divergence) or lost a peer
    (peer_lost) must not read as a clean perf number just because the
    counts are buried inside the degraded-kind histogram."""
    d = ph.get("degraded") or {}
    q = sum(
        v
        for k, v in d.items()
        if k.startswith("quorum") or k == "mesh_divergence"
    )
    if q:
        ph["quorum_events"] = q
    if d.get("peer_lost"):
        ph["peer_lost"] = d["peer_lost"]


def _loadavg():
    try:
        import os

        return [round(x, 2) for x in os.getloadavg()]
    except OSError:  # pragma: no cover
        return None


_CALIBRATE_CHILD = """
import json, sys, time
import numpy as np
from fastapriori_tpu.utils.compile_cache import enable_compile_cache
enable_compile_cache()
# Host reference op: fixed-size sort, ~0.5 s on an idle core.  A
# contended or throttled host shows directly as a larger figure, which
# attributes an end-to-end wall regression to the host side.
x = np.random.RandomState(0).rand(1 << 22)
t0 = time.perf_counter(); np.sort(x); host_ms = (time.perf_counter() - t0) * 1e3
out = {"host_sort_ms": round(host_ms, 1)}
try:
    import jax, jax.numpy as jnp

    if jax.default_backend() != "cpu":
        a = jnp.ones((128, 128), jnp.int8)
        f = jax.jit(lambda a: jnp.sum(a.astype(jnp.int32)))
        f(a).block_until_ready()  # compile
        # Dispatch round-trip floor: median of 5 tiny fetch cycles.
        rts = []
        for _ in range(5):
            t0 = time.perf_counter()
            int(f(a))
            rts.append((time.perf_counter() - t0) * 1e3)
        out["device_roundtrip_ms"] = round(sorted(rts)[2], 1)
        # Device->host link bandwidth: a 64 MB fetch (the tunnel's DOWN
        # direction is far slower than its ~1.3 GB/s up direction and is
        # what result fetches pay).
        big = jax.jit(lambda a: jnp.tile(a.astype(jnp.uint8), (512, 1)))(
            jnp.ones((128, 1024), jnp.int8) * 3
        )
        big.block_until_ready()
        t0 = time.perf_counter(); np.asarray(big)
        out["link_down_mbyte_s"] = round(
            big.nbytes / (time.perf_counter() - t0) / 1e6, 1
        )
        # Sustained int8 matmul rate at a standard shape.  The chain
        # lives INSIDE one jitted fori_loop (separate dispatches would
        # each pay the ~110 ms tunnel round-trip and measure only the
        # launch floor); only a SCALAR comes back (a full-matrix fetch
        # would measure the down-link, above); the figure is the
        # two-length DELTA of min-of-5 walls — forced data dependency +
        # readback is the only timing this tunnel can't fake.
        from functools import partial
        n = 8192
        b = jnp.ones((n, n), jnp.int8)

        @partial(jax.jit, static_argnums=1)
        def chain(b, iters):
            def body(_, c):
                return jnp.matmul(
                    b, c, preferred_element_type=jnp.int32
                ).astype(jnp.int8)
            return jax.lax.fori_loop(0, iters, body, b)[0, 0]

        def mn5(iters):
            ts = []
            for _ in range(5):
                t0 = time.perf_counter()
                np.asarray(chain(b, iters))
                ts.append(time.perf_counter() - t0)
            return min(ts)

        np.asarray(chain(b, 2)); np.asarray(chain(b, 98))  # compile both
        dt = max(mn5(98) - mn5(2), 1e-9)
        out["device_matmul_tops"] = round(2 * 96 * n**3 / dt / 1e12, 1)
except Exception as e:  # noqa: BLE001
    out["device_error"] = str(e)[:120]
print(json.dumps(out))
"""


def _calibrate(tag: str) -> dict:
    """Host + device health probes bracketing the run: a cross-round wall
    gap that exceeds the drift band must be attributable — these two
    numbers say whether the HOST (contended/throttled core) or the
    TUNNEL/DEVICE (round-trip floor, sustained matmul rate) moved."""
    import subprocess

    try:
        proc = subprocess.run(
            [sys.executable, "-c", _CALIBRATE_CHILD],
            stdout=subprocess.PIPE, timeout=240,
        )
        line = next(
            (l for l in proc.stdout.decode().splitlines()
             if l.startswith("{")), None,
        )
        out = json.loads(line) if line else {}
    # lint: waive G006 -- probe is best-effort by design: its failure is recorded, never fatal
    except Exception as e:  # noqa: BLE001 - probes must never kill the run
        out = {"error": str(e)[:120]}
    out["loadavg"] = _loadavg()
    print(f"calibrate[{tag}]: {json.dumps(out)}", file=sys.stderr)
    return out


def _calibrate_gated(tag: str) -> dict:
    """Link-probe gating (VERDICT r5 weak #2/next #1b: bench.py measured
    a collapsed 3.7 MB/s link and recorded the congested run as the
    round's number anyway).  When the down-link probe reads below the
    floor (``FA_LINK_FLOOR_MBS``, default 9 — healthy is 14-38 on this
    tunnel), wait ``FA_LINK_WAIT_S`` and re-probe up to
    ``FA_LINK_RETRIES`` times; the FULL probe series is recorded so the
    run's link state is attributable either way, and a run that starts
    congested after all retries is TAGGED (``below_floor``), not
    silently blended into the round-over-round series."""
    from fastapriori_tpu.utils.env import env_float, env_int

    floor = env_float("FA_LINK_FLOOR_MBS", 9.0, minimum=0.0)
    retries = env_int("FA_LINK_RETRIES", 3, minimum=0)
    wait_s = env_float("FA_LINK_WAIT_S", 120.0, minimum=0.0)
    probes = []
    out = {}
    for i in range(retries + 1):
        out = _calibrate(tag if i == 0 else f"{tag}.retry{i}")
        out["t"] = round(time.time(), 1)
        probes.append(
            {"t": out["t"], "link_down_mbyte_s": out.get("link_down_mbyte_s")}
        )
        link = out.get("link_down_mbyte_s")
        if link is None or link >= floor:
            break
        if i < retries:
            print(
                f"link probe {link} MB/s below floor {floor} MB/s; "
                f"waiting {wait_s:.0f}s before retry {i + 1}/{retries}",
                file=sys.stderr,
            )
            time.sleep(wait_s)
    out = dict(out)
    out["probes"] = probes
    out["link_floor_mbyte_s"] = floor
    link = out.get("link_down_mbyte_s")
    out["below_floor"] = link is not None and link < floor
    return out


def _tag_link_probes(merged) -> None:
    """Annotate every config row (and the webdocs attach) with the link
    probe NEAREST its completion time, so a table row's provenance names
    its link state (VERDICT r5 weak #7: rows spanning 2x link conditions
    were indistinguishable)."""
    cal = merged.get("calibration") or {}
    probes = []
    for side in ("start", "end"):
        c = cal.get(side) or {}
        probes.extend(
            p for p in c.get("probes", []) or []
            if p.get("link_down_mbyte_s") is not None
        )
        if not c.get("probes") and c.get("link_down_mbyte_s") is not None:
            probes.append(
                {"t": c.get("t"), "link_down_mbyte_s": c["link_down_mbyte_s"]}
            )
    probes = [p for p in probes if p.get("t")]
    if not probes:
        for row in (merged.get("configs") or {}).values():
            row.pop("t_done", None)
        merged.pop("webdocs_t_done", None)
        return

    def nearest(t):
        return min(probes, key=lambda p: abs(p["t"] - t))

    for row in (merged.get("configs") or {}).values():
        t = row.pop("t_done", None)
        if t is not None:
            row["link_probe_mbyte_s"] = nearest(t)["link_down_mbyte_s"]
    t_wd = merged.pop("webdocs_t_done", None)
    if t_wd is not None:
        merged["webdocs_link_probe_mbyte_s"] = nearest(t_wd)[
            "link_down_mbyte_s"
        ]


# Hard ceiling for the driver-parsed stdout line: the driver's capture
# window keeps ~2000 chars, and r5's 3.7 KB record line came back as
# parsed=null (VERDICT r5 weak #1).  Headline metrics + webdocs phases +
# a pointer fit comfortably; everything else lives in the record FILE.
COMPACT_LINE_BYTES = 1500


def _emit_final(merged) -> int:
    """Write the FULL record to bench_logs/ and print ONE compact JSON
    line (≤ :data:`COMPACT_LINE_BYTES`) for the driver to parse."""
    import os

    here = os.path.dirname(os.path.abspath(__file__))
    log_dir = os.path.join(here, "bench_logs")
    rel = None
    try:
        os.makedirs(log_dir, exist_ok=True)
        rel = os.path.join("bench_logs", f"record_{int(time.time())}.json")
        # lint: waive G009 -- per-run log under a timestamped name: a torn write cannot shadow a good artifact, and the compact stdout line is the committed record
        with open(os.path.join(here, rel), "w") as fh:
            json.dump(merged, fh, indent=1, sort_keys=True)
            fh.write("\n")
    except OSError as e:  # the compact line must still print
        print(f"full-record write failed: {e}", file=sys.stderr)
    compact = {
        k: merged[k]
        for k in (
            "metric", "value", "unit", "vs_baseline", "warm_wall_s",
            "mfu_pct", "webdocs_txns_per_sec", "webdocs_warm_wall_s",
            "webdocs_mfu_pct", "webdocs_link_probe_mbyte_s",
        )
        if k in merged
    }
    if "webdocs_phases" in merged:
        compact["webdocs_phases"] = merged["webdocs_phases"]
    ec = merged.get("engine_compare") or {}
    if ec.get("vertical_vs_bitmap_wall") is not None:
        # The ISSUE 7 headline: bitmap wall over vertical wall on the
        # sparse-corpus config (>1 = vertical wins), plus the k<=3
        # split; full per-level walls/bytes live in the record file.
        compact["engine_compare"] = {
            "vertical_vs_bitmap_wall": ec["vertical_vs_bitmap_wall"],
            "vertical_vs_bitmap_k_le3": ec.get(
                "vertical_vs_bitmap_k_le3"
            ),
        }
        if ec.get("pallas"):
            # ISSUE 18 headline: the modeled Pallas-tier HBM saving
            # (VMEM-resident prefix) + the device-trace artifact path
            # the attribution evidence lives at.
            compact["engine_compare"]["pallas_expected_speedup"] = (
                ec["pallas"].get("expected_speedup")
            )
            compact["engine_compare"]["pallas_device_trace"] = (
                ec["pallas"].get("device_trace")
            )
            ks = ec["pallas"].get("kernel_summary") or {}
            if ks.get("by_stage"):
                # ISSUE 19 satellite: per-stage device-time attribution
                # (raw kernel names folded onto span stage labels).
                compact["engine_compare"]["pallas_device_by_stage"] = {
                    k: round(v, 1) for k, v in ks["by_stage"].items()
                }
    hv = (merged.get("scaling") or {}).get("hier_vs_flat") or {}
    if hv.get("collective_vs_flat") is not None:
        # The ISSUE 15 headline: hierarchical-exchange collective bytes
        # over the flat sparse exchange's, at the largest virtual mesh
        # both series ran on (per-level intra/inter series in the
        # record file).
        compact["hier"] = {
            "devices": hv.get("devices"),
            "collective_vs_flat": hv["collective_vs_flat"],
        }
    rsc = (merged.get("rules_full_scale") or {}).get("scaling") or {}
    d4 = (rsc.get("devices") or {}).get("4") or {}
    if d4.get("join_vs_1dev") is not None:
        # The ISSUE 8 headline: sharded phase-2 join overhead at 4
        # virtual devices (flat = ideal on a shared-core host) and the
        # resident scan's zero-host-round-trip contract; the full
        # per-device series lives in the record file.
        compact["rule_scaling_4dev"] = {
            "join_vs_1dev": d4["join_vs_1dev"],
            "users_per_s": d4.get("users_per_s"),
            "rule_table_host_bytes": d4.get("rule_table_host_bytes"),
        }
    serve_row = (merged.get("configs") or {}).get("movielens_serve") or {}
    serve = serve_row.get("serve") or {}
    sus = serve.get("sustained") or {}
    if sus.get("achieved_rps") is not None:
        # The ISSUE 10 headline: the resident server's sustained
        # open-loop rate vs its closed-batch capacity, tail latency, the
        # overload scenario's recorded sheds, AND the serving run's own
        # degraded event count (serve_error batches / cascade walks must
        # be visible on the compact line, not just in the record file);
        # scenario detail lives in the record file.
        compact["serve_movielens"] = {
            "achieved_rps": sus["achieved_rps"],
            "batch_users_per_s": serve.get("batch_users_per_s"),
            "p99_ms": sus.get("p99_ms"),
            "shed": sus.get("shed"),
            "overload_shed": (serve.get("overload") or {}).get("shed"),
            "rule_table_host_bytes": serve.get("rule_table_host_bytes"),
            "degraded": sum(
                ((serve_row.get("phases") or {}).get("degraded") or {})
                .values()
            ),
        }
        if serve.get("pipeline_vs_serial") is not None:
            # ISSUE 19 headline: the two-stage dispatcher's measured
            # sustained-rps win over the serial dispatcher, plus the
            # trace-cited serve.scan idle-gap shrink behind it.
            compact["serve_movielens"]["pipeline_vs_serial"] = serve[
                "pipeline_vs_serial"
            ]
            compact["serve_movielens"]["scan_idle_shrink"] = (
                serve.get("scan_idle") or {}
            ).get("shrink")
        ms = serve.get("mesh_scaling") or {}
        if ms.get("4", {}).get("speedup_vs_1host") is not None:
            # ISSUE 19 headline: 1/2/4 virtual-host open-loop scaling
            # (speedup vs the 1-host mesh leg; per-leg detail in the
            # record file).
            compact["serve_movielens"]["mesh_speedup"] = {
                n: ms[n]["speedup_vs_1host"]
                for n in ("2", "4")
                if ms.get(n, {}).get("speedup_vs_1host") is not None
            }
        if serve.get("trace"):
            # ISSUE 11: the compact driver line names the trace artifact
            # when one was written (detail lives in the record file).
            compact["trace"] = serve["trace"]
    # ISSUE 9 satellite: the compact line ALWAYS carries the degraded
    # event count (summed across every phase summary in the record), so
    # a silently-degraded run can never masquerade as a clean perf
    # number — the per-kind breakdown and the full cascade trail live
    # in the record file's phase dicts.
    degraded_total = 0
    for key, val in merged.items():
        if key == "phases" or key.endswith("_phases"):
            if isinstance(val, dict):
                degraded_total += sum(
                    (val.get("degraded") or {}).values()
                )
    compact["degraded"] = degraded_total
    cal = (merged.get("calibration") or {}).get("start") or {}
    if cal.get("link_down_mbyte_s") is not None:
        compact["link_down_mbyte_s"] = cal["link_down_mbyte_s"]
    if cal.get("below_floor"):
        compact["link_below_floor"] = True
    if rel is not None:
        compact["record_file"] = rel
    # Enforce the ceiling by shedding the bulkiest keys, never by
    # truncating mid-JSON (a torn line is exactly the r5 failure).
    for drop in (
        "webdocs_phases",
        "engine_compare",
        "rule_scaling_4dev",
        "serve_movielens",
        "hier",
        "webdocs_link_probe_mbyte_s",
        "mfu_pct",
    ):
        if len(json.dumps(compact)) <= COMPACT_LINE_BYTES:
            break
        compact.pop(drop, None)
    print(json.dumps(compact))
    return 0


def _parser():
    ap = argparse.ArgumentParser()
    ap.add_argument(
        "--config",
        choices=sorted(CONFIGS),
        default="t10i4d100k",
        help="synthetic dataset preset (BASELINE.md configs)",
    )
    ap.add_argument("--n-txns", type=int, default=None)
    ap.add_argument("--min-support", type=float, default=None)
    ap.add_argument("--seed", type=int, default=2017)
    ap.add_argument(
        "--workload",
        choices=["mine", "recommend", "serve"],
        default="mine",
        help="mine = frequent-itemset mining; recommend = end-to-end "
        "rules + per-user recommendation (BASELINE.md config 5); "
        "serve = the resident serving tier under a seeded open-loop "
        "arrival stream — sustained + overload scenarios with "
        "p50/p95/p99 latency and shed counts (ISSUE 10)",
    )
    ap.add_argument(
        "--platform",
        choices=["default", "cpu"],
        default="default",
        help="force the JAX platform in-process (env vars are unreliable "
        "when a hardware plugin self-registers at interpreter start)",
    )
    ap.add_argument(
        "--scaling",
        action="store_true",
        help="also report mining wall time on 1/2/4/8-device virtual CPU "
        "meshes to stderr (functional scaling check, not real-chip perf)",
    )
    ap.add_argument(
        "--engine-compare",
        action="store_true",
        help="run ONLY the per-mining-engine compare (bitmap vs "
        "vertical on the clickstream-sparse config, 1 and 4 virtual "
        "devices) and print its record as the JSON line",
    )
    ap.add_argument(
        "--skip-baseline",
        action="store_true",
        help="skip the reference-style numpy baseline (vs_baseline=0)",
    )
    ap.add_argument(
        "--engine",
        choices=["auto", "fused", "level"],
        default="auto",
        help="auto = the engine's own per-dataset choice (config.py); "
        "without --data-file the run is additionally orchestrated in "
        "time-boxed subprocesses so a hung backend still yields a result",
    )
    ap.add_argument(
        "--fused-budget-s",
        type=float,
        default=3600.0,
        help="orchestrated mode: wall-clock budget for the first "
        "(engine-auto) attempt — bounds a hung backend, not the engine "
        "choice (auto may legitimately run the level engine for a while)",
    )
    ap.add_argument(
        "--data-file",
        default=None,
        help="pre-generated D.dat to mine instead of running datagen "
        "(auto mode generates once in the parent and passes it down so "
        "the fused attempt's budget is spent on mining, not datagen)",
    )
    ap.add_argument(
        "--warm-samples",
        type=int,
        default=3,
        help="warm runs to sample (median is the metric); the flagship "
        "webdocs attach uses 5 — more robust against transient tunnel "
        "stalls, which r4's driver capture showed can move a median 2x",
    )
    ap.add_argument(
        "--trace",
        default=None,
        metavar="PATH",
        help="serve workload: record the span tracer during the model "
        "build + closed-batch pass and export Perfetto-loadable "
        "Chrome-trace JSON here (the open-loop scenarios run with "
        "tracing DISABLED — their achieved-rps is the no-overhead "
        "number); the record and compact line gain trace=PATH",
    )
    return ap


def _orchestrate(args) -> int:
    """Robustness wrapper for unattended runs (the driver invokes bench.py
    with no flags): the engine-auto child runs in a subprocess with a
    wall-clock budget (first compile of the whole-loop program can be slow
    on some backends); if it produces no result line, rerun with the
    per-level engine, then on cpu.  Engine CHOICE itself lives in the
    miner (config.py engine="auto") — this wrapper only bounds hangs.
    Guarantees exactly one JSON line on stdout."""
    import os
    import subprocess
    import tempfile

    # Soft wall-clock budget for the whole orchestrated record: the
    # attaches below are ordered by importance and each checks the
    # remaining budget, so a slow tunnel degrades the record gracefully
    # (later attaches drop out with a printed reason) instead of the
    # driver's own timeout truncating it arbitrarily.
    from fastapriori_tpu.utils.env import env_float

    deadline = time.monotonic() + env_float(
        "FA_BENCH_BUDGET_S", 2700.0, minimum=0.0
    )
    # Probes/attaches only make sense for the driver-shaped full run;
    # platform isn't known yet (the probe below may fall back to cpu),
    # so gate on the shape here and re-check platform per attach.
    full_shape = (
        args.config == "t10i4d100k"
        and args.n_txns == CONFIGS["t10i4d100k"][0]
        and args.workload == "mine"
    )
    cal_start = _calibrate_gated("start") if full_shape else None
    # lint: env-ok -- free-form path knob: every string is a valid directory
    cache_dir = os.environ.get("FA_COMPILE_CACHE") or os.path.join(
        os.path.expanduser("~"), ".cache", "fastapriori_tpu", "jax"
    )

    def cache_entries():
        try:
            return len(os.listdir(cache_dir))
        except OSError:
            return 0

    cache_before = cache_entries()

    # Launch the backend liveness probe concurrently with datagen so a
    # healthy run never waits on it; join before the first engine child.
    probe_proc = None
    if args.platform == "default":
        probe_proc = subprocess.Popen(
            [
                sys.executable,
                "-c",
                "import jax, jax.numpy as jnp;"
                "x = jnp.ones((8, 8), jnp.int8);"
                "jnp.sum(x).block_until_ready();"
                "print('ok')",
            ],
            stdout=subprocess.PIPE,
            stderr=subprocess.DEVNULL,
        )

    # Use the caller's dataset when given; otherwise generate ONCE here —
    # children mine the same file either way.
    if args.data_file is not None:
        d_path, own_file = args.data_file, False
    else:
        t0 = time.perf_counter()
        raw = gen_lines(args)
        d_file = tempfile.NamedTemporaryFile(
            mode="w", suffix=".dat", delete=False
        )
        d_file.write("\n".join(raw) + "\n")
        d_file.close()
        del raw
        d_path, own_file = d_file.name, True
        print(
            f"datagen [{args.config}]: {args.n_txns} txns in "
            f"{time.perf_counter()-t0:.1f}s",
            file=sys.stderr,
        )

    if probe_proc is not None:
        try:
            out, _ = probe_proc.communicate(timeout=150)
            alive = probe_proc.returncode == 0 and b"ok" in out
        except subprocess.TimeoutExpired:
            probe_proc.kill()
            probe_proc.communicate()
            alive = False
        if not alive:
            print(
                "default backend unresponsive (accelerator tunnel down?); "
                "falling back to --platform cpu for this run",
                file=sys.stderr,
            )
            args.platform = "cpu"

    base = [
        sys.executable,
        __file__,
        "--config", args.config,
        "--n-txns", str(args.n_txns),
        "--min-support", str(args.min_support),
        "--seed", str(args.seed),
        "--workload", args.workload,
        "--warm-samples", str(args.warm_samples),
        "--data-file", d_path,
    ] + (["--skip-baseline"] if args.skip_baseline else [])
    try:
        # Attempt order: engine-auto (budgeted), forced level, then —
        # only when the default platform failed both (e.g. the tunnel
        # died AFTER the probe) — the level engine on cpu.  The finite
        # timeouts exist to bound a hung accelerator, so they apply only
        # to the default platform; an explicit/fallback cpu run may
        # legitimately take as long as it takes.
        #
        # On cpu (explicit or probe fallback) the fused whole-loop engine
        # is the WORST choice — it repeats padded-m_cap work every level
        # with no MXU to hide it (round 1's 0.15x regression); the level
        # engine with its one-f32-BLAS-matmul-per-phase path degrades to
        # ~baseline speed instead, so it goes straight there.
        if args.platform == "cpu":
            attempts = [("level", "cpu", None)]
        else:
            attempts = [
                ("auto", args.platform, args.fused_budget_s),
                ("level", args.platform, 3600.0),
                ("level", "cpu", None),
            ]
        for engine, platform, timeout in attempts:
            try:
                proc = subprocess.run(
                    base + ["--engine", engine, "--platform", platform],
                    stdout=subprocess.PIPE,
                    timeout=timeout,
                )
            except subprocess.TimeoutExpired:
                print(
                    f"engine={engine} platform={platform} exceeded "
                    f"{timeout}s budget; falling back",
                    file=sys.stderr,
                )
                continue
            out = proc.stdout.decode()
            line = next(
                (l for l in out.splitlines() if l.startswith("{")), None
            )
            if proc.returncode == 0 and line:
                merged = json.loads(line)
                merged.update(_north_star_attach(args, platform, deadline))
                full = _is_driver_run(args, platform)
                if full:
                    _full_suite_attach(args, platform, merged, deadline)
                    _rules_attach(args, platform, merged, deadline)
                if args.workload == "mine":
                    # The scaling curve is part of every round's record
                    # (VERDICT r3 weak #6).  Best-effort like the
                    # north-star attach.
                    try:
                        merged["scaling"] = _scaling_measure(args, deadline)
                    # lint: waive G006 -- attach is best-effort: skip is printed and the record stays valid
                    except Exception as e:  # noqa: BLE001
                        print(
                            f"scaling attach skipped: {e}", file=sys.stderr
                        )
                    # Per-device-count rule-generation + resident-scan
                    # children (ISSUE 8): rules_full_scale and the
                    # movielens recommend row gain the join/sort/scan
                    # scaling series.  Best-effort like the mining curve.
                    try:
                        rsc = _rule_scaling_measure(args, deadline)
                        merged.setdefault("rules_full_scale", {})[
                            "scaling"
                        ] = rsc
                        mv = (merged.get("configs") or {}).get(
                            "movielens_recommend"
                        )
                        if mv is not None:
                            mv["scaling"] = {
                                n: {
                                    k: d.get(k)
                                    for k in (
                                        "users_per_s",
                                        "users_vs_1dev",
                                        "scan_dispatches",
                                        "shards",
                                    )
                                }
                                for n, d in rsc.get(
                                    "devices", {}
                                ).items()
                            }
                    # lint: waive G006 -- attach is best-effort: skip is printed and the record stays valid
                    except Exception as e:  # noqa: BLE001
                        print(
                            f"rule scaling attach skipped: {e}",
                            file=sys.stderr,
                        )
                if full:
                    # Per-mining-engine compare on the sparse-corpus
                    # config (ISSUE 7: the vertical engine's win is
                    # measured into every round's record).
                    try:
                        merged["engine_compare"] = (
                            _engine_compare_measure(args, deadline)
                        )
                    # lint: waive G006 -- attach is best-effort: skip is printed and the record stays valid
                    except Exception as e:  # noqa: BLE001
                        print(
                            f"engine-compare attach skipped: {e}",
                            file=sys.stderr,
                        )
                if full:
                    _multiproc_attach(args, merged, deadline, 2, "two_process")
                    _multiproc_attach(
                        args, merged, deadline, 4, "four_process"
                    )
                    merged["compile_cache"] = {
                        "primed": cache_before > 0,
                        "entries_before": cache_before,
                        "new_entries": cache_entries() - cache_before,
                    }
                    cal_end = _calibrate("end")
                    cal_end["t"] = round(time.time(), 1)
                    merged["calibration"] = {
                        "start": cal_start,
                        "end": cal_end,
                    }
                    _tag_link_probes(merged)
                    try:
                        _prev_round_compare(merged)
                    # lint: waive G006 -- comparison is advisory: skip is printed, record unaffected
                    except Exception as e:  # noqa: BLE001
                        print(f"prev-round compare: {e}", file=sys.stderr)
                return _emit_final(merged)
            print(
                f"engine={engine} platform={platform} failed "
                f"(rc={proc.returncode}); falling back",
                file=sys.stderr,
            )
        print(json.dumps({"metric": "bench_failed", "value": 0,
                          "unit": "txns/sec", "vs_baseline": 0}))
        return 1
    finally:
        if own_file:
            os.unlink(d_path)


def _dataset_cache(config: str, seed: int) -> str:
    """Generate (once) and cache a preset's dataset under /tmp, keyed by
    ALL generating parameters — a differently-seeded or reshaped config
    must not silently mine a stale file.  Atomic publish so concurrent
    bench runs never interleave writes."""
    import argparse as _ap
    import os
    import tempfile

    n_txns, n_items, avg_len, _ms, style = CONFIGS[config]
    cache = (
        f"/tmp/{config}_bench_s{seed}_n{n_txns}_i{n_items}"
        f"_l{avg_len}_{style}.dat"
    )
    if not os.path.exists(cache):
        t0 = time.perf_counter()
        c_args = _ap.Namespace(
            n_txns=n_txns, n_items=n_items, avg_len=avg_len,
            seed=seed, style=style,
        )
        raw = gen_lines(c_args)
        fd, tmp = tempfile.mkstemp(dir="/tmp", suffix=".dat")
        with os.fdopen(fd, "w") as fh:
            fh.write("\n".join(raw) + "\n")
        os.replace(tmp, cache)
        del raw
        print(
            f"datagen [{config}]: {n_txns} txns in "
            f"{time.perf_counter()-t0:.1f}s",
            file=sys.stderr,
        )
    return cache


def _child_json(cmd, timeout):
    """Run a bench child, return its stdout JSON line (or None)."""
    import subprocess

    proc = subprocess.run(cmd, stdout=subprocess.PIPE, timeout=timeout)
    line = next(
        (l for l in proc.stdout.decode().splitlines() if l.startswith("{")),
        None,
    )
    if proc.returncode != 0 or not line:
        return None
    return json.loads(line)


def _is_driver_run(args, platform) -> bool:
    """True for the driver-shaped invocation (zero-flag default config at
    full size on a live accelerator) — the full-record attaches below
    only run there; smoke/CI invocations stay cheap."""
    return (
        args.config == "t10i4d100k"
        and args.n_txns == CONFIGS["t10i4d100k"][0]
        and args.workload == "mine"
        and platform != "cpu"
    )


def _north_star_attach(args, platform, deadline=None) -> dict:
    """North-star fields folded into the single driver-parsed JSON line
    (VERDICT weak #5): when the driver invokes the default config, ALSO
    measure webdocs (1.7M txns @ minSupport=0.1 — the BASELINE.json
    north-star run) with ZERO engine flags — the engine's own auto
    choice, the same path a user gets — and report its txns/s, warm
    wall, MFU and per-phase breakdown as webdocs_* fields.
    Best-effort: any failure or timeout leaves the main metric intact."""
    if not _is_driver_run(args, platform):
        return {}
    timeout = 1500
    if deadline is not None:
        timeout = min(timeout, max(deadline - time.monotonic(), 0))
        if timeout < 120:
            print(
                "north-star attach skipped: bench budget exhausted",
                file=sys.stderr,
            )
            return {}
    try:
        n_txns, _ni, _al, min_support, _st = CONFIGS["webdocs"]
        cache = _dataset_cache("webdocs", args.seed)
        wd = _child_json(
            [
                sys.executable, __file__,
                "--config", "webdocs",
                "--n-txns", str(n_txns),
                "--min-support", str(min_support),
                "--seed", str(args.seed),
                "--data-file", cache,
                "--skip-baseline",
                # 5 warm samples on the flagship config: r4's driver
                # capture showed a single-session median can sit 2x off
                # the same binary's same-day medians; a wider sample with
                # the per-phase breakdown makes that attributable.
                "--warm-samples", "5",
            ],
            timeout=timeout,
        )
        if wd is None:
            print("north-star webdocs run failed", file=sys.stderr)
            return {}
        out = {
            "webdocs_txns_per_sec": wd.get("value"),
            "webdocs_warm_wall_s": wd.get("warm_wall_s"),
            "webdocs_t_done": round(time.time(), 1),
        }
        if "warm_band_s" in wd:
            out["webdocs_warm_band_s"] = wd["warm_band_s"]
        if "mfu_pct" in wd:
            out["webdocs_mfu_pct"] = wd["mfu_pct"]
        if "phases" in wd:
            out["webdocs_phases"] = wd["phases"]
        return out
    # lint: waive G006 -- attach is best-effort: skip is printed and the record stays valid
    except Exception as e:  # noqa: BLE001 - attach must never kill the run
        print(f"north-star attach skipped: {e}", file=sys.stderr)
        return {}


def _full_suite_attach(args, platform, merged, deadline) -> None:
    """The remaining BASELINE.md configs (retail, kosarak, movielens +
    recommend) into the driver record (VERDICT r4 weak #2: rows 2/3/5
    existed only as session logs; the recommend path — half the
    reference's functionality — had never appeared in a driver capture).
    Each child is best-effort with its own timeout; a missed deadline
    skips the rest and says so."""
    if platform == "cpu":
        return
    configs = {}
    for name, workload, timeout in (
        ("retail", "mine", 600),
        ("kosarak", "mine", 900),
        ("movielens", "recommend", 900),
        # The serving row rides next to the recommend row it recovers
        # (ISSUE 10): same corpus + users, open-loop arrivals.
        ("movielens", "serve", 900),
    ):
        key = name if workload == "mine" else f"{name}_{workload}"
        if time.monotonic() + timeout / 3 > deadline:
            print(
                f"config attach [{key}] skipped: bench budget exhausted "
                "(FA_BENCH_BUDGET_S)",
                file=sys.stderr,
            )
            break
        try:
            cache = _dataset_cache(name, args.seed)
            argv = [
                sys.executable, __file__,
                "--config", name,
                "--workload", workload,
                "--seed", str(args.seed),
                "--data-file", cache,
            ]
            if workload == "serve":
                # The serving child ships a trace artifact next to the
                # record file (ISSUE 11): compact line gains trace=.
                import os as _os

                log_dir = _os.path.join(
                    _os.path.dirname(_os.path.abspath(__file__)),
                    "bench_logs",
                )
                _os.makedirs(log_dir, exist_ok=True)
                argv += [
                    "--trace",
                    _os.path.join(
                        log_dir, f"trace_serve_{int(time.time())}.json"
                    ),
                ]
            d = _child_json(argv, timeout=timeout)
            if d is None:
                print(f"config attach [{key}] failed", file=sys.stderr)
                continue
            configs[key] = {
                k: d[k]
                for k in (
                    "metric", "value", "unit", "vs_baseline",
                    "vs_baseline_est", "warm_wall_s", "warm_band_s",
                    "baseline_wall_s", "mfu_pct", "n_users",
                    "n_itemsets", "phases", "serve",
                )
                if k in d
            }
            configs[key]["t_done"] = round(time.time(), 1)
        # lint: waive G006 -- attach is best-effort: skip is printed and the record stays valid
        except Exception as e:  # noqa: BLE001
            print(f"config attach [{key}] skipped: {e}", file=sys.stderr)
    if configs:
        merged["configs"] = configs


_RULES_CHILD = """
import json, sys, time
from fastapriori_tpu.utils.compile_cache import enable_compile_cache
enable_compile_cache()
from fastapriori_tpu.config import MinerConfig
from fastapriori_tpu.models.apriori import FastApriori
from fastapriori_tpu.rules.gen import gen_rule_arrays_levels, sort_rule_arrays

d_path = sys.argv[1]
min_support = float(sys.argv[2])
miner = FastApriori(config=MinerConfig(min_support=min_support, retain_csr=False, log_metrics=True))
t0 = time.perf_counter()
levels, data = miner.run_file_raw(d_path)
mine_s = time.perf_counter() - t0
n_itemsets = sum(m.shape[0] for m, _ in levels) + data.num_items
# Device-eligible phase 2 (ISSUE 4 tentpole): the engine's own auto
# choice — device joins at this scale, host below the size floor — over
# the mining context's mesh; the per-engine attribution rides the
# record (join_s = generation + prune, sort_s = priority sort).
t0 = time.perf_counter()
surv = gen_rule_arrays_levels(
    levels, data.item_counts,
    context=miner.context, config=miner.config, metrics=miner.metrics,
)
join_s = time.perf_counter() - t0
t1 = time.perf_counter()
arrays = sort_rule_arrays(surv, data.freq_items)
sort_s = time.perf_counter() - t1
gen_s = join_s + sort_s
n_rules = len(arrays[1])
dev_recs = [r for r in miner.metrics.records if r.get("event") == "rule_gen_device"]
out = {
    "n_itemsets": n_itemsets, "n_rules": n_rules,
    "mine_s": round(mine_s, 2), "gen_rules_s": round(gen_s, 2),
    "join_s": round(join_s, 2), "sort_s": round(sort_s, 2),
    "engine": "device" if dev_recs else "host",
    "value": round(n_rules / gen_s, 1), "unit": "rules/sec",
}
if dev_recs:
    out["join_dispatches"] = dev_recs[-1].get("dispatches")
    out["raw_rules"] = dev_recs[-1].get("raw_rules")
print(json.dumps(out))
"""


def _rules_attach(args, platform, merged, deadline) -> None:
    """Full-scale phase 2 in the driver record (VERDICT r4 weak #3): the
    zero-flag CLI's dominant cost at the reference's hardcoded default
    support (Main.scala:23 minSupport=0.092 — webdocs: 2.5M itemsets ->
    16M rules) was benchmarked nowhere.  One child mines webdocs at
    0.092 and times rule generation + dominance prune + priority sort
    (rules/gen.py — the reference's AssociationRules.scala:122-188)."""
    if platform == "cpu":
        return
    timeout = 1200
    if time.monotonic() + timeout / 3 > deadline:
        print(
            "rules attach skipped: bench budget exhausted", file=sys.stderr
        )
        return
    try:
        cache = _dataset_cache("webdocs", args.seed)
        d = _child_json(
            [sys.executable, "-c", _RULES_CHILD, cache, "0.092"],
            timeout=timeout,
        )
        if d is None:
            print("rules attach failed", file=sys.stderr)
            return
        d["metric"] = "rules_per_sec_webdocs_minsup0.092"
        merged["rules_full_scale"] = d
        print(
            f"rules[webdocs@0.092]: {d['n_rules']} rules from "
            f"{d['n_itemsets']} itemsets in {d['gen_rules_s']}s "
            f"(engine {d.get('engine')}, join {d.get('join_s')}s, "
            f"sort {d.get('sort_s')}s; mine {d['mine_s']}s)",
            file=sys.stderr,
        )
    # lint: waive G006 -- attach is best-effort: skip is printed and the record stays valid
    except Exception as e:  # noqa: BLE001
        print(f"rules attach skipped: {e}", file=sys.stderr)


_MULTIPROC_CHILD = """
import json, sys, time
import jax

coordinator, n_proc, pid, d_path, min_support = sys.argv[1:6]
jax.config.update("jax_platforms", "cpu")
from fastapriori_tpu.parallel.mesh import initialize_distributed

initialize_distributed(
    coordinator_address=coordinator,
    num_processes=int(n_proc),
    process_id=int(pid),
)
from fastapriori_tpu.config import MinerConfig
from fastapriori_tpu.models.apriori import FastApriori

miner = FastApriori(
    config=MinerConfig(min_support=float(min_support), engine="level")
)
miner.run_file_sharded(d_path)  # warm (compiles)
rec_start = len(miner.metrics.records)
t0 = time.perf_counter()
levels, data = miner.run_file_sharded(d_path)
wall = time.perf_counter() - t0
recs = miner.metrics.records[rec_start:]
# Per-phase walls (VERDICT r5 next #7 remainder): ingest / pair /
# levels / fetch, so the SPMD overhead decomposes the same way the
# single-process phases do.  Multi-process runs fetch counts eagerly;
# the level events' fetch_ms is the link term and is SUBTRACTED from
# the level compute walls so the four phases are disjoint (summing
# fetch on top of walls that contain it would double-count the link).
ingest_s = sum(
    r.get("wall_ms", 0.0) / 1e3
    for r in recs
    if r.get("event") in ("preprocess", "bitmap_build")
)
pair_s = sum(
    r.get("wall_ms", 0.0) / 1e3
    for r in recs
    if r.get("event") == "level" and r.get("k") == 2
)
fetch_lv = sum(
    r.get("fetch_ms", 0.0) / 1e3
    for r in recs
    if r.get("event") == "level" and r.get("k", 0) >= 3
)
levels_s = sum(
    r.get("wall_ms", 0.0) / 1e3
    for r in recs
    if (r.get("event") == "level" and r.get("k", 0) >= 3)
    or r.get("event") == "tail_fuse"
) - fetch_lv
fetch_s = fetch_lv + sum(
    r.get("wall_ms", 0.0) / 1e3
    for r in recs
    if r.get("event") in ("counts_resolve", "counts_drain")
)
if int(pid) == 0:
    print(json.dumps({
        "wall_s": round(wall, 3),
        "ingest_s": round(ingest_s, 3),
        "mine_s": round(wall - ingest_s, 3),
        "phases": {
            "ingest_s": round(ingest_s, 3),
            "pair_s": round(pair_s, 3),
            "levels_s": round(levels_s, 3),
            "fetch_s": round(fetch_s, 3),
        },
        "n_itemsets": int(sum(m.shape[0] for m, _ in levels)),
    }))
"""


def _multiproc_attach(args, merged, deadline, n_proc, key) -> None:
    """A REAL n-process jax.distributed wall-clock point in the scaling
    block (VERDICT r4 weak #7, r5 next #7: two_process gains per-phase
    walls and a four_process point exists).  All processes share this
    host's core(s), so the recorded figures are the sharded-ingest
    path's overhead decomposition (ingest/pair/levels/fetch wall under
    SPMD), not a speedup claim — BASELINE.md reads them with that
    caveat."""
    import copy
    import os
    import socket
    import subprocess
    import tempfile

    if time.monotonic() + 180 * n_proc > deadline:
        print(f"{key} attach skipped: budget", file=sys.stderr)
        return
    # The child wait is bounded by BOTH the per-process allowance and
    # the remaining bench budget (plus kill slack) — the gate above
    # reserves less than the full allowance, so an unbounded wait could
    # overrun the deadline by minutes on a slow host.
    wait_s = min(300 * n_proc, max(deadline - time.monotonic() - 30, 60))
    try:
        small = copy.copy(args)
        small.n_txns = min(args.n_txns, 50_000)
        raw = gen_lines(small)
        f = tempfile.NamedTemporaryFile(
            mode="w", suffix=".dat", delete=False
        )
        f.write("\n".join(raw) + "\n")
        f.close()
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
        coord = f"127.0.0.1:{port}"
        procs = [
            subprocess.Popen(
                [
                    sys.executable, "-c", _MULTIPROC_CHILD, coord,
                    str(n_proc), str(pid), f.name, str(args.min_support),
                ],
                stdout=subprocess.PIPE,
                stderr=subprocess.DEVNULL,
            )
            for pid in range(n_proc)
        ]
        try:
            out0, _ = procs[0].communicate(timeout=wait_s)
            for p in procs[1:]:
                p.communicate(timeout=60)
        finally:
            for p in procs:
                if p.poll() is None:
                    p.kill()
                    p.communicate()
            os.unlink(f.name)
        line = next(
            (l for l in out0.decode().splitlines() if l.startswith("{")),
            None,
        )
        if procs[0].returncode == 0 and line:
            rec = json.loads(line)
            rec["n_txns"] = small.n_txns
            merged.setdefault("scaling", {})[key] = rec
            ph = rec.get("phases", {})
            print(
                f"scaling[{key} jax.distributed] wall={rec['wall_s']}s "
                f"ingest={ph.get('ingest_s')}s pair={ph.get('pair_s')}s "
                f"levels={ph.get('levels_s')}s fetch={ph.get('fetch_s')}s",
                file=sys.stderr,
            )
        else:
            print(f"{key} attach failed", file=sys.stderr)
    # lint: waive G006 -- attach is best-effort: skip is printed and the record stays valid
    except Exception as e:  # noqa: BLE001
        print(f"{key} attach skipped: {e}", file=sys.stderr)


def _prev_round_compare(merged) -> None:
    """Regression guard (VERDICT r4 next #8): compare this record
    against the newest BENCH_r*.json in the repo so a driver capture
    that lands 2x off immediately shows WHICH phase moved.  The deltas
    ride the parsed record (vs_prev_round) AND print at the very end of
    stderr so they land in the captured tail."""
    import glob
    import os

    here = os.path.dirname(os.path.abspath(__file__))
    files = sorted(glob.glob(os.path.join(here, "BENCH_r*.json")))
    if not files:
        return
    prev_path = files[-1]
    try:
        with open(prev_path) as fh:
            prev = json.load(fh).get("parsed") or {}
    # lint: waive G006 -- a malformed previous record only disables the advisory compare
    except Exception:  # noqa: BLE001
        return
    cmp_out = {"prev_record": os.path.basename(prev_path)}
    lines = []
    for k in (
        "value", "warm_wall_s", "mfu_pct",
        "webdocs_txns_per_sec", "webdocs_warm_wall_s", "webdocs_mfu_pct",
    ):
        a, b = prev.get(k), merged.get(k)
        if isinstance(a, (int, float)) and isinstance(b, (int, float)) and a:
            cmp_out[k] = {"prev": a, "now": b, "ratio": round(b / a, 3)}
            lines.append(f"  {k}: {a} -> {b} ({round(b / a, 3)}x)")
    pp, np_ = prev.get("webdocs_phases"), merged.get("webdocs_phases")
    if isinstance(pp, dict) and isinstance(np_, dict):
        deltas = {}
        for k in sorted(set(pp) | set(np_)):
            a, b = pp.get(k), np_.get(k)
            if isinstance(a, (int, float)) and isinstance(b, (int, float)):
                deltas[k] = {"prev": a, "now": b}
                lines.append(f"  webdocs_phases.{k}: {a} -> {b}")
        if deltas:
            cmp_out["webdocs_phase_delta"] = deltas
    merged["vs_prev_round"] = cmp_out
    print(
        f"vs_prev_round [{cmp_out['prev_record']}]:", file=sys.stderr
    )
    for l in lines:
        print(l, file=sys.stderr)


def _recommend_workload(args, raw, d_path) -> int:
    """BASELINE.md config 5: end-to-end rules + per-user recommendation
    (mirrors the reference's phase 2, AssociationRules.scala)."""
    from fastapriori_tpu.config import MinerConfig
    from fastapriori_tpu.io.reader import tokenize_line
    from fastapriori_tpu.models.apriori import FastApriori
    from fastapriori_tpu.models.recommender import AssociationRules
    from fastapriori_tpu.utils.datagen import generate_user_baskets

    n_users = max(1000, args.n_txns // 10)
    u_lines = [
        tokenize_line(l)
        for l in generate_user_baskets(
            n_users=n_users, n_items=args.n_items, seed=args.seed + 1
        )
    ]
    cfg = MinerConfig(
        min_support=args.min_support,
        engine=args.engine,
        retain_csr=False,
    )
    miner = FastApriori(config=cfg)
    # Matrix-form pipeline — the same path the CLI takes: level
    # matrices feed rule generation directly (array-form rules, no
    # per-rule Python objects).
    levels, data = miner.run_file_raw(d_path)
    rec = AssociationRules(
        [], data.freq_items, data.item_to_rank, config=cfg,
        context=miner.context, levels=levels,
        item_counts=data.item_counts,
    )
    n_itemsets = sum(m.shape[0] for m, _ in levels) + data.num_items
    rec.run(u_lines[:128], use_device=True)  # warm the containment kernel
    # Same sampling policy as the mining workload: lower-middle median of
    # up to 3 warm runs (the first full-size run still pays one-off
    # backend costs on tunneled chips — 2x the steady rate).
    walls = []
    for _ in range(max(args.warm_samples, 1)):
        t0 = time.perf_counter()
        out = rec.run(u_lines)
        walls.append(time.perf_counter() - t0)
        if walls[-1] > 60.0:
            break
    wall = sorted(walls)[(len(walls) - 1) // 2]
    assert len(out) == n_users
    # Phase attribution: rule-pipeline events only (gen_rules runs once,
    # inside the warm-up call above).  Mining phases are deliberately
    # NOT attached here: the recommend workload mines exactly once, so
    # its mining records are cold (compile-laden) and would read as a
    # regression next to the mine workload's warm medians.
    phases = {}
    n_distinct = None
    phases["rule_engine"] = "host"
    for r in rec.metrics.records:
        if r.get("event") == "gen_rules":
            phases["gen_rules_s"] = round(r.get("wall_ms", 0.0) / 1e3, 3)
            phases["n_rules"] = r.get("rules")
        elif r.get("event") == "rule_gen_device":
            phases["rule_engine"] = "device"
            phases["rule_join_dispatches"] = r.get("dispatches")
        elif r.get("event") == "user_dedup":
            phases["user_dedup_ms"] = round(r.get("wall_ms", 0.0), 1)
            n_distinct = r.get("distinct")
        elif r.get("event") == "first_match" and r.get("device"):
            # Per-phase attribution mirroring the mining phases (VERDICT
            # r5 weak #5): upload vs scan-dispatch vs fetch.  Records
            # accumulate per run, so the surviving values are the LAST
            # (steady-state) warm run's.
            phases["rule_upload_ms"] = r.get("rule_upload_ms")
            phases["scan_dispatches"] = r.get(
                "scan_dispatches", r.get("dispatches", 1)
            )
            phases["scan_ms"] = r.get("scan_ms")
            phases["fetch_ms"] = r.get("fetch_ms")
            phases["chunks_run"] = r.get("chunks_run")
            if r.get("resident_table"):
                # ISSUE 8 acceptance fields: the table was BUILT on
                # device (sharded rank-strided layout) and its bytes
                # never cross the host link after the level-table
                # upload — identically zero, recorded, not asserted.
                phases["resident_table"] = True
                phases["rule_table_host_bytes"] = r.get(
                    "rule_table_host_bytes"
                )
                phases["scan_shards"] = r.get("shards")
                phases["scan_psum_bytes"] = r.get("psum_bytes")
    phases["first_match_s"] = round(wall, 3)
    print(
        f"recommend: {n_users} users in {wall:.2f}s "
        f"({n_itemsets} itemsets)",
        file=sys.stderr,
    )
    vs_baseline = 0.0
    vs_baseline_est = False
    # Reference-style baseline: the per-user priority-ordered rule scan
    # (AssociationRules.scala:95-102) on this host — numpy doing each
    # chunk's containment work (recommender._host_first_match), the same
    # stand-in convention as the mining baseline above.  The vectorized
    # scan covers the FULL user population up to ~2e10 user×rule checks
    # (movielens-scale included), so the recommend row carries a REAL,
    # non-estimated vs_baseline (VERDICT r5 weak #5 / ISSUE 4); only
    # absurdly large populations fall back to the distinct-basket-scaled
    # subsample, still flagged as an estimate.
    n_rules = rec.n_rules or 0
    sample = len(u_lines)
    if not args.skip_baseline and n_users * n_rules > 2e10:
        sample = max(1000, int(2e10 / max(n_rules, 1)))
        vs_baseline_est = sample < len(u_lines)
    if not args.skip_baseline:
        base_lines = u_lines[:sample]
        t0 = time.perf_counter()
        base_out = rec.run(base_lines, use_device=False)
        base_wall = time.perf_counter() - t0
        sub = {e for e in out if e[0] < sample}
        assert set(base_out) == sub, (
            "host and device recommendations disagree"
        )
        if vs_baseline_est:
            # Scale by distinct baskets, not raw users: the host scan
            # early-exits per DISTINCT basket, so its cost unit is the
            # post-dedup count — a prefix's dedup ratio differs from the
            # full population's, and a raw-user scale would inherit it.
            d_sample = [
                r.get("distinct")
                for r in rec.metrics.records
                if r.get("event") == "user_dedup"
            ][-1]
            scale = (n_distinct or d_sample or 1) / max(d_sample or 1, 1)
            base_wall *= scale
        vs_baseline = base_wall / wall
        print(
            f"baseline (host first-match scan"
            f"{', est. from ' + str(sample) + ' users' if vs_baseline_est else ''}"
            f"): {base_wall:.2f}s -> speedup {vs_baseline:.2f}x",
            file=sys.stderr,
        )
    print(
        json.dumps(
            {
                "metric": f"users_per_sec_recommend_{args.config}",
                "value": round(n_users / wall, 1),
                "unit": "users/sec",
                "vs_baseline": round(vs_baseline, 3),
                **({"vs_baseline_est": True} if vs_baseline_est else {}),
                "warm_wall_s": round(wall, 3),
                "warm_band_s": [
                    round(min(walls), 3),
                    round(wall, 3),
                    round(max(walls), 3),
                ],
                "n_users": n_users,
                "n_itemsets": n_itemsets,
                "phases": phases,
            }
        )
    )
    return 0


def _serve_registry_row(server, loadgen_row) -> dict:
    """One scenario's live-registry snapshot (ISSUE 11 satellite):
    sheds / queue peak / batch fill from the server's metrics registry,
    cross-checked against the load generator's own counts — the two
    measurement paths (hot-path instruments vs post-hoc aggregation)
    must agree, or the registry is lying and ``agrees_loadgen`` says so
    in the record."""
    snap = server.metrics_snapshot()["server"]
    fill = snap.get("fa_serve_batch_fill") or {}
    queue = snap.get("fa_serve_queue_depth") or {}
    row = {
        "shed_total": snap.get("fa_serve_shed_total"),
        "served_total": snap.get("fa_serve_served_total"),
        "submitted_total": snap.get("fa_serve_submitted_total"),
        "queue_peak": queue.get("max"),
        "batch_fill_avg": (
            round(fill["sum"] / fill["count"], 1)
            if fill.get("count")
            else 0
        ),
        "batches": fill.get("count"),
    }
    # Fresh-server scenarios: lifetime totals == scenario totals, so
    # the cross-check is exact equality.
    row["agrees_loadgen"] = bool(
        row["shed_total"] == loadgen_row.get("shed")
        and row["queue_peak"] == loadgen_row.get("max_queue")
        and row["batches"] == loadgen_row.get("batches")
    )
    return row


def _scan_idle_gap(events) -> dict:
    """Idle fraction between consecutive ``serve.scan`` spans in one
    traced burst (ISSUE 19): the device-facing stage's bubble.  The
    serial dispatcher re-packs between scans (the gap IS host pack
    time); the two-stage pipeline overlaps pack with the previous scan,
    so the gap shrinks — cited from spans, not asserted."""
    spans = sorted(
        (e["ts_us"], e["dur_us"])
        for e in events
        if e.get("ph") == "X" and e.get("name") == "serve.scan"
    )
    if len(spans) < 2:
        return {"spans": len(spans)}
    window = spans[-1][0] + spans[-1][1] - spans[0][0]
    idle = sum(
        max(b - (a0 + a1), 0.0)
        for (a0, a1), (b, _) in zip(spans, spans[1:])
    )
    return {
        "spans": len(spans),
        "idle_us": round(idle, 1),
        "window_us": round(window, 1),
        "idle_frac": round(idle / max(window, 1e-9), 4),
    }


def _serve_workload(args, raw, d_path) -> int:
    """Open-loop sustained-load serving bench (ISSUE 10): the resident
    server (serve/) on the same corpus + user population as the
    recommend workload, measured the way production traffic arrives —
    a seeded Poisson schedule independent of completions — instead of
    the closed batch pass.  Records, alongside the r5-comparable
    closed-batch capacity: offered vs achieved rates, p50/p95/p99
    latency from scheduled arrival (no coordinated omission), queue
    depth, shed counts, and the model's resident-table facts
    (``rule_table_host_bytes`` stays 0 across the run).  Two scenarios:
    *sustained* (offered = 0.9x measured capacity — the ≥-batch-
    throughput acceptance row) and *overload* (offered = 3x capacity
    against a deliberately shallow queue — offered > capacity must
    degrade to recorded sheds, never an unbounded queue or a hang)."""
    from fastapriori_tpu.config import MinerConfig
    from fastapriori_tpu.io.reader import tokenize_line
    from fastapriori_tpu.reliability import ledger
    from fastapriori_tpu.serve import (
        RecommendServer,
        ServingState,
        run_open_loop,
    )
    from fastapriori_tpu.utils.datagen import generate_user_baskets

    from fastapriori_tpu.obs import trace as obs_trace

    # The serve record carries its OWN degradation summary (the
    # can't-masquerade invariant): count from a clean ledger so the
    # fields below are this workload's, not the mine's.
    ledger.reset()
    # --trace: span-record the model build, the closed-batch pass and a
    # small traced server burst (serve.batch spans with the host/device
    # split), then DISABLE tracing before the measured open-loop
    # scenarios — their achieved-rps stays the no-overhead number the
    # acceptance compares against the no-obs control below.
    obs_trace.maybe_enable(bool(args.trace))
    n_users = max(1000, args.n_txns // 10)
    u_lines = [
        tokenize_line(l)
        for l in generate_user_baskets(
            n_users=n_users, n_items=args.n_items, seed=args.seed + 1
        )
    ]
    cfg = MinerConfig(
        min_support=args.min_support, engine=args.engine, retain_csr=False,
    )
    state = ServingState.from_mine(d_path, config=cfg, source="bench")
    state.warm()
    # Closed-batch capacity — the r5-comparable number (the whole user
    # population through the serving data path, no arrival process):
    # median of warm samples, the mining workloads' sampling rule.
    state.recommend_batch(u_lines)  # warm the fixed-shape scan
    walls = []
    for _ in range(max(args.warm_samples, 1)):
        t0 = time.perf_counter()
        out = state.recommend_batch(u_lines)
        walls.append(time.perf_counter() - t0)
        if walls[-1] > 60.0:
            break
    batch_wall = sorted(walls)[(len(walls) - 1) // 2]
    capacity = n_users / batch_wall
    assert len(out) == n_users
    print(
        f"serve capacity (closed batch): {capacity:.0f} users/s "
        f"({state.describe().get('engine')} engine, "
        f"{state.n_rules} rules)",
        file=sys.stderr,
    )

    serve_rec = {
        "model": state.describe(),
        "batch_users_per_s": round(capacity, 1),
    }
    # Pipeline probe (ISSUE 19): a short traced burst under the SERIAL
    # dispatcher (pipeline_depth=0), then under the two-stage pipeline,
    # measuring the idle gap between consecutive serve.scan spans — the
    # host-work bubble the pack/dispatch split exists to close.  The
    # probe runs the DEVICE engine (forced, via a checkpoint round-trip
    # like serve_smoke's device leg) because serve.scan is the device
    # stage's span — an auto-host model would emit serve.host_scan and
    # the gap measurement would have nothing to stand on.  Probes run
    # traced and are excluded from every measured scenario below.
    import os
    import shutil
    import tempfile

    if not obs_trace.TRACER.enabled:
        obs_trace.TRACER.enable()
    probe = {}
    probe_root = tempfile.mkdtemp(prefix="fa_bench_probe_")
    try:
        pref = os.path.join(probe_root, "m_")
        state.save(pref)
        dev_state = ServingState.load(pref, config=cfg, engine="device")
        dev_state.warm()
        for label, depth in (("serial", 0), ("pipelined", None)):
            ev_base = len(obs_trace.TRACER.events())
            pserver = RecommendServer(
                dev_state, pipeline_depth=depth, batch_rows=256,
            ).start(warm=False)
            run_open_loop(
                pserver, u_lines[:256],
                rate_rps=max(capacity * 0.9, 100.0),
                n_requests=min(512, n_users), seed=args.seed + 7,
                drain_timeout_s=60.0, label=f"probe_{label}",
            )
            pserver.stop(drain=True)
            probe[label] = _scan_idle_gap(
                obs_trace.TRACER.events()[ev_base:]
            )
    finally:
        shutil.rmtree(probe_root, ignore_errors=True)
    probe["engine"] = "device"
    serve_rec["scan_idle"] = probe
    ser_f = (probe.get("serial") or {}).get("idle_frac")
    pip_f = (probe.get("pipelined") or {}).get("idle_frac")
    if ser_f is not None and pip_f is not None:
        serve_rec["scan_idle"]["shrink"] = round(ser_f - pip_f, 4)
        print(
            f"serve scan idle gap: serial {ser_f:.1%} -> pipelined "
            f"{pip_f:.1%}",
            file=sys.stderr,
        )
    if args.trace:
        # The exported trace carries the build spans plus BOTH probe
        # bursts (serve.batch/serve.pack vs serve.scan, serial and
        # pipelined threads) — the idle-gap citation's artifact.
        serve_rec["trace"] = obs_trace.TRACER.export(args.trace)
        print(f"serve trace written: {serve_rec['trace']}", file=sys.stderr)
    # Tracing OFF for everything measured below, regardless of how it
    # was enabled (--trace above OR FA_TRACE=1 via maybe_enable): the
    # sustained/overload numbers and the no-obs control must both run
    # span-free, or obs_overhead_pct measures nothing.
    obs_trace.TRACER.disable()
    # Sustained: offered just under capacity; the server must achieve
    # ~the offered rate with bounded latency and (near-)zero sheds.
    server = RecommendServer(state).start(warm=False)
    n_sus = int(min(max(2 * n_users, 4000), capacity * 6 + 1000))
    serve_rec["sustained"] = run_open_loop(
        server,
        u_lines,
        rate_rps=0.9 * capacity,
        n_requests=n_sus,
        seed=args.seed,
        drain_timeout_s=120.0,
        label="sustained",
    )
    serve_rec["sustained"]["registry"] = _serve_registry_row(
        server, serve_rec["sustained"]
    )
    sus_stats = server.stats()
    server.stop(drain=True)
    # Serial-dispatcher control (ISSUE 19 acceptance): the SAME
    # sustained scenario at pipeline_depth=0 — the two-stage win is
    # MEASURED as pipelined/serial achieved rps, not asserted.
    serial_srv = RecommendServer(state, pipeline_depth=0).start(warm=False)
    serial_sus = run_open_loop(
        serial_srv,
        u_lines,
        rate_rps=0.9 * capacity,
        n_requests=n_sus,
        seed=args.seed,
        drain_timeout_s=120.0,
        label="sustained_serial",
    )
    serial_srv.stop(drain=True)
    serve_rec["sustained_serial"] = {
        "achieved_rps": serial_sus["achieved_rps"],
        "p99_ms": serial_sus["p99_ms"],
        "shed": serial_sus["shed"],
    }
    if serial_sus["achieved_rps"]:
        serve_rec["pipeline_vs_serial"] = round(
            serve_rec["sustained"]["achieved_rps"]
            / serial_sus["achieved_rps"],
            3,
        )
    # Overload: offered 3x capacity against a ~250 ms queue — admission
    # control must shed (recorded) instead of queueing unboundedly.
    overload_depth = max(256, int(0.25 * capacity))
    server2 = RecommendServer(
        state, queue_depth=overload_depth
    ).start(warm=False)
    n_over = int(min(3 * capacity * 2.0 + 1000, 300_000))
    serve_rec["overload"] = run_open_loop(
        server2,
        u_lines,
        rate_rps=3.0 * capacity,
        n_requests=n_over,
        seed=args.seed + 1,
        drain_timeout_s=120.0,
        label="overload",
    )
    serve_rec["overload"]["queue_depth"] = overload_depth
    serve_rec["overload"]["registry"] = _serve_registry_row(
        server2, serve_rec["overload"]
    )
    server2.stop(drain=True)
    serve_rec["server"] = sus_stats
    # No-obs control (ISSUE 11 acceptance): the SAME sustained scenario
    # with the registry updates off (metrics=False; tracing is already
    # off) — the instrumented sustained achieved-rps must sit within 2%
    # of this control, recorded so the claim is checkable from the
    # record alone.
    server3 = RecommendServer(state, metrics=False).start(warm=False)
    control = run_open_loop(
        server3,
        u_lines,
        rate_rps=0.9 * capacity,
        n_requests=n_sus,
        seed=args.seed,
        drain_timeout_s=120.0,
        label="sustained_no_obs",
    )
    server3.stop(drain=True)
    ctrl_rps = control["achieved_rps"] or 1e-9
    serve_rec["no_obs_control"] = {
        "achieved_rps": control["achieved_rps"],
        "p99_ms": control["p99_ms"],
        "obs_overhead_pct": round(
            (1.0 - serve_rec["sustained"]["achieved_rps"] / ctrl_rps)
            * 100.0,
            2,
        ),
    }
    # Mesh scaling (ISSUE 19): 1/2/4 VIRTUAL hosts (LocalHost — full
    # admission/pipeline machinery, zero transport) behind the request
    # router, open-loop offered ~0.9x capacity PER host — the
    # near-linear-scaling row.  Virtual hosts rather than subprocess
    # ProcHosts on purpose: the file hand-off protocol's per-request
    # constant saturates a single-box bench long before the hosts do,
    # which would measure the transport, not the mesh.  (ProcHost
    # end-to-end behavior is covered by serve_smoke and the chaos
    # serve_kill scenario.)  Each host mounts its own ServingState
    # loaded from one shared checkpoint; speedups are vs the 1-host
    # MESH leg, so routing overhead is in the denominator too.
    from fastapriori_tpu.serve import LocalHost, MeshRouter

    mesh_root = tempfile.mkdtemp(prefix="fa_bench_mesh_")
    scaling = {}
    try:
        ckpt = os.path.join(mesh_root, "ckpt_")
        state.save(ckpt)
        for n in (1, 2, 4):
            mesh_states = [state]
            for _ in range(n - 1):
                extra = ServingState.load(ckpt, config=cfg)
                extra.warm()
                mesh_states.append(extra)
            hosts = [
                LocalHost(
                    f"w{i}",
                    RecommendServer(st, queue_depth=4096).start(
                        warm=False
                    ),
                )
                for i, st in enumerate(mesh_states)
            ]
            mesh = MeshRouter(hosts)
            rate = 0.9 * capacity * n
            n_req = int(min(rate * 3.0, 40_000))
            leg = run_open_loop(
                mesh,
                u_lines,
                rate_rps=rate,
                n_requests=n_req,
                seed=args.seed + n,
                drain_timeout_s=180.0,
                label=f"mesh_{n}host",
            )
            mstats = mesh.stats()
            mesh.stop()
            scaling[str(n)] = {
                "hosts": n,
                "offered_rps": leg["offered_rps"],
                "achieved_rps": leg["achieved_rps"],
                "p99_ms": leg["p99_ms"],
                "shed": leg["shed"],
                "router_shed": mstats["router_shed"],
                "rerouted": mstats["rerouted"],
            }
            print(
                f"serve mesh {n} host(s): offered {leg['offered_rps']}/s "
                f"achieved {leg['achieved_rps']}/s p99 {leg['p99_ms']}ms "
                f"shed {leg['shed']}",
                file=sys.stderr,
            )
        base = (scaling.get("1") or {}).get("achieved_rps")
        if base:
            for n in ("2", "4"):
                if scaling.get(n, {}).get("achieved_rps"):
                    scaling[n]["speedup_vs_1host"] = round(
                        scaling[n]["achieved_rps"] / base, 3
                    )
    finally:
        shutil.rmtree(mesh_root, ignore_errors=True)
    serve_rec["mesh_scaling"] = scaling
    # The serving acceptance facts, pulled up for the compact line.
    serve_rec["rule_table_host_bytes"] = state.rule_table_host_bytes
    # A degraded serving run must be VISIBLY degraded in the record
    # (the ledger invariant every other workload already honors): the
    # per-kind event counts — serve_engine choices, sheds' cascade
    # walks, serve_error fatal batches, scan-fetch retries — plus the
    # ordered cascade trail.  An all-"0"-answering broken server can
    # then never read as a clean record-setting row.
    phases = {"degraded": ledger.summary()}
    _quorum_summary(phases)
    trail = [
        {
            k: e[k]
            for k in ("chain", "frm", "to", "reason", "site")
            if k in e
        }
        for e in ledger.snapshot()
        if e.get("kind") == "cascade"
    ]
    if trail:
        phases["cascade_trail"] = trail
    sus = serve_rec["sustained"]
    print(
        f"serve sustained: offered {sus['offered_rps']}/s achieved "
        f"{sus['achieved_rps']}/s p99 {sus['p99_ms']}ms shed "
        f"{sus['shed']}; overload shed "
        f"{serve_rec['overload']['shed']}/{n_over}",
        file=sys.stderr,
    )
    print(
        json.dumps(
            {
                "metric": f"users_per_sec_serve_{args.config}",
                "value": sus["achieved_rps"],
                "unit": "users/sec",
                "vs_baseline": round(sus["achieved_rps"] / capacity, 3),
                "n_users": n_users,
                "serve": serve_rec,
                "phases": phases,
            }
        )
    )
    return 0


_SCALING_CHILD = """
import json, os, sys, time
n_dev = int(sys.argv[2])
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + f" --xla_force_host_platform_device_count={n_dev}"
    ).strip()
import jax
jax.config.update("jax_platforms", "cpu")
try:
    # JAX >= 0.5 spells the device split as a config option; the pinned
    # 0.4.37 rejects the name — there the XLA flag above is the only
    # (and sufficient) mechanism (same split as tests/conftest.py).
    jax.config.update("jax_num_cpu_devices", n_dev)
except AttributeError:
    pass
from fastapriori_tpu.config import MinerConfig
from fastapriori_tpu.models.apriori import FastApriori
# The scaling check exercises the SHARDED level path (the engine choice
# is a separate concern benchmarked on the real chip); argv[4] pins the
# count-reduction engine so the record carries BOTH the r5-comparable
# dense psum series and the sparse engine's measured comms bytes.
# tail_fuse_rows is pinned ON (cpu auto disables the fold) so the
# shallow-tail fold's per-iteration reduction — sparse since r7
# (ops/fused.py, the PR-6 residue) — shows its bytes in the same
# per-level comms fields as the classic levels.
# argv[5]: exchange_groups for the ISSUE-15 hierarchical series —
# 1 pins the flat single-level exchange (the r6-comparable sparse
# series), 0 lets the auto topology group the mesh (sqrt grouping on
# these virtual meshes; flat below 8 devices where hier cannot win).
cfg = MinerConfig(min_support=float(sys.argv[3]), num_devices=int(sys.argv[2]),
                  engine="level", log_metrics=True,
                  count_reduce=sys.argv[4], tail_fuse_rows=8192,
                  exchange_groups=int(sys.argv[5]) if len(sys.argv) > 5 else 1)
m = FastApriori(config=cfg)
m.run_file(sys.argv[1])
rec_start = len(m.metrics.records)  # comms for the WARM run only
t0 = time.perf_counter(); m.run_file(sys.argv[1])
wall = time.perf_counter() - t0
warm = m.metrics.records[rec_start:]
psum = sum(r.get("psum_bytes", 0) for r in warm)
gather = sum(r.get("gather_bytes", 0) for r in warm)
eng = next((r["engine"] for r in warm if r.get("event") == "count_reduce"),
           "dense")
exch = next((r for r in warm if r.get("event") == "level"
             and r.get("exchange")), {}).get("exchange", "flat")
intra = sum(r.get("intra_bytes", 0) for r in warm)
inter = sum(r.get("inter_bytes", 0) for r in warm)

def _lvl(r, k):
    d = {"k": k, "reduce": r.get("reduce", "dense"),
         "psum_bytes": r.get("psum_bytes", 0),
         "gather_bytes": r.get("gather_bytes", 0)}
    # Per-stage (intra/inter) collective bytes per level — the
    # ISSUE-15 series the hierarchical exchange is judged on.
    for f in ("exchange", "intra_bytes", "inter_bytes"):
        if r.get(f) is not None:
            d[f] = r[f]
    if k == "tail":
        d["levels"] = r.get("levels", 0)
    return d

levels = [_lvl(r, r.get("k")) for r in warm if r.get("event") == "level"]
levels += [_lvl(r, "tail") for r in warm if r.get("event") == "tail_fuse"]
print(json.dumps({"wall_s": wall, "psum_bytes": psum,
                  "gather_bytes": gather, "count_reduce": eng,
                  "exchange": exch, "intra_bytes": intra,
                  "inter_bytes": inter, "levels": levels}))
"""


def _scaling_measure(args, deadline=None) -> dict:
    """Mining wall time on 1/2/4/8-device virtual CPU meshes — validates
    that the sharded path scales functionally and records the
    per-device-count walls + psum traffic (BASELINE.json's metric is
    scaling efficiency across chips; real chips are unavailable in this
    environment, so the virtual-mesh curve is the recorded proxy —
    VERDICT r3 weak #6 wants it in EVERY round's bench artifact)."""
    import copy
    import os
    import subprocess
    import tempfile

    small = copy.copy(args)
    small.n_txns = min(args.n_txns, 50_000)
    raw = gen_lines(small)
    f = tempfile.NamedTemporaryFile(mode="w", suffix=".dat", delete=False)
    f.write("\n".join(raw) + "\n")
    f.close()
    out = {"platform": "virtual-cpu", "n_txns": small.n_txns, "devices": {}}
    try:
        # 16/32 virtual devices extend the curve into the regime the
        # hierarchical exchange exists for (ISSUE 15: the flat mask
        # gather is linear in S; the acceptance figure is hier strictly
        # below flat at S >= 8, sublinear at 16/32).
        for n in (1, 2, 4, 8, 16, 32):
            timeout = 1800.0
            if deadline is not None:
                timeout = min(timeout, max(deadline - time.monotonic(), 0))
                if timeout < 60:
                    print(
                        f"scaling n={n} skipped: bench budget exhausted",
                        file=sys.stderr,
                    )
                    break
            # Dense first (the r5-comparable psum-invariance series),
            # then — on real meshes — the sparse engine with the FLAT
            # exchange (the r6 acceptance figure: collective bytes <=
            # 25% of dense at 4+ devices), then — where the auto
            # topology actually groups (n >= 8) — the HIERARCHICAL
            # exchange, whose bytes-vs-flat ratio is the ISSUE-15
            # acceptance figure.  Child argv: (engine, exchange_groups);
            # groups=1 pins flat, 0 = auto grouping.
            engines = [("dense", 1)]
            if n > 1:
                engines.append(("sparse", 1))
            if n >= 8:
                engines.append(("hier", 0))
            for engine, xgroups in engines:
                proc = subprocess.run(
                    [sys.executable, "-c", _SCALING_CHILD, f.name, str(n),
                     str(args.min_support),
                     "sparse" if engine == "hier" else engine,
                     str(xgroups)],
                    capture_output=True,
                    timeout=timeout,
                )
                line = next(
                    (
                        l
                        for l in proc.stdout.decode().splitlines()
                        if l.startswith("{")
                    ),
                    None,
                )
                if proc.returncode == 0 and line:
                    rec = json.loads(line)
                    if engine == "dense":
                        out["devices"][str(n)] = rec
                    else:
                        out["devices"].setdefault(str(n), {})[
                            engine
                        ] = rec
    finally:
        os.unlink(f.name)
    # All virtual devices share ONE physical core, so wall time cannot
    # drop with device count — ideal sharding keeps it FLAT.  The
    # honest recordable figure is therefore the sharding OVERHEAD
    # (wall_n / wall_1: psum/reshard/dispatch cost the mesh adds), not
    # per-device efficiency, which a shared core structurally caps at
    # 1/n.
    base = (out["devices"].get("1") or {}).get("wall_s")
    for n, rec in out["devices"].items():
        ov = (
            round(rec["wall_s"] / base, 3)
            if base and rec.get("wall_s")
            else None
        )
        rec["overhead_vs_1dev"] = ov
        sp = rec.get("sparse")
        if sp and rec.get("psum_bytes"):
            # The headline ISSUE-6 figure: sparse collective bytes
            # (mask gather + compact psum) as a fraction of the dense
            # psum payload on the same mesh.
            sp["collective_vs_dense"] = round(
                (sp["psum_bytes"] + sp["gather_bytes"])
                / rec["psum_bytes"],
                4,
            )
        hr = rec.get("hier")
        if hr:
            if rec.get("psum_bytes"):
                hr["collective_vs_dense"] = round(
                    (hr["psum_bytes"] + hr["gather_bytes"])
                    / rec["psum_bytes"],
                    4,
                )
            if sp and (sp["psum_bytes"] + sp["gather_bytes"]):
                # The headline ISSUE-15 figure: the two-level
                # exchange's total collective bytes as a fraction of
                # the flat sparse exchange's on the same mesh
                # (strictly < 1 wherever the auto topology groups).
                hr["collective_vs_flat"] = round(
                    (hr["psum_bytes"] + hr["gather_bytes"])
                    / (sp["psum_bytes"] + sp["gather_bytes"]),
                    4,
                )
        print(
            f"scaling[virtual-cpu] n={n}: {rec.get('wall_s', 0.0):.2f}s "
            f"overhead_vs_1dev={ov} psum={rec.get('psum_bytes')}"
            + (
                f" sparse_vs_dense={sp['collective_vs_dense']}"
                if sp and "collective_vs_dense" in sp
                else ""
            )
            + (
                f" hier_vs_flat={hr['collective_vs_flat']}"
                f" (exchange={hr.get('exchange')})"
                if hr and "collective_vs_flat" in hr
                else ""
            ),
            file=sys.stderr,
        )
    ov8 = (out["devices"].get("8") or {}).get("overhead_vs_1dev")
    if ov8 is not None:
        out["sharding_overhead_8dev"] = ov8
    # The largest mesh with both series carries the record's headline
    # hier-vs-flat ratio (rendered on the compact driver line).
    for n in ("32", "16", "8"):
        hr = (out["devices"].get(n) or {}).get("hier") or {}
        if hr.get("collective_vs_flat") is not None:
            out["hier_vs_flat"] = {
                "devices": int(n),
                "collective_vs_flat": hr["collective_vs_flat"],
                "exchange": hr.get("exchange"),
            }
            break
    return out


_RULE_SCALING_CHILD = """
import json, os, sys, time
n_dev = int(sys.argv[2])
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + f" --xla_force_host_platform_device_count={n_dev}"
    ).strip()
import jax
jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", n_dev)
except AttributeError:
    pass
from fastapriori_tpu.config import MinerConfig
from fastapriori_tpu.io.reader import tokenize_line
from fastapriori_tpu.models.apriori import FastApriori
from fastapriori_tpu.models.recommender import AssociationRules
from fastapriori_tpu.utils.datagen import generate_user_baskets

d_path, min_support, n_items, n_users = (
    sys.argv[1], float(sys.argv[3]), int(sys.argv[4]), int(sys.argv[5])
)
# rule_engine="device" forces the device join engine below the auto size
# floor (the scaling corpus is far under 2M rules); the shard count then
# resolves to the mesh's FULL txn axis (rules/gen.py auto policy), so
# n_dev=1 is the single-chip device-engine wall the ratios divide by.
cfg = MinerConfig(min_support=min_support, engine="level",
                  num_devices=n_dev, rule_engine="device")
miner = FastApriori(config=cfg)
levels, data = miner.run_file_raw(d_path)
u_lines = [tokenize_line(l) for l in generate_user_baskets(
    n_users=n_users, n_items=n_items, seed=7)]
def fresh():
    return AssociationRules(
        [], data.freq_items, data.item_to_rank, config=cfg,
        context=miner.context, levels=levels,
        item_counts=data.item_counts)

# Warm the compiles on a THROWAWAY instance (shared context: the
# shard_map join/build/scan kernels land in ctx._fns + the jit cache),
# so the measured instance's rule_gen_device / table_build_ms walls are
# dispatch+decode, not XLA compile — the mining children's warm-run
# convention (a compile 2x slower at n=8 would otherwise corrupt the
# join_vs_1dev headline).  The warm run takes the FULL user list: the
# scan's micro-batch shape follows the basket count (recommender
# rec_batch_rows cap — config.rec_batch_rows / FA_REC_BATCH), so a
# small warm batch would leave the timed run's 4096-row compile inside
# the measured wall.
fresh().run(u_lines, use_device=True)
rec = fresh()
rec.run(u_lines[:128], use_device=True)  # measured: warm gen + table build
t0 = time.perf_counter()
out = rec.run(u_lines, use_device=True)
wall = time.perf_counter() - t0
gen = [r for r in rec.metrics.records
       if r.get("event") == "rule_gen_device"][-1]
fms = [r for r in rec.metrics.records
       if r.get("event") == "first_match" and r.get("device")]
fm0, fm = fms[0], fms[-1]  # first run carries the one-off table build
print(json.dumps({
    "shards": gen.get("shards", 1),
    "n_rules": rec.n_rules,
    "resident_table": bool(fm.get("resident_table")),
    "join_s": round(gen.get("wall_ms", 0.0) / 1e3, 3),
    "join_dispatch_s": round(gen.get("dispatch_ms", 0.0) / 1e3, 3),
    "join_dispatches": gen.get("dispatches"),
    "sort_s": round(fm0.get("table_build_ms", 0.0) / 1e3, 3),
    "join_gather_bytes": gen.get("gather_bytes", 0),
    "join_psum_bytes": gen.get("psum_bytes", 0),
    "comms": gen.get("comms", []),
    "scan_dispatches": fm.get("scan_dispatches", fm.get("dispatches")),
    "scan_psum_bytes": fm.get("psum_bytes", 0),
    "rule_table_host_bytes": fm.get("rule_table_host_bytes"),
    "scan_ms": fm.get("scan_ms"),
    "fetch_ms": fm.get("fetch_ms"),
    "users_per_s": round(n_users / wall, 1),
}))
"""


def _rule_scaling_measure(args, deadline=None) -> dict:
    """Sharded rule generation + device-resident recommend scan on
    1/2/4/8-device virtual CPU meshes (ISSUE 8): per-device-count
    join/sort walls, scan dispatches, collective bytes and users/s — the
    scaling children of the ``rules_full_scale`` record and the
    movielens recommend row.  Virtual devices share this host's core(s),
    so — exactly like the mining curve's convention — the honest
    recorded figure is the sharding OVERHEAD (``join_vs_1dev``: flat is
    ideal; the ≤0.5x join-wall target is a real-chip claim), while the
    per-level gather/psum-byte series and the zero-host-round-trip
    contract (``rule_table_host_bytes == 0``) are exact and
    chip-transferable."""
    import copy
    import os
    import subprocess
    import tempfile

    small = copy.copy(args)
    small.n_txns = min(args.n_txns, 50_000)
    # Phase-2-bound support level: the mining scaling corpus at its
    # default 0.01 survives only ~4.6K rules — a warm sharded join is
    # then ~10 ms of pure dispatch overhead and the ratio series is
    # noise.  0.002 yields ~67K itemsets -> ~190K rules / 9 levels on
    # the same corpus (a real join load, ~0.1 s warm at 1 device)
    # while the child still mines in bench-budget time.
    small.min_support = min(args.min_support, 0.002)
    raw = gen_lines(small)
    f = tempfile.NamedTemporaryFile(mode="w", suffix=".dat", delete=False)
    f.write("\n".join(raw) + "\n")
    f.close()
    n_users = 20_000
    out = {
        "platform": "virtual-cpu",
        "n_txns": small.n_txns,
        "n_users": n_users,
        "min_support": small.min_support,
        "devices": {},
    }
    try:
        for n in (1, 2, 4, 8):
            timeout = 1800.0
            if deadline is not None:
                timeout = min(timeout, max(deadline - time.monotonic(), 0))
                if timeout < 60:
                    print(
                        f"rule scaling n={n} skipped: bench budget "
                        "exhausted",
                        file=sys.stderr,
                    )
                    break
            try:
                proc = subprocess.run(
                    [sys.executable, "-c", _RULE_SCALING_CHILD, f.name,
                     str(n), str(small.min_support), str(args.n_items),
                     str(n_users)],
                    capture_output=True,
                    timeout=timeout,
                )
            except subprocess.TimeoutExpired:
                # Keep the device counts already measured — one hung
                # child must not discard the whole series.
                print(
                    f"rule scaling n={n} timed out after {timeout:.0f}s",
                    file=sys.stderr,
                )
                continue
            line = next(
                (
                    l
                    for l in proc.stdout.decode().splitlines()
                    if l.startswith("{")
                ),
                None,
            )
            if proc.returncode == 0 and line:
                out["devices"][str(n)] = json.loads(line)
            else:
                print(
                    f"rule scaling n={n} failed (rc={proc.returncode})",
                    file=sys.stderr,
                )
    finally:
        os.unlink(f.name)
    base = (out["devices"].get("1") or {}).get("join_s")
    base_u = (out["devices"].get("1") or {}).get("users_per_s")
    for n, rec in out["devices"].items():
        jv = (
            round(rec["join_s"] / base, 3)
            if base and rec.get("join_s") is not None
            else None
        )
        rec["join_vs_1dev"] = jv
        if base_u and rec.get("users_per_s"):
            rec["users_vs_1dev"] = round(rec["users_per_s"] / base_u, 3)
        print(
            f"rule-scaling[virtual-cpu] n={n}: join {rec.get('join_s')}s "
            f"(vs_1dev {jv}) sort {rec.get('sort_s')}s "
            f"scan_dispatches={rec.get('scan_dispatches')} "
            f"gather={rec.get('join_gather_bytes')} "
            f"host_bytes={rec.get('rule_table_host_bytes')} "
            f"users/s={rec.get('users_per_s')}",
            file=sys.stderr,
        )
    jv4 = (out["devices"].get("4") or {}).get("join_vs_1dev")
    if jv4 is not None:
        out["join_overhead_4dev"] = jv4
    return out


_ENGINE_COMPARE_CHILD = """
import json, os, sys, time
n_dev = int(sys.argv[2])
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + f" --xla_force_host_platform_device_count={n_dev}"
    ).strip()
import jax
jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", n_dev)
except AttributeError:
    pass
from fastapriori_tpu.config import MinerConfig
from fastapriori_tpu.models.apriori import FastApriori
# Both engines run the per-level path with the pipelined/overlapped
# ingest OFF, so the per-level walls compare pure counting work — the
# ISSUE 7 claim is about the k<=3 counting kernels, not the ingest
# overlap (which serves only the bitmap layout today).
cfg = MinerConfig(min_support=float(sys.argv[3]), num_devices=n_dev,
                  engine="level", mine_engine=sys.argv[4],
                  log_metrics=True, ingest_pipeline_blocks=1)
m = FastApriori(config=cfg)
trace_dir = sys.argv[5] if len(sys.argv) > 5 else "-"
trace_path = None
if trace_dir != "-":
    # ISSUE 18: the warm-up run (not the timed run -- capture overhead
    # must not pollute wall_s) records an XLA device trace so the
    # engine-compare pallas row cites kernel-level evidence.
    from fastapriori_tpu.obs import device_trace
    with device_trace.capture(trace_dir, explicit=True) as ti:
        m.run_file(sys.argv[1])
    if ti["active"]:
        trace_path = device_trace.find_perfetto_trace(trace_dir)
else:
    m.run_file(sys.argv[1])
rec_start = len(m.metrics.records)
t0 = time.perf_counter(); m.run_file(sys.argv[1])
wall = time.perf_counter() - t0
warm = m.metrics.records[rec_start:]
eng = next((r["engine"] for r in warm if r.get("event") == "mine_engine"),
           "bitmap")
levels = [
    {"k": r.get("k"), "wall_ms": round(r.get("wall_ms", 0.0), 1),
     "reduce": r.get("reduce", "dense"),
     "psum_bytes": r.get("psum_bytes", 0),
     "gather_bytes": r.get("gather_bytes", 0),
     "dispatches": r.get("dispatches", 0)}
    for r in warm if r.get("event") == "level"
]
build = next((round(r.get("wall_ms", 0.0) / 1e3, 3) for r in warm
              if r.get("event") in ("arena_build", "bitmap_build")), None)
out = {
    "wall_s": round(wall, 3),
    "mine_engine": eng,
    "build_s": build,
    "levels": levels,
    "psum_bytes": sum(l["psum_bytes"] for l in levels),
    "gather_bytes": sum(l["gather_bytes"] for l in levels),
    "k_le3_ms": round(sum(l["wall_ms"] for l in levels
                          if isinstance(l["k"], int) and l["k"] <= 3), 1),
    "macs": sum(r.get("macs", 0) for r in warm),
    "vops": sum(r.get("vops", 0) for r in warm),
    "member_bytes_saved": sum(r.get("member_bytes_saved", 0)
                              for r in warm if r.get("event") == "level"),
}
if trace_path is not None:
    out["device_trace"] = trace_path
print(json.dumps(out))
"""


def _engine_compare_measure(args, deadline=None) -> dict:
    """Per-engine record for the sparse-corpus config (ISSUE 7
    acceptance: the vertical engine's win is MEASURED, not asserted):
    mine ``clickstream-sparse`` under mine_engine=bitmap and =vertical —
    at 1 device (the headline wall + per-level walls) and 4 virtual
    devices (the collective-byte comparison on a real mesh) — and
    record per-engine ``mine_engine`` / per-level wall / psum+gather
    bytes plus the headline ``vertical_vs_bitmap_wall`` speedup and the
    k<=2,3 wall split."""
    import copy
    import os
    import subprocess
    import tempfile

    spec = CONFIGS["clickstream-sparse"]
    small = copy.copy(args)
    small.n_txns, small.n_items, small.avg_len = spec[0], spec[1], spec[2]
    small.style = spec[4]
    min_support = spec[3]
    raw = gen_lines(small)
    f = tempfile.NamedTemporaryFile(mode="w", suffix=".dat", delete=False)
    f.write("\n".join(raw) + "\n")
    f.close()
    out = {
        "config": "clickstream-sparse",
        "n_txns": small.n_txns,
        "min_support": min_support,
        "devices": {},
    }
    try:
        for n in (1, 4):
            if deadline is not None and time.monotonic() > deadline - 60:
                print(
                    f"engine compare n={n} skipped: bench budget "
                    "exhausted",
                    file=sys.stderr,
                )
                break
            row = {}
            for engine in ("bitmap", "vertical"):
                # ISSUE 18: the n=1 vertical child also captures an XLA
                # device trace (warm-up run) — the kernel-attribution
                # artifact the modeled pallas row cites.
                trace_dir = (
                    tempfile.mkdtemp(prefix="fa_devtrace_")
                    if engine == "vertical" and n == 1
                    else "-"
                )
                proc = subprocess.run(
                    [sys.executable, "-c", _ENGINE_COMPARE_CHILD,
                     f.name, str(n), str(min_support), engine, trace_dir],
                    capture_output=True,
                    timeout=1800.0,
                )
                line = next(
                    (
                        l
                        for l in proc.stdout.decode().splitlines()
                        if l.startswith("{")
                    ),
                    None,
                )
                if proc.returncode == 0 and line:
                    row[engine] = json.loads(line)
                else:
                    print(
                        f"engine compare {engine} n={n} failed "
                        f"(rc={proc.returncode})",
                        file=sys.stderr,
                    )
            bw = (row.get("bitmap") or {}).get("wall_s")
            vw = (row.get("vertical") or {}).get("wall_s")
            if bw and vw:
                row["vertical_vs_bitmap_wall"] = round(bw / vw, 3)
            bk = (row.get("bitmap") or {}).get("k_le3_ms")
            vk = (row.get("vertical") or {}).get("k_le3_ms")
            if bk and vk:
                row["vertical_vs_bitmap_k_le3"] = round(bk / vk, 3)
            vert = row.get("vertical") or {}
            if vert.get("member_bytes_saved"):
                # ISSUE 18: the pallas flavor is a MODELED row on CPU
                # tier-1 hosts (the kernels are TPU-only; interpreter
                # walls measure nothing).  The per-level HBM-traffic
                # model: the XLA vertical path writes+reads the
                # [P_cap, NL] prefix intermediate (member_bytes_saved,
                # ops/vertical.py vertical_member_bytes) that the
                # Pallas tier keeps VMEM-resident; the remaining
                # traffic is proxied by the word-op count (each vop
                # touches one 4-byte arena/plane word).  Real-chip
                # walls replace this model when a TPU bench lands.
                vop_bytes = vert.get("vops", 0) * 4
                row["pallas"] = {
                    "modeled": True,
                    "member_bytes_saved": vert["member_bytes_saved"],
                    "expected_speedup": round(
                        (vop_bytes + vert["member_bytes_saved"])
                        / max(vop_bytes, 1),
                        3,
                    ),
                    "device_trace": vert.get("device_trace"),
                }
                if vert.get("device_trace"):
                    # ISSUE 19 satellite: fold the raw kernel rows onto
                    # host span stage labels so the record attributes
                    # device time per STAGE (serve.scan / mine.count /
                    # xfer), not per mangled XLA program name.
                    from fastapriori_tpu.obs import device_trace

                    ks = device_trace.kernel_summary(
                        os.path.dirname(vert["device_trace"]), top=12
                    )
                    if ks.get("kernels"):
                        row["pallas"]["kernel_summary"] = ks
            out["devices"][str(n)] = row
            print(
                f"engine-compare[clickstream-sparse] n={n}: "
                f"bitmap {bw}s vs vertical {vw}s "
                f"(speedup {row.get('vertical_vs_bitmap_wall')}x, "
                f"k<=3 {row.get('vertical_vs_bitmap_k_le3')}x)",
                file=sys.stderr,
            )
    finally:
        os.unlink(f.name)
    one = out["devices"].get("1") or {}
    if one.get("vertical_vs_bitmap_wall"):
        out["vertical_vs_bitmap_wall"] = one["vertical_vs_bitmap_wall"]
        out["vertical_vs_bitmap_k_le3"] = one.get(
            "vertical_vs_bitmap_k_le3"
        )
    if one.get("pallas"):
        out["pallas"] = one["pallas"]
    return out


def main(argv=None) -> int:
    args = _parser().parse_args(argv)
    from fastapriori_tpu.utils.compile_cache import enable_compile_cache

    cache_primed = enable_compile_cache()
    if args.platform == "cpu":
        import jax

        jax.config.update("jax_platforms", "cpu")
    n_txns, n_items, avg_len, min_support, style = CONFIGS[args.config]
    args.n_txns = args.n_txns if args.n_txns is not None else n_txns
    args.min_support = (
        args.min_support if args.min_support is not None else min_support
    )
    args.n_items, args.avg_len, args.style = n_items, avg_len, style
    if args.engine_compare:
        # Standalone per-engine compare: one JSON line, no orchestration.
        ec = _engine_compare_measure(args)
        print(
            json.dumps(
                {
                    "metric": "engine_compare_clickstream_sparse",
                    "value": ec.get("vertical_vs_bitmap_wall", 0),
                    "unit": "bitmap_wall/vertical_wall",
                    "vs_baseline": 0,
                    "engine_compare": ec,
                }
            )
        )
        return 0
    if args.engine == "auto" and args.data_file is None:
        # Unattended entry (the driver): wrap in time-boxed subprocesses.
        # With --data-file the caller is iterating interactively — run the
        # engine-auto path in-process (no child indirection to bound).
        return _orchestrate(args)

    import tempfile

    from fastapriori_tpu.io.reader import tokenize_line
    from fastapriori_tpu.models.apriori import FastApriori

    if args.data_file is not None:
        d_path = args.data_file
        raw = None  # materialized lazily only if the baseline needs it
        # The metric divides by the transaction count — trust the file,
        # not the preset, when the caller supplies data.
        with open(d_path, "rb") as fh:
            args.n_txns = sum(1 for _ in fh)
    else:
        t0 = time.perf_counter()
        raw = gen_lines(args)
        d_file = tempfile.NamedTemporaryFile(
            mode="w", suffix=".dat", delete=False
        )
        d_file.write("\n".join(raw) + "\n")
        d_file.close()
        d_path = d_file.name
        print(
            f"datagen [{args.config}]: {args.n_txns} txns in "
            f"{time.perf_counter()-t0:.1f}s",
            file=sys.stderr,
        )
    if args.workload == "recommend":
        return _recommend_workload(args, raw, d_path)
    if args.workload == "serve":
        return _serve_workload(args, raw, d_path)

    # Mine workload only (the recommend workload has no sharded mining
    # to scale); orchestrated runs attach their own sweep instead.
    scaling_block = _scaling_measure(args) if args.scaling else None

    # Cold run (includes jit compiles), then warm run for the steady rate.
    # run_file = ingest straight from disk (native C++ scan when built),
    # matching the reference's from-HDFS measurement boundary.
    from fastapriori_tpu.config import MinerConfig

    miner = FastApriori(
        config=MinerConfig(
            min_support=args.min_support, engine=args.engine,
            log_metrics=True, retain_csr=False,
        )
    )
    # The measured object is the matrix-form pipeline (run_file_raw):
    # level matrices are what the writer and rule generator consume
    # directly, so the per-itemset frozenset decode is not part of the
    # production path; the equality assert below decodes OUTSIDE the
    # timed region (via the miner's own decode helper).
    t0 = time.perf_counter()
    miner.run_file_raw(d_path)
    cold = time.perf_counter() - t0
    # Steady-state rate: MEDIAN of three warm runs (same rule for the
    # baseline below — identical sampling both sides).  The first
    # post-compile run still pays one-off backend costs (deferred
    # transfer-program setup, allocator warmup — on tunneled TPU backends
    # these are large and run-to-run variance is high), so a single warm
    # sample under-reports the sustained rate by 2-3x; a min would bias
    # the headline optimistically.
    warm_runs = []
    run_records = []  # per-run metrics slice, for the MFU report
    for _ in range(max(args.warm_samples, 1)):
        rec_start = len(miner.metrics.records)
        t0 = time.perf_counter()
        levels, data = miner.run_file_raw(d_path)
        warm_runs.append(time.perf_counter() - t0)
        run_records.append(miner.metrics.records[rec_start:])
        if warm_runs[-1] > 60.0:  # huge datasets: one warm sample is enough
            break
    result = miner._decode_levels(levels, data)
    # Lower-middle median: with 3 samples this is the true median; with 2
    # (the >60s early break) it picks the faster one rather than crediting
    # a transient stall as the sustained rate.
    med_i = sorted(range(len(warm_runs)), key=warm_runs.__getitem__)[
        (len(warm_runs) - 1) // 2
    ]
    warm = warm_runs[med_i]
    print(
        f"mining: cold {cold:.2f}s"
        # A primed persistent compile cache makes "cold" machine-state-
        # dependent — disclose it so cold figures are never compared
        # across different cache states.  Warm medians (the metric) are
        # cache-independent.
        f"{' (compile cache primed)' if cache_primed else ''} "
        f"warm {warm:.2f}s "
        f"(median of {' '.join(f'{w:.2f}' for w in warm_runs)}; "
        f"{len(result)} frequent itemsets)",
        file=sys.stderr,
    )
    tps = args.n_txns / warm
    mfu = _mfu_report(run_records[med_i], warm)

    vs_baseline = 0.0
    # The reference-style baseline scans the whole bitmap once per
    # candidate; its cost is ~(itemsets x txns).  Past ~1e11 bool-ops it
    # would dominate the bench run by an hour — report vs_baseline=0
    # rather than extrapolate.
    if len(result) * args.n_txns > 1e11 and not args.skip_baseline:
        print(
            f"baseline skipped: est. cost {len(result)} itemsets x "
            f"{args.n_txns} txns too large for the reference-style scan",
            file=sys.stderr,
        )
        args.skip_baseline = True
    if not args.skip_baseline:
        if raw is None:
            with open(d_path) as fh:
                raw = fh.read().splitlines()
        lines = [tokenize_line(l) for l in raw]
        # Same best-of-3 methodology as the framework measurement above,
        # so vs_baseline compares like with like.
        base_runs = []
        for _ in range(3):
            t0 = time.perf_counter()
            base_result = reference_style_mine(lines, args.min_support)
            base_runs.append(time.perf_counter() - t0)
            if base_runs[-1] > 60.0:
                break
        base = sorted(base_runs)[(len(base_runs) - 1) // 2]
        assert dict(base_result) == dict(result), (
            "baseline and framework disagree"
        )
        base_tps = args.n_txns / base
        vs_baseline = tps / base_tps
        print(
            f"baseline (reference-style numpy): {base:.2f}s "
            f"-> speedup {vs_baseline:.2f}x",
            file=sys.stderr,
        )

    line = {
        "metric": (
            f"transactions_per_sec_{args.config}"
            f"_minsup{args.min_support}"
        ),
        "value": round(tps, 1),
        "unit": "txns/sec",
        "vs_baseline": round(vs_baseline, 3),
        # Walls reported separately (VERDICT weak #6): the ratio's
        # run-to-run noise comes almost entirely from the single-core
        # baseline denominator; chip-side medians are stable.
        "warm_wall_s": round(warm, 3),
        # Tunnel-drift band (VERDICT r3 weak #1): the same binary's warm
        # wall varies with time of day on a tunneled chip, so the record
        # carries [min, median, max] of this invocation's warm samples —
        # cross-session comparisons must compare medians and read the
        # band, never cherry-pick a best sample.
        "warm_band_s": [
            round(min(warm_runs), 3),
            round(warm, 3),
            round(max(warm_runs), 3),
        ],
    }
    if not args.skip_baseline and vs_baseline > 0:
        line["baseline_wall_s"] = round(base, 3)
    line.update(mfu)
    line["phases"] = _phase_summary(run_records[med_i], cold_s=cold)
    if scaling_block is not None:
        line["scaling"] = scaling_block
    print(json.dumps(line))
    return 0


# v5e single-chip peaks: 394 int8 TOPS (bf16/f32-via-MXU is half).  The
# kernels are int8 matmuls with exactly computable MAC counts (the engines
# attach "macs" to their per-phase metric events), so achieved TOPS / peak
# is a true MFU, not an estimate — except the fused engine's macs, which
# are a documented per-iteration model (models/apriori.py).
V5E_INT8_PEAK_TOPS = 394.0


def _mfu_report(records, mining_wall_s):
    """Per-phase achieved-TOPS table (stderr) + headline MFU fields for
    the JSON line.  Only meaningful on the TPU backend; on cpu the macs
    still aggregate but no peak/MFU is claimed."""
    import jax

    on_tpu = jax.default_backend() == "tpu"
    total_macs = 0
    for r in records:
        macs = r.get("macs")
        if not macs:
            continue
        total_macs += macs
        wall_s = r.get("wall_ms", 0) / 1e3
        tops = 2 * macs / wall_s / 1e12 if wall_s > 0 else 0.0
        tag = {k: r[k] for k in ("k", "m_cap", "n2") if k in r}
        line = (
            f"mfu[{r['event']}{tag if tag else ''}]: "
            f"{macs/1e9:.2f} GMAC in {wall_s*1e3:.0f} ms "
            f"-> {tops:.1f} TOPS"
        )
        if on_tpu:
            line += f" ({100*tops/V5E_INT8_PEAK_TOPS:.1f}% of v5e peak)"
        print(line, file=sys.stderr)
    if not total_macs:
        return {}
    tops = 2 * total_macs / mining_wall_s / 1e12
    out = {"total_gmacs": round(total_macs / 1e9, 2),
           "mining_tops": round(tops, 2)}
    if on_tpu:
        out["mfu_pct"] = round(100 * tops / V5E_INT8_PEAK_TOPS, 2)
    print(
        f"mfu[TOTAL]: {total_macs/1e9:.2f} GMAC over {mining_wall_s:.2f} s "
        f"end-to-end -> {tops:.2f} TOPS"
        + (f" ({out['mfu_pct']}% of v5e int8 peak)" if on_tpu else ""),
        file=sys.stderr,
    )
    return out


if __name__ == "__main__":
    sys.exit(main())
