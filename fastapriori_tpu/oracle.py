"""Pure-Python oracle: an exact, self-contained reimplementation of the
reference pipeline's semantics, used as the golden model in tests.

Every function mirrors a reference component (see SURVEY.md §2) and cites the
behavior it reproduces:

- tokenization:           Utils.scala:21,23  (``trim().split("\\s+")``)
- minCount:               FastApriori.scala:38-39 (``ceil(minSupport * N)``)
- item occurrence counts: FastApriori.scala:55-58 (``flatMap(_.map((_,1)))``
  — duplicates *within* a line each count)
- rank assignment:        FastApriori.scala:60-62 (descending count; the
  reference's tie order is Spark-nondeterministic, we fix it deterministically
  — see :func:`item_sort_key`)
- basket filter + dedup:  FastApriori.scala:66-79 (``toSet``; drop size<=1;
  dedupe identical baskets with multiplicity)
- pair counting:          FastApriori.scala:212-241
- candidate generation:   FastApriori.scala:167-193
- level counting:         FastApriori.scala:132-160
- level-loop termination: FastApriori.scala:111 (``while kItems.length >= k``)
- rule generation:        AssociationRules.scala:122-145
- dominance prune:        AssociationRules.scala:147-182
- rule ordering:          AssociationRules.scala:116-120 (confidence desc,
  consequent-as-int asc)
- recommendation:         AssociationRules.scala:80-106
- output formats:         Utils.scala:29-49

This module deliberately shares NO code with the framework proper so that
framework-vs-oracle golden tests are meaningful.
"""

from __future__ import annotations

import math
import re
from collections import Counter
from typing import Dict, List, Sequence, Tuple

ItemSet = frozenset  # of int ranks

_TRIM = "".join(chr(i) for i in range(0x21))  # Java String.trim charset


def tokenize(line: str) -> List[str]:
    """Java-compatible ``line.trim().split("\\s+")``.

    Java's split on an empty (trimmed) string returns ``[""]`` — a single
    empty token — which Python's ``str.split()`` would drop.  ``re.split``
    reproduces the Java behavior exactly (Utils.scala:21).
    """
    # Java rules, not Python's: trim() removes chars <= 0x20 and regex
    # \s is ASCII-only (see io/reader.py tokenize_line).
    return re.split(r"[ \t\n\x0B\f\r]+", line.strip(_TRIM))


def read_lines(path: str) -> List[List[str]]:
    # Split on '\n' only (drop the trailing-newline tail) — the same
    # record rule as the native scanner and Spark textFile; Python's
    # splitlines() would also split on \x0b/\x0c/\x1c-\x1e/\x85 etc.
    with open(path, "r") as f:
        content = f.read()
    if not content:
        return []
    lines = content.split("\n")
    if lines[-1] == "":
        lines.pop()
    return [tokenize(line) for line in lines]


def item_sort_key(item_count: Tuple[str, int]):
    """Deterministic stand-in for the reference's ``sortBy(-_._2)``
    (FastApriori.scala:60), whose tie order is Spark-collect
    nondeterministic.  Ties broken by numeric value of the item token
    ascending (items are integer strings in this domain), falling back to the
    raw token."""
    item, count = item_count
    try:
        num = int(item)
        return (-count, 0, num, item)
    except ValueError:
        return (-count, 1, 0, item)


def count_items(transactions: Sequence[Sequence[str]]) -> Counter:
    """C3 first half: global occurrence counts (within-line duplicates each
    count — ``flatMap(_.map((_,1)))``, FastApriori.scala:55)."""
    c: Counter = Counter()
    for t in transactions:
        c.update(t)
    return c


def freq_items_and_ranks(
    counts: Counter, min_count: int
) -> Tuple[List[str], Dict[str, int]]:
    """C3 second half: frequent items sorted by descending count, dense ranks
    0..F-1 (FastApriori.scala:57-62)."""
    freq = [(i, c) for i, c in counts.items() if c >= min_count]
    freq.sort(key=item_sort_key)
    freq_items = [i for i, _ in freq]
    item_to_rank = {item: r for r, item in enumerate(freq_items)}
    return freq_items, item_to_rank


def dedup_transactions(
    transactions: Sequence[Sequence[str]], item_to_rank: Dict[str, int]
) -> Tuple[List[ItemSet], List[int]]:
    """C4: filter to frequent items, map to ranks, drop baskets of size <= 1,
    dedupe identical baskets with multiplicity (FastApriori.scala:66-79).

    Returns (distinct baskets in first-seen order, multiplicity weights)."""
    order: List[ItemSet] = []
    mult: Dict[ItemSet, int] = {}
    for t in transactions:
        basket = frozenset(item_to_rank[i] for i in t if i in item_to_rank)
        if len(basket) <= 1:
            continue
        if basket in mult:
            mult[basket] += 1
        else:
            mult[basket] = 1
            order.append(basket)
    return order, [mult[b] for b in order]


def _support(baskets: List[ItemSet], weights: List[int], s: ItemSet) -> int:
    return sum(w for b, w in zip(baskets, weights) if s <= b)


def gen_pairs(
    baskets: List[ItemSet], weights: List[int], F: int, min_count: int
) -> List[Tuple[ItemSet, int]]:
    """C6: all C(F,2) pairs, weighted support, threshold
    (FastApriori.scala:212-241)."""
    out = []
    for i in range(F - 1):
        for j in range(i + 1, F):
            c = _support(baskets, weights, frozenset((i, j)))
            if c >= min_count:
                out.append((frozenset((i, j)), c))
    return out


def gen_candidates(
    k_items: List[ItemSet], F: int
) -> List[Tuple[ItemSet, List[int]]]:
    """C7: ordered-extension candidate generation with classic Apriori subset
    prune (FastApriori.scala:167-193).  Result order of extensions is
    ascending rank (the reference uses a HashSet, order-irrelevant there)."""
    k_set = set(k_items)
    out = []
    for x in k_items:
        cands = set(range(max(x) + 1, F)) - x
        for elem in x:
            if not cands:
                break
            sub = x - {elem}
            cands = {y for y in cands if (sub | {y}) in k_set}
        if cands:
            out.append((x, sorted(cands)))
    return out


def gen_next_level(
    candidates: List[Tuple[ItemSet, List[int]]],
    baskets: List[ItemSet],
    weights: List[int],
    min_count: int,
) -> List[Tuple[ItemSet, int]]:
    """C8: per (prefix, extensions) group, weighted support of prefix+ext
    (FastApriori.scala:132-160)."""
    out = []
    for sub, items in candidates:
        for i in items:
            s = sub | {i}
            c = _support(baskets, weights, s)
            if c >= min_count:
                out.append((s, c))
    return out


def mine(
    transactions: Sequence[Sequence[str]], min_support: float
) -> Tuple[List[Tuple[ItemSet, int]], Dict[str, int], List[str]]:
    """C9 + FastApriori.run (FastApriori.scala:31-44, 88-130): full mining.

    Returns (freqItemsets with counts — levels >=2 first then 1-itemsets,
    itemToRank, freqItems), mirroring the reference's result triple."""
    n = len(transactions)
    min_count = math.ceil(min_support * n)
    counts = count_items(transactions)
    freq_items, item_to_rank = freq_items_and_ranks(counts, min_count)
    F = len(freq_items)
    baskets, weights = dedup_transactions(transactions, item_to_rank)

    freq_itemsets: List[Tuple[ItemSet, int]] = []
    k_items_with_count = gen_pairs(baskets, weights, F, min_count)
    freq_itemsets.extend(k_items_with_count)
    k_items = [s for s, _ in k_items_with_count]
    k = 3
    while len(k_items) >= k:
        cands = gen_candidates(k_items, F)
        k_items_with_count = gen_next_level(cands, baskets, weights, min_count)
        freq_itemsets.extend(k_items_with_count)
        k_items = [s for s, _ in k_items_with_count]
        k += 1

    # 1-itemsets appended last with their raw occurrence counts
    # (FastApriori.scala:41,83).
    freq_itemsets.extend(
        (frozenset((item_to_rank[i],)), counts[i]) for i in freq_items
    )
    return freq_itemsets, item_to_rank, freq_items


# ---------------------------------------------------------------------------
# Rules + recommendation (AssociationRules.scala)
# ---------------------------------------------------------------------------

Rule = Tuple[ItemSet, int, float]  # (antecedent, consequent rank, confidence)


def gen_rules(freq_itemsets: List[Tuple[ItemSet, int]]) -> List[Rule]:
    """C11: rule generation (AssociationRules.scala:122-145) followed by the
    level-wise "cut leaves" dominance prune (:147-182).

    A rule at antecedent-size i survives iff ALL of its
    (antecedent-minus-one-element → same consequent) rules survived level
    i-1 AND every one of them has strictly lower confidence."""
    support = {s: c for s, c in freq_itemsets}
    by_size: Dict[int, List[Tuple[ItemSet, int]]] = {}
    for s, c in freq_itemsets:
        by_size.setdefault(len(s), []).append((s, c))

    rules_by_len: Dict[int, List[Rule]] = {}
    for s, c in freq_itemsets:
        if len(s) == 1:
            continue
        for item in s:
            ant = s - {item}
            conf = c / support[ant]
            rules_by_len.setdefault(len(ant), []).append((ant, item, conf))

    if not rules_by_len:
        return []
    min_len = min(rules_by_len)
    max_len = max(rules_by_len)
    real_rules: List[Rule] = list(rules_by_len[min_len])
    low_level = list(rules_by_len[min_len])
    for i in range(min_len + 1, max_len + 1):
        by_consequent: Dict[int, List[Rule]] = {}
        for r in low_level:
            by_consequent.setdefault(r[1], []).append(r)
        survivors = []
        for ant, consequent, conf in rules_by_len[i]:
            if consequent not in by_consequent:
                continue
            subs = {r[0]: r[2] for r in by_consequent[consequent]}
            ok = True
            for elem in ant:
                sub = ant - {elem}
                if sub not in subs:
                    ok = False  # subset rule did not survive (:173)
                    break
                if subs[sub] >= conf:
                    ok = False  # not strictly confidence-increasing (:168)
                    break
            if ok:
                survivors.append((ant, consequent, conf))
        real_rules.extend(survivors)
        low_level = survivors
    return real_rules


def sort_rules(rules: List[Rule], freq_items: List[str]) -> List[Rule]:
    """C12 ordering: confidence desc, consequent-as-int asc
    (AssociationRules.scala:116-120 — the reference's ``.toInt`` would
    crash on non-integer item strings; like rules/gen.py sort_rules, fall
    back to ordering those after the integers, by string)."""

    def key(r: Rule):
        item = freq_items[r[1]]
        try:
            return (-r[2], 0, int(item), item)
        except ValueError:
            return (-r[2], 1, 0, item)

    return sorted(rules, key=key)


def recommend(
    user_lines: Sequence[Sequence[str]],
    rules: List[Rule],
    freq_items: List[str],
    item_to_rank: Dict[str, int],
) -> List[Tuple[int, str]]:
    """C10 + C12: dedupe user baskets, first-match recommendation
    (AssociationRules.scala:33-113).  Returns (row index, item or "0")."""
    sorted_rules = [
        (ant, cons, len(ant)) for ant, cons, _ in sort_rules(rules, freq_items)
    ]
    out: List[Tuple[int, str]] = []
    cache: Dict[ItemSet, str] = {}
    for idx, line in enumerate(user_lines):
        basket = frozenset(item_to_rank[i] for i in line if i in item_to_rank)
        if not basket:
            out.append((idx, "0"))
            continue
        if basket in cache:
            out.append((idx, cache[basket]))
            continue
        rec = "0"
        n = len(basket)
        for ant, cons, size in sorted_rules:
            if size <= n and cons not in basket and ant <= basket:
                rec = freq_items[cons]
                break
        cache[basket] = rec
        out.append((idx, rec))
    return out


# ---------------------------------------------------------------------------
# Output formatting (Utils.scala:29-49)
# ---------------------------------------------------------------------------

def format_freq_itemsets(
    freq_itemsets: List[Tuple[ItemSet, int]], freq_items: List[str]
) -> str:
    """Ranks sorted descending within a line, lines sorted lexicographically
    (Utils.scala:36-39)."""
    lines = [
        " ".join(freq_items[r] for r in sorted(s, reverse=True))
        for s, _ in freq_itemsets
    ]
    lines.sort()
    return "".join(line + "\n" for line in lines)


def format_recommends(recommends: List[Tuple[int, str]]) -> str:
    """Sorted by row index, one item per line (Utils.scala:48)."""
    return "".join(
        item + "\n" for _, item in sorted(recommends, key=lambda x: x[0])
    )


def run_pipeline(
    d_lines: Sequence[Sequence[str]],
    u_lines: Sequence[Sequence[str]],
    min_support: float,
) -> Tuple[str, str]:
    """End-to-end: returns (freqItemset file text, recommends file text)."""
    freq_itemsets, item_to_rank, freq_items = mine(d_lines, min_support)
    rules = gen_rules(freq_itemsets)
    recs = recommend(u_lines, rules, freq_items, item_to_rank)
    return (
        format_freq_itemsets(freq_itemsets, freq_items),
        format_recommends(recs),
    )
