"""Hierarchical (two-level) mesh exchange primitives (ISSUE 15,
ROADMAP direction 3).

Every linear-in-S collective in the mining pipeline has the same shape:
a per-shard payload crosses the FULL txn axis in one flat exchange — the
packed survivor-mask union all_gather of the sparse count reduction
(ops/count.py ``local_sparse_psum``: S·N/8 bytes per shard), the sharded
rule join's next-level table reassembly (ops/contain.py
``_tiled_all_gather``: S blocks per shard per level), and the compact
segment psum.  Fine to ~4-8 shards; past that the exchange itself is the
ceiling (PR 6 / PR 8 residue).

This module is the scalable-allreduce construction of arxiv 1312.3020
composed with the multi-stage reduction staging of arxiv 1710.07358,
specialized to a 1-D ``shard_map`` axis: the S shards are viewed as a
``(groups, per_group)`` grid via ``axis_index_groups`` — collectives
first run WITHIN each group (intra: the fast tier — same host over ICI
on a real pod, or a contiguous rank range on a virtual mesh), then ONE
exchange runs ACROSS groups (inter: the slow tier — DCN), with every
shard acting as its group's leader for its own grid column, so the
"intra-group broadcast" of the classic construction is implicit (column
c of every group already participated in column c's inter exchange).

For REDUCTIONS (the mask-union OR, the segment psum) the staging is
also a byte win: the intra stage folds ``per_group`` payloads into one
group aggregate, so the inter stage moves ``groups`` aggregates instead
of S raw payloads — per-shard union-gather bytes drop from ``S·N/8`` to
``(per_group + groups)·N/8`` (≈ ``2·√S·N/8`` under √S grouping).  For
CONCATENATIONS (the rule-table reassembly) the received total is
invariant (every shard must end with all S blocks); the win is message
structure — ``(per_group-1) + (groups-1)`` exchanges of large contiguous
chunks instead of ``S-1`` small blocks, with the slow-tier stage moving
whole group chunks.

All three primitives are BIT-EXACT twins of their flat forms: the OR
union and int32 sums are associative/commutative, and the tiled
reassembly preserves shard-order layout because groups are contiguous
rank ranges.  The flat exchange stays in ops/* as the differential
oracle and the ``hier→flat`` cascade fallback
(reliability/watchdog.py CHAINS["exchange"]).
"""

from __future__ import annotations

import math
from typing import Optional, Tuple

import jax.numpy as jnp
from jax import lax

# A resolved exchange topology: (groups, per_group) with
# groups * per_group == n_shards, both > 1.  None everywhere means the
# flat single-level exchange.
GroupSpec = Optional[Tuple[int, int]]


def index_groups(spec: Tuple[int, int]):
    """The two ``axis_index_groups`` partitions of a ``(groups,
    per_group)`` grid over axis indices ``0..S-1``: ``intra`` —
    contiguous rank ranges, one per group (stage 1 runs inside each) —
    and ``inter`` — one column per intra-group position, taking rank
    ``g·per + c`` of every group ``g`` (stage 2 runs across groups;
    every shard sits in exactly one column, so no separate broadcast
    stage is needed)."""
    groups, per = spec
    intra = [[g * per + i for i in range(per)] for g in range(groups)]
    inter = [[g * per + i for g in range(groups)] for i in range(per)]
    return intra, inter


def auto_group_count(n_shards: int, n_procs: int = 1) -> int:
    """The 0-knob topology (config.exchange_groups == 0): on a real
    multi-host mesh the groups ARE the process boundaries (intra =
    ICI within a host, inter = DCN across hosts) whenever they divide
    the axis; on a single-process virtual mesh, the divisor of S
    closest to √S from below — the byte-optimal split for the
    reduction exchanges ((per+groups)·N/8 is minimized at per = groups
    = √S).  Returns 1 (flat) whenever the hierarchy cannot strictly
    beat the flat exchange (per + groups < S needs S >= 8 for √
    grouping)."""
    if n_shards < 8 and not (1 < n_procs < n_shards):
        return 1
    if 1 < n_procs < n_shards and n_shards % n_procs == 0:
        return n_procs
    best = 1
    root = int(math.isqrt(n_shards))
    for g in range(root, 1, -1):
        if n_shards % g == 0:
            best = g
            break
    # A composite S always has a divisor <= isqrt(S), so best == 1
    # here means S is prime — no admissible split, stay flat; and a
    # split whose per+groups does not strictly undercut S cannot win.
    if best == 1 or best + n_shards // best >= n_shards:
        return 1
    return best


def spill_order(primary: int, n_hosts: int, groups: int = 0) -> list:
    """Serving-mesh admission fan-out (ISSUE 19): the spill sequence
    for a request whose primary host refused admission.  The host set
    is viewed as the same ``(groups, per_group)`` grid the exchange
    tiers use: spill WITHIN the primary's group first (the fast tier —
    same pod on real hardware), then the remaining hosts in ring order,
    so overflow traffic stays pod-local until the whole pod saturates.
    Meshes with no admissible grouping degenerate to the plain ring."""
    if not 0 <= primary < n_hosts:
        from fastapriori_tpu.errors import InputError

        raise InputError(
            f"spill_order: primary {primary} outside 0..{n_hosts - 1}"
        )
    spec = resolve_spec(n_hosts, groups)
    ring = [(primary + k) % n_hosts for k in range(n_hosts)]
    if spec is None:
        return ring
    _g, per = spec
    pod = primary // per
    return (
        [h for h in ring if h // per == pod]
        + [h for h in ring if h // per != pod]
    )


def resolve_spec(n_shards: int, requested: int, n_procs: int = 1) -> GroupSpec:
    """Validate/resolve the group-count knob against the mesh:
    ``requested`` 0 = auto (:func:`auto_group_count`), 1 = flat; any
    other value must divide ``n_shards`` (InputError otherwise — the
    FA_NO_PALLAS strictness contract: a typo'd topology silently
    running flat would be invisible in a record).  ``n_shards`` itself
    resolves to flat (per_group 1 degenerates: the intra stage is the
    identity and the inter stage IS the flat exchange).  Returns the
    ``(groups, per_group)`` spec, or None for the flat exchange."""
    if requested < 0:
        from fastapriori_tpu.errors import InputError

        raise InputError(
            f"exchange_groups must be >= 0 (0 = auto, 1 = flat), got "
            f"{requested}"
        )
    if requested == 0:
        requested = auto_group_count(n_shards, n_procs)
    if requested in (1, n_shards):
        return None
    if n_shards % requested != 0:
        from fastapriori_tpu.errors import InputError

        raise InputError(
            f"exchange_groups={requested} does not divide the txn mesh "
            f"axis ({n_shards} shards): use a divisor, 1 (flat), or 0 "
            "(auto — process boundaries on multi-host, sqrt grouping "
            "on virtual meshes)"
        )
    return (requested, n_shards // requested)


def resolve_active_spec(
    n_shards: int, config=None, *, unclamped: bool = False
) -> GroupSpec:
    """The full knob resolution (:func:`resolve_spec` over strict
    ``FA_EXCHANGE_GROUPS`` / ``config.exchange_groups``), clamped at
    the quorum consensus floor (a peer that walked hier→flat already
    issues flat collectives).  Shared by the mining engine
    (models/apriori.py ``_exchange_spec``, which adds the ledger
    events) and the sharded rule join (rules/gen.py) so the two
    resolutions can never drift.  ``unclamped`` skips the quorum
    floor — the caller that wants to RECORD a quorum clamp needs the
    pre-clamp resolution to tell "clamped" apart from "flat anyway"."""
    import jax

    from fastapriori_tpu.reliability import quorum
    from fastapriori_tpu.utils.env import env_int

    req = env_int("FA_EXCHANGE_GROUPS", -1, minimum=0)
    if req < 0:
        req = (
            getattr(config, "exchange_groups", 0)
            if config is not None
            else 0
        )
    spec = resolve_spec(n_shards, req, jax.process_count())
    if unclamped:
        return spec
    if spec is not None and not quorum.stage_allowed("exchange", "hier"):
        spec = None
    return spec


def describe_spec(spec: GroupSpec) -> str:
    """Human/obs-facing one-token summary of a resolved topology:
    ``"hier(GxP)"`` or ``"flat"``.  Used by the elastic-mesh respec
    note (ISSUE 17) and post-mortem tooling — the survivor set's
    re-derived exchange shape must be readable off the flight timeline
    without reconstructing the knob resolution."""
    if spec is None:
        return "flat"
    return f"hier({spec[0]}x{spec[1]})"


# ---------------------------------------------------------------------------
# in-kernel primitives (called inside shard_map-traced code)


def hier_union_packed(
    packed: jnp.ndarray,  # uint8 [...]: bit-packed per-shard mask
    axis_name: str,
    spec: Tuple[int, int],
) -> jnp.ndarray:
    """Two-level OR-union of per-shard bit-packed masks — the
    hierarchical twin of ``all_gather`` + OR-reduce in
    ops/count.py ``local_sparse_psum`` (bit-exact: OR is associative).
    Stage 1 unions within each group (per_group payloads over the fast
    tier); stage 2 unions the group aggregates across groups (groups
    payloads over the slow tier)."""
    intra, inter = index_groups(spec)
    g1 = lax.all_gather(packed, axis_name, axis_index_groups=intra)
    u1 = lax.reduce(g1, jnp.uint8(0), lax.bitwise_or, (0,))
    g2 = lax.all_gather(u1, axis_name, axis_index_groups=inter)
    return lax.reduce(g2, jnp.uint8(0), lax.bitwise_or, (0,))


def hier_psum(
    x: jnp.ndarray, axis_name: str, spec: Tuple[int, int]
) -> jnp.ndarray:
    """Two-level psum (intra-group, then across group columns) —
    bit-exact for the integer payloads every count reduction moves
    (int32 addition is associative and commutative)."""
    intra, inter = index_groups(spec)
    s1 = lax.psum(x, axis_name, axis_index_groups=intra)
    return lax.psum(s1, axis_name, axis_index_groups=inter)


def hier_tiled_all_gather(
    x: jnp.ndarray, axis_name: str, axis: int, spec: Tuple[int, int]
) -> jnp.ndarray:
    """Two-level tiled reassembly of per-shard blocks, concatenated
    along ``axis`` in SHARD ORDER — the layout twin of ops/contain.py
    ``_tiled_all_gather`` (groups are contiguous rank ranges, and
    ``axis_index_groups`` rows land in group-tuple order, so
    group-major concatenation IS rank order).  Stage 1 assembles each
    group's contiguous chunk; stage 2 exchanges whole group chunks
    across the grid columns."""
    intra, inter = index_groups(spec)

    def _concat(g, base_shape):
        if axis == 0:
            return g.reshape((-1,) + base_shape[1:])
        assert axis == 1, axis
        g = jnp.moveaxis(g, 0, 1)
        return g.reshape(base_shape[0], -1, *base_shape[2:])

    chunk = _concat(
        lax.all_gather(x, axis_name, axis_index_groups=intra), x.shape
    )
    return _concat(
        lax.all_gather(chunk, axis_name, axis_index_groups=inter),
        chunk.shape,
    )


# ---------------------------------------------------------------------------
# payload models (host-side accounting — bench/metrics cite these, the
# same role ops/count.py sparse_psum_bytes plays for the flat exchange)


def union_stage_bytes(
    n_bytes: int, n_shards: int, spec: GroupSpec
) -> Tuple[int, int]:
    """Per-shard ``(intra, inter)`` received bytes of one mask-union
    exchange with per-shard payload ``n_bytes``: flat = everything on
    the single (slow) tier; hierarchical = ``per·b`` intra +
    ``groups·b`` inter — the reduction's byte win."""
    if spec is None:
        return 0, n_shards * n_bytes
    groups, per = spec
    return per * n_bytes, groups * n_bytes


def gather_stage_bytes(
    n_bytes: int, n_shards: int, spec: GroupSpec
) -> Tuple[int, int]:
    """Per-shard ``(intra, inter)`` received bytes of one tiled
    reassembly with per-shard payload ``n_bytes``: the received total
    is invariant (every shard ends holding all S blocks — S·b), but
    the hierarchy moves only whole group chunks on the slow tier and
    in ``groups-1`` messages instead of ``S-per`` — the staging win
    the per-level rule-join accounting records."""
    if spec is None:
        return 0, n_shards * n_bytes
    groups, per = spec
    return per * n_bytes, groups * per * n_bytes
