"""Device mesh + SPMD execution layer (reference C15, SURVEY.md §1 L1).

This is the TPU-native replacement for the reference's Spark substrate:

- Spark ``reduceByKey`` + ``collect`` counting rounds  → ``lax.psum`` over
  the 1-D transaction mesh axis inside ``shard_map``;
- ``sc.broadcast`` of candidate/itemset tables         → replicated specs
  (``P(None)``) — XLA broadcasts once over ICI;
- ``sc.parallelize`` scatter of candidates             → replicated device
  arrays (candidates are small; the *data* is what is sharded);
- executors holding the full bitmap (FastApriori.scala:100) → each device
  holds only ``T'/n`` rows of the bitmap.

Multi-host: call :func:`initialize_distributed` first (wraps
``jax.distributed.initialize``); the mesh then spans all processes' devices
and the same ``shard_map`` code drives ICI within a host and DCN across
hosts.
"""

from __future__ import annotations

import functools
import os
from typing import Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from fastapriori_tpu import compat
from fastapriori_tpu.errors import InputError

from fastapriori_tpu.ops import count as count_ops
from fastapriori_tpu.ops.bitmap import next_pow2 as _next_pow2
from fastapriori_tpu.reliability import failpoints, ledger, retry

AXIS = "txn"
CAND = "cand"

# FA_NO_PALLAS is a kill switch for the production hot path, so its
# spelling is STRICT: a typo ("of", "fasle") used to silently disable
# the Pallas kernel for the whole run (ADVICE r5 #4) — now it is an
# InputError at the first dispatch.
_PALLAS_ENV_FALSY = ("", "0", "false", "no")
_PALLAS_ENV_TRUTHY = ("1", "true", "yes", "on")


def pallas_disabled_by_env() -> bool:
    """Strictly parsed ``FA_NO_PALLAS``: True = the Pallas level kernel
    is disabled.  Unrecognized spellings raise
    :class:`~fastapriori_tpu.errors.InputError` instead of silently
    degrading the hot path."""
    raw = os.environ.get("FA_NO_PALLAS", "")
    val = raw.strip().lower()
    if val in _PALLAS_ENV_FALSY:
        return False
    if val in _PALLAS_ENV_TRUTHY:
        return True
    from fastapriori_tpu.errors import InputError

    raise InputError(
        f"unrecognized FA_NO_PALLAS value {raw!r}: use one of "
        f"{'/'.join(_PALLAS_ENV_TRUTHY)} to disable the Pallas level "
        f"kernel, {'/'.join(p for p in _PALLAS_ENV_FALSY if p)} (or "
        "unset) to keep it"
    )


def initialize_distributed(**kwargs) -> None:
    """Multi-host init (the analog of standing up the Spark cluster,
    README.md:22-35).  No-op on a single process."""
    jax.distributed.initialize(**kwargs)


def allgather_bytes(blob: bytes) -> list:
    """Exchange one bytes blob per process; returns the list in process
    order.  The cross-host transport for sharded ingest's tiny global
    tables (item counts, shard sizes) — the analog of the reference's
    collect-to-driver for C3 (FastApriori.scala:58); the BULK data (the
    basket shards) never crosses hosts.  Single-process: [blob]."""
    failpoints.fire("allgather")
    if jax.process_count() == 1:
        return [blob]
    from jax.experimental import multihost_utils

    lens = multihost_utils.process_allgather(
        np.array([len(blob)], dtype=np.int64)
    ).reshape(-1)
    m = int(lens.max())
    arr = np.zeros(max(m, 1), dtype=np.uint8)
    arr[: len(blob)] = np.frombuffer(blob, np.uint8)
    gathered = multihost_utils.process_allgather(arr)
    return [
        bytes(gathered[i, : int(lens[i])]) for i in range(gathered.shape[0])
    ]


@jax.jit
def _gather_counts_jit(counts_list, pos_list):
    """Per-level survivor gathers concatenated into one fetchable array
    (jit's own per-shape cache covers the varying level shapes)."""
    return jnp.concatenate(
        [
            jnp.take(c.reshape(-1), p)
            for c, p in zip(counts_list, pos_list)
        ]
    )


@jax.jit
def _gather_counts_u24_jit(counts_list, pos_list):
    """3-byte variant: counts provably < 2^24 (callers gate on n_raw)
    leave the chip as three uint8 planes — 25% fewer bytes over a
    down-link this round's probes measured as low as 5 MB/s."""
    g = _gather_counts_jit(counts_list, pos_list)
    return jnp.stack(
        [
            (g & 0xFF).astype(jnp.uint8),
            ((g >> 8) & 0xFF).astype(jnp.uint8),
            ((g >> 16) & 0xFF).astype(jnp.uint8),
        ]
    )


def _pad_positions(pos: np.ndarray) -> np.ndarray:
    """int32 gather positions padded to the next power of two (fill 0 —
    a valid index whose gathered value the consumer slices off).  Exact
    survivor counts are data-dependent, so unpadded position shapes
    compiled a FRESH gather program per mine — part of the 14 compile-
    cache misses r5 measured on a primed cache (VERDICT r5 next #5);
    pow2 buckets bound the distinct compiled shapes."""
    out = np.zeros(_next_pow2(max(int(pos.size), 1)), dtype=np.int32)
    out[: pos.size] = pos.astype(np.int32)
    return out


class PendingCounts:
    """An in-flight survivor-count gather: ONE dispatch already issued,
    its compact output crossing the link as an audited async fetch
    (reliability/retry.py fetch_async); :meth:`result` blocks, decodes
    the optional u24 3-byte planes, and strips the per-segment pow2
    position padding (`_pad_positions`) so callers see exactly their
    real counts, concatenated in segment order."""

    def __init__(self, fetch, seg_real, seg_pad, u24: bool):
        self._fetch = fetch
        self._seg_real = seg_real
        self._seg_pad = seg_pad
        self._u24 = u24

    def result(self) -> np.ndarray:
        out = self._fetch.result()
        if self._u24:
            dec = (
                out[0].astype(np.int64)
                | (out[1].astype(np.int64) << 8)
                | (out[2].astype(np.int64) << 16)
            )
        else:
            dec = out.astype(np.int64)
        parts = []
        off = 0
        for real, pad in zip(self._seg_real, self._seg_pad):
            parts.append(dec[off : off + real])
            off += pad
        return (
            np.concatenate(parts) if parts else np.empty(0, np.int64)
        )


class DeviceContext:
    """Owns the (txn × cand) device mesh and the jitted counting kernels.

    ``num_devices=None`` uses every visible device; ``1`` gives the
    single-chip path (same code — a 1-device mesh; psum is the identity).

    ``cand_devices`` splits the mesh into a 2-D ``(txn, cand)`` grid
    (default 1 = the plain transaction mesh).  The bitmap is sharded over
    ``txn`` and replicated over ``cand``; the level engine then shards
    each level's candidate-prefix rows over ``cand`` (SURVEY.md §7's
    optional 2-D mesh) — candidate-space parallelism layered on top of
    the transaction sharding, the analog of the reference running many
    candidate tasks per executor (FastApriori.scala:140).  Useful when
    txn shards would otherwise go thin on a large pod (T'/n small).
    """

    def __init__(
        self,
        num_devices: Optional[int] = None,
        devices: Optional[Sequence[jax.Device]] = None,
        cand_devices: int = 1,
    ):
        devs = list(devices if devices is not None else jax.devices())
        if num_devices is not None:
            devs = devs[:num_devices]
        if cand_devices < 1 or len(devs) % cand_devices != 0:
            raise InputError(
                f"cand_devices={cand_devices} must be >= 1 and divide the "
                f"device count ({len(devs)}); with --platform cpu, pass "
                "--num-devices to provision that many virtual devices"
            )
        self.n_devices = len(devs)
        self.cand_shards = cand_devices
        self.txn_shards = len(devs) // cand_devices
        self.mesh = Mesh(
            # lint: host-data -- python list of Device handles, no array fetch
            np.array(devs).reshape(self.txn_shards, cand_devices),
            (AXIS, CAND),
        )
        self._fns: Dict[Tuple[int, ...], Tuple] = {}
        # Hierarchical-exchange topology (parallel/hier.py GroupSpec):
        # (groups, per_group) routes every sparse count reduction and
        # the sharded rule join's reassembly through the two-level
        # exchange; None = flat (the oracle).  Resolved once per mine
        # by the engine layer (models/apriori.py _exchange_groups —
        # config.exchange_groups / FA_EXCHANGE_GROUPS / quorum floor)
        # and installed here because the kernel builders below are the
        # one place every collective's compile is keyed; the spec is
        # part of each cache key, so a mid-mine hier→flat re-clamp
        # compiles (and issues) the flat collectives from the next
        # dispatch on.
        self.exchange_spec: Optional[Tuple[int, int]] = None
        self._fused_hints: Dict[Tuple, int] = {}
        self._fused_fails: set = set()
        self._auto_level: set = set()
        self._pair_caps: Dict[Tuple, int] = {}
        # Pallas kernel-tier state (ops/pallas_vertical.py): sticky
        # local disables set by the vertical_kernel/serve_scan cascade
        # walks (forward-only — a failed kernel never re-arms within a
        # context), plus the last vertical plan so the engine layer can
        # attribute a transient to the Pallas tier.
        self._vertical_pallas_off = False
        self._serve_pallas_off = False
        self._vertical_pallas_last = False
        self._serve_pallas_last = False

    def set_exchange_spec(
        self, spec: Optional[Tuple[int, int]]
    ) -> None:
        """Install the resolved two-level exchange topology (or None
        for flat).  Forward walks only come from the engine layer /
        quorum consensus; the builders read it at call time."""
        self.exchange_spec = spec

    def respec_summary(self) -> Dict[str, object]:
        """The collective-shaping state of this mesh as a small dict —
        the elastic-mesh rejoin (ISSUE 17) stamps it into the
        ``mesh_epoch_reseed`` flight note so a continued run's
        post-mortem shows exactly which topology each epoch mined
        under."""
        from fastapriori_tpu.parallel import hier

        return {
            "txn_shards": self.txn_shards,
            "cand_shards": self.cand_shards,
            "exchange": hier.describe_spec(self.exchange_spec),
        }

    # -- data placement ----------------------------------------------------
    def shard_bitmap(self, bitmap: np.ndarray) -> jax.Array:
        """Place B with rows sharded over the txn axis."""
        assert bitmap.shape[0] % self.txn_shards == 0, (
            bitmap.shape,
            self.txn_shards,
        )
        return jax.device_put(
            bitmap, NamedSharding(self.mesh, P(AXIS, None))
        )

    def _unpack_fn(self):
        if "unpack" not in self._fns:
            from fastapriori_tpu.ops.fused import _unpack

            inner = jax.jit(
                compat.shard_map(
                    _unpack,
                    mesh=self.mesh,
                    in_specs=P(AXIS, None),
                    out_specs=P(AXIS, None),
                ),
                donate_argnums=0,  # free the packed buffer after unpack
            )

            def unpack(arr):
                # The donation exists to FREE the packed buffer promptly;
                # it can never be reused for the 8x-larger unpacked
                # output, and jax warns about exactly that on every run —
                # suppress the known-benign warning, keep the early free.
                import warnings

                with warnings.catch_warnings():
                    warnings.filterwarnings(
                        "ignore",
                        message="Some donated buffers were not usable",
                    )
                    return inner(arr)

            self._fns["unpack"] = unpack
        return self._fns["unpack"]

    def upload_packed(self, packed: np.ndarray) -> jax.Array:
        """Upload an already bit-packed ``uint8[T, F//8]`` bitmap (e.g.
        from ops/bitmap.py build_packed_bitmap_csr) sharded over the txn
        axis and unpack it on device into the resident int8 form."""
        assert packed.shape[0] % self.txn_shards == 0, (
            packed.shape,
            self.txn_shards,
        )
        arr = jax.device_put(packed, self.sharding_rows())
        return self._unpack_fn()(arr)

    def shard_weight_digits(self, w_digits: np.ndarray) -> jax.Array:
        """Place the [D, T] digit matrix with T sharded."""
        return jax.device_put(
            w_digits, NamedSharding(self.mesh, P(None, AXIS))
        )

    # -- multi-host sharded ingest ---------------------------------------
    # Each process holds only ITS rows of the global bitmap (sharded
    # ingest, preprocess.py preprocess_file_sharded); the global array is
    # assembled without any cross-host data movement — the mesh's device
    # order is process-major, so process p's rows are exactly the rows
    # the txn sharding assigns to p's devices.
    def upload_packed_local(self, packed_local: np.ndarray) -> jax.Array:
        """Multi-process twin of :meth:`upload_packed`: ``packed_local``
        is THIS process's rows (uniform count across processes)."""
        if jax.process_count() == 1:
            return self.upload_packed(packed_local)
        global_shape = (
            packed_local.shape[0] * jax.process_count(),
            packed_local.shape[1],
        )
        arr = jax.make_array_from_process_local_data(
            self.sharding_rows(), packed_local, global_shape
        )
        return self._unpack_fn()(arr)

    def shard_weight_digits_local(self, w_digits_local: np.ndarray):
        """Multi-process twin of :meth:`shard_weight_digits` ([D, T_local]
        per process, T sharded globally)."""
        if jax.process_count() == 1:
            return self.shard_weight_digits(w_digits_local)
        global_shape = (
            w_digits_local.shape[0],
            w_digits_local.shape[1] * jax.process_count(),
        )
        return jax.make_array_from_process_local_data(
            NamedSharding(self.mesh, P(None, AXIS)),
            w_digits_local,
            global_shape,
        )

    def shard_rows_local(self, local: np.ndarray) -> jax.Array:
        """Rows-on-txn placement from per-process row slices (all
        processes must pass the same local row count)."""
        if jax.process_count() == 1:
            if local.ndim == 1:
                return self.shard_weights_like(local)
            return self.shard_bitmap(local)
        global_shape = (
            local.shape[0] * jax.process_count(),
        ) + local.shape[1:]
        spec = P(AXIS, *([None] * (local.ndim - 1)))
        return jax.make_array_from_process_local_data(
            NamedSharding(self.mesh, spec), local, global_shape
        )

    def local_row_slice(self, n_rows_global: int) -> slice:
        """This process's contiguous row range of a txn-sharded array
        (device order is process-major).

        Guards its own invariants — process-major, evenly divisible txn
        sharding with no cand axis spanning processes — with a real
        exception (an assert would vanish under ``python -O`` and the
        caller would silently mis-slice)."""
        n_proc = jax.process_count()
        if (
            self.cand_shards != 1
            or self.txn_shards % n_proc != 0
            or n_rows_global % n_proc != 0
        ):
            from fastapriori_tpu.errors import InputError

            raise InputError(
                "multi-process row sharding needs a 1-D txn mesh with "
                "devices and rows divisible by processes (txn_shards="
                f"{self.txn_shards}, cand_shards={self.cand_shards}, "
                f"rows={n_rows_global}, processes={n_proc})"
            )
        per = n_rows_global // n_proc
        p = jax.process_index()
        return slice(p * per, (p + 1) * per)

    def local_rows(self, arr) -> np.ndarray:
        """This process's rows of a txn-sharded device array as numpy
        (whole array when single-process).  Inverse of
        :meth:`shard_rows_local`; lives here so every placement
        invariant (process-major row order, cand-axis REPLICATION — a
        2-D mesh holds cand_shards identical copies of each row block,
        which must be deduplicated, not concatenated) stays in one
        place."""
        if jax.process_count() == 1:
            # lint: fetch-site -- local_rows IS the host-materialization API, retry-wrapped
            return retry.fetch(lambda: np.asarray(arr), "local_rows")
        seen = {}
        for s in arr.addressable_shards:
            start = s.index[0].start or 0
            if start not in seen:
                seen[start] = s.data
        return np.concatenate(
            # lint: fetch-site -- this process's addressable shards only
            [np.asarray(seen[k]) for k in sorted(seen)]
        )

    def shard_weights_like(self, x: np.ndarray) -> jax.Array:
        """Place a 1-D per-transaction (or per-basket) vector sharded over
        the txn axis."""
        return jax.device_put(x, NamedSharding(self.mesh, P(AXIS)))

    def sharding_rows(self) -> NamedSharding:
        """Sharding for 2-D arrays with rows on the txn axis."""
        return NamedSharding(self.mesh, P(AXIS, None))

    def sharding_vector(self) -> NamedSharding:
        return NamedSharding(self.mesh, P(AXIS))

    @property
    def platform(self) -> str:
        return self.mesh.devices.flat[0].platform

    def pair_counter(
        self, n_digits: int, n_chunks: int = 1, fast_f32: bool = False
    ):
        """Jitted level-2 survivor counter (ops/fused.py pre-pass)."""
        key = ("pairs", n_digits, n_chunks, fast_f32)
        if key not in self._fns:
            from fastapriori_tpu.ops.fused import make_pair_counter

            self._fns[key] = make_pair_counter(
                self.mesh, n_digits, n_chunks, fast_f32
            )
        return self._fns[key]

    def fused_miner(
        self,
        m_cap: int,
        l_max: int,
        n_digits: int,
        n_chunks: int = 1,
        fast_f32: bool = False,
        packed_input: bool = True,
        sparse_caps: Optional[Tuple[int, int]] = None,
    ):
        """Jitted whole-loop mining program (ops/fused.py), cached per
        static configuration.  ``packed_input=False`` = the variant fed
        by the level engine's resident unpacked bitmap.
        ``sparse_caps``: threshold-sparse count reductions (the program
        then takes the replicated [S] prune-threshold array as its
        fourth argument)."""
        if not fast_f32 and l_max >= 128:
            # The fused kernel widens its membership accumulator to
            # int32 past int8's exactness bound (ops/fused.py
            # contains_prefix) — a real HBM-traffic degradation worth a
            # ledger entry.
            ledger.record(
                "int8_widen", once_key="fused", site="fused", l_max=l_max
            )
        xspec = self.exchange_spec if sparse_caps is not None else None
        key = (
            "fused", m_cap, l_max, n_digits, n_chunks, fast_f32,
            packed_input, sparse_caps, xspec,
        )
        if key not in self._fns:
            from fastapriori_tpu.ops.fused import make_fused_miner

            self._fns[key] = make_fused_miner(
                self.mesh, m_cap, l_max, n_digits, n_chunks, fast_f32,
                packed_input=packed_input, sparse_caps=sparse_caps,
                groups=xspec,
            )
        return self._fns[key]

    def tail_miner(
        self,
        scales: Tuple[int, ...],
        k0: int,
        m_cap: int,
        p_cap: int,
        l_max: int,
        n_chunks: int,
        has_heavy: bool,
        sparse_cap: Optional[int] = None,
        flat_caps: bool = False,
    ):
        """Jitted shallow-tail program (ops/fused.py make_tail_miner),
        cached per static configuration (one compile per seed depth).
        ``sparse_cap`` runs the per-iteration count reductions as the
        threshold-sparse exchange (the PR-6 residue fold); ``flat_caps``
        builds the fused-checkpoint segment shape (full-m_cap slot
        caps, ops/fused.py tail_slot_caps)."""
        if k0 + l_max - 1 >= 128:
            # Same widen as the fused engine, reached when the SEED depth
            # plus tail depth crosses int8's bound (ops/fused.py
            # _tail_mine_local).
            ledger.record(
                "int8_widen", once_key="tail", site="tail", k0=k0,
                l_max=l_max,
            )
        xspec = self.exchange_spec if sparse_cap is not None else None
        key = (
            "tail", tuple(scales), k0, m_cap, p_cap, l_max, n_chunks,
            has_heavy, sparse_cap, flat_caps, xspec,
        )
        if key not in self._fns:
            from fastapriori_tpu.ops.fused import make_tail_miner

            self._fns[key] = make_tail_miner(
                self.mesh, tuple(scales), k0, m_cap, p_cap, l_max,
                n_chunks, has_heavy, sparse_cap=sparse_cap,
                flat_caps=flat_caps, groups=xspec,
            )
        return self._fns[key]

    def fused_m_cap_hint(self, profile: Tuple) -> Optional[int]:
        """Last row budget that compiled AND completed for this static
        profile — lets repeat runs skip the pair-count sizing pre-pass."""
        return self._fused_hints.get(profile)

    def record_fused_m_cap(self, profile: Tuple, m_cap: int) -> None:
        self._fused_hints[profile] = m_cap

    def fused_failed(self, profile: Tuple) -> bool:
        """True when a previous run of this profile exhausted the fused
        row-budget cap — repeat runs go straight to the level engine
        instead of re-paying the doomed attempts."""
        return profile in self._fused_fails

    def record_fused_fail(self, profile: Tuple) -> None:
        self._fused_fails.add(profile)

    def pair_cap_hint(self, key: Tuple) -> Optional[int]:
        """Last pair-threshold budget that held this profile's survivors
        — repeat runs start there instead of re-paying the overflow
        retry's extra dispatch + compile every time (the config default
        is sized for the common case, not the ceiling)."""
        return self._pair_caps.get(key)

    def record_pair_cap(self, key: Tuple, cap: int) -> None:
        self._pair_caps[key] = cap

    def auto_level(self, profile: Tuple) -> bool:
        """True when the auto engine choice (models/apriori.py) already
        picked the level engine for this static profile — repeat runs
        skip the decision pre-pass.  Separate from the fused-FAILURE memo
        so a later explicitly-forced fused run is not blocked by a mere
        auto decision."""
        return profile in self._auto_level

    def record_auto_level(self, profile: Tuple) -> None:
        self._auto_level.add(profile)

    def replicate(self, x: np.ndarray) -> jax.Array:
        spec = P(*([None] * x.ndim))
        return jax.device_put(x, NamedSharding(self.mesh, spec))

    # -- kernels -----------------------------------------------------------
    def _get_fns(self, scales: Tuple[int, ...]):
        """Jitted shard_map-wrapped kernels for a given (static) digit-scale
        tuple.  One compilation per distinct input shape, cached by jax."""
        if scales in self._fns:
            return self._fns[scales]
        mesh = self.mesh

        pair = jax.jit(
            compat.shard_map(
                functools.partial(
                    count_ops.local_pair_counts,
                    scales=scales,
                    axis_name=AXIS,
                ),
                mesh=mesh,
                in_specs=(P(AXIS, None), P(None, AXIS)),
                out_specs=P(None, None),
            )
        )

        def _level(bitmap, w_digits, prefix_cols):
            return count_ops.local_level_counts(
                bitmap, w_digits, scales, prefix_cols, axis_name=AXIS
            )

        level = jax.jit(
            compat.shard_map(
                _level,
                mesh=mesh,
                in_specs=(P(AXIS, None), P(None, AXIS), P(None, None)),
                out_specs=P(None, None),
            )
        )

        item = jax.jit(
            compat.shard_map(
                functools.partial(
                    count_ops.local_item_supports,
                    scales=scales,
                    axis_name=AXIS,
                ),
                mesh=mesh,
                in_specs=(P(AXIS, None), P(None, AXIS)),
                out_specs=P(None),
            )
        )

        self._fns[scales] = (pair, level, item)
        return self._fns[scales]

    def pair_gather(
        self, bitmap, w_digits, scales, min_count: int, num_items: int,
        cap: int, heavy_b=None, heavy_w=None, fast_f32: bool = False,
        sparse_cap: Optional[int] = None, sparse_thr=None,
    ):
        """On-device pair threshold (ops/count.py local_pair_gather);
        returns ``(flat_idx int32[cap], counts int32[cap], n2 int, tri
        int, counts_dev, reduce_info)`` — the first four as HOST values
        (tri = level-3 candidate census for the engine auto-choice),
        ``counts_dev`` the UNFETCHED device-resident [F, F] count
        matrix for :meth:`pair_regather`, ``reduce_info`` the
        count-reduction engine + payload-byte accounting for the
        metrics stream.  The kernel packs the host-bound outputs
        into one int32 array so the host pays ONE device→host fetch: on
        a tunneled chip every separate fetch is a full ~110 ms round
        trip, and the previous four-output form spent ~400 ms of the
        pair phase on three extra round trips (VERDICT r3 weak #3).
        ``heavy_b``/``heavy_w``: replicated heavy-row remainder arrays
        (single-low-digit weight split) — None runs the legacy
        multi-digit form.

        ``sparse_cap`` + ``sparse_thr`` ([S] int32, the per-shard prune
        thresholds) run the [F, F] reduction as the threshold-sparse
        exchange; a union-compaction overflow falls back to ONE dense
        re-dispatch (ledger event) — exact either way."""
        has_heavy = heavy_b is not None
        f_pad = bitmap.shape[1]
        xspec = self.exchange_spec if sparse_cap is not None else None
        key = (
            "pair_gather", tuple(scales), cap, fast_f32, has_heavy,
            sparse_cap, xspec,
        )
        if key not in self._fns:
            mesh = self.mesh
            scl = tuple(scales)

            def _local(bitmap, w_digits, min_count, num_items, *rest):
                rest = list(rest)
                thr = rest.pop(0) if sparse_cap is not None else None
                hb, hw = rest if rest else (None, None)
                return count_ops.local_pair_gather(
                    bitmap, w_digits, scl, min_count, num_items, cap,
                    heavy_b=hb, heavy_w=hw,
                    axis_name=AXIS, fast_f32=fast_f32,
                    sparse_thr=(
                        thr[lax.axis_index(AXIS)]
                        if sparse_cap is not None
                        else None
                    ),
                    sparse_cap=sparse_cap,
                    groups=xspec,
                )

            in_specs = (
                (P(AXIS, None), P(None, AXIS), P(), P())
                + ((P(None),) if sparse_cap is not None else ())
                + ((P(None, None), P(None)) if has_heavy else ())
            )
            self._fns[key] = jax.jit(
                compat.shard_map(
                    _local,
                    mesh=mesh,
                    in_specs=in_specs,
                    out_specs=(P(None), P(None, None)),
                )
            )
        args = [bitmap, w_digits, jnp.int32(min_count), jnp.int32(num_items)]
        if sparse_cap is not None:
            args += [jnp.asarray(sparse_thr, dtype=jnp.int32)]
        if has_heavy:
            args += [heavy_b, heavy_w]
        packed, counts_dev = self._fns[key](*args)
        if sparse_cap is not None:
            # lint: fetch-site -- sparse-engine pair fetch (packed 2cap+3 ints incl. the union census), retry-wrapped
            out = retry.fetch(lambda: np.asarray(packed), "pair_sparse")
            nu = int(out[2 * cap + 2])
            if nu > sparse_cap:
                # Union compaction overflowed: the scattered counts are
                # a SUBSET of the union — unusable.  One dense
                # re-dispatch keeps the mine exact; the recorded census
                # lets repeat runs size the budget right.
                ledger.record(
                    "count_sparse_overflow", site="pair",
                    n_union=nu, cap=sparse_cap,
                )
                res = self.pair_gather(
                    bitmap, w_digits, scales, min_count, num_items, cap,
                    heavy_b=heavy_b, heavy_w=heavy_w, fast_f32=fast_f32,
                )
                # The wasted sparse attempt's bytes still crossed the
                # mesh — account them on top of the dense redo's (the
                # level path's overflow branch does the same).
                g_b, p_b = count_ops.sparse_psum_bytes(
                    f_pad * f_pad, sparse_cap, self.txn_shards, xspec
                )
                res[-1]["fallback"] = "sparse_overflow"
                res[-1]["n_union"] = nu
                res[-1]["psum_bytes"] += p_b
                res[-1]["gather_bytes"] += g_b
                return res
            gather_b, psum_b = count_ops.sparse_psum_bytes(
                f_pad * f_pad, sparse_cap, self.txn_shards, xspec
            )
            info = self._reduce_info(
                f_pad * f_pad, sparse_cap, xspec, psum_b, gather_b
            )
            info["n_union"] = nu
        else:
            # lint: fetch-site -- the pair phase's ONE audited fetch (packed 2cap+2 ints), retry-wrapped
            out = retry.fetch(lambda: np.asarray(packed), "pair")
            info = {
                "reduce": "dense",
                "psum_bytes": 4 * f_pad * f_pad,
                "gather_bytes": 0,
            }
        return (
            out[:cap],
            out[cap : 2 * cap],
            int(out[2 * cap]),
            int(out[2 * cap + 1]),
            counts_dev,
            info,
        )

    def _reduce_info(
        self,
        n_valid: int,
        sparse_cap: int,
        xspec: Optional[Tuple[int, int]],
        psum_b: int,
        gather_b: int,
    ) -> dict:
        """The sparse reduce_info dict every sparse gather returns —
        one constructor so the pair/vertical/level accounting can never
        drift: engine + payload totals plus the two-level exchange's
        per-stage (intra/inter) attribution (ops/count.py
        sparse_stage_bytes), the fields bench's scaling series and the
        trace counter tracks consume."""
        intra_b, inter_b = count_ops.sparse_stage_bytes(
            n_valid, sparse_cap, self.txn_shards, xspec
        )
        info = {
            "reduce": "sparse",
            "psum_bytes": psum_b,
            "gather_bytes": gather_b,
            "exchange": "hier" if xspec is not None else "flat",
            "intra_bytes": intra_b,
            "inter_bytes": inter_b,
        }
        if xspec is not None:
            info["exchange_groups"] = xspec[0]
        return info

    # -- vertical (Eclat) engine: tid-lane arena + AND/popcount kernels ----
    def upload_tid_arena(self, arena_np: np.ndarray, buckets=None):
        """Place the vertical engine's tid-lane arena
        (``uint32[F_pad+1, NL]``, ops/vertical.py) with LANES sharded
        over the txn axis — lane block s holds the same contiguous
        transaction range as the horizontal engine's row shard s, so
        the sparse count reduction's pigeonhole thresholds carry over
        unchanged.  ``buckets``: the index-compressed pow2-bucketed
        segment form (ops/vertical.py compress_arena) — the compact
        host→device payload is scattered into the dense arena in ONE
        device dispatch (the arxiv 1102.1003 layout's upload saving on
        sparse corpora); None uploads the dense arena directly.
        Returns ``(arena, upload_bytes)``."""
        assert arena_np.shape[1] % self.txn_shards == 0, (
            arena_np.shape,
            self.txn_shards,
        )
        sharding = NamedSharding(self.mesh, P(None, AXIS))
        if buckets is None:
            return jax.device_put(arena_np, sharding), arena_np.nbytes
        from fastapriori_tpu.ops.vertical import assemble_arena

        f_pad = arena_np.shape[0] - 1
        nl = arena_np.shape[1]
        shapes = tuple(
            (b[0].shape, b[1].shape) for b in buckets
        )
        key = ("varena", f_pad, nl, shapes)
        if key not in self._fns:
            self._fns[key] = jax.jit(
                lambda bk: assemble_arena(bk, f_pad, nl),
                out_shardings=sharding,
            )
        dev = [
            (
                jax.device_put(ids),
                jax.device_put(segs),
                jax.device_put(words),
            )
            for ids, segs, words in buckets
        ]
        payload = sum(
            ids.nbytes + segs.nbytes + words.nbytes
            for ids, segs, words in buckets
        )
        return self._fns[key](dev), payload

    def upload_lane_planes(self, planes_np: np.ndarray):
        """Weight bit-planes (``uint32[B, NL]``) sharded over the lane
        (txn) axis alongside the arena."""
        return jax.device_put(
            planes_np, NamedSharding(self.mesh, P(None, AXIS))
        )

    # -- multi-process vertical lanes (ISSUE 15: the PR-7 residue) -------
    # Lane blocks shard over the txn axis exactly like bitmap ROWS —
    # lane l holds transactions [32l, 32l+32), and each process's rows
    # pad to the same local count — so process p's local lanes are
    # precisely the lanes the P(None, AXIS) sharding assigns to p's
    # devices: the global arena assembles with zero cross-host data
    # movement, the lane twin of upload_packed_local.
    def upload_tid_arena_local(self, arena_local: np.ndarray):
        """Multi-process twin of :meth:`upload_tid_arena`:
        ``arena_local`` is ``uint32[F_pad+1, NL_local]`` holding THIS
        process's lanes (uniform lane count across processes — the
        engine pads every shard to the same local row count).  The
        bucket-compressed upload stays single-process (its scatter
        dispatch would need a global index remap for marginal gain on
        the already-local payload).  Returns ``(arena, upload_bytes)``."""
        if jax.process_count() == 1:
            return self.upload_tid_arena(arena_local)
        global_shape = (
            arena_local.shape[0],
            arena_local.shape[1] * jax.process_count(),
        )
        arr = jax.make_array_from_process_local_data(
            NamedSharding(self.mesh, P(None, AXIS)),
            arena_local,
            global_shape,
        )
        return arr, arena_local.nbytes

    def upload_lane_planes_local(self, planes_local: np.ndarray):
        """Multi-process twin of :meth:`upload_lane_planes` (``[B,
        NL_local]`` per process; B must be globally uniform — the
        engine derives it from the ingest-exchanged global max weight,
        ops/vertical.py weight_bit_planes ``min_planes``)."""
        if jax.process_count() == 1:
            return self.upload_lane_planes(planes_local)
        global_shape = (
            planes_local.shape[0],
            planes_local.shape[1] * jax.process_count(),
        )
        return jax.make_array_from_process_local_data(
            NamedSharding(self.mesh, P(None, AXIS)),
            planes_local,
            global_shape,
        )

    def vertical_pair_gather(
        self, arena, w_planes, scales, min_count: int, num_items: int,
        cap: int, txn_chunk: int, fast_f32: bool = False,
        sparse_cap: Optional[int] = None, sparse_thr=None,
    ):
        """Vertical twin of :meth:`pair_gather` (ops/vertical.py
        vertical_pair_local): per-plane Gram matmuls over lane chunks
        unpacked on the fly (k=2 is the one level where EVERY pair is a
        candidate, so the matmul beats per-candidate intersections —
        RDD-Eclat computes F2 horizontally too), landing in the SAME
        resident [F, F] count matrix — the packed host payload, the
        level-3 census, the ``n2 > cap`` overflow retry
        (:meth:`pair_regather`) and the sparse-reduction overflow
        fallback are all shared with the horizontal engine.
        ``txn_chunk`` bounds the per-chunk unpacked [F, tc] bit matrix.
        Returns the same 6-tuple as :meth:`pair_gather`."""
        f_pad = arena.shape[0] - 1
        nl_local = arena.shape[1] // self.txn_shards
        # The kernel zero-pads its scan axis to the chunk grid, so any
        # chunk count works — size it purely from the [F, tc] bit
        # intermediate budget.
        n_chunks = max(1, -(-nl_local * 32 // max(txn_chunk, 32)))
        xspec = self.exchange_spec if sparse_cap is not None else None
        key = (
            "vpair", tuple(scales), f_pad, cap, n_chunks, fast_f32,
            sparse_cap, xspec,
        )
        if key not in self._fns:
            mesh = self.mesh
            scl = tuple(scales)

            def _local(arena, w_planes, min_count, num_items, *rest):
                from fastapriori_tpu.ops.vertical import (
                    vertical_pair_local,
                )

                thr = rest[0] if sparse_cap is not None else None
                return vertical_pair_local(
                    arena, w_planes, scl, min_count, num_items, cap,
                    n_chunks,
                    axis_name=AXIS,
                    fast_f32=fast_f32,
                    sparse_thr=(
                        thr[lax.axis_index(AXIS)]
                        if sparse_cap is not None
                        else None
                    ),
                    sparse_cap=sparse_cap,
                    groups=xspec,
                )

            in_specs = (
                (P(None, AXIS), P(None, AXIS), P(), P())
                + ((P(None),) if sparse_cap is not None else ())
            )
            self._fns[key] = jax.jit(
                compat.shard_map(
                    _local,
                    mesh=mesh,
                    in_specs=in_specs,
                    out_specs=(P(None), P(None, None)),
                )
            )
        args = [
            arena, w_planes, jnp.int32(min_count), jnp.int32(num_items),
        ]
        if sparse_cap is not None:
            args += [jnp.asarray(sparse_thr, dtype=jnp.int32)]
        packed, counts_dev = self._fns[key](*args)
        n_cand = f_pad * f_pad
        if sparse_cap is not None:
            # lint: fetch-site -- vertical sparse-engine pair fetch (packed 2cap+3 ints incl. union census), retry-wrapped
            out = retry.fetch(lambda: np.asarray(packed), "vpair_sparse")
            nu = int(out[2 * cap + 2])
            if nu > sparse_cap:
                # Union compaction overflowed — the scattered counts
                # are a subset of the union; redo this dispatch dense
                # (ledger + memoized census, the pair_gather pattern).
                ledger.record(
                    "count_sparse_overflow", site="vpair",
                    n_union=nu, cap=sparse_cap,
                )
                res = self.vertical_pair_gather(
                    arena, w_planes, scales, min_count, num_items, cap,
                    txn_chunk, fast_f32=fast_f32,
                )
                g_b, p_b = count_ops.sparse_psum_bytes(
                    n_cand, sparse_cap, self.txn_shards, xspec
                )
                res[-1]["fallback"] = "sparse_overflow"
                res[-1]["n_union"] = nu
                res[-1]["psum_bytes"] += p_b
                res[-1]["gather_bytes"] += g_b
                return res
            gather_b, psum_b = count_ops.sparse_psum_bytes(
                n_cand, sparse_cap, self.txn_shards, xspec
            )
            info = self._reduce_info(
                n_cand, sparse_cap, xspec, psum_b, gather_b
            )
            info["n_union"] = nu
        else:
            # lint: fetch-site -- the vertical pair phase's ONE audited fetch (packed 2cap+2 ints), retry-wrapped
            out = retry.fetch(lambda: np.asarray(packed), "vpair")
            info = {
                "reduce": "dense",
                "psum_bytes": 4 * n_cand,
                "gather_bytes": 0,
            }
        return (
            out[:cap],
            out[cap : 2 * cap],
            int(out[2 * cap]),
            int(out[2 * cap + 1]),
            counts_dev,
            info,
        )

    def _vertical_pallas_plan(
        self, arena, prefix_stack, cand_stack, n_planes: int,
        lane_tile: int,
    ) -> Optional[tuple]:
        """``(cand_tile, lane_tile, interpret)`` for the vertical Pallas
        kernel (ops/pallas_vertical.py), or None for the XLA path.  The
        strict FA_NO_PALLAS parse runs on EVERY backend — a typo'd value
        must fail loudly even on runs where Pallas was never a candidate
        (the level_gather_batch contract).  The quorum floor
        (``vertical_kernel`` chain) keeps the tier choice mesh-wide
        consistent; ``_vertical_pallas_off`` is the sticky local disable
        the cascade walk sets.  Tests monkeypatch this method to return
        interpreter-mode plans on CPU."""
        no_pallas_env = pallas_disabled_by_env()
        if self.platform != "tpu":
            return None
        if no_pallas_env:
            # The run IS degraded (the XLA path round-trips the [P, NL]
            # prefix intermediate through HBM) — say so once.
            ledger.record(
                "pallas_disabled",
                once_key="env",
                reason="FA_NO_PALLAS",
                value=os.environ.get("FA_NO_PALLAS", ""),
            )
            return None
        if self._vertical_pallas_off:
            return None
        from fastapriori_tpu.reliability import quorum

        if not quorum.stage_allowed("vertical_kernel", "pallas"):
            return None
        from fastapriori_tpu.ops.pallas_vertical import (
            plan_vertical_tiles,
        )

        plan = plan_vertical_tiles(
            prefix_stack.shape[1], arena.shape[0] - 1, n_planes,
            cand_stack.shape[1], lane_tile,
        )
        return plan + (False,) if plan else None

    def vertical_pallas_active(self) -> bool:
        """True when the LAST vertical level dispatch ran the Pallas
        tier (the engine layer's cascade attribution signal)."""
        return self._vertical_pallas_last

    def disable_vertical_pallas(self) -> None:
        """Sticky local disable (vertical_kernel pallas→xla walk)."""
        self._vertical_pallas_off = True

    def _serve_pallas_plan(self, chunk: int) -> Optional[tuple]:
        """``(rule_tile, interpret)`` for the serving first-match kernel,
        or None for the XLA while_loop scan.  Same strict-parse /
        warn-once contract as :meth:`_vertical_pallas_plan`; the rule
        tile is the scan chunk (a pow2 multiple of 128 by construction,
        models/recommender.py _ensure_scan_table).  The serve_scan chain
        is host-local (reliability/quorum.py: serving never crosses the
        mesh), so no quorum consult here."""
        no_pallas_env = pallas_disabled_by_env()
        if self.platform != "tpu":
            return None
        if no_pallas_env:
            ledger.record(
                "pallas_disabled",
                once_key="env",
                reason="FA_NO_PALLAS",
                value=os.environ.get("FA_NO_PALLAS", ""),
            )
            return None
        if self._serve_pallas_off:
            return None
        return (chunk, False)

    def serve_pallas_active(self) -> bool:
        """True when the LAST strided-scan mount ran the Pallas tier."""
        return self._serve_pallas_last

    def disable_serve_pallas(self) -> None:
        """Sticky local disable (serve_scan pallas→xla walk)."""
        self._serve_pallas_off = True

    def vertical_level_gather_batch(
        self,
        arena,
        w_planes,
        scales,
        prefix_stack,
        min_count: int,
        cand_stack,
        cand_chunk: int,
        sparse_cap: Optional[int] = None,
        sparse_thr=None,
        lane_tile: int = 0,
    ) -> tuple:
        """Vertical twin of :meth:`level_gather_batch`: a whole level's
        prefix blocks in one launch over the tid-lane arena
        (ops/vertical.py vertical_level_batch), same host contract —
        ``(bits [NB, C//8(+4)] uint8, counts [NB, C] int32)`` with the
        per-block union censuses riding the bits payload under the
        sparse reduction.  No ``k1``/heavy/wide_member machinery: the
        AND identity handles prefix padding and popcounts are exact at
        any depth."""
        xspec = self.exchange_spec if sparse_cap is not None else None
        pallas_plan = self._vertical_pallas_plan(
            arena, prefix_stack, cand_stack, w_planes.shape[0], lane_tile
        )
        self._vertical_pallas_last = pallas_plan is not None
        key = (
            "vlevel_batch", tuple(scales), cand_chunk, sparse_cap, xspec,
            lane_tile, pallas_plan,
        )
        if key not in self._fns:
            mesh = self.mesh
            scl = tuple(scales)
            s_cap = sparse_cap
            l_tile = lane_tile
            p_plan = pallas_plan

            def _local(arena, w_planes, ps, mc, cs, *rest):
                from fastapriori_tpu.ops.vertical import (
                    vertical_level_batch,
                )

                thr = rest[0] if s_cap is not None else None
                out = vertical_level_batch(
                    arena, w_planes, scl, ps, cs, cand_chunk,
                    axis_name=AXIS,
                    sparse_thr=(
                        thr[lax.axis_index(AXIS)]
                        if s_cap is not None
                        else None
                    ),
                    sparse_cap=s_cap,
                    groups=xspec,
                    lane_tile=l_tile,
                    pallas=p_plan,
                )
                if s_cap is not None:
                    counts, nus = out
                    return (
                        count_ops.keep_bits_with_census(counts, mc, nus),
                        counts,
                    )
                return count_ops.keep_bits(out, mc), out

            in_specs = (
                (
                    P(None, AXIS),
                    P(None, AXIS),
                    P(None, None, None),
                    P(),
                    P(None, None),
                )
                + ((P(None),) if sparse_cap is not None else ())
            )
            self._fns[key] = jax.jit(
                compat.shard_map(
                    _local,
                    mesh=mesh,
                    in_specs=in_specs,
                    out_specs=(P(None, None), P(None, None)),
                )
            )
        args = [
            arena, w_planes, prefix_stack, jnp.int32(min_count),
            cand_stack,
        ]
        if sparse_cap is not None:
            args += [jnp.asarray(sparse_thr, dtype=jnp.int32)]
        return self._fns[key](*args)

    def ingest_pair_miner(self, block_rows, t_pad: int, cap: int,
                          census: bool, l3: Optional[Tuple[int, int, int]] = None):
        """ONE dispatch from the per-block packed uploads straight to
        (resident unpacked bitmap, packed pair-survivor output, resident
        [F, F] count matrix) — the pipelined ingest submits it the moment
        the last block lands, so bitmap assembly AND the whole pair phase
        (C5 + C6) execute in the shadow of host-side weight/CSR assembly
        (VERDICT r4 next #2: the reference's genTwoFreqItems is the first
        thing after bitmap broadcast, FastApriori.scala:104).  The Gram
        runs as one f32 matmul over the RAW int32 block weights — exact
        while every count < 2^24 (the caller gates on n_raw) — so it
        needs neither the weight-digit split nor the heavy-row
        correction, which the host is still assembling at that moment.

        Single-device-mesh only (the pipelined capture ingest's
        precondition).  ``block_rows`` keys the compile on the per-block
        shapes; ``census`` adds the level-3 triangle count
        (ops/count.py _pair_triangles) for the engine auto-choice.

        ``l3=(p3, cap3, n_chunks)`` appends the level-3 counts to the
        same packed output (ops/count.py l3_threshold_pack — the
        dispatch-fold of VERDICT r5 next #2): level 3 then costs the
        mining loop NO dispatch and rides the one pair fetch.  The
        section is valid only when n2 <= p3 and n3 <= cap3; the host
        checks both and falls back to the classic level-3 dispatch."""
        key = ("ingest_pair", tuple(block_rows), t_pad, cap, census, l3)
        if key not in self._fns:
            from fastapriori_tpu.ops.fused import _unpack

            def _fn(blocks, ws, min_count, num_items):
                pk = (
                    jnp.concatenate(blocks, axis=0)
                    if len(blocks) > 1
                    else blocks[0]
                )
                total = pk.shape[0]
                if t_pad > total:
                    pk = jnp.concatenate(
                        [
                            pk,
                            jnp.zeros(
                                (t_pad - total, pk.shape[1]), jnp.uint8
                            ),
                        ],
                        axis=0,
                    )
                bitmap = _unpack(pk)
                w = jnp.concatenate(ws) if len(ws) > 1 else ws[0]
                if t_pad > total:
                    w = jnp.concatenate(
                        [w, jnp.zeros(t_pad - total, jnp.int32)]
                    )
                b_f = bitmap.astype(jnp.float32)
                w_f = w.astype(jnp.float32)
                scaled = b_f * w_f[:, None]
                # lint: f32-gate -- caller gates on n_raw < 2^24 (docstring)
                counts = lax.dot_general(
                    scaled,
                    b_f,
                    (((0,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32,
                ).astype(jnp.int32)
                packed = count_ops.pair_threshold_pack(
                    counts, min_count, num_items, cap, census
                )
                if l3 is not None:
                    # The SAME mask definition the pair packing used to
                    # extract the survivor slots (ops/count.py
                    # frequent_pair_mask) — the l3 candidate prune is
                    # keyed to those slots and must never drift.
                    mask = count_ops.frequent_pair_mask(
                        counts, min_count, num_items
                    )
                    p3, cap3, n_chunks = l3
                    packed = jnp.concatenate(
                        [
                            packed,
                            count_ops.l3_threshold_pack(
                                bitmap, w_f, mask, packed[:cap],
                                packed[2 * cap], min_count, num_items,
                                p3, cap3, n_chunks,
                            ),
                        ]
                    )
                return bitmap, packed, counts

            self._fns[key] = jax.jit(_fn)
        return self._fns[key]

    def pair_regather(self, counts_dev, min_count: int, num_items: int,
                      cap: int):
        """Overflow retry of :meth:`pair_gather` over the resident count
        matrix (ops/count.py local_pair_regather): no Gram re-run, and a
        matmul-free one-off compile.  Returns host ``(flat_idx, counts,
        n2)``."""
        key = ("pair_regather", cap)
        if key not in self._fns:

            def _re(counts, min_count, num_items):
                idx, cnt, n2 = count_ops.local_pair_regather(
                    counts, min_count, num_items, cap
                )
                return jnp.concatenate([idx, cnt, n2[None]])

            self._fns[key] = jax.jit(_re)
        out = retry.fetch(
            # lint: fetch-site -- overflow-retry fetch of the re-packed survivors, retry-wrapped
            lambda: np.asarray(
                self._fns[key](
                    counts_dev, jnp.int32(min_count), jnp.int32(num_items)
                )
            ),
            "pair_regather",
        )
        return out[:cap], out[cap : 2 * cap], int(out[2 * cap])

    def level_gather_batch(
        self,
        bitmap,
        w_digits,
        scales,
        prefix_stack,
        k1: int,
        min_count: int,
        cand_stack,
        n_chunks: int,
        heavy_b=None,
        heavy_w=None,
        fast_f32: bool = False,
        sparse_cap: Optional[int] = None,
        sparse_thr=None,
    ) -> tuple:
        """A whole level's blocks in one launch (ops/count.py
        local_level_gather_batch) — launches carry ~100 ms of fixed
        round-trip cost on tunneled backends, so NB blocks pay it once.
        ``heavy_b``/``heavy_w``: replicated heavy-row remainder arrays
        (single-low-digit weight split); None = legacy multi-digit.
        Returns ``(bits [NB, C//8] uint8, counts [NB, C] int32)`` — the
        survivor bitmask is the only host-bound output (fetch C/8 bytes,
        not 4C); counts stay resident for :meth:`gather_level_counts`.

        ``sparse_cap`` + ``sparse_thr`` ([S] int32 per-shard prune
        thresholds) switch each block's candidate reduction to the
        threshold-sparse exchange (ops/count.py local_sparse_psum); the
        per-block union censuses then ride the bits payload as 4
        trailing uint8 bytes per block — ``bits [NB, C//8 + 4]`` — so
        the host's ONE async fetch also carries the overflow check
        (n_union > cap ⇒ that level must redo dense)."""
        has_heavy = heavy_b is not None
        # int8 membership accumulation is exact only for prefix widths
        # k1 <= 127 (ops/count.py local_level_gather); deeper levels
        # widen to int32 instead of silently miscounting (ADVICE r5 #1).
        wide_member = not fast_f32 and k1 >= 128
        if wide_member:
            ledger.record(
                "int8_widen", once_key="level", site="level", k1=int(k1)
            )
        # Strict FA_NO_PALLAS parse runs on EVERY backend: a typo'd
        # value must fail loudly even on runs where Pallas was never a
        # candidate.
        no_pallas_env = pallas_disabled_by_env()
        # Fused Pallas path (TPU only): the [tc, P] membership
        # intermediate stays in VMEM tile-by-tile instead of round-
        # tripping HBM — the measured bound of the level phase.  Tiles
        # must divide the PER-SHARD shapes; any misfit (or
        # FA_NO_PALLAS=1) falls back to the chunked-scan XLA path.
        pallas_tiles = None
        if (
            self.platform == "tpu"
            and not fast_f32
            and not wide_member  # no Pallas path for the int32 widen
            and tuple(scales) == (1,)  # kernel takes ONE unscaled w ⊙ B
        ):
            if no_pallas_env:
                # The run IS degraded (the XLA fallback round-trips the
                # membership intermediate through HBM) — say so once.
                ledger.record(
                    "pallas_disabled",
                    once_key="env",
                    reason="FA_NO_PALLAS",
                    value=os.environ.get("FA_NO_PALLAS", ""),
                )
            else:
                from fastapriori_tpu.ops.pallas_level import pick_tile

                # t generous (B tiles are cheap: [tt, F] int8), m
                # bounded so the in-VMEM [mt, tt] membership tile stays
                # <= 16 MB.
                tt = pick_tile(bitmap.shape[0] // self.txn_shards)
                mt = pick_tile(
                    prefix_stack.shape[1] // self.cand_shards,
                    candidates=(1024, 512, 256),
                )
                if tt and mt:
                    pallas_tiles = (tt, mt)
        xspec = self.exchange_spec if sparse_cap is not None else None
        key = (
            "level_gather_batch", tuple(scales), n_chunks, fast_f32,
            has_heavy, pallas_tiles, wide_member, sparse_cap, xspec,
        )
        if key not in self._fns:
            mesh = self.mesh
            scl = tuple(scales)
            p_tiles = pallas_tiles
            wide = wide_member
            s_cap = sparse_cap

            def _local(bitmap, w_digits, ps, k1, mc, cs, *rest):
                rest = list(rest)
                thr = rest.pop(0) if s_cap is not None else None
                hb, hw = rest if rest else (None, None)
                out = count_ops.local_level_gather_batch(
                    bitmap, w_digits, scl, ps, k1, cs, n_chunks,
                    heavy_b=hb, heavy_w=hw,
                    axis_name=AXIS, cand_axis_name=CAND,
                    fast_f32=fast_f32,
                    pallas_tiles=p_tiles,
                    wide_member=wide,
                    sparse_thr=(
                        thr[lax.axis_index(AXIS)]
                        if s_cap is not None
                        else None
                    ),
                    sparse_cap=s_cap,
                    groups=xspec,
                )
                if s_cap is not None:
                    counts, nus = out
                    # The per-block union censuses ride the ONE bits
                    # fetch (ops/count.py keep_bits_with_census — the
                    # shared payload definition).
                    return (
                        count_ops.keep_bits_with_census(counts, mc, nus),
                        counts,
                    )
                return count_ops.keep_bits(out, mc), out

            # Blocks unsharded (scanned on device); prefix rows and the
            # candidate gather sharded over cand; heavy remainder arrays
            # replicated.
            in_specs = (
                (
                    P(AXIS, None),
                    P(None, AXIS),
                    P(None, CAND, None),
                    P(),
                    P(),
                    P(None, CAND),
                )
                + ((P(None),) if sparse_cap is not None else ())
                + ((P(None, None), P(None)) if has_heavy else ())
            )
            self._fns[key] = jax.jit(
                compat.shard_map(
                    _local,
                    mesh=mesh,
                    in_specs=in_specs,
                    out_specs=(P(None, CAND), P(None, CAND)),
                )
            )
        args = [
            bitmap, w_digits, prefix_stack, jnp.int32(k1),
            jnp.int32(min_count), cand_stack,
        ]
        if sparse_cap is not None:
            args += [jnp.asarray(sparse_thr, dtype=jnp.int32)]
        if has_heavy:
            args += [heavy_b, heavy_w]
        return self._fns[key](*args)

    def gather_level_counts_start(
        self, pending, u24: bool = False, site: str = "counts"
    ) -> PendingCounts:
        """Launch the survivor-count gather dispatch and its NON-BLOCKING
        device→host copy (``pending`` as in :meth:`gather_level_counts`);
        returns a :class:`PendingCounts` whose ``result()`` yields the
        decoded int64 counts.  Positions pad to pow2 buckets on upload
        (`_pad_positions` — data-exact sizes compiled a fresh gather per
        mine; the wrapper strips the padding).  The caller drops its
        ``counts_dev`` references the moment this returns — the gather's
        compact output is the only thing still resident, which is what
        lets the level loop's byte-budgeted drain free each level's
        [NB, C] tensor mid-mine instead of retaining it to end-of-mine
        (ADVICE r5 #2)."""
        padded = [_pad_positions(p) for _, p in pending]
        args = (
            tuple(c for c, _ in pending),
            tuple(jnp.asarray(p) for p in padded),
        )
        fn = _gather_counts_u24_jit if u24 else _gather_counts_jit
        return PendingCounts(
            retry.fetch_async(fn(*args), site),
            [int(p.size) for _, p in pending],
            [p.size for p in padded],
            u24,
        )

    @staticmethod
    def finish_level_counts(handle: PendingCounts):
        """Consume a :meth:`gather_level_counts_start` handle into host
        int64 counts (blocks; retry-wrapped inside the handle, which
        also owns the u24 decode and the padding strip)."""
        return handle.result()

    def gather_level_counts(self, pending, u24: bool = False):
        """End-of-mine survivor-count resolution in ONE dispatch + ONE
        fetch: ``pending`` is ``[(counts_dev [NB, C] int32, flat
        positions)]`` per deferred level — each level's survivor
        positions gathered from its resident count array, concatenated,
        and fetched once (the per-level count fetches used to cross the
        slow tunnel down-link padded; this crosses exact bytes once).
        Positions are cast to int32 on upload ([NB, C] count arrays
        anywhere near 2^31 elements would exhaust HBM long before the
        cast could overflow).  ``u24``: counts provably < 2^24 (the
        caller's n_raw gate) cross the link as 3 bytes each.  Returns
        concatenated int64 counts (host)."""
        return self.finish_level_counts(
            self.gather_level_counts_start(pending, u24=u24)
        )

    def pair_counts(self, bitmap, w_digits, scales) -> jax.Array:
        pair, _, _ = self._get_fns(tuple(scales))
        return pair(bitmap, w_digits)

    def level_counts(self, bitmap, w_digits, scales, prefix_cols) -> jax.Array:
        _, level, _ = self._get_fns(tuple(scales))
        return level(bitmap, w_digits, prefix_cols)

    def item_supports(self, bitmap, w_digits, scales) -> jax.Array:
        _, _, item = self._get_fns(tuple(scales))
        return item(bitmap, w_digits)

    def first_match_scan(
        self, baskets, basket_len, ant_cols, ant_size, consequent,
        chunk: int,
    ):
        """The whole resident-rule-table priority scan as one dispatch
        (ops/contain.py local_first_match_scan); returns
        ``(best, chunks_run)``."""
        key = ("first_match_scan", chunk)
        if key not in self._fns:
            from fastapriori_tpu.ops.contain import (
                make_sharded_first_match_scan,
            )

            self._fns[key] = make_sharded_first_match_scan(self.mesh, chunk)
        return self._fns[key](
            baskets, basket_len, ant_cols, ant_size, consequent
        )

    # -- device-resident rule generation (rules/gen.py device engine) ------
    @staticmethod
    def _fire_rule_upload():
        """The ONE ``rules.upload`` failpoint site shared by the three
        rule-table placements (device-0 / row-sharded / replicated):
        a single label keeps arming one-shot across engines and the
        ledger unambiguous about which phase the injection hit."""
        failpoints.fire("rules.upload")

    def device0_put(self, x: np.ndarray) -> jax.Array:
        """Single-device placement for the rule-generation tables: the
        join/prune kernels are gather/sort work with no matmul to shard,
        and the rule phase runs after mining on one chip — device 0 of
        the mesh keeps them off the other shards' HBM."""
        self._fire_rule_upload()
        # lint: host-data -- numpy table upload, no device fetch
        return jax.device_put(x, self.mesh.devices.flat[0])

    def rule_level_join(self, k: int, bits: int, first: bool):
        """Jitted per-level rule join + dominance prune (ops/contain.py
        rule_level_kernel), cached per static (k, key width, base-level)
        profile; jax's shape cache covers the pow2 row buckets."""
        key = ("rule_join", k, bits, first)
        if key not in self._fns:
            from fastapriori_tpu.ops.contain import rule_level_kernel

            self._fns[key] = jax.jit(
                functools.partial(
                    rule_level_kernel, k=k, bits=bits, first=first
                )
            )
        return self._fns[key]

    # -- sharded rule generation + device-resident priority scan -----------
    def shard_rule_rows(self, x: np.ndarray) -> jax.Array:
        """Row-sharded placement of a rule-phase table (the query rows of
        the sharded join — parent keys replicate from these via the
        in-kernel all_gather; same ``rules.upload`` failpoint site as the
        single-chip upload)."""
        self._fire_rule_upload()
        assert x.shape[0] % self.txn_shards == 0, (x.shape, self.txn_shards)
        spec = P(AXIS, *([None] * (x.ndim - 1)))
        # lint: host-data -- numpy table upload, no device fetch
        return jax.device_put(x, NamedSharding(self.mesh, spec))

    def replicate_rule_table(self, x: np.ndarray) -> jax.Array:
        """Replicated placement for the small rule-phase side tables
        (1-itemset counts, consequent priorities) — same failpoint site
        as the sharded upload."""
        self._fire_rule_upload()
        return self.replicate(x)

    def rule_level_join_sharded(self, k: int, bits: int, first: bool):
        """Jitted shard_map-wrapped sharded rule join (ops/contain.py
        rule_level_shard_kernel): query rows sharded over the txn axis,
        parent state replicated, outputs replicated after the in-kernel
        mask/denominator/table exchanges.  Mesh-polymorphic: a 1-shard
        mesh reproduces the single-chip kernel bit for bit."""
        xspec = self.exchange_spec
        key = ("rule_join_shard", k, bits, first, xspec)
        if key not in self._fns:
            from fastapriori_tpu.ops.contain import rule_level_shard_kernel

            mesh = self.mesh
            per = 32 // bits
            n_pcols = 1 if first else max(1, -(-(k - 1) // per))
            n_scols = max(1, -(-k // per))
            fn = functools.partial(
                rule_level_shard_kernel,
                k=k,
                bits=bits,
                first=first,
                axis_name=AXIS,
                n_shards=self.txn_shards,
                groups=xspec,
            )
            in_specs = (
                P(AXIS, None),  # mat (query rows sharded)
                P(AXIS),  # cnts
                P(),  # n_real
                tuple(P(None) for _ in range(n_pcols)),  # psorted
                P(None),  # porder
                P(None),  # pcnts
                P(),  # np_real
                P(None),  # prev_surv
                P(None),  # prev_d
            )
            out_specs = (
                P(None),  # packed mask + miss
                tuple(P(None) for _ in range(n_scols)),  # skeys
                P(None),  # order
                P(None),  # d_flat
                P(None),  # surv_flat
                P(None, None),  # mat_full
                P(None),  # cnts_full
            )
            self._fns[key] = jax.jit(
                compat.shard_map(
                    fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs
                )
            )
        return self._fns[key]

    def rule_scan_build(
        self, ks, n_pads, r_pad: int, k_max: int, zcol: int
    ):
        """Jitted device-side scan-table build (ops/contain.py
        rule_scan_build): consumes the join kernels' resident per-level
        state, emits the priority-sorted compact table SHARDED over the
        txn axis (rank-strided rows) via ``out_shardings`` — the one
        resharding dispatch between rule generation and serving.  Cached
        per static (level shapes, table bucket, mesh) profile; survivor
        offsets arrive traced so repeat mines with equal buckets reuse
        the compile."""
        key = ("rule_scan_build", tuple(ks), tuple(n_pads), r_pad, k_max,
               zcol)
        if key not in self._fns:
            from fastapriori_tpu.ops.contain import rule_scan_build

            n_shards = self.txn_shards
            rows = NamedSharding(self.mesh, P(AXIS, None))
            vec = NamedSharding(self.mesh, P(AXIS))

            def _build(level_arrays, offsets, pr):
                return rule_scan_build(
                    level_arrays,
                    offsets,
                    pr,
                    ks=tuple(ks),
                    r_pad=r_pad,
                    k_max=k_max,
                    zcol=zcol,
                    n_shards=n_shards,
                )

            self._fns[key] = jax.jit(
                _build, out_shardings=(rows, vec, vec)
            )
        return self._fns[key]

    def strided_first_match_scan(self, chunk: int):
        """The sharded-resident-table priority scan (ops/contain.py
        local_strided_match_scan); returns ``(best_rank, consequent,
        chunks_run)`` per micro-batch.  On TPU the local body mounts the
        fused Pallas first-match kernel (serve_scan chain stage
        "pallas", :meth:`_serve_pallas_plan`); the plan is part of the
        compile key so the pallas→xla walk re-mounts the while_loop
        body on the next warm."""
        plan = self._serve_pallas_plan(chunk)
        self._serve_pallas_last = plan is not None
        key = ("strided_match_scan", chunk, plan)
        if key not in self._fns:
            from fastapriori_tpu.ops.contain import (
                make_strided_first_match_scan,
            )

            self._fns[key] = make_strided_first_match_scan(
                self.mesh, chunk, self.txn_shards, pallas=plan
            )
        return self._fns[key]

    def tail_miner_with_resolve(
        self,
        scales: Tuple[int, ...],
        k0: int,
        m_cap: int,
        p_cap: int,
        l_max: int,
        n_chunks: int,
        has_heavy: bool,
        gather_shapes: Tuple,
        u24: bool,
        sparse_cap: Optional[int] = None,
    ):
        """The shallow-tail fold's program EXTENDED with the end-of-mine
        ``counts_resolve`` gather (ROADMAP pipeline follow-up): the tail
        dispatch that finishes the mine also compacts every pending
        level's survivor counts — the resolve costs ZERO extra dispatches
        (bench keeps reporting ``resolve_dispatches`` separately; it
        reads 0 when the fold carried it).  Inlines the cached tail
        program and the shared gather jit into ONE XLA program.

        Compile-shape tradeoff: the fused program's cache key includes
        the gather structure (``gather_shapes``), so a tail profile can
        recompile when the pending-count layout changes.  Every
        dimension of that structure is already bucketed — count tensors
        are [NB-bucket, C-pow2], positions pow2-padded, and the segment
        count is bounded by the lattice depth — so the distinct fused
        shapes per dataset stay a handful; the persistent compile cache
        (and its jax_log_compiles signatures) covers the rest."""
        key = (
            "tail_resolve", tuple(scales), k0, m_cap, p_cap, l_max,
            n_chunks, has_heavy, gather_shapes, u24, sparse_cap,
            self.exchange_spec if sparse_cap is not None else None,
        )
        if key not in self._fns:
            tail_fn = self.tail_miner(
                tuple(scales), k0, m_cap, p_cap, l_max, n_chunks,
                has_heavy, sparse_cap=sparse_cap,
            )
            gfn = _gather_counts_u24_jit if u24 else _gather_counts_jit

            def _fn(targs, counts_list, pos_list):
                return tail_fn(*targs), gfn(counts_list, pos_list)

            self._fns[key] = jax.jit(_fn)
        return self._fns[key]

