from fastapriori_tpu.parallel.mesh import DeviceContext  # noqa: F401
