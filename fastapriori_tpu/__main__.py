import sys

from fastapriori_tpu.cli import main

sys.exit(main())
