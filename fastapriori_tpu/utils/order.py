"""Deterministic orderings standing in for the reference's
Spark-nondeterministic collect orders."""

from __future__ import annotations

from typing import Tuple


def item_sort_key(item_count: Tuple[str, int]):
    """Sort key for frequent-item rank assignment: descending count
    (FastApriori.scala:60 ``sortBy(-_._2)``), ties broken by numeric value
    of the item token ascending (items are integer strings in this domain),
    falling back to the raw token.

    The reference's tie order is whatever Spark's ``collect()`` returned
    that run; a deterministic tie-break changes only which of two equal-count
    items gets the lower rank, which can permute item order *within* an
    output line for equal-count items — the itemset *sets* are identical.
    """
    item, count = item_count
    try:
        return (-count, 0, int(item), item)
    except ValueError:
        return (-count, 1, 0, item)
