"""Structured observability.

The reference's entire observability surface is ``====``-prefixed wall-clock
prints around phases and Apriori levels (Main.scala:28-37,
FastApriori.scala:103-119, AssociationRules.scala:73-181 — SURVEY.md §5).
Here the same events are emitted as structured JSON lines, plus the
reference-style human line for familiarity.
"""

from __future__ import annotations

import contextlib
import json
import sys
import time
from typing import Any, Dict


class MetricsLogger:
    """Per-level / per-phase metrics as JSON lines.

    Each record carries an ``event`` name plus arbitrary fields; records go
    to ``stream`` (default stderr) so stdout stays clean for data output.
    """

    def __init__(self, enabled: bool = True, stream=None):
        self.enabled = enabled
        self.stream = stream if stream is not None else sys.stderr
        self.records: list[Dict[str, Any]] = []

    def emit(self, event: str, **fields: Any) -> None:
        rec = {"event": event, **fields}
        self.records.append(rec)
        if self.enabled:
            print(json.dumps(rec), file=self.stream, flush=True)

    def bind_global_ledger(self) -> "MetricsLogger":
        """Route degradation-ledger events (reliability/ledger.py) through
        this logger as ``event="degraded"`` records, so a degraded run is
        visibly degraded in the metrics stream and in every bench record
        built from it — not just mysteriously slower.  Latest binding
        wins (the ledger is a process singleton; the mining sites it
        instruments have no logger in scope)."""
        from fastapriori_tpu.reliability import ledger

        ledger.attach_metrics(self)
        return self

    @contextlib.contextmanager
    def timed(self, event: str, **fields: Any):
        t0 = time.perf_counter()
        holder: Dict[str, Any] = {}
        try:
            yield holder
        finally:
            holder.setdefault("wall_ms", round((time.perf_counter() - t0) * 1e3, 3))
            self.emit(event, **fields, **holder)


@contextlib.contextmanager
def phase_timer(label: str, enabled: bool = True):
    """Reference-style ``==== Use Time <label> <ms>`` print
    (e.g. FastApriori.scala:108)."""
    t0 = time.perf_counter()
    yield
    if enabled:
        ms = int((time.perf_counter() - t0) * 1e3)
        print(f"==== Use Time {label} {ms}", file=sys.stderr)
