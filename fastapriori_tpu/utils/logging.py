"""Structured observability.

The reference's entire observability surface is ``====``-prefixed wall-clock
prints around phases and Apriori levels (Main.scala:28-37,
FastApriori.scala:103-119, AssociationRules.scala:73-181 — SURVEY.md §5).
Here the same events are emitted as structured JSON lines, plus the
reference-style human line for familiarity — and (ISSUE 11) mirrored
into the span tracer (``fastapriori_tpu/obs/trace.py``): every
``timed`` section opens a span, every ``emit`` lands as an instant
event, and the per-level collective-byte fields ride as Chrome counter
events, so the JSON metrics stream and the Perfetto trace are two
views of ONE event source.
"""

from __future__ import annotations

import contextlib
import json
import sys
import time
from typing import Any, Dict, Optional

from fastapriori_tpu.obs import trace

# MetricsLogger.records retention cap (ISSUE 11 satellite): the list
# fed bench's full-record path unboundedly — a long `serve` run grew it
# forever.  The cap is deliberately far above any bench run's event
# count (webdocs mines emit hundreds of records, not tens of
# thousands), so the full-record path keeps working; past it, records
# drop COUNTED (`records_dropped`), never silently.
RECORDS_CAP = 100_000

# The process's active logger (latest enabled instance wins — the same
# latest-binding rule the degradation ledger uses): `phase_timer` and
# other module-level emit sites route through it so phase walls land in
# the metrics stream and the trace, not just on stderr.
_active: Optional["MetricsLogger"] = None


def active_logger() -> Optional["MetricsLogger"]:
    return _active


class MetricsLogger:
    """Per-level / per-phase metrics as JSON lines.

    Each record carries an ``event`` name plus arbitrary fields; records go
    to ``stream`` (default stderr) so stdout stays clean for data output.
    Retention is bounded (:data:`RECORDS_CAP` + ``records_dropped``).
    """

    def __init__(
        self,
        enabled: bool = True,
        stream=None,
        records_cap: int = RECORDS_CAP,
    ):
        global _active
        self.enabled = enabled
        self.stream = stream if stream is not None else sys.stderr
        self.records: list[Dict[str, Any]] = []
        self.records_cap = records_cap
        self.records_dropped = 0
        if enabled:
            _active = self

    def _record(self, rec: Dict[str, Any]) -> None:
        """The ONE retention + output path (bounded append, counted
        drops, JSON line when enabled) — emit and timed share it so the
        retention contract cannot diverge."""
        if len(self.records) < self.records_cap:
            self.records.append(rec)
        else:
            self.records_dropped += 1
        if self.enabled:
            print(json.dumps(rec), file=self.stream, flush=True)

    def emit(self, event: str, **fields: Any) -> None:
        trace.instant(event, **fields)
        self._record({"event": event, **fields})

    def bind_global_ledger(self) -> "MetricsLogger":
        """Route degradation-ledger events (reliability/ledger.py) through
        this logger as ``event="degraded"`` records, so a degraded run is
        visibly degraded in the metrics stream and in every bench record
        built from it — not just mysteriously slower.  Latest binding
        wins (the ledger is a process singleton; the mining sites it
        instruments have no logger in scope)."""
        from fastapriori_tpu.reliability import ledger

        ledger.attach_metrics(self)
        return self

    @contextlib.contextmanager
    def timed(self, event: str, **fields: Any):
        t0 = time.perf_counter()
        holder = _TimedHolder()
        # One span per timed section: nesting comes from the tracer's
        # thread-local stack (run -> phase -> level -> dispatch), ids
        # stay deterministic (per-parent occurrence counting).  The
        # record lands in a finally — a section that RAISES (a fetch
        # exhausting retries, an injected abort) still leaves its
        # partial fields in the metrics stream, same as pre-tracer.
        with trace.span(event, **fields) as sp:
            try:
                yield holder
            finally:
                holder.setdefault(
                    "wall_ms", round((time.perf_counter() - t0) * 1e3, 3)
                )
                sp.update(**holder)
                if "psum_bytes" in holder or "gather_bytes" in holder:
                    # Collective payloads as Chrome counter tracks — the
                    # byte timeline the sparse-exchange analysis (arxiv
                    # 1312.3020) sums per level today.
                    trace.counter(
                        "collective_bytes",
                        psum=holder.get("psum_bytes", 0),
                        gather=holder.get("gather_bytes", 0),
                    )
                if "intra_bytes" in holder or "inter_bytes" in holder:
                    # The hierarchical exchange's per-stage split
                    # (ISSUE 15): a second track so a Perfetto view
                    # shows fast-tier vs slow-tier traffic per level —
                    # the trace artifact the direction-3 perf claims
                    # cite (the PR 11 observability contract).
                    trace.counter(
                        "exchange_stage_bytes",
                        intra=holder.get("intra_bytes", 0),
                        inter=holder.get("inter_bytes", 0),
                    )
                self._record({"event": event, **fields, **holder})


class _TimedHolder(dict):
    """The mutable mapping ``timed`` yields; ``update``/``setdefault``
    are dict's own."""


@contextlib.contextmanager
def phase_timer(label: str, enabled: bool = True, metrics=None):
    """Reference-style ``==== Use Time <label> <ms>`` phase wall
    (e.g. FastApriori.scala:108) — routed through the span tracer and
    the active :class:`MetricsLogger` (ISSUE 11 satellite), so the
    reference-style walls appear in traces and metrics streams, not
    just as a bare stderr print.  ``metrics`` overrides the active
    logger; the human line still prints when ``enabled``."""
    t0 = time.perf_counter()
    with trace.span("phase", label=label):
        yield
    ms = int((time.perf_counter() - t0) * 1e3)
    logger = metrics if metrics is not None else _active
    if logger is not None:
        logger.emit("phase", label=label, wall_ms=ms)
    if enabled:
        print(f"==== Use Time {label} {ms}", file=sys.stderr)
