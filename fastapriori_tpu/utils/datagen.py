"""Synthetic transaction datasets in the style of the IBM Quest generator
(T10I4D100K et al., the benchmark family in BASELINE.md).

Transactions are drawn from a pool of correlated "patterns" (frequent
itemsets planted in the data) plus noise, giving realistic support
distributions: a tail of infrequent items and a core of correlated frequent
ones.  Deterministic for a given seed.  Fully vectorized with numpy so the
BASELINE.md-scale configs (1.7M transactions x 177 items for the Webdocs
stand-in) generate in seconds, not tens of minutes.
"""

from __future__ import annotations

from typing import Iterator, List

import numpy as np


def _make_patterns(rng, n_items, n_patterns, avg_pattern_len):
    """Pattern pool as a padded int matrix + normalized pick weights."""
    sizes = np.maximum(
        1, rng.exponential(avg_pattern_len, n_patterns).astype(np.int64)
    )
    sizes = np.minimum(sizes, min(3 * avg_pattern_len, n_items))
    pat = np.zeros((n_patterns, int(sizes.max())), dtype=np.int64)
    for i, s in enumerate(sizes):
        pat[i, :s] = rng.choice(n_items, size=int(s), replace=False) + 1
    weights = rng.exponential(1.0, n_patterns)
    weights /= weights.sum()
    # Expected frequent items contributed per weighted pattern draw.
    yield_per_draw = float((sizes * weights).sum())
    return pat, weights, yield_per_draw


def _txn_block(rng, pat, weights, yield_per_draw, targets, n_items,
               corruption):
    """Generate one block of transactions as sorted unique item rows.

    Returns (items, row_counts): a flat int array of 1-based item ids and
    the number of items per transaction, rows concatenated in order.
    """
    n = targets.shape[0]
    keep_rate = max(1e-3, 1.0 - corruption)
    npat = np.ceil(
        targets / max(yield_per_draw * keep_rate, 1e-3)
    ).astype(np.int64) + 1
    draws = rng.choice(pat.shape[0], size=int(npat.sum()), p=weights)
    row_of_draw = np.repeat(np.arange(n), npat)
    items = pat[draws]  # (total_draws, max_pat_len), 0 = padding
    keep = (items > 0) & (rng.random(items.shape) >= corruption)
    rows = np.repeat(row_of_draw, items.shape[1])[keep.ravel()]
    flat = items.ravel()[keep.ravel()]

    # Uniform noise injection so the infrequent tail exists.
    n_noise = max(1, int(0.1 * n))
    noise_rows = rng.integers(0, n, size=n_noise)
    noise_items = rng.integers(1, n_items + 1, size=n_noise)
    rows = np.concatenate([rows, noise_rows])
    flat = np.concatenate([flat, noise_items])

    # Dedupe within each transaction, then truncate each to its target
    # length, dropping uniformly at random (random key sort).
    key = rows * np.int64(n_items + 1) + flat
    uniq_key, first = np.unique(key, return_index=True)
    rows, flat = rows[first], flat[first]
    order = np.lexsort((rng.random(rows.shape[0]), rows))
    rows, flat = rows[order], flat[order]
    counts = np.bincount(rows, minlength=n)
    starts = np.concatenate([[0], np.cumsum(counts)[:-1]])
    rank = np.arange(rows.shape[0]) - starts[rows]
    sel = rank < targets[rows]
    rows, flat = rows[sel], flat[sel]
    # Guarantee non-empty rows (corruption can empty a txn): give any
    # empty transaction one uniform item.
    counts = np.bincount(rows, minlength=n)
    empty = np.flatnonzero(counts == 0)
    if empty.size:
        rows = np.concatenate([rows, empty])
        flat = np.concatenate(
            [flat, rng.integers(1, n_items + 1, size=empty.size)]
        )
    order = np.lexsort((flat, rows))
    return flat[order], np.bincount(rows, minlength=n)


_TOK_CACHE: dict = {}


def _token_table(n_items: int):
    """item id -> str, computed once per distinct vocabulary size."""
    tab = _TOK_CACHE.get(n_items)
    if tab is None:
        tab = np.array([str(i) for i in range(n_items + 1)], dtype=object)
        _TOK_CACHE[n_items] = tab
    return tab


def _format_rows(flat, counts, n_items) -> List[str]:
    """Vectorized int->str (cached table lookup) then per-row join."""
    toks = _token_table(n_items)[flat]
    out = []
    pos = 0
    for c in counts:
        out.append(" ".join(toks[pos:pos + int(c)]))
        pos += int(c)
    return out


def iter_transaction_blocks(
    n_txns: int = 100_000,
    n_items: int = 1000,
    avg_txn_len: int = 10,
    n_patterns: int = 100,
    avg_pattern_len: int = 4,
    corruption: float = 0.25,
    seed: int = 2017,
    block: int = 100_000,
) -> Iterator[List[str]]:
    """Stream transaction lines in blocks (bounded memory at Webdocs
    scale: 1.7M x 177 tokens never materializes as one Python list)."""
    rng = np.random.default_rng(seed)
    pat, weights, ypd = _make_patterns(
        rng, n_items, n_patterns, avg_pattern_len
    )
    done = 0
    while done < n_txns:
        n = min(block, n_txns - done)
        targets = np.clip(
            rng.exponential(avg_txn_len, n).astype(np.int64),
            1,
            min(3 * avg_txn_len, n_items),
        )
        flat, counts = _txn_block(
            rng, pat, weights, ypd, targets, n_items, corruption
        )
        yield _format_rows(flat, counts, n_items)
        done += n


def generate_transactions(
    n_txns: int = 100_000,
    n_items: int = 1000,
    avg_txn_len: int = 10,
    n_patterns: int = 100,
    avg_pattern_len: int = 4,
    corruption: float = 0.25,
    seed: int = 2017,
) -> List[str]:
    """Return raw transaction lines (space-separated 1-based item ids)."""
    lines: List[str] = []
    for blk in iter_transaction_blocks(
        n_txns, n_items, avg_txn_len, n_patterns, avg_pattern_len,
        corruption, seed,
    ):
        lines.extend(blk)
    return lines


def _doc_block(rng, p_cum, pat, pat_w_cum, targets, pattern_frac, n_items):
    """One block of doc-style transactions: independent zipf draws plus a
    fraction of tokens contributed by planted head-item patterns."""
    n = targets.shape[0]
    n_zipf = np.maximum(1, (targets * (1.0 - pattern_frac)).astype(np.int64))
    rows_z = np.repeat(np.arange(n), n_zipf)
    # Clip: float error can leave p_cum[-1] a hair below 1.0, and a draw
    # above it would index past the vocabulary.
    flat_z = np.minimum(
        np.searchsorted(p_cum, rng.random(rows_z.shape[0]), side="right"),
        n_items - 1,
    ) + 1
    # Pattern overlay: each txn picks a couple of patterns whose items are
    # all drawn from the popularity head, planting real correlations.
    npat = np.maximum(
        1, (targets * pattern_frac / max(pat.shape[1], 1)).astype(np.int64)
    )
    row_of_draw = np.repeat(np.arange(n), npat)
    draws = np.searchsorted(
        pat_w_cum, rng.random(row_of_draw.shape[0]), side="right"
    )
    items = pat[draws]
    rows_p = np.repeat(row_of_draw, items.shape[1])
    flat_p = items.ravel()
    keep = flat_p > 0
    rows = np.concatenate([rows_z, rows_p[keep]])
    flat = np.concatenate([flat_z, flat_p[keep]])
    # Dedupe within txn.  The combined key encodes (row, item) lexicographic
    # order, so ONE in-place sort both groups rows and orders items within
    # each row — replacing unique()'s internal sort plus a lexsort.
    key = rows * np.int64(n_items + 1) + flat
    key.sort(kind="stable")
    first = np.empty(key.shape[0], dtype=bool)
    first[0] = True
    np.not_equal(key[1:], key[:-1], out=first[1:])
    key = key[first]
    rows, flat = np.divmod(key, np.int64(n_items + 1))
    return flat, np.bincount(rows, minlength=n)


def iter_doc_transaction_blocks(
    n_txns: int = 1_700_000,
    n_items: int = 50_000,
    avg_txn_len: int = 177,
    zipf_s: float = 1.05,
    zipf_shift: float = 12.0,
    n_patterns: int = 60,
    avg_pattern_len: int = 4,
    pattern_frac: float = 0.15,
    head_items: int = 400,
    seed: int = 2017,
    block: int = 100_000,
) -> Iterator[List[str]]:
    """Doc-corpus-style transactions (the Webdocs stand-in, BASELINE.md
    config 4): item marginals follow a shifted zipf law — so the number of
    items above any support threshold is controlled and decays smoothly —
    with planted patterns over the popularity head providing genuine
    multi-item correlations.  The quest-style generator
    (:func:`iter_transaction_blocks`) puts ALL co-occurrence mass on a few
    heavy patterns, which at document length (~177 items/txn) makes every
    pair of popular items co-occur and Apriori's output exponential; real
    doc corpora decay.
    """
    rng = np.random.default_rng(seed)
    ranks = np.arange(n_items, dtype=np.float64)
    p = 1.0 / np.power(ranks + zipf_shift, zipf_s)
    p /= p.sum()
    p_cum = np.cumsum(p)
    sizes = np.clip(
        np.maximum(1, rng.exponential(avg_pattern_len, n_patterns)),
        2, 8,
    ).astype(np.int64)
    pat = np.zeros((n_patterns, int(sizes.max())), dtype=np.int64)
    for i, s in enumerate(sizes):
        pat[i, :s] = rng.choice(
            min(head_items, n_items), size=int(s), replace=False
        ) + 1
    pat_w = rng.exponential(1.0, n_patterns)
    pat_w_cum = np.cumsum(pat_w / pat_w.sum())

    done = 0
    while done < n_txns:
        n = min(block, n_txns - done)
        targets = np.clip(
            rng.exponential(avg_txn_len, n).astype(np.int64),
            1,
            min(3 * avg_txn_len, n_items),
        )
        flat, counts = _doc_block(
            rng, p_cum, pat, pat_w_cum, targets, pattern_frac, n_items
        )
        yield _format_rows(flat, counts, n_items)
        done += n


def generate_doc_transactions(**kw) -> List[str]:
    """Materialized form of :func:`iter_doc_transaction_blocks`."""
    lines: List[str] = []
    for blk in iter_doc_transaction_blocks(**kw):
        lines.extend(blk)
    return lines


def generate_user_baskets(
    n_users: int = 10_000,
    n_items: int = 1000,
    avg_len: int = 5,
    seed: int = 2018,
) -> List[str]:
    """User baskets for the recommendation phase (U.dat analog)."""
    rng = np.random.default_rng(seed)
    sizes = np.clip(
        rng.exponential(avg_len, n_users).astype(np.int64),
        1,
        min(3 * avg_len, n_items),
    )
    rows = np.repeat(np.arange(n_users), sizes)
    flat = rng.integers(1, n_items + 1, size=int(sizes.sum()))
    key = rows * np.int64(n_items + 1) + flat
    _, first = np.unique(key, return_index=True)
    rows, flat = rows[np.sort(first)], flat[np.sort(first)]
    counts = np.bincount(rows, minlength=n_users)
    # Unique-ing can only shrink rows, never empty them (sizes >= 1).
    return _format_rows(flat, counts, n_items)
