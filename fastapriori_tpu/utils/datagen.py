"""Synthetic transaction datasets in the style of the IBM Quest generator
(T10I4D100K et al., the benchmark family in BASELINE.md).

Transactions are drawn from a pool of correlated "patterns" (frequent
itemsets planted in the data) plus noise, giving realistic support
distributions: a tail of infrequent items and a core of correlated frequent
ones.  Deterministic for a given seed.
"""

from __future__ import annotations

import random
from typing import List


def generate_transactions(
    n_txns: int = 100_000,
    n_items: int = 1000,
    avg_txn_len: int = 10,
    n_patterns: int = 100,
    avg_pattern_len: int = 4,
    corruption: float = 0.25,
    seed: int = 2017,
) -> List[str]:
    """Return raw transaction lines (space-separated 1-based item ids)."""
    rng = random.Random(seed)
    # Pattern pool: random subsets, exponentially decaying pick weights.
    patterns = []
    for _ in range(n_patterns):
        size = max(1, int(rng.expovariate(1.0 / avg_pattern_len)))
        size = min(size, 3 * avg_pattern_len)
        patterns.append(rng.sample(range(1, n_items + 1), min(size, n_items)))
    weights = [rng.expovariate(1.0) for _ in range(n_patterns)]

    lines = []
    for _ in range(n_txns):
        target = max(1, int(rng.expovariate(1.0 / avg_txn_len)))
        target = min(target, 3 * avg_txn_len)
        txn: set = set()
        while len(txn) < target:
            p = rng.choices(patterns, weights=weights, k=1)[0]
            for item in p:
                if len(txn) >= target:
                    break
                # corruption: drop items from the pattern at random
                if rng.random() > corruption:
                    txn.add(item)
            else:
                # occasionally inject uniform noise so the tail exists
                if rng.random() < 0.1:
                    txn.add(rng.randint(1, n_items))
        lines.append(" ".join(str(i) for i in sorted(txn)))
    return lines


def generate_user_baskets(
    n_users: int = 10_000,
    n_items: int = 1000,
    avg_len: int = 5,
    seed: int = 2018,
) -> List[str]:
    """User baskets for the recommendation phase (U.dat analog)."""
    rng = random.Random(seed)
    lines = []
    for _ in range(n_users):
        size = max(1, min(int(rng.expovariate(1.0 / avg_len)), 3 * avg_len))
        basket = rng.sample(range(1, n_items + 1), min(size, n_items))
        lines.append(" ".join(str(i) for i in basket))
    return lines
