"""Persistent XLA compilation cache for the entry points.

Every fresh process pays 40-90 s of XLA compiles at webdocs scale (the
whole-loop fused program, the per-shape level kernels, the tail fold).
JAX's persistent cache makes those one-time per MACHINE instead of per
process — measured 43.5 s -> 3.8 s cold start on the v5e tunnel for a
mid-size mine.  The reference has the same concern solved the same way
at a different layer: its Spark executors are long-lived JVMs that keep
their JITted code across jobs (README.md:22-35 cluster setup).

Opt-out with FA_NO_COMPILE_CACHE=1; relocate with FA_COMPILE_CACHE.
Compile-shape logging (one stderr line per traced compile — the
cache-miss shape signatures) is on by default here; FA_NO_COMPILE_LOG=1
silences it.  Library imports never touch this — only the CLI/bench
entry points call it, so embedding applications keep full control of
JAX global config.
"""

from __future__ import annotations

import os


def enable_compile_cache() -> bool:
    """Best-effort (a cache failure must never fail the run); returns
    True when the cache directory already held entries — callers that
    report cold-start times disclose it, since a primed cache makes
    "cold" a machine-state-dependent figure.

    The opt-out knobs are STRICTLY parsed (utils/env.py, the
    FA_NO_PALLAS contract) and parsed BEFORE the best-effort block: a
    typo'd knob is an InputError, never a silently-on cache."""
    from fastapriori_tpu.utils.env import env_flag

    if env_flag("FA_NO_COMPILE_CACHE"):
        return False
    log_compiles = not env_flag("FA_NO_COMPILE_LOG")
    # lint: env-ok -- free-form path knob: every string is a valid directory
    path = os.environ.get("FA_COMPILE_CACHE") or os.path.join(
        os.path.expanduser("~"), ".cache", "fastapriori_tpu", "jax"
    )
    try:
        os.makedirs(path, exist_ok=True)
        primed = bool(os.listdir(path))
        import jax

        jax.config.update("jax_compilation_cache_dir", path)
        # Default threshold (1 s) would skip the many ~0.5-1 s level
        # kernels that dominate a cold mining run's compile budget.
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
        # Shape-signature logging on every compile-cache miss (VERDICT
        # r5 next #5: 14 misses on a PRIMED cache meant data-dependent
        # shapes were escaping the pow2-bucket discipline, invisibly):
        # jax_log_compiles emits one stderr line per traced compile with
        # the jaxpr's global shapes — exactly the signature needed to
        # pin the escapee.  Entry points only (this function), opt out
        # with FA_NO_COMPILE_LOG=1 (parsed strictly above, outside this
        # best-effort block).
        if log_compiles:
            jax.config.update("jax_log_compiles", True)
        return primed
    except (OSError, ImportError, AttributeError, ValueError, RuntimeError):
        # Cache priming is purely an optimization: an unwritable dir
        # (OSError), a jax version without these config names
        # (AttributeError/ValueError), or a config locked after backend
        # init (RuntimeError) all mean "run uncached", never "fail".
        return False
