"""Strict FA_* environment-knob parsers (stdlib-only).

Every ops knob in this codebase follows the FA_NO_PALLAS contract
(ADVICE r5 #4): a typo'd value must raise
:class:`~fastapriori_tpu.errors.InputError` at first use, never silently
run the default — an invisible degradation on a production mine is
exactly the bug class the degradation ledger exists to kill.  graftlint
G012 enforces the contract statically: every ``FA_*`` read must route
through a parser that raises ``InputError``, and every knob must be
registered in ``tools/lint/env_registry.json`` (rendered into README's
knob table, so the docs cannot drift from the checked artifact).

Free-form knobs (paths like ``FA_COMPILE_CACHE``, where every string is
valid) are the one legitimate exception; their read sites carry an
``env-ok`` waiver naming that reason.
"""

from __future__ import annotations

import os
from typing import Optional

from fastapriori_tpu.errors import InputError

_FALSY = ("", "0", "false", "no")
_TRUTHY = ("1", "true", "yes", "on")


def env_flag(name: str, default: bool = False) -> bool:
    """Strict boolean knob: unset/``0``/``false``/``no`` -> False,
    ``1``/``true``/``yes``/``on`` -> True, anything else ->
    ``InputError``."""
    raw = os.environ.get(name, "")
    val = raw.strip().lower()
    if val in _FALSY:
        return default if raw == "" else False
    if val in _TRUTHY:
        return True
    raise InputError(
        f"unrecognized {name} value {raw!r}: use one of "
        f"{'/'.join(_TRUTHY)} to enable, "
        f"{'/'.join(v for v in _FALSY if v)} (or unset) to disable"
    )


def env_choice(
    name: str, choices: tuple, default: Optional[str] = None
) -> Optional[str]:
    """Strict enumerated knob: unset -> ``default``, a listed choice ->
    itself (case-normalized), anything else -> ``InputError`` — the
    FA_RULE_ENGINE/FA_COUNT_REDUCE contract."""
    raw = os.environ.get(name, "")
    val = raw.strip().lower()
    if not val:
        return default
    if val in choices:
        return val
    raise InputError(
        f"unrecognized {name} value {raw!r}: use one of "
        f"{'/'.join(choices)} (or unset for the config default)"
    )


def env_int(
    name: str, default: int, minimum: Optional[int] = None
) -> int:
    """Strict integer knob; ``minimum`` bounds the valid range."""
    raw = os.environ.get(name, "").strip()
    if not raw:
        return default
    try:
        val = int(raw)
    except ValueError:
        raise InputError(
            f"unrecognized {name} value {raw!r}: expected an integer"
        ) from None
    if minimum is not None and val < minimum:
        raise InputError(
            f"{name}={val} is out of range: must be >= {minimum}"
        )
    return val


def env_float(
    name: str, default: float, minimum: Optional[float] = None
) -> float:
    """Strict float knob; ``minimum`` bounds the valid range."""
    raw = os.environ.get(name, "").strip()
    if not raw:
        return default
    try:
        val = float(raw)
    except ValueError:
        raise InputError(
            f"unrecognized {name} value {raw!r}: expected a number"
        ) from None
    if minimum is not None and val < minimum:
        raise InputError(
            f"{name}={val} is out of range: must be >= {minimum}"
        )
    return val
