from fastapriori_tpu.utils.order import item_sort_key  # noqa: F401
from fastapriori_tpu.utils.logging import MetricsLogger, phase_timer  # noqa: F401
