"""Configuration for the miner and recommender.

The reference hardcodes its knobs (minSupport=0.092 at Main.scala:23, Spark
parallelism at Main.scala:18-20); here they are real flags with the
reference's values as defaults (SURVEY.md §5 "Config / flag system").
"""

from __future__ import annotations

import dataclasses
from typing import Optional

# Reference default: Main.scala:23.
DEFAULT_MIN_SUPPORT = 0.092


@dataclasses.dataclass
class MinerConfig:
    """Knobs for the mining engine and its device kernels."""

    min_support: float = DEFAULT_MIN_SUPPORT
    # Pad the candidate-prefix axis to powers of two >= this, so the level
    # kernels compile for a small set of bucket shapes instead of one shape
    # per level (SURVEY.md §7 "padding/bucketing discipline").
    min_prefix_bucket: int = 128
    # Pad the transaction axis to a multiple of this (after sharding the
    # per-device rows still align to MXU-friendly tiles).
    txn_tile: int = 8
    # Pad the item axis (F) to a multiple of this (MXU lane width).
    item_tile: int = 128
    # Optional cap on devices used (None = all devices in the mesh).
    num_devices: Optional[int] = None
    # 2-D mesh split: devices arrange as (num/cand_devices, cand_devices)
    # over axes (txn, cand); the level engine shards candidate-prefix rows
    # over cand (SURVEY.md §7 optional 2-D mesh).  1 = plain txn mesh.
    cand_devices: int = 1
    # Emit per-level structured metrics as JSON lines to stderr.
    log_metrics: bool = False
    # Recommender: rules per first-match chunk (priority-ordered; the
    # scan stops as soon as every basket has matched, so most runs touch
    # only the first chunk).
    rule_chunk: int = 1 << 13
    # Scan micro-batch rows for the resident-table first-match scan —
    # ONE knob shared by the batch recommender (which caps each
    # replicated micro-batch at this many basket rows) and the serving
    # tier's request micro-batcher (serve/server.py collects at most
    # this many queued requests per dispatch), replacing the static 4K
    # constant (PR 8 residue / ISSUE 10).  Pow2-bucketed at use (G011:
    # the scan compiles per batch shape; a data-exact row count would
    # compile per population) with a floor of 32.  FA_REC_BATCH
    # overrides, strictly parsed.
    rec_batch_rows: int = 1 << 12
    # Serving tier (serve/server.py): max milliseconds a partial
    # micro-batch lingers waiting to fill before dispatching anyway —
    # the latency side of the batch-size/linger trade-off (arxiv
    # 1309.0215's buffer/latency knob).  0 dispatches every batch
    # immediately (minimum latency, maximum dispatch overhead).
    serve_linger_ms: float = 2.0
    # Serving tier: admission-control queue bound, in REQUESTS.  A
    # submit finding the queue full is shed — answered "0" immediately
    # and counted, with the accept->shed transition recorded on the
    # degradation cascade — so offered load past capacity degrades to
    # bounded latency + recorded sheds, never an unbounded queue.
    # 0 = auto (4x the resolved micro-batch rows).
    serve_queue_depth: int = 0
    # Rule generation (phase 2) engine: "auto" (default) runs the
    # device-resident level-wise join + dominance prune (rules/gen.py
    # device path — packed-key sorted gathers, one dispatch per level)
    # when an accelerator context is available, the raw rule count
    # reaches `rule_device_min_rules`, and every itemset count fits the
    # exact-compare gate (< 2^24); "host" forces the numpy path (the
    # differential oracle), "device" forces the device path regardless
    # of size/platform (tests; still falls back to host — with a ledger
    # event — when the count gate fails).  FA_RULE_ENGINE overrides,
    # strictly parsed like FA_NO_PALLAS.
    rule_engine: str = "auto"
    # Below this many raw rules (sum over levels of k·N_k) the host path
    # wins: the device path pays per-level dispatch round trips and the
    # table uploads, which only amortize on big levels (VERDICT r5
    # weak #8 is a 16.34M-rule workload; 2M is ~0.5 s of host joins).
    rule_device_min_rules: int = 1 << 21
    # Phase-2 shard count over the txn mesh axis (rules/gen.py
    # resolve_rule_shards): 0 = auto — shard the per-level rule joins
    # (and the recommender's resident-table priority scan) over the
    # FULL txn axis on eligible meshes (single process, no cand axis),
    # falling back to the single-chip engine elsewhere; 1 pins phase 2
    # to device 0 (the PR-4 engine); any other value must equal the
    # mesh's txn shard count (InputError otherwise — phase 2 shards
    # over the existing mesh, it cannot carve a sub-mesh).
    # FA_RULE_SHARDS overrides, strictly parsed.
    rule_shards: int = 0
    # Count-reduction engine for the mesh collectives (ops/count.py
    # local_sparse_psum): "auto" (default) runs the threshold-sparse
    # exchange — per-shard local prune at the weighted-pigeonhole
    # threshold, packed-mask all_gather of the survivor union, compact
    # segment psum, on-device scatter-back — on multi-device single-
    # process txn meshes where candidate supports are power-law and the
    # dense [NB, C] / [F, F] psum is ICI/DCN-bound (ROADMAP item 2;
    # arxiv 1312.3020); "dense" forces the classic full-tensor psum
    # (the differential oracle); "sparse" forces the sparse exchange
    # where it is defined (1-device meshes, multi-process ingest, 2-D
    # cand meshes and tiny candidate sets still fall back to dense,
    # with a ledger event).  Counts are bit-exact either way: a shard
    # only prunes candidates that provably cannot reach min_count
    # globally, and every union survivor's compact segment sums ALL
    # shards' contributions.  FA_COUNT_REDUCE overrides, strictly
    # parsed like FA_NO_PALLAS.
    count_reduce: str = "auto"
    # Sparse exchange: union-compaction slot budget per reduction (the
    # psum payload is 4·cap bytes).  None = auto (pow2 bucket of
    # n_candidates/16, floor 1024 — ops/count.py sparse_union_cap); an
    # explicit value is pow2-bucketed and forced.  A union overflow
    # falls back to the dense psum for that dispatch (ledger event) and
    # records the grown budget for repeat runs.  FA_COUNT_SPARSE_CAP
    # overrides, strictly parsed.
    count_sparse_cap: Optional[int] = None
    # Below this many candidate slots per reduction the sparse exchange
    # cannot beat the dense psum (two collectives' latency vs one small
    # payload) — such dispatches stay dense even under count_reduce=
    # "sparse".
    count_sparse_min: int = 4096
    # Hierarchical (two-level) exchange topology for the pod-scale
    # collectives (parallel/hier.py, ISSUE 15 / ROADMAP direction 3):
    # the txn axis's S shards view as a (groups, per_group) grid — the
    # sparse count reduction's mask-union gather and compact psum run
    # intra-group then once across groups (per-shard gather bytes drop
    # from S·N/8 to (per_group+groups)·N/8), and the sharded rule
    # join's table reassembly restages the same way.  0 = auto (group
    # at process boundaries on a real multi-host mesh; the divisor of
    # S nearest √S on single-process virtual meshes; flat below S=8
    # where the hierarchy cannot strictly win); 1 = flat (the
    # single-level oracle exchange, also the hier→flat cascade
    # fallback); any other value must divide the txn shard count
    # (InputError otherwise).  Bit-exact at any topology — OR/int32-sum
    # are associative and the reassembly preserves shard order.
    # FA_EXCHANGE_GROUPS overrides, strictly parsed.
    exchange_groups: int = 0
    # Mining-engine LAYOUT choice (ROADMAP item 3): "bitmap" runs the
    # horizontal bitmap-matmul engines (the fused/level machinery below
    # — and the differential oracle, pinned bit-exact on every corpus);
    # "vertical" runs the Eclat-style tid-lane engine (ops/vertical.py:
    # per-item packed uint32 tid lanes, level-k support by sharded
    # lane-wise AND + popcount — only the actual candidates are
    # counted, a ~32·F/k op reduction on sparse wide-item corpora where
    # the Gram/level matmuls run at 0.2-0.8% MFU); "auto" (default)
    # picks vertical when the pair-phase density estimate
    # (Σ item_counts / (n_raw · F)) falls below
    # `vertical_density_max` AND the frequent-item axis is at least
    # `vertical_min_items` wide — dense retail baskets keep the MXU
    # engines, sparse clickstream corpora get the lane engine — with
    # the choice (and any forced-engine fallback: cand meshes,
    # multi-process ingest, CSR-less CompressedData) recorded on the
    # degradation ledger.  FA_MINE_ENGINE overrides, strictly parsed
    # like FA_COUNT_REDUCE.
    mine_engine: str = "auto"
    vertical_density_max: float = 0.01
    vertical_min_items: int = 512
    # Vertical engine: candidate slots per scan step inside one launch
    # (bounds the [chunk, NL] gathered intersection lanes in HBM; pow2-
    # bucketed, clamped to the dispatch's candidate budget).
    # FA_VERTICAL_CHUNK overrides, strictly parsed.
    vertical_cand_chunk: int = 1 << 12
    # Vertical engine: lanes (uint32 words of the tid axis) per streamed
    # slab of the level-k AND+popcount — bounds the [P_cap, lane_tile]
    # prefix intermediate so big-T corpora stream the lane axis instead
    # of materializing [P_cap, NL] whole (the old ~50K-lane ceiling).
    # Also the lane-tile ceiling of the Pallas vertical kernel
    # (ops/pallas_vertical.py), so both tiers stream identically.
    # pow2-bucketed; FA_VERTICAL_LANE_TILE overrides, strictly parsed.
    vertical_lane_tile: int = 1 << 13
    # Level engine (transfer-minimal kernels, ops/count.py
    # local_level_gather / local_pair_gather): transaction-axis scan chunk
    # (bounds the [tc, P] membership intermediate in HBM), padded prefix
    # width (one compilation serves every level below this depth), padded
    # candidate-gather width, and the survivor budget for the on-device
    # pair threshold (doubles on overflow).
    level_txn_chunk: int = 1 << 14
    level_k_max: int = 24
    level_cand_cap: int = 1 << 18
    # Max candidate-prefix rows per level dispatch.  Dispatches carry a
    # large fixed cost on remote/tunneled chips (~100+ ms each: argument
    # transfer + launch round trip that the runtime does NOT pipeline),
    # so big levels want few big dispatches; the [txn_chunk, P] device
    # intermediate bounds how big.
    level_prefix_cap: int = 1 << 14
    # Initial survivor budget for the on-device pair threshold: bounds
    # the ONE packed device->host payload of the pair phase (2·cap·4
    # bytes — 128 KB here, ~7 ms on a ~19 MB/s tunneled link, vs ~50 ms
    # at the old 1<<17).  An n2 overflow retries with the exact
    # next-pow2 budget, so a large-pair dataset pays one extra dispatch
    # rather than every dataset paying the fat payload.
    pair_cap: int = 1 << 14
    # Ingest-overlapped pair program: ALSO count level 3 inside the same
    # dispatch (ops/count.py l3_threshold_pack — the pair mask already
    # encodes the full k=3 candidate set), so level 3 costs the mining
    # loop no dispatch and rides the one pair fetch.  pair_l3_rows is
    # the static pair-prefix budget (n2 above it invalidates the
    # section; the host falls back to the classic level-3 dispatch and
    # records the grown budget for repeat runs), pair_l3_cap the
    # level-3 survivor budget (2·cap·4 bytes of extra fetch payload).
    # 0 rows disables the fold.
    pair_l3_rows: int = 1 << 13
    pair_l3_cap: int = 1 << 14
    # Deferred-count HBM retention budget (ADVICE r5 #2): the level loop
    # keeps each level's [NB, C] int32 count tensor device-resident for
    # the single end-of-mine gather; once their summed bytes exceed this
    # budget the loop DRAINS them early — one gather dispatch compacts
    # the survivors, the big tensors free, and the (async) fetch is
    # consumed at end-of-mine.  Deep lattices therefore hold O(budget)
    # extra HBM instead of O(levels); each drain costs one dispatch,
    # so the common shallow case (under budget) still pays exactly one.
    pending_fetch_budget_bytes: int = 256 << 20
    # Level engine, single-process local-file ingest: split D.dat into
    # this many line-aligned blocks, compress each natively and start its
    # (async) device upload immediately — block i+1's host compression
    # overlaps block i's transfer, hiding the bitmap upload behind
    # pass 2 (on tunneled chips the 50+ MB Webdocs upload was a full
    # pair-phase stall).  1 disables the overlap (single block).
    ingest_pipeline_blocks: int = 8
    # Host threads for the pipelined ingest's pass-1 counting and pass-2
    # compression (the native scanner releases the GIL, so byte-range
    # blocks really run in parallel — the single-host analog of the
    # multi-host sharded ingest, same count-merge correctness).  None =
    # one thread per core.  A 1-core host (like some tunneled-TPU dev
    # hosts) degenerates to the serial path with no overhead worth
    # noting.
    ingest_threads: Optional[int] = None
    # Keep the full basket CSR (CompressedData.basket_indices/offsets)
    # under the capture-replay pipelined ingest.  The CSR costs ~0.7 GB
    # of per-block numpy copies at webdocs scale and nothing in the
    # mining pipeline reads it there (the bitmap is built block-by-block
    # in the callback; heavy rows are extracted at callback time), so
    # the CLI/bench set False; the library default preserves the
    # documented CompressedData contract for API callers.  False is an
    # optimization of the CAPTURE ingest flavor only (single-threaded
    # host + native extension): the threaded and non-pipelined flavors
    # materialize the CSR as a byproduct and keep it regardless, so a
    # CSR-less CompressedData is host-dependent — re-mining one through
    # a CSR-consuming path raises a ValueError naming this knob.
    retain_csr: bool = True
    # Mining engine: "auto" (default) picks per dataset — the fused
    # whole-loop program when the level-2 survivor budget AND the level-3
    # candidate census (one extra matmul inside the pair pre-pass,
    # ops/count.py _pair_triangles) both fit the memory-derived row-budget
    # ceiling, else the per-level engine — so the zero-flag CLI path is
    # always the fast path (the reference's driver has exactly one path,
    # Main.scala:16-38).  "fused" forces the whole-loop attempt (falling
    # back to "level" on row-budget overflow, with complete levels
    # salvaged); "level" forces one kernel launch per level with host
    # candidate generation.
    engine: str = "auto"
    # Fused engine: floor for the starting per-level frequent-set row
    # budget (the budget itself is sized from the level-2 survivor count
    # pre-pass).  On overflow the engine re-compiles with a budget sized
    # from the overflowing level's true survivor count, up to the
    # memory-derived ceiling (min of fused_m_cap_max and what fits the
    # device HBM budget — models/apriori.py _fused_m_cap_memory_limit),
    # then falls back to the per-level engine.
    fused_m_cap: int = 512
    fused_m_cap_max: int = 32768
    # HBM budget for sizing that ceiling.  None = read the device's
    # bytes_limit (16 GiB assumed when the backend doesn't report one)
    # and keep `fused_hbm_fraction` of it for the mining program — the
    # rest covers XLA workspace/fragmentation.  Tests inject a tiny
    # budget here to drive the salvage path without real memory pressure.
    fused_hbm_budget_bytes: Optional[int] = None
    fused_hbm_fraction: float = 0.5
    # Fused engine: max Apriori levels held in the output buffers.
    fused_l_max: int = 24
    # Shallow-tail fold (level engine): once a level's survivor count
    # drops to this threshold, the REMAINING loop runs as ONE seeded
    # device program (ops/fused.py make_tail_miner) instead of one
    # ~110 ms launch per level.  None = auto (16384 on accelerators,
    # disabled on cpu where there is no launch floor to amortize and
    # every distinct seed depth would pay a while-loop compile); 0
    # disables; an explicit value forces, platform-independent.
    tail_fuse_rows: Optional[int] = None
    # Tail fold: compacted candidate-prefix budget per iteration (the
    # counting matmul runs [p_cap, F] rows, not [m_cap, F]) and the max
    # tail depth per dispatch (overflowing either resumes the per-level
    # engine from the last complete level).
    tail_fuse_p_cap: int = 2048
    tail_fuse_l_max: int = 8
    # Fused engine: per-device transaction-chunk target — bounds the
    # [chunk, m_cap] containment intermediate in HBM (the scan over chunks
    # accumulates counts).
    fused_txn_chunk: int = 1 << 17
    # Crash-safe mid-mine checkpointing (CLI --checkpoint-every-level):
    # when set, the level loop rewrites <prefix>checkpoint.npz (atomic
    # write + run-manifest entry, io/checkpoint.py) after EVERY completed
    # level, so --resume-from restarts from the deepest completed level
    # instead of from scratch.  Costs: per-level counts resolve eagerly
    # (the deferred single-fetch optimization is incompatible with
    # durable per-level state).  With engine="auto"/"level" the
    # whole-lattice fused program is skipped (one opaque multi-level
    # dispatch has no mid-points to checkpoint; the shallow-tail fold
    # stays on — it checkpoints at the fold boundary); engine="fused"
    # instead mines in SEGMENTS (below).  None disables (the default).
    checkpoint_prefix: Optional[str] = None
    # Fused-engine checkpoint cadence (ISSUE 9): with engine="fused" AND
    # checkpoint_prefix set, the lattice is mined in device SEGMENTS —
    # seeded whole-loop dispatches (the ops/fused.py tail program with
    # 2x row headroom and flat slot caps) of this many levels each, a
    # durable checkpoint committed after every segment — so a fused
    # mine kills-and-resumes byte-identically at the segment boundary
    # instead of forfeiting the engine.  A segment whose level outgrows
    # its row budget degrades to per-level dispatches (cascade event)
    # until the lattice shrinks back under the failed seed.  1 (the
    # default) checkpoints after every level, matching the level
    # engine's durability; larger values trade checkpoint granularity
    # for fewer dispatch round trips.
    checkpoint_every_levels: int = 1
