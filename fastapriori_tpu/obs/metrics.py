"""Serving metrics registry: allocation-free instruments + Prometheus
text exposition (ISSUE 11 tentpole, part b).

The serving tier's load bench aggregates AFTER the run; a resident
server needs metrics DURING it — queue depth when the overload hits,
batch fill while the linger knob is tuned, shed counts while they
happen.  This registry is what ``RecommendServer`` updates in its hot
path and exposes through ``server.metrics_text()`` (scraped mid-run by
the load bench) and ``serve --metrics-dump PATH`` (periodic atomic
snapshots through the PR-2 committer).

Hot-path discipline: every instrument is fixed-size at construction —
``observe``/``inc``/``set`` are integer increments plus (for
histograms) one binary search over a static bound tuple; no
allocation, no locking on the write path (single-writer counters
tolerate torn reads in a text snapshot; the GIL keeps int increments
atomic).  The Prometheus text form renders cumulative buckets
(``_bucket{le=...}``/``_sum``/``_count``) so any standard scraper
parses it.
"""

from __future__ import annotations

from bisect import bisect_left
from typing import Dict, List, Optional, Sequence, Tuple

# Latency-shaped default bounds (milliseconds): sub-ms dispatch floors
# through multi-second stalls.
LATENCY_BUCKETS_MS = (
    0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 50.0,
    100.0, 250.0, 500.0, 1000.0, 2500.0,
)


class Counter:
    __slots__ = ("name", "help", "value")

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self.value = 0

    def inc(self, n: int = 1) -> None:
        self.value += n

    def render(self) -> List[str]:
        return [
            f"# HELP {self.name} {self.help}",
            f"# TYPE {self.name} counter",
            f"{self.name} {self.value}",
        ]

    def snapshot(self):
        return self.value


class Gauge:
    __slots__ = ("name", "help", "value", "max_value")

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self.value = 0
        self.max_value = 0

    def set(self, v) -> None:
        self.value = v
        if v > self.max_value:
            self.max_value = v

    def render(self) -> List[str]:
        return [
            f"# HELP {self.name} {self.help}",
            f"# TYPE {self.name} gauge",
            f"{self.name} {self.value}",
            f"{self.name}_max {self.max_value}",
        ]

    def snapshot(self):
        return {"value": self.value, "max": self.max_value}


class Histogram:
    """Fixed-bucket histogram: ``bounds`` are upper bucket edges (an
    implicit +Inf bucket follows).  ``observe`` is one bisect over the
    static bound tuple + two int adds — exact bucket placement is
    test-pinned (a value equal to a bound lands in that bound's bucket,
    the Prometheus ``le`` contract)."""

    __slots__ = ("name", "help", "bounds", "counts", "total", "sum")

    def __init__(
        self, name: str, bounds: Sequence[float], help: str = ""
    ):
        self.name = name
        self.help = help
        self.bounds: Tuple[float, ...] = tuple(float(b) for b in bounds)
        if list(self.bounds) != sorted(set(self.bounds)):
            raise ValueError(
                f"histogram {name}: bounds must be strictly increasing, "
                f"got {bounds}"
            )
        self.counts = [0] * (len(self.bounds) + 1)
        self.total = 0
        self.sum = 0.0

    def observe(self, v: float) -> None:
        self.counts[bisect_left(self.bounds, v)] += 1
        self.total += 1
        self.sum += v

    def render(self) -> List[str]:
        out = [
            f"# HELP {self.name} {self.help}",
            f"# TYPE {self.name} histogram",
        ]
        cum = 0
        for bound, n in zip(self.bounds, self.counts):
            cum += n
            le = f"{bound:g}"
            out.append(f'{self.name}_bucket{{le="{le}"}} {cum}')
        cum += self.counts[-1]
        out.append(f'{self.name}_bucket{{le="+Inf"}} {cum}')
        out.append(f"{self.name}_sum {round(self.sum, 6)}")
        out.append(f"{self.name}_count {self.total}")
        return out

    def snapshot(self):
        return {
            "buckets": dict(
                zip([f"{b:g}" for b in self.bounds] + ["+Inf"], self.counts)
            ),
            "count": self.total,
            "sum": round(self.sum, 6),
        }


class _LabeledHistogram:
    """One histogram per label value (bounded by the label cardinality —
    here audited fetch SITES, a lint-censused finite set)."""

    __slots__ = ("name", "help", "label", "bounds", "series")

    def __init__(self, name, bounds, help="", label="site"):
        self.name = name
        self.help = help
        self.label = label
        self.bounds = tuple(bounds)
        self.series: Dict[str, Histogram] = {}

    def observe(self, key: str, v: float) -> None:
        h = self.series.get(key)
        if h is None:
            h = self.series[key] = Histogram(self.name, self.bounds)
        h.observe(v)

    def render(self) -> List[str]:
        out = [
            f"# HELP {self.name} {self.help}",
            f"# TYPE {self.name} histogram",
        ]
        for key in sorted(self.series):
            h = self.series[key]
            cum = 0
            lbl = f'{self.label}="{key}"'
            for bound, n in zip(h.bounds, h.counts):
                cum += n
                out.append(
                    f'{self.name}_bucket{{{lbl},le="{bound:g}"}} {cum}'
                )
            cum += h.counts[-1]
            out.append(f'{self.name}_bucket{{{lbl},le="+Inf"}} {cum}')
            out.append(f'{self.name}_sum{{{lbl}}} {round(h.sum, 6)}')
            out.append(f'{self.name}_count{{{lbl}}} {h.total}')
        return out

    def snapshot(self):
        return {k: h.snapshot() for k, h in sorted(self.series.items())}


class MetricsRegistry:
    """An ordered collection of instruments with one text/snapshot
    surface.  Instrument getters are get-or-create and idempotent, so
    hot paths hold direct instrument references and cold paths may
    re-resolve by name."""

    def __init__(self):
        self._instruments: Dict[str, object] = {}

    def counter(self, name: str, help: str = "") -> Counter:
        inst = self._instruments.get(name)
        if inst is None:
            inst = self._instruments[name] = Counter(name, help)
        return inst

    def gauge(self, name: str, help: str = "") -> Gauge:
        inst = self._instruments.get(name)
        if inst is None:
            inst = self._instruments[name] = Gauge(name, help)
        return inst

    def histogram(
        self,
        name: str,
        bounds: Sequence[float] = LATENCY_BUCKETS_MS,
        help: str = "",
    ) -> Histogram:
        inst = self._instruments.get(name)
        if inst is None:
            inst = self._instruments[name] = Histogram(name, bounds, help)
        return inst

    def labeled_histogram(
        self,
        name: str,
        bounds: Sequence[float] = LATENCY_BUCKETS_MS,
        help: str = "",
        label: str = "site",
    ) -> _LabeledHistogram:
        # Re-resolving by name is a plain dict hit with NO factory
        # allocation — cold misses construct inline — so per-fetch
        # callers (fetch_latency_observe) stay allocation-free without
        # holding a reference that a test's registry reset would orphan.
        inst = self._instruments.get(name)
        if inst is None:
            inst = self._instruments[name] = _LabeledHistogram(
                name, bounds, help, label
            )
        return inst

    def render(self) -> str:
        lines: List[str] = []
        for name in sorted(self._instruments):
            lines.extend(self._instruments[name].render())
        return "\n".join(lines) + ("\n" if lines else "")

    def snapshot(self) -> dict:
        return {
            name: inst.snapshot()
            for name, inst in sorted(self._instruments.items())
        }

    def reset(self) -> None:
        self._instruments.clear()


# -- cross-process aggregation (ISSUE 19 satellite) ---------------------
# A serving mesh has one registry PER HOST PROCESS; the router merges
# their snapshot() dicts into one scrapeable surface.  Snapshots are the
# merge currency (JSON-safe, so a subprocess host's registry rides a
# file): the instrument kind is recovered from the snapshot shape —
# Counter -> number, Gauge -> {value,max}, Histogram ->
# {buckets,count,sum}, labeled histogram -> {label: histogram}.


def _snap_kind(val) -> str:
    if isinstance(val, (int, float)) and not isinstance(val, bool):
        return "counter"
    if isinstance(val, dict):
        if set(val) == {"value", "max"}:
            return "gauge"
        if set(val) == {"buckets", "count", "sum"}:
            return "histogram"
        return "labeled"
    raise ValueError(f"unrecognized metrics snapshot value: {val!r}")


def _copy_val(val):
    if isinstance(val, dict):
        return {k: _copy_val(v) for k, v in val.items()}
    return val


def _merge_hist(name: str, a: dict, b: dict) -> dict:
    if set(a["buckets"]) != set(b["buckets"]):
        raise ValueError(
            f"histogram {name}: bucket bounds differ across hosts "
            f"({sorted(a['buckets'])} vs {sorted(b['buckets'])}) — "
            "mesh hosts must run the same instrument layout"
        )
    return {
        "buckets": {
            k: a["buckets"][k] + b["buckets"][k] for k in a["buckets"]
        },
        "count": a["count"] + b["count"],
        "sum": round(a["sum"] + b["sum"], 6),
    }


def _merge_val(name: str, a, b):
    ka, kb = _snap_kind(a), _snap_kind(b)
    if ka != kb:
        raise ValueError(
            f"metric {name}: instrument kind differs across hosts "
            f"({ka} vs {kb})"
        )
    if ka == "counter":
        return a + b
    if ka == "gauge":
        return {
            "value": max(a["value"], b["value"]),
            "max": max(a["max"], b["max"]),
        }
    if ka == "histogram":
        return _merge_hist(name, a, b)
    out = {k: _copy_val(v) for k, v in a.items()}
    for k, v in b.items():
        out[k] = _merge_hist(name, out[k], v) if k in out else _copy_val(v)
    return out


def merge_snapshots(snaps: Sequence[dict]) -> dict:
    """Fold per-host registry ``snapshot()`` dicts into one mesh-level
    snapshot: counters SUM, gauges MAX (current value and peak —
    per-host queue depths are not additive load), histograms add
    BUCKET-WISE (same bounds required; a mismatch raises rather than
    silently skewing percentiles), labeled histograms merge per label.
    The result is itself snapshot-shaped — :func:`render_snapshot`
    exposes it as ordinary Prometheus text."""
    out: Dict[str, object] = {}
    for snap in snaps:
        for name, val in snap.items():
            if name in out:
                out[name] = _merge_val(name, out[name], val)
            else:
                out[name] = _copy_val(val)
    return dict(sorted(out.items()))


def _hist_lines(name: str, lbl: str, hs: dict) -> List[str]:
    # Cumulative le-ordered buckets (the Prometheus contract); bucket
    # keys sort numerically with +Inf last — a JSON round-trip keeps
    # insertion order, but don't depend on it.
    finite = sorted(
        (k for k in hs["buckets"] if k != "+Inf"), key=float
    )
    out = []
    cum = 0
    for k in finite:
        cum += hs["buckets"][k]
        sel = f'{lbl},le="{k}"' if lbl else f'le="{k}"'
        out.append(f"{name}_bucket{{{sel}}} {cum}")
    cum += hs["buckets"].get("+Inf", 0)
    sel = f'{lbl},le="+Inf"' if lbl else 'le="+Inf"'
    out.append(f"{name}_bucket{{{sel}}} {cum}")
    suffix = f"{{{lbl}}}" if lbl else ""
    out.append(f"{name}_sum{suffix} {round(hs['sum'], 6)}")
    out.append(f"{name}_count{suffix} {hs['count']}")
    return out


def render_snapshot(
    snap: dict, helps: Optional[Dict[str, str]] = None,
    label: str = "site",
) -> str:
    """Prometheus text exposition of a snapshot dict (typically the
    output of :func:`merge_snapshots`) — the same format the live
    registries render, so one scraper config serves single-host and
    mesh deployments."""
    lines: List[str] = []
    for name in sorted(snap):
        val = snap[name]
        kind = _snap_kind(val)
        h = (helps or {}).get(name, "")
        if kind == "counter":
            lines += [
                f"# HELP {name} {h}", f"# TYPE {name} counter",
                f"{name} {val}",
            ]
        elif kind == "gauge":
            lines += [
                f"# HELP {name} {h}", f"# TYPE {name} gauge",
                f"{name} {val['value']}", f"{name}_max {val['max']}",
            ]
        elif kind == "histogram":
            lines += [f"# HELP {name} {h}", f"# TYPE {name} histogram"]
            lines += _hist_lines(name, "", val)
        else:
            lines += [f"# HELP {name} {h}", f"# TYPE {name} histogram"]
            for key in sorted(val):
                lines += _hist_lines(name, f'{label}="{key}"', val[key])
    return "\n".join(lines) + ("\n" if lines else "")


# Process-global registry for instruments whose sites have no server or
# config in scope (the ledger pattern): today the per-site audited-fetch
# latency histograms updated by reliability/retry.py.
GLOBAL = MetricsRegistry()


def fetch_latency_observe(site: str, ms: float) -> None:
    """Record one audited fetch's wall latency (reliability/retry.py) —
    the per-site serving-path fetch histograms the registry snapshot
    exposes."""
    GLOBAL.labeled_histogram(
        "fa_fetch_latency_ms",
        help="audited device fetch wall latency by site",
    ).observe(site, ms)


_dump_interval_memo: Optional[float] = None


def dump_interval_s() -> float:
    """``FA_METRICS_DUMP_S``: seconds between periodic metrics-snapshot
    writes under ``serve --metrics-dump`` (strictly parsed, default 5;
    must be positive).  Parsed once per process; tests use
    :func:`reload_from_env`."""
    global _dump_interval_memo
    if _dump_interval_memo is None:
        from fastapriori_tpu.utils.env import env_float

        _dump_interval_memo = env_float(
            "FA_METRICS_DUMP_S", 5.0, minimum=0.05
        )
    return _dump_interval_memo


def reload_from_env() -> None:
    global _dump_interval_memo
    _dump_interval_memo = None
