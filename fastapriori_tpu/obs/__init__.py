"""Unified observability substrate (ISSUE 11 tentpole).

Three pieces, deliberately stdlib-only (no jax import — the tracer must
be importable from the lint-censused reliability layer and from tools):

- :mod:`fastapriori_tpu.obs.trace` — nestable, thread-aware spans with
  deterministic ids, exported as Chrome-trace-event JSON (Perfetto-
  loadable); near-zero cost when disabled.
- :mod:`fastapriori_tpu.obs.metrics` — allocation-free fixed-bucket
  histograms + counters/gauges with a Prometheus-text snapshot: the
  serving tier's scrapeable registry.
- :mod:`fastapriori_tpu.obs.flight` — a bounded ring of the last N
  span/ledger/watchdog events, dumped to a manifest-committed artifact
  on classified errors, ``AbandonedThreadCap``, and chaos-soak hangs.
- :mod:`fastapriori_tpu.obs.device_trace` — ISSUE 18's device-internal
  view: XLA profiler capture + stdlib Perfetto parsing that attributes
  per-kernel device time (jax is lazy-imported inside the capture
  helper, so the stdlib-only-at-import promise above still holds).
"""

from fastapriori_tpu.obs import device_trace, flight, metrics, trace  # noqa: F401
from fastapriori_tpu.obs.metrics import MetricsRegistry  # noqa: F401
from fastapriori_tpu.obs.trace import TRACER, span  # noqa: F401
