"""Crash flight recorder: the last N observability events, dumped on
failure (ISSUE 11 tentpole, part c).

A watchdog trip, an ``AbandonedThreadCap``, a chaos-soak hang — by the
time these surface, the interesting part (what the process was doing
right before) is gone from every log that only aggregates.  The flight
recorder keeps a bounded ring of the most recent span / ledger /
watchdog events (always on — appends are a lock + deque append, spans
enter only while tracing is enabled) and dumps it to a
manifest-committed JSON artifact when something dies:

- the degradation ledger forwards every event here (watchdog timeouts,
  cascade walks, retries included);
- the tracer appends each completed span while enabled;
- :func:`auto_dump` fires on ``AbandonedThreadCap``
  (reliability/watchdog.py) against the prefix the CLI registered, and
  ``tools/chaos.py`` dumps explicitly on FAIL/hang scenarios — so a
  chaos failure ships its own post-mortem.

``FA_FLIGHT_RECORDER_N`` sizes the ring (strict; 0 disables).
"""

from __future__ import annotations

import json
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

FLIGHT_NAME = "flight.json"


def ring_size() -> int:
    """``FA_FLIGHT_RECORDER_N``: ring capacity in events (strictly
    parsed; default 256, 0 disables recording).  Read once at recorder
    construction; tests use :func:`reload_from_env`."""
    from fastapriori_tpu.utils.env import env_int

    return env_int("FA_FLIGHT_RECORDER_N", 256, minimum=0)


class FlightRecorder:
    """Bounded ring (module docstring).  ``seq`` is a monotone event
    number, so a dump shows exactly how many events the ring dropped
    and overwrite order is testable."""

    def __init__(self, cap: Optional[int] = None):
        self._cap = ring_size() if cap is None else cap
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=self._cap or 1)
        self._seq = 0
        self._t0 = time.monotonic()
        # Wall-clock anchor for the same instant as _t0: event t_s
        # values are monotonic-relative, so cross-PROCESS ordering (the
        # per-rank dumps tools/flight_merge.py reassembles) needs the
        # anchor in the dump body — t_abs = t0_unix_s + t_s.
        self._t0_wall = time.time()
        self._dump_prefix: Optional[str] = None
        self.dumps = 0

    @property
    def cap(self) -> int:
        return self._cap

    def note(self, kind: str, **fields: Any) -> None:
        if not self._cap:
            return
        with self._lock:
            self._seq += 1
            self._ring.append(
                {
                    "seq": self._seq,
                    "t_s": round(time.monotonic() - self._t0, 6),
                    "kind": kind,
                    **fields,
                }
            )

    def snapshot(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [dict(e) for e in self._ring]

    def set_dump_prefix(self, prefix: Optional[str]) -> None:
        """Register where :func:`auto_dump` writes — the CLI sets its
        output prefix here, so reliability-layer triggers (which have
        no path in scope) can still ship the post-mortem."""
        self._dump_prefix = prefix

    def dump(
        self,
        prefix: str,
        reason: str,
        extra: Optional[dict] = None,
    ) -> str:
        """Write ``<prefix>flight.json`` through the crash-safe
        committer + run manifest: the ring snapshot, the trigger
        reason, and the drop accounting (``first_seq``>1 means the ring
        wrapped).  Returns the artifact path."""
        from fastapriori_tpu.io.writer import write_artifact_bytes, write_manifest

        events = self.snapshot()
        body = {
            "version": 1,
            "reason": reason,
            "ring_capacity": self._cap,
            "total_events": self._seq,
            "first_seq": events[0]["seq"] if events else None,
            "t0_unix_s": round(self._t0_wall, 6),
            "events": events,
        }
        if extra:
            body["context"] = extra
        manifest: Dict[str, dict] = {}
        path = write_artifact_bytes(
            prefix + FLIGHT_NAME,
            [(json.dumps(body, indent=1) + "\n").encode("utf-8")],
            FLIGHT_NAME,
            manifest,
        )
        # lint: waive G020 -- crash-path post-mortem dump: the dumping process may already be fenced out, and checkpoint_fence() raising StaleFenceError here would mask the original failure the dump exists to explain
        write_manifest(prefix, manifest)
        self.dumps += 1
        return path

    def auto_dump(self, reason: str, extra: Optional[dict] = None) -> Optional[str]:
        """Dump against the registered prefix; None (recorded, not
        written) when no prefix was registered — never an error on the
        failure path it instruments."""
        if self._dump_prefix is None:
            return None
        try:
            return self.dump(self._dump_prefix, reason, extra)
        # The recorder rides error paths (AbandonedThreadCap, chaos
        # hangs): a failing dump must never mask the original failure.
        # lint: waive G006 G009 -- best-effort post-mortem on an already-failing path; the committer handles atomicity
        except Exception:
            return None

    def reset(self, cap: Optional[int] = None) -> None:
        with self._lock:
            self._cap = ring_size() if cap is None else cap
            self._ring = deque(maxlen=self._cap or 1)
            self._seq = 0
            self._t0 = time.monotonic()
            self._t0_wall = time.time()
            self.dumps = 0


RECORDER = FlightRecorder()


def note(kind: str, **fields: Any) -> None:
    RECORDER.note(kind, **fields)


def snapshot() -> List[Dict[str, Any]]:
    return RECORDER.snapshot()


def dump(prefix: str, reason: str, extra: Optional[dict] = None) -> str:
    return RECORDER.dump(prefix, reason, extra)


def auto_dump(reason: str, extra: Optional[dict] = None) -> Optional[str]:
    return RECORDER.auto_dump(reason, extra)


def set_dump_prefix(prefix: Optional[str]) -> None:
    RECORDER.set_dump_prefix(prefix)


def reload_from_env() -> None:
    """Re-read FA_FLIGHT_RECORDER_N and rebuild the ring (tests)."""
    RECORDER.reset()
