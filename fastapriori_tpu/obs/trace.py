"""Span tracer: nestable, thread-aware, deterministic-id spans with
Chrome-trace-event export (ISSUE 11 tentpole, part a).

The profiling-before-optimizing discipline of arxiv 1309.0215 needs a
timeline, not aggregate walls: PR 10's "dedup wall" (sustained 7.2K rps
vs 10.8K closed-batch) is *inferred*; a trace showing when host work
(admission/dedup/pack) blocks the device scan *measures* it.  The
tracer threads through the mining level loop, the fused segments, rule
generation, every audited fetch (reliability/retry.py) and the serving
dispatcher, and exports the Perfetto-loadable Chrome trace-event JSON
(``mine --trace out.trace.json``).

Contracts:

- **Near-zero cost when disabled** (the default): ``span()`` is one
  attribute read + one branch returning a shared no-op context manager
  — no allocation, no clock read (test-pinned; the serve bench's
  no-obs control bounds the end-to-end overhead < 2%).
- **Deterministic ids**: a span's id is its path — parent id, name,
  and per-parent occurrence index (``main:mine#0/level#3``), NOT a
  global counter that interleaves across threads — so two identical
  seeded runs produce identical span trees modulo timestamps
  (test-pinned).  Root spans are keyed by thread name (deterministic
  here: ``MainThread``, ``fa-serve-dispatch``, ``fa-watchdog:<site>``).
- **Thread-aware**: each thread nests under its own root; export maps
  threads to stable small tids with ``thread_name`` metadata events.
- **Bounded**: past ``max_events`` new events are counted as dropped,
  never grown unboundedly (the MetricsLogger.records lesson).

Enable via the CLI ``--trace PATH`` flags or the strict ``FA_TRACE``
knob (spans recorded process-wide; export still needs a path).
"""

from __future__ import annotations

import json
import threading
import time
from typing import Any, Dict, List, Optional

# G014 span-census declaration: every audited fetch site label
# (tools/lint/inventory.json FETCH census) receives a span scope through
# reliability/retry.py's central instrumentation.  The site strings are
# built dynamically there ("fetch." + site), so this literal census IS
# the statically-checkable coverage claim: graftlint G014 fails when a
# fetch site is added without a declaration here (or a declaration goes
# stale), and tests/test_obs.py pins that a declared site really
# produces a span when traced.
FETCH_SITE_SPANS = (
    "fetch.counts",
    "fetch.counts_drain",
    "fetch.counts_resolve",
    "fetch.fused",
    "fetch.level_bits",
    "fetch.level_bits_sparse",
    "fetch.level_counts",
    "fetch.local_rows",
    "fetch.pair",
    "fetch.pair_pre",
    "fetch.pair_regather",
    "fetch.pair_sparse",
    "fetch.rec_match",
    "fetch.rule_counts",
    "fetch.rule_mask",
    "fetch.rule_mask_shard",
    "fetch.serve_match",
    "fetch.serve_swap_ready",
    "fetch.tail",
    "fetch.vlevel_bits",
    "fetch.vlevel_bits_sparse",
    "fetch.vpair",
    "fetch.vpair_sparse",
)


class _NoopSpan:
    """The disabled-path context manager: one shared instance, no state."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def update(self, **args: Any) -> None:
        pass


_NOOP = _NoopSpan()


class _Span:
    __slots__ = ("name", "sid", "parent_sid", "t0", "args", "_children")

    def __init__(self, name: str, sid: str, parent_sid: Optional[str]):
        self.name = name
        self.sid = sid
        self.parent_sid = parent_sid
        self.t0 = 0.0
        self.args: Dict[str, Any] = {}
        self._children: Dict[str, int] = {}

    def child_sid(self, name: str) -> str:
        idx = self._children.get(name, 0)
        self._children[name] = idx + 1
        return f"{self.sid}/{name}#{idx}"


class _SpanCtx:
    __slots__ = ("_tracer", "_name", "_args", "_span")

    def __init__(self, tracer: "Tracer", name: str, args: Dict[str, Any]):
        self._tracer = tracer
        self._name = name
        self._args = args
        self._span: Optional[_Span] = None

    def __enter__(self):
        self._span = self._tracer._push(self._name, self._args)
        return self

    def __exit__(self, exc_type, exc, tb):
        if exc_type is not None and self._span is not None:
            self._span.args.setdefault("error", exc_type.__name__)
        self._tracer._pop(self._span)
        return False

    def update(self, **args: Any) -> None:
        """Attach attributes to this span (visible in the exported
        trace's ``args``)."""
        if self._span is not None:
            self._span.args.update(args)


DEFAULT_MAX_EVENTS = 200_000
_max_events_memo: Optional[int] = None


def max_events_from_env() -> int:
    """``FA_TRACE_EVENTS``: the tracer's bounded-buffer capacity
    (strict int >= 1; default 200K).  ROADMAP obs residue: a
    webdocs-scale full trace outgrows the default cap and DROPS (with
    only a counter saying so) — this knob raises the ceiling for a
    deliberate big capture without changing the default's bound or the
    counted-drop behavior.  Parsed once per process; tests use
    :func:`reload_from_env`."""
    global _max_events_memo
    if _max_events_memo is None:
        from fastapriori_tpu.utils.env import env_int

        _max_events_memo = env_int(
            "FA_TRACE_EVENTS", DEFAULT_MAX_EVENTS, minimum=1
        )
    return _max_events_memo


class Tracer:
    """Process-wide span collector (module docstring).  A singleton like
    the degradation ledger: the sites that trace (retry wrappers, ops
    dispatch points) have no config in scope."""

    def __init__(self, max_events: Optional[int] = None):
        self.enabled = False
        self.max_events = (
            max_events_from_env() if max_events is None else max_events
        )
        self._lock = threading.Lock()
        self._events: List[Dict[str, Any]] = []
        self.dropped = 0
        self._tls = threading.local()
        self._epoch = time.perf_counter()
        self._thread_ids: Dict[str, int] = {}

    # -- lifecycle ------------------------------------------------------
    def enable(self) -> "Tracer":
        with self._lock:
            self._events.clear()
            self.dropped = 0
            self._thread_ids.clear()
        # Fresh thread-local stacks AND root occurrence counters, so two
        # enable()+identical-run cycles produce identical span ids (the
        # determinism contract).
        self._tls = threading.local()
        self._epoch = time.perf_counter()
        self.enabled = True
        return self

    def disable(self) -> None:
        self.enabled = False

    def reset(self) -> None:
        with self._lock:
            self._events.clear()
            self.dropped = 0
            self._thread_ids.clear()
        self._tls = threading.local()
        self._epoch = time.perf_counter()

    # -- thread-local span stack ---------------------------------------
    def _stack(self) -> list:
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = []
            self._tls.stack = stack
            self._tls.root_counts = {}
        return stack

    def _thread_key(self) -> str:
        name = threading.current_thread().name
        return "main" if name == "MainThread" else name

    def _tid(self, key: str) -> int:
        with self._lock:
            tid = self._thread_ids.get(key)
            if tid is None:
                tid = len(self._thread_ids) + 1
                self._thread_ids[key] = tid
        return tid

    def _push(self, name: str, args: Dict[str, Any]) -> _Span:
        stack = self._stack()
        if stack:
            sid = stack[-1].child_sid(name)
            parent = stack[-1].sid
        else:
            counts = self._tls.root_counts
            idx = counts.get(name, 0)
            counts[name] = idx + 1
            sid = f"{self._thread_key()}:{name}#{idx}"
            parent = None
        span = _Span(name, sid, parent)
        span.args.update(args)
        span.t0 = time.perf_counter()
        stack.append(span)
        return span

    def _pop(self, span: Optional[_Span]) -> None:
        t1 = time.perf_counter()
        stack = self._stack()
        # Pop down TO the span (an unbalanced inner exit never corrupts
        # outer spans; stranded frames close with their parent).
        while stack:
            top = stack.pop()
            if top is span:
                break
        if span is None:
            return
        self._record(
            {
                "ph": "X",
                "name": span.name,
                "sid": span.sid,
                "parent": span.parent_sid,
                "ts_us": (span.t0 - self._epoch) * 1e6,
                "dur_us": (t1 - span.t0) * 1e6,
                "thread": self._thread_key(),
                "args": span.args,
            }
        )
        from fastapriori_tpu.obs import flight

        flight.note(
            "span", name=span.name, sid=span.sid,
            dur_ms=round((t1 - span.t0) * 1e3, 3), **span.args,
        )

    def _record(self, event: Dict[str, Any]) -> None:
        with self._lock:
            if len(self._events) >= self.max_events:
                self.dropped += 1
                return
            self._events.append(event)

    # -- public emit API ------------------------------------------------
    def span(self, name: str, **args: Any):
        """Open a nested span (context manager).  Disabled: one branch,
        the shared no-op instance — near-zero cost."""
        if not self.enabled:
            return _NOOP
        return _SpanCtx(self, name, args)

    def instant(self, name: str, **args: Any) -> None:
        """A point-in-time event under the current span scope."""
        if not self.enabled:
            return
        stack = self._stack()
        self._record(
            {
                "ph": "i",
                "name": name,
                "sid": None,
                "parent": stack[-1].sid if stack else None,
                "ts_us": (time.perf_counter() - self._epoch) * 1e6,
                "thread": self._thread_key(),
                "args": args,
            }
        )

    def counter(self, name: str, **values: Any) -> None:
        """A Chrome counter event (rendered as a track in Perfetto) —
        collective bytes, queue depth, shed counts."""
        if not self.enabled:
            return
        self._record(
            {
                "ph": "C",
                "name": name,
                "sid": None,
                "parent": None,
                "ts_us": (time.perf_counter() - self._epoch) * 1e6,
                "thread": self._thread_key(),
                "args": values,
            }
        )

    def annotate(self, **args: Any) -> None:
        """Attach attributes to the CURRENT innermost span (retry
        counts, watchdog trips — the annotation form the reliability
        layer uses where it has no span handle)."""
        if not self.enabled:
            return
        stack = self._stack()
        if stack:
            stack[-1].args.update(args)

    # -- inspection / export -------------------------------------------
    def events(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [dict(e) for e in self._events]

    def span_tree(self) -> List[tuple]:
        """The deterministic structure: sorted ``(sid, name, parent)``
        for every completed span — two identical seeded runs produce
        equal trees (timestamps excluded by construction)."""
        with self._lock:
            return sorted(
                (e["sid"], e["name"], e["parent"])
                for e in self._events
                if e["ph"] == "X"
            )

    def chrome_trace(self) -> Dict[str, Any]:
        """The export form: Chrome trace-event JSON (Perfetto loads it
        directly).  Threads map to stable small tids in first-span
        order, named via ``thread_name`` metadata events."""
        events = self.events()
        out: List[Dict[str, Any]] = [
            {
                "ph": "M",
                "name": "process_name",
                "pid": 1,
                "tid": 0,
                "args": {"name": "fastapriori_tpu"},
            }
        ]
        threads: Dict[str, int] = {}
        for e in events:
            key = e["thread"]
            if key not in threads:
                threads[key] = len(threads) + 1
                out.append(
                    {
                        "ph": "M",
                        "name": "thread_name",
                        "pid": 1,
                        "tid": threads[key],
                        "args": {"name": key},
                    }
                )
        for e in events:
            ev: Dict[str, Any] = {
                "ph": e["ph"],
                "name": e["name"],
                "cat": e["name"].split(".")[0].split(":")[0],
                "pid": 1,
                "tid": threads[e["thread"]],
                "ts": round(e["ts_us"], 3),
                "args": dict(e["args"]),
            }
            if e["ph"] == "X":
                ev["dur"] = round(e["dur_us"], 3)
                ev["args"]["sid"] = e["sid"]
            if e["ph"] == "i":
                ev["s"] = "t"
            out.append(ev)
        return {
            "traceEvents": out,
            "displayTimeUnit": "ms",
            "otherData": {"dropped_events": self.dropped},
        }

    def export(self, path: str, manifest: Optional[dict] = None) -> str:
        """Write the Chrome trace JSON through the crash-safe committer
        (atomic tmp+fsync+rename; ``write.trace`` failpoint site), so a
        killed export never leaves a torn trace under the final name."""
        from fastapriori_tpu.io.writer import write_artifact_bytes

        body = json.dumps(self.chrome_trace()) + "\n"
        return write_artifact_bytes(
            path, [body.encode("utf-8")], "trace", manifest
        )


def validate_chrome_trace(obj) -> List[str]:
    """Schema problems in a Chrome-trace-event JSON object (empty list =
    Perfetto-loadable shape).  Shared by tests/test_obs.py and
    tools/obs_smoke.py so the artifact contract is checked by ONE
    definition."""
    problems: List[str] = []
    if not isinstance(obj, dict) or "traceEvents" not in obj:
        return ["top level must be an object with a traceEvents array"]
    events = obj["traceEvents"]
    if not isinstance(events, list) or not events:
        return ["traceEvents must be a non-empty array"]
    for i, e in enumerate(events):
        if not isinstance(e, dict):
            problems.append(f"event {i}: not an object")
            continue
        ph = e.get("ph")
        if ph not in ("X", "i", "C", "M"):
            problems.append(f"event {i}: unknown ph {ph!r}")
            continue
        if not isinstance(e.get("name"), str) or not e["name"]:
            problems.append(f"event {i}: missing name")
        if not isinstance(e.get("pid"), int) or not isinstance(
            e.get("tid"), int
        ):
            problems.append(f"event {i}: pid/tid must be ints")
        if ph != "M":
            ts = e.get("ts")
            if not isinstance(ts, (int, float)) or ts < 0:
                problems.append(f"event {i}: bad ts {ts!r}")
        if ph == "X":
            dur = e.get("dur")
            if not isinstance(dur, (int, float)) or dur < 0:
                problems.append(f"event {i}: bad dur {dur!r}")
            if not isinstance(
                e.get("args", {}).get("sid"), str
            ):
                problems.append(f"event {i}: span missing sid")
        if "args" in e and not isinstance(e["args"], dict):
            problems.append(f"event {i}: args must be an object")
    return problems


TRACER = Tracer()


def span(name: str, **args: Any):
    return TRACER.span(name, **args)


def instant(name: str, **args: Any) -> None:
    TRACER.instant(name, **args)


def counter(name: str, **values: Any) -> None:
    TRACER.counter(name, **values)


def annotate(**args: Any) -> None:
    TRACER.annotate(**args)


_env_memo: Optional[bool] = None


def enabled_by_env() -> bool:
    """The strict ``FA_TRACE`` knob: ``1`` enables span recording
    process-wide (the CLI ``--trace PATH`` flags additionally export);
    a typo'd value raises InputError — the FA_NO_PALLAS contract.
    Parsed once per process; tests use :func:`reload_from_env`."""
    global _env_memo
    if _env_memo is None:
        from fastapriori_tpu.utils.env import env_flag

        _env_memo = env_flag("FA_TRACE", False)
    return _env_memo


def reload_from_env() -> None:
    global _env_memo, _max_events_memo
    _env_memo = None
    _max_events_memo = None
    TRACER.max_events = max_events_from_env()


def maybe_enable(explicit: bool = False) -> bool:
    """Enable the global tracer when ``explicit`` (a ``--trace`` flag)
    or ``FA_TRACE`` asks for it; returns the resulting enabled state."""
    if explicit or enabled_by_env():
        TRACER.enable()
    return TRACER.enabled
