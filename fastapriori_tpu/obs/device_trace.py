"""Device-internal kernel trace attribution (ISSUE 18 tentpole, part c).

The span tracer (:mod:`fastapriori_tpu.obs.trace`) sees *host-side*
wall time: a ``vlevel`` span covers dispatch + device execution + sync
without saying which kernel burned the time.  This module adds the
device-internal view: a bracketing helper around
``jax.profiler.start_trace`` / ``stop_trace`` that captures an XLA
device trace (Perfetto-loadable), plus a stdlib-only parser that
aggregates per-kernel device durations out of the captured artifact —
the evidence the bench ``--engine-compare`` pallas row cites.

Contracts:

- ``obs`` stays stdlib-only at *import* (the package docstring's
  promise): jax is imported lazily inside :func:`capture`, never at
  module scope.  :func:`kernel_summary` is pure stdlib (gzip + json).
- Capture NEVER crashes the run it observes.  Any profiler failure
  (unsupported platform, double-start, missing deps) is swallowed into
  a once-keyed ``device_trace_unavailable`` ledger event and the run
  proceeds untraced — same posture as the Pallas tier itself.
- The strict ``FA_DEVICE_TRACE`` knob (``1`` enables capture where the
  caller passes ``explicit=False``) follows the FA_NO_PALLAS contract:
  a typo'd value raises InputError rather than silently disabling.

Interpreter-mode caveat (mirrors ops/pallas_vertical.py): on CPU the
profiler traces the *interpreted or XLA:CPU* program, so per-kernel
rows attribute host execution, not TPU VMEM behaviour.  Rows are still
useful as structural evidence (which kernels ran, how many launches);
wall-time claims belong to real-chip captures only.
"""

from __future__ import annotations

import glob
import gzip
import json
import os
from contextlib import contextmanager
from typing import Any, Dict, Iterator, Optional

_env_memo: Optional[bool] = None


def enabled_by_env() -> bool:
    """The strict ``FA_DEVICE_TRACE`` knob, parsed once per process
    (tests use :func:`reload_from_env`)."""
    global _env_memo
    if _env_memo is None:
        from fastapriori_tpu.utils.env import env_flag

        _env_memo = env_flag("FA_DEVICE_TRACE", False)
    return _env_memo


def reload_from_env() -> None:
    global _env_memo
    _env_memo = None


@contextmanager
def capture(logdir: str, explicit: bool = False) -> Iterator[Dict[str, Any]]:
    """Bracket a region with an XLA device-trace capture into ``logdir``.

    Yields a mutable info dict; after the block exits it carries
    ``active`` (whether a capture actually ran) and, when active,
    ``trace_dir``.  When neither ``explicit`` nor ``FA_DEVICE_TRACE``
    asks for capture, the body runs untraced at zero cost.  Profiler
    errors are ledger-recorded (once per process per phase), never
    raised: the traced computation must not die for its observer.
    """
    info: Dict[str, Any] = {"active": False, "trace_dir": logdir}
    if not (explicit or enabled_by_env()):
        yield info
        return
    from fastapriori_tpu.reliability import ledger

    started = False
    try:
        import jax

        # create_perfetto_trace asks XLA to emit the merged
        # perfetto_trace.json.gz beside the per-host protobuf dumps —
        # the one artifact kernel_summary() can read with stdlib gzip.
        jax.profiler.start_trace(logdir, create_perfetto_trace=True)
        started = True
    except Exception as exc:  # lint: waive G006 -- observer must not kill the traced run; failure is ledgered once-keyed and the run proceeds untraced
        ledger.record(
            "device_trace_unavailable",
            once_key="start",
            phase="start",
            error=f"{type(exc).__name__}: {exc}",
        )
    try:
        yield info
    finally:
        if started:
            try:
                import jax

                jax.profiler.stop_trace()
                info["active"] = True
            except Exception as exc:  # lint: waive G006 -- stop_trace failure on an already-running mine: ledgered, never raised
                ledger.record(
                    "device_trace_unavailable",
                    once_key="stop",
                    phase="stop",
                    error=f"{type(exc).__name__}: {exc}",
                )


def find_perfetto_trace(trace_dir: str) -> Optional[str]:
    """Locate the ``perfetto_trace.json.gz`` a capture left under
    ``trace_dir`` (the profiler nests it in a timestamped run dir)."""
    pattern = os.path.join(
        trace_dir, "**", "perfetto_trace.json.gz"
    )
    hits = sorted(glob.glob(pattern, recursive=True))
    return hits[-1] if hits else None


# Raw kernel/event substrings -> host-side span stage labels (ISSUE 19
# satellite): XLA mangles program names, but the mangled forms keep
# recognizable fragments of the operations each serving/mining stage
# dispatches.  ORDERED — first match wins, so the specific fragments
# (the Pallas kernel symbols) precede the generic ones.  Unmatched
# kernels map to "other": attribution must never silently drop device
# time, a whole-stage gap would misread as pipeline overlap.
STAGE_PATTERNS = (
    ("strided_best_rank", "serve.scan"),   # serving Pallas match kernel
    ("first_match", "serve.scan"),          # XLA serving scan program
    ("serve", "serve.scan"),
    ("vertical_kernel", "mine.count"),      # Pallas popcount kernel
    ("vertical", "mine.count"),
    ("count", "mine.count"),
    ("contain", "rules.join"),
    ("rule", "rules.join"),
    ("gather", "serve.scan"),               # decode/gather of scan hits
    ("convert", "xfer"),
    ("copy", "xfer"),
    ("transfer", "xfer"),
)


def stage_for_kernel(name: str) -> str:
    """Map one raw (possibly mangled) kernel event name onto the span
    stage label its dispatch site owns — the first matching substring
    in :data:`STAGE_PATTERNS` wins, ``"other"`` otherwise."""
    low = name.lower()
    for frag, stage in STAGE_PATTERNS:
        if frag in low:
            return stage
    return "other"


def kernel_summary(trace_dir: str, top: int = 0) -> Dict[str, Any]:
    """Aggregate per-kernel device durations from a captured trace.

    Pure stdlib: gunzips the Perfetto/Chrome-trace JSON and sums the
    complete-event (``ph == "X"``) durations by event name.  Returns
    ``{"trace": path-or-None, "kernels": [{name, stage, calls,
    total_us}...], "by_stage": {stage: total_us}}`` sorted by total
    time descending (``top`` truncates the kernel rows when > 0; the
    stage aggregate always covers every event), each kernel mapped
    back onto its host span stage via :func:`stage_for_kernel` so
    ``--engine-compare`` attributes device time per STAGE, not per
    mangled name.  Missing or malformed traces yield an empty kernel
    list, never an exception — the summary rides in bench artifacts
    where a parse error must not sink the whole record.
    """
    path = find_perfetto_trace(trace_dir)
    out: Dict[str, Any] = {"trace": path, "kernels": []}
    if path is None:
        return out
    try:
        with gzip.open(path, "rt", encoding="utf-8", errors="replace") as fh:
            doc = json.load(fh)
    except Exception:  # lint: waive G006 -- malformed trace artifact summarizes as empty; a parse error must not sink the bench record
        return out
    events = doc.get("traceEvents", []) if isinstance(doc, dict) else []
    agg: Dict[str, Dict[str, float]] = {}
    for ev in events:
        if not isinstance(ev, dict) or ev.get("ph") != "X":
            continue
        name = ev.get("name")
        dur = ev.get("dur")
        if not isinstance(name, str) or not isinstance(dur, (int, float)):
            continue
        slot = agg.setdefault(name, {"calls": 0, "total_us": 0.0})
        slot["calls"] += 1
        slot["total_us"] += float(dur)
    rows = [
        {
            "name": k,
            "stage": stage_for_kernel(k),
            "calls": int(v["calls"]),
            "total_us": v["total_us"],
        }
        for k, v in agg.items()
    ]
    rows.sort(key=lambda r: (-r["total_us"], r["name"]))
    by_stage: Dict[str, float] = {}
    for r in rows:
        by_stage[r["stage"]] = by_stage.get(r["stage"], 0.0) + r["total_us"]
    if top > 0:
        rows = rows[:top]
    out["kernels"] = rows
    out["by_stage"] = dict(
        sorted(by_stage.items(), key=lambda kv: (-kv[1], kv[0]))
    )
    return out
