"""Host-side preprocessing: frequent-item discovery and transaction
compression (reference components C3/C4/C10, SURVEY.md §2).

The reference runs these as Spark shuffle passes (FastApriori.scala:52-85,
AssociationRules.scala:33-64).  On TPU the mining kernels want a dense
weighted bitmap, so preprocessing runs on the host and produces:

- ``freq_items``: item strings sorted by descending occurrence count
  (rank 0 = most frequent — FastApriori.scala:60-62);
- ``item_counts``: occurrence counts aligned to rank.  Occurrences, not
  transaction support: the reference counts via ``flatMap(_.map((_,1)))``
  (FastApriori.scala:55) so duplicates *within* a line each count;
- deduplicated baskets with multiplicity weights (FastApriori.scala:66-79)
  in CSR form: per transaction, keep frequent items, map to ranks, drop
  baskets of size <= 1, merge identical baskets into one row with an int32
  weight.

Two interchangeable engines: the pure-Python/numpy path below, and the
native C++ one-pass scanner (fastapriori_tpu/native) used automatically for
large inputs when built — equality is enforced by tests/test_native.py.
"""

from __future__ import annotations

import dataclasses
import math
from collections import Counter
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from fastapriori_tpu.errors import InputError
from fastapriori_tpu.io.reader import JAVA_WS
from fastapriori_tpu.utils.order import item_sort_key


@dataclasses.dataclass
class ShardInfo:
    """Present when a CompressedData holds one PROCESS's shard of the
    transactions (multi-host sharded ingest, :func:`preprocess_file_sharded`):
    the basket CSR covers only this process's byte range of D.dat, while
    every scalar/table field (n_raw, min_count, freq_items, item_counts)
    is GLOBAL.  Identical baskets in different shards stay separate rows
    with their own multiplicities — weighted support counts are identical
    with or without cross-shard dedup."""

    process_id: int
    num_processes: int
    local_counts: List[int]  # distinct-basket count per process
    max_weight: int  # GLOBAL max multiplicity (uniform digit count)

    @property
    def global_count(self) -> int:
        return sum(self.local_counts)


@dataclasses.dataclass
class CompressedData:
    """Output of phase 1 preprocessing — the miner's entire input.

    Baskets are stored CSR-style: ``basket_indices`` holds the sorted item
    ranks of every basket back-to-back; basket ``i`` spans
    ``basket_indices[basket_offsets[i]:basket_offsets[i+1]]``.

    Row-granularity note: rows are deduplicated WITHIN the producing
    ingest unit — globally for the plain in-memory/whole-file paths, per
    byte-range block for the pipelined and multi-host sharded ingests
    (models/apriori.py) — so identical baskets from different blocks may
    appear as separate weighted rows.  Every weighted count (and
    therefore all mining output) is identical either way; only
    ``total_count``, row order, and per-row weights are
    representation-dependent.  Consumers must treat rows as a weighted
    multiset, not as globally distinct baskets."""

    n_raw: int  # raw transaction count N (FastApriori.scala:38)
    min_count: int  # ceil(minSupport * N)   (FastApriori.scala:39)
    freq_items: List[str]  # rank -> item string
    item_to_rank: Dict[str, int]
    item_counts: np.ndarray  # int64[F] occurrence counts by rank
    basket_indices: np.ndarray  # int32[nnz] flattened sorted ranks
    basket_offsets: np.ndarray  # int64[T'+1]
    weights: np.ndarray  # int32[T'] multiplicities
    shard: Optional[ShardInfo] = None  # multi-host sharded ingest

    @property
    def num_items(self) -> int:
        return len(self.freq_items)

    @property
    def total_count(self) -> int:  # T' (FastApriori.scala:79)
        return len(self.weights)

    @property
    def baskets(self) -> List[np.ndarray]:
        """Ragged view (one array per basket); prefer the CSR fields."""
        if self.total_count > 0 and len(self.basket_offsets) != (
            self.total_count + 1
        ):
            raise InputError(
                "CompressedData carries no basket CSR (produced by the "
                "pipelined capture ingest with retain_csr=False); "
                "re-ingest with retain_csr=True to read baskets"
            )
        return [
            self.basket_indices[self.basket_offsets[i] : self.basket_offsets[i + 1]]
            for i in range(self.total_count)
        ]


def count_item_occurrences(
    transactions: Sequence[Sequence[str]],
) -> Counter:
    """C3 first half (FastApriori.scala:55-56): global occurrence counts."""
    counts: Counter = Counter()
    for t in transactions:
        counts.update(t)
    return counts


def build_rank_map(
    counts: Counter, min_count: int
) -> Tuple[List[str], Dict[str, int], np.ndarray]:
    """C3 second half (FastApriori.scala:57-62): threshold, sort by
    descending count (deterministic tie-break — utils/order.py), dense
    ranks."""
    freq = [(i, c) for i, c in counts.items() if c >= min_count]
    freq.sort(key=item_sort_key)
    freq_items = [i for i, _ in freq]
    item_counts = np.asarray([c for _, c in freq], dtype=np.int64)
    item_to_rank = {item: r for r, item in enumerate(freq_items)}
    return freq_items, item_to_rank, item_counts


def dedup_baskets(
    transactions: Sequence[Sequence[str]],
    item_to_rank: Dict[str, int],
    min_size: int = 2,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """C4 (FastApriori.scala:66-79): filter to frequent items, rank-map,
    ``toSet`` dedupe within a line, drop baskets smaller than ``min_size``,
    merge identical baskets with multiplicity.  Returns CSR
    ``(indices, offsets, weights)`` with baskets in first-seen order."""
    mult: Dict[Tuple[int, ...], int] = {}
    for t in transactions:
        ranks = {item_to_rank[i] for i in t if i in item_to_rank}
        if len(ranks) < min_size:
            continue
        key = tuple(sorted(ranks))
        mult[key] = mult.get(key, 0) + 1
    offsets = np.zeros(len(mult) + 1, dtype=np.int64)
    sizes = [len(k) for k in mult.keys()]
    offsets[1:] = np.cumsum(sizes, dtype=np.int64) if sizes else 0
    indices = (
        np.concatenate([np.asarray(k, dtype=np.int32) for k in mult.keys()])
        if mult
        else np.empty(0, dtype=np.int32)
    )
    weights = np.fromiter(mult.values(), dtype=np.int32, count=len(mult))
    return indices, offsets, weights


def _python_preprocess(
    transactions: Sequence[Sequence[str]], min_support: float
) -> CompressedData:
    n_raw = len(transactions)
    min_count = int(math.ceil(min_support * n_raw))
    counts = count_item_occurrences(transactions)
    freq_items, item_to_rank, item_counts = build_rank_map(counts, min_count)
    indices, offsets, weights = dedup_baskets(transactions, item_to_rank)
    return CompressedData(
        n_raw=n_raw,
        min_count=min_count,
        freq_items=freq_items,
        item_to_rank=item_to_rank,
        item_counts=item_counts,
        basket_indices=indices,
        basket_offsets=offsets,
        weights=weights,
    )


def _native_result_to_data(result) -> CompressedData:
    n_raw, min_count, freq_items, item_counts, indices, offsets, weights = (
        result
    )
    return CompressedData(
        n_raw=n_raw,
        min_count=min_count,
        freq_items=freq_items,
        item_to_rank={item: r for r, item in enumerate(freq_items)},
        item_counts=item_counts,
        basket_indices=indices,
        basket_offsets=offsets,
        weights=weights,
    )


def _use_native(native: Optional[bool], size_hint: int) -> bool:
    if native is False:
        return False
    from fastapriori_tpu.native import native_available

    available = native_available()
    if native is True:
        if not available:
            raise InputError(
                "native preprocessing requested but the extension is not "
                "built; run `make -C fastapriori_tpu/native`"
            )
        return True
    return available and size_hint >= 50_000


def ingest_thread_count(configured: Optional[int]) -> int:
    """Host threads for the pipelined ingest (pass-1 segmented scan +
    pass-2 block replay, native/preprocess.cc): the ``FA_INGEST_THREADS``
    env knob overrides the config, which overrides one-per-core.
    Strictly parsed like FA_NO_PALLAS — a typo'd value is an InputError,
    not a silent serial ingest."""
    import os

    raw = os.environ.get("FA_INGEST_THREADS", "").strip()
    if raw:
        try:
            n = int(raw)
        except ValueError:
            n = 0
        if n < 1:
            from fastapriori_tpu.errors import InputError

            raise InputError(
                f"unrecognized FA_INGEST_THREADS value {raw!r}: expected "
                "a positive integer (unset = one thread per core)"
            )
        return n
    if configured:
        return configured
    return os.cpu_count() or 1


def preprocess(
    transactions: Sequence[Sequence[str]],
    min_support: float,
    native: Optional[bool] = None,
) -> CompressedData:
    """Full phase-1 preprocessing (mirrors genFreqItems,
    FastApriori.scala:46-86) from already-tokenized lines.

    ``native``: force (True) or forbid (False) the C++ fast path; None
    auto-selects it when the extension is built and the input is large.
    """
    if _use_native(native, len(transactions)) and _tokens_serialize_exactly(
        transactions
    ):
        from fastapriori_tpu.native.loader import (
            join_transactions,
            preprocess_buffer,
        )

        return _native_result_to_data(
            preprocess_buffer(join_transactions(transactions), min_support)
        )
    return _python_preprocess(transactions, min_support)


def _tokens_serialize_exactly(transactions) -> bool:
    """True iff re-serializing the token lists for the native byte
    scanner round-trips exactly: a token whose FIRST or LAST char is
    <= 0x20 (e.g. a bare "\\x01" token from a "7 \\x01 8" line) would be
    eaten by the scanner's Java-trim at a line edge or glued to a
    neighbor, and a token containing Java \\s ANYWHERE (e.g. "a b",
    only possible via the public transactions= API — the tokenizer
    itself splits on \\s) would be re-split into different items.
    Interior control chars that are not Java \\s are safe to keep.
    Such tokens route to the Python path instead; file inputs
    (preprocess_file) scan the raw bytes and never re-serialize.  An
    empty token is safe only as a line's SOLE token (the empty-line
    form, which serializes to an empty line); a ZERO-token line has no
    serialized form at all and routes to the Python path."""
    return all(
        (len(line) == 1 and line[0] == "")
        or (
            bool(line)
            and all(
                t
                and t[0] > "\x20"
                and t[-1] > "\x20"
                and JAVA_WS.isdisjoint(t)
                for t in line
            )
        )
        for line in transactions
    )


def preprocess_file(
    path: str, min_support: float, native: Optional[bool] = None
) -> CompressedData:
    """Phase-1 preprocessing straight from a ``D.dat`` file — the native
    path parses the raw bytes without ever materializing Python token
    lists (the reference's ingest+first-shuffle, Utils.scala:21 +
    FastApriori.scala:52-85, as one C++ scan)."""
    if _use_native(native, 1 << 62):  # file path: prefer native when built
        from fastapriori_tpu.native.loader import preprocess_file as nat_file

        return _native_result_to_data(nat_file(path, min_support))
    from fastapriori_tpu.io.reader import read_dat

    return _python_preprocess(read_dat(path), min_support)


def dedup_user_baskets(
    user_lines: Sequence[Sequence[str]], item_to_rank: Dict[str, int]
) -> Tuple[List[np.ndarray], List[List[int]], List[int]]:
    """C10 (AssociationRules.scala:33-64): filter users to frequent items,
    dedupe identical baskets keeping the original row indexes per distinct
    basket; empty baskets are returned separately (they recommend "0"
    immediately — AssociationRules.scala:49).

    Returns (distinct baskets, per-basket original row-index lists,
    empty-row indexes)."""
    index_map: Dict[Tuple[int, ...], List[int]] = {}
    order: List[Tuple[int, ...]] = []
    empty: List[int] = []
    for idx, line in enumerate(user_lines):
        ranks = {item_to_rank[i] for i in line if i in item_to_rank}
        if not ranks:
            empty.append(idx)
            continue
        key = tuple(sorted(ranks))
        if key in index_map:
            index_map[key].append(idx)
        else:
            index_map[key] = [idx]
            order.append(key)
    baskets = [np.asarray(k, dtype=np.int32) for k in order]
    indexes = [index_map[k] for k in order]
    return baskets, indexes, empty


# ----------------------------------------------------------------------
# Multi-host sharded ingest (the distributed analog of the reference's
# C3/C4 Spark passes, FastApriori.scala:52-85): each PROCESS reads and
# compresses only its own byte range of D.dat; only the tiny per-token
# count tables cross hosts (parallel/mesh.py allgather_bytes).  Identical
# baskets in different shards stay separate rows with their own
# multiplicities — weighted support counts are unchanged, so cross-shard
# dedup is unnecessary for correctness.


def shard_byte_range(size: int, idx: int, n: int) -> Tuple[int, int]:
    """Nominal byte range for shard ``idx`` of ``n``; the reader aligns
    the start forward to the first line beginning at/after it (shard 0
    starts at 0), and reads through the end of the line straddling the
    nominal end — every line lands in exactly one shard."""
    return (size * idx) // n, (size * (idx + 1)) // n


def split_buffer_ranges(data: bytes, n: int) -> List[Tuple[int, int]]:
    """Partition an in-memory buffer into ``n`` line-aligned byte ranges
    — the same alignment rule as :func:`read_shard` (start aligned
    forward past the straddling line, which the previous range owns), so
    the ranges cover every line exactly once.  Used by the pipelined
    single-host ingest to overlap per-block compression with the
    device upload."""
    size = len(data)
    cuts = [0]
    for i in range(1, n):
        b = (size * i) // n
        prev = cuts[-1]
        if b <= prev:
            cuts.append(prev)
            continue
        if data[b - 1 : b] == b"\n":
            cuts.append(b)
        else:
            j = data.find(b"\n", b)
            cuts.append(size if j < 0 else j + 1)
    cuts.append(size)
    # cuts is non-decreasing by construction (a find() past a later
    # nominal boundary makes that later range empty — harmless, the
    # line belongs to the earlier range).
    return list(zip(cuts[:-1], cuts[1:]))


def _open_ranged(path: str):
    """``(binary file handle, total size)`` — fsspec for remote URLs, so
    a multi-host run can byte-range-shard a remote ``D.dat`` (the
    reference read its input off HDFS, Utils.scala:21; each process here
    seeks/reads ONLY its own range, never the whole object)."""
    if "://" in path:
        from fastapriori_tpu.io.reader import _require_fsspec

        fs, rpath = _require_fsspec(path).core.url_to_fs(path)
        return fs.open(rpath, "rb"), fs.size(rpath)
    import os

    return open(path, "rb"), os.path.getsize(path)


def read_shard(path: str, idx: int, n: int) -> bytes:
    """Read shard ``idx``'s lines (see :func:`shard_byte_range`)."""
    fh, size = _open_ranged(path)
    lo, hi = shard_byte_range(size, idx, n)
    with fh:
        if lo > 0:
            # Align forward: skip the partial line the previous shard owns.
            fh.seek(lo - 1)
            prev = fh.read(1)
            if prev != b"\n":
                fh.readline()
            lo = fh.tell()
        else:
            fh.seek(0)
        data = fh.read(max(hi - lo, 0))
        if not data:
            return b""
        # Extend through the end of the straddling line.
        if not data.endswith(b"\n"):
            data += fh.readline()
        return data


def preprocess_file_sharded(
    path: str,
    min_support: float,
    process_id: Optional[int] = None,
    num_processes: Optional[int] = None,
    allgather=None,
) -> CompressedData:
    """Phase-1 preprocessing of THIS process's shard of ``D.dat`` against
    globally merged item counts.  Every process must call this (SPMD);
    the returned CompressedData carries global tables + local baskets and
    a :class:`ShardInfo` the mining engine uses to build its slice of the
    global bitmap (``jax.make_array_from_process_local_data``).

    ``process_id``/``num_processes``/``allgather`` default to the live
    ``jax.distributed`` world; tests inject their own to exercise the
    logic without multiple processes."""
    import pickle

    if allgather is None:
        from fastapriori_tpu.parallel.mesh import allgather_bytes as allgather
    if process_id is None or num_processes is None:
        import jax

        process_id = jax.process_index()
        num_processes = jax.process_count()

    from fastapriori_tpu.native.loader import (
        compress_with_ranks,
        count_buffer,
    )

    data = read_shard(path, process_id, num_processes)
    n_lines, tokens, counts = count_buffer(data)

    # Merge the per-process count tables (all tiny next to the data).
    blobs = allgather(
        pickle.dumps((n_lines, tokens, counts), protocol=4)
    )
    assert len(blobs) == num_processes, (len(blobs), num_processes)
    merged: Dict[str, int] = {}
    n_raw = 0
    for blob in blobs:
        nl, toks, cnts = pickle.loads(blob)
        n_raw += nl
        for tok, c in zip(toks, cnts.tolist()):
            merged[tok] = merged.get(tok, 0) + c
    min_count = math.ceil(min_support * n_raw)
    # Identical global ranks on every process: same sort key as the
    # single-host paths (utils/order.py — deterministic tie-break).
    freq = [(t, c) for t, c in merged.items() if c >= min_count]
    freq.sort(key=item_sort_key)
    freq_items = [t for t, _ in freq]
    item_counts = np.array([c for _, c in freq], dtype=np.int64)

    _, indices, offsets, weights = compress_with_ranks(data, freq_items)

    # Per-process distinct-basket counts + global max weight (uniform
    # padding and digit count across processes).
    local_blob = pickle.dumps(
        (len(weights), int(weights.max()) if len(weights) else 1),
        protocol=4,
    )
    local_counts: List[int] = []
    max_w = 1
    for blob in allgather(local_blob):
        t_loc, w_loc = pickle.loads(blob)
        local_counts.append(t_loc)
        max_w = max(max_w, w_loc)

    return CompressedData(
        n_raw=n_raw,
        min_count=min_count,
        freq_items=freq_items,
        item_to_rank={item: r for r, item in enumerate(freq_items)},
        item_counts=item_counts,
        basket_indices=indices,
        basket_offsets=offsets,
        weights=weights,
        shard=ShardInfo(
            process_id=process_id,
            num_processes=num_processes,
            local_counts=local_counts,
            max_weight=max_w,
        ),
    )
