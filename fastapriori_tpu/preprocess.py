"""Host-side preprocessing: frequent-item discovery and transaction
compression (reference components C3/C4/C10, SURVEY.md §2).

The reference runs these as Spark shuffle passes (FastApriori.scala:52-85,
AssociationRules.scala:33-64).  On TPU the mining kernels want a dense
weighted bitmap, so preprocessing runs on the host (numpy + dict hashing;
a native C++ fast path lives in fastapriori_tpu/native) and produces:

- ``freq_items``: item strings sorted by descending occurrence count
  (rank 0 = most frequent — FastApriori.scala:60-62);
- ``item_counts``: occurrence counts aligned to rank.  Occurrences, not
  transaction support: the reference counts via ``flatMap(_.map((_,1)))``
  (FastApriori.scala:55) so duplicates *within* a line each count;
- deduplicated baskets with multiplicity weights (FastApriori.scala:66-79):
  per transaction, keep frequent items, map to ranks, drop baskets of size
  <= 1, merge identical baskets into one row with an int32 weight.
"""

from __future__ import annotations

import dataclasses
import math
from collections import Counter
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from fastapriori_tpu.utils.order import item_sort_key


@dataclasses.dataclass
class CompressedData:
    """Output of phase 1 preprocessing — the miner's entire input."""

    n_raw: int  # raw transaction count N (FastApriori.scala:38)
    min_count: int  # ceil(minSupport * N)   (FastApriori.scala:39)
    freq_items: List[str]  # rank -> item string
    item_to_rank: Dict[str, int]
    item_counts: np.ndarray  # int64[F] occurrence counts by rank
    baskets: List[np.ndarray]  # T' ragged rows of sorted ranks, len >= 2
    weights: np.ndarray  # int32[T'] multiplicities

    @property
    def num_items(self) -> int:
        return len(self.freq_items)

    @property
    def total_count(self) -> int:  # T' (FastApriori.scala:79)
        return len(self.baskets)


def count_item_occurrences(
    transactions: Sequence[Sequence[str]],
) -> Counter:
    """C3 first half (FastApriori.scala:55-56): global occurrence counts."""
    counts: Counter = Counter()
    for t in transactions:
        counts.update(t)
    return counts


def build_rank_map(
    counts: Counter, min_count: int
) -> Tuple[List[str], Dict[str, int], np.ndarray]:
    """C3 second half (FastApriori.scala:57-62): threshold, sort by
    descending count (deterministic tie-break — utils/order.py), dense
    ranks."""
    freq = [(i, c) for i, c in counts.items() if c >= min_count]
    freq.sort(key=item_sort_key)
    freq_items = [i for i, _ in freq]
    item_counts = np.asarray([c for _, c in freq], dtype=np.int64)
    item_to_rank = {item: r for r, item in enumerate(freq_items)}
    return freq_items, item_to_rank, item_counts


def dedup_baskets(
    transactions: Sequence[Sequence[str]],
    item_to_rank: Dict[str, int],
    min_size: int = 2,
) -> Tuple[List[np.ndarray], np.ndarray]:
    """C4 (FastApriori.scala:66-79): filter to frequent items, rank-map,
    ``toSet`` dedupe within a line, drop baskets smaller than ``min_size``,
    merge identical baskets with multiplicity.  Basket identity is the
    sorted rank tuple.  Returns (baskets in first-seen order, weights)."""
    mult: Dict[Tuple[int, ...], int] = {}
    for t in transactions:
        ranks = {item_to_rank[i] for i in t if i in item_to_rank}
        if len(ranks) < min_size:
            continue
        key = tuple(sorted(ranks))
        mult[key] = mult.get(key, 0) + 1
    baskets = [np.asarray(k, dtype=np.int32) for k in mult.keys()]
    weights = np.asarray(list(mult.values()), dtype=np.int32)
    return baskets, weights


def preprocess(
    transactions: Sequence[Sequence[str]],
    min_support: float,
    native: Optional[bool] = None,
) -> CompressedData:
    """Full phase-1 preprocessing (mirrors genFreqItems,
    FastApriori.scala:46-86).

    ``native``: force (True) or forbid (False) the C++ fast path; None
    auto-selects it when the extension is built and input is large.
    """
    from fastapriori_tpu.native import maybe_native_preprocess

    n_raw = len(transactions)
    min_count = int(math.ceil(min_support * n_raw))

    result = maybe_native_preprocess(transactions, min_count, native)
    if result is not None:
        freq_items, item_to_rank, item_counts, baskets, weights = result
    else:
        counts = count_item_occurrences(transactions)
        freq_items, item_to_rank, item_counts = build_rank_map(counts, min_count)
        baskets, weights = dedup_baskets(transactions, item_to_rank)

    return CompressedData(
        n_raw=n_raw,
        min_count=min_count,
        freq_items=freq_items,
        item_to_rank=item_to_rank,
        item_counts=item_counts,
        baskets=baskets,
        weights=weights,
    )


def dedup_user_baskets(
    user_lines: Sequence[Sequence[str]], item_to_rank: Dict[str, int]
) -> Tuple[List[np.ndarray], List[List[int]], List[int]]:
    """C10 (AssociationRules.scala:33-64): filter users to frequent items,
    dedupe identical baskets keeping the original row indexes per distinct
    basket; empty baskets are returned separately (they recommend "0"
    immediately — AssociationRules.scala:49).

    Returns (distinct baskets, per-basket original row-index lists,
    empty-row indexes)."""
    index_map: Dict[Tuple[int, ...], List[int]] = {}
    order: List[Tuple[int, ...]] = []
    empty: List[int] = []
    for idx, line in enumerate(user_lines):
        ranks = {item_to_rank[i] for i in line if i in item_to_rank}
        if not ranks:
            empty.append(idx)
            continue
        key = tuple(sorted(ranks))
        if key in index_map:
            index_map[key].append(idx)
        else:
            index_map[key] = [idx]
            order.append(key)
    baskets = [np.asarray(k, dtype=np.int32) for k in order]
    indexes = [index_map[k] for k in order]
    return baskets, indexes, empty
