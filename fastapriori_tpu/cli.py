"""Command-line driver (reference C1, Main.scala:15-41).

Usage mirrors the reference's spark-submit contract:

    python -m fastapriori_tpu <input-prefix> <output-prefix> [tmp] [flags]

- ``args(0)`` input prefix: reads ``<input>D.dat`` and ``<input>U.dat``
  (path concatenation, Utils.scala:21-23);
- ``args(1)`` output prefix: writes ``<output>freqItemset`` and
  ``<output>recommends`` (Utils.scala:39,48);
- a third positional arg is accepted and ignored, like the reference
  (README.md promises a temporary path, Main.scala never reads args(2));
- ``--min-support`` defaults to the reference's hardcoded 0.092
  (Main.scala:23).

Phase wall-clock is printed in the reference's ``====`` style
(Main.scala:32,37) alongside structured JSON metrics (``--metrics``).
"""

from __future__ import annotations

import argparse
import sys
import time
from typing import List, Optional

from fastapriori_tpu.config import DEFAULT_MIN_SUPPORT, MinerConfig
from fastapriori_tpu.io.writer import save_recommends


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="fastapriori_tpu",
        description="TPU-native Apriori mining + association-rule "
        "recommendation (reference-compatible CLI)",
    )
    p.add_argument("input", help="input prefix containing D.dat and U.dat")
    p.add_argument("output", help="output prefix for freqItemset/recommends")
    p.add_argument(
        "tmp",
        nargs="?",
        default=None,
        help="temporary path (accepted and ignored, like the reference)",
    )
    p.add_argument(
        "--min-support",
        type=float,
        default=DEFAULT_MIN_SUPPORT,
        help=f"minimum support (default {DEFAULT_MIN_SUPPORT}, "
        "the reference's hardcoded value)",
    )
    p.add_argument(
        "--num-devices",
        type=int,
        default=None,
        help="devices in the mesh (default: all visible)",
    )
    p.add_argument(
        "--cand-devices",
        type=int,
        default=1,
        help="2-D mesh: split devices as (num/cand, cand) over (txn, "
        "cand); the level engine shards each level's candidate prefixes "
        "over the cand axis (default 1 = plain transaction mesh)",
    )
    p.add_argument(
        "--engine",
        choices=["auto", "fused", "level"],
        default="auto",
        help="mining engine: auto = pick per dataset from the pair "
        "pre-pass (fused when the lattice fits the row budget, level "
        "otherwise); fused = whole level loop as one device program; "
        "level = one kernel launch per level",
    )
    p.add_argument(
        "--distributed",
        action="store_true",
        help="call jax.distributed.initialize() first (multi-host mesh "
        "over ICI/DCN; the analog of standing up the Spark cluster)",
    )
    p.add_argument(
        "--metrics",
        action="store_true",
        help="emit structured JSON metrics to stderr",
    )
    p.add_argument(
        "--save-counts",
        action="store_true",
        help="also write <output>freqItems with [count] suffixes "
        "(the reference's unused saveFreqItemsetWithCount, "
        "Utils.scala:51-63) — the resume artifact",
    )
    p.add_argument(
        "--resume-from",
        default=None,
        help="prefix holding previous run artifacts: with a complete "
        "freqItems table, skips mining and runs recommendation only "
        "(reference Utils.getAll, Utils.scala:65-81); with only a "
        "mid-mine checkpoint.npz (from --checkpoint-every-level), "
        "restarts mining from the deepest completed level.  Artifacts "
        "are validated against the run's MANIFEST.json when present",
    )
    p.add_argument(
        "--checkpoint-every-level",
        action="store_true",
        help="crash-safe mining: atomically rewrite "
        "<output>checkpoint.npz after every completed Apriori level so "
        "an interrupted mine resumes mid-lattice via --resume-from "
        "(costs eager per-level count fetches; with --engine fused the "
        "lattice mines in resumable device segments instead of "
        "skipping the engine)",
    )
    p.add_argument(
        "--checkpoint-cadence",
        type=int,
        default=1,
        help="with --engine fused and --checkpoint-every-level: levels "
        "mined per device segment between checkpoint commits (default "
        "1 = a durable checkpoint after every level, matching the "
        "level engine)",
    )
    p.add_argument(
        "--profile-dir",
        default=None,
        help="write a jax.profiler trace for the mining phase here",
    )
    p.add_argument(
        "--trace",
        default=None,
        metavar="PATH",
        help="record the span tracer (run → phase → level → dispatch → "
        "fetch, with collective-byte counter tracks) and export "
        "Chrome-trace-event JSON here — load it in Perfetto "
        "(ui.perfetto.dev); FA_TRACE=1 records without exporting",
    )
    p.add_argument(
        "--platform",
        choices=["default", "cpu"],
        default="default",
        help="force the JAX platform in-process (env vars are unreliable "
        "when a hardware plugin self-registers at interpreter start — "
        "'cpu' runs the full pipeline without an accelerator)",
    )
    return p


def build_serve_parser() -> argparse.ArgumentParser:
    """The ``serve`` subcommand (ISSUE 10): a resident recommend service
    over the serving-tier subsystem (fastapriori_tpu/serve/) — build the
    model once (mine, or warm-restart from a serving checkpoint), then
    answer a file/stdin request stream through the admission-controlled
    micro-batching server."""
    p = argparse.ArgumentParser(
        prog="fastapriori_tpu serve",
        description="resident recommend service: mount the model once "
        "(device-resident rule scan table), serve baskets from a file "
        "or stdin through the micro-batching request loop",
    )
    p.add_argument(
        "input",
        help="input prefix containing D.dat (model build; ignored with "
        "--from-serving)",
    )
    p.add_argument(
        "output",
        nargs="?",
        default=None,
        help="output prefix: writes <output>recommends (+ manifest); "
        "omitted = responses to stdout",
    )
    p.add_argument(
        "--requests",
        default=None,
        help="request source: a file of basket lines, or '-' for stdin "
        "(default: <input>U.dat)",
    )
    p.add_argument(
        "--from-serving",
        default=None,
        help="warm-restart: load <prefix>serving.npz (a ServingState "
        "checkpoint) instead of mining <input>D.dat",
    )
    p.add_argument(
        "--save-serving",
        action="store_true",
        help="after the model builds, write <output>serving.npz (the "
        "warm-restart artifact; requires an output prefix)",
    )
    p.add_argument(
        "--min-support",
        type=float,
        default=DEFAULT_MIN_SUPPORT,
        help=f"minimum support for the model build (default "
        f"{DEFAULT_MIN_SUPPORT})",
    )
    p.add_argument(
        "--num-devices", type=int, default=None,
        help="devices in the mesh (default: all visible)",
    )
    p.add_argument(
        "--serve-engine",
        choices=["auto", "device", "host"],
        default="auto",
        help="scan engine: auto picks the device table when the "
        "model/batch product justifies a dispatch, host forces the "
        "oracle scan",
    )
    p.add_argument(
        "--batch-rows",
        type=int,
        default=None,
        help="micro-batch rows (pow2-bucketed; default "
        "config.rec_batch_rows / FA_REC_BATCH)",
    )
    p.add_argument(
        "--linger-ms",
        type=float,
        default=None,
        help="max ms a partial micro-batch waits to fill before "
        "dispatching (default config.serve_linger_ms)",
    )
    p.add_argument(
        "--queue-depth",
        type=int,
        default=None,
        help="admission-control queue bound in requests (default 4x "
        "the micro-batch rows); a full queue sheds ('0' + ledger)",
    )
    p.add_argument(
        "--pipeline-depth",
        type=int,
        default=None,
        help="two-stage dispatcher hand-off ring depth (default "
        "FA_SERVE_PIPELINE_DEPTH=2; 0 forces the serial "
        "pack+scan-on-one-thread dispatcher)",
    )
    p.add_argument(
        "--hosts",
        type=int,
        default=None,
        help="serving hosts in the mesh (default FA_SERVE_HOSTS=1); "
        ">1 mounts the model on N in-process hosts behind the "
        "request router — round-robin + spill admission, global "
        "shed, one merged metrics surface",
    )
    p.add_argument(
        "--rate",
        type=float,
        default=None,
        help="open-loop pacing in requests/sec (seeded Poisson "
        "schedule; overload SHEDS — the sustained-load shape); "
        "default: closed submission with bounded backpressure",
    )
    p.add_argument(
        "--seed", type=int, default=0,
        help="arrival-schedule seed for --rate (default 0)",
    )
    p.add_argument(
        "--metrics", action="store_true",
        help="emit structured JSON metrics to stderr",
    )
    p.add_argument(
        "--trace",
        default=None,
        metavar="PATH",
        help="record the span tracer (serve-batch spans split "
        "admission/dedup/pack host time from device scan time) and "
        "export Perfetto-loadable Chrome-trace JSON here",
    )
    p.add_argument(
        "--metrics-dump",
        default=None,
        metavar="PATH",
        help="periodically write the server's Prometheus-text metrics "
        "snapshot here (atomic rewrite every FA_METRICS_DUMP_S "
        "seconds, final snapshot at shutdown) — the scrape surface "
        "for a file-based collector",
    )
    p.add_argument(
        "--platform", choices=["default", "cpu"], default="default",
        help="force the JAX platform in-process ('cpu' serves without "
        "an accelerator)",
    )
    return p


def _serve_main(argv: List[str]) -> int:
    from fastapriori_tpu.errors import InputError

    args = build_serve_parser().parse_args(argv)
    try:
        return _run_serve(args)
    except InputError as e:
        from fastapriori_tpu.obs import flight

        flight.auto_dump(
            "classified_error", extra={"error": f"InputError: {e}"[:400]}
        )
        print(f"error: {e}", file=sys.stderr)
        return 2
    except FileNotFoundError as e:
        missing = e.filename if e.filename else str(e)
        print(f"error: file {missing!r} not found", file=sys.stderr)
        return 2


def _start_metrics_dump(server, path: Optional[str]):
    """``serve --metrics-dump PATH``: a daemon thread rewriting the
    server's Prometheus-text snapshot ATOMICALLY (the PR-2 committer —
    a scraper never reads a torn file) every ``FA_METRICS_DUMP_S``
    seconds.  Returns a stop callable that writes the final snapshot
    and joins the thread (bounded), or None when no path was given."""
    if not path:
        return None
    import threading

    from fastapriori_tpu.io.writer import write_artifact_bytes
    from fastapriori_tpu.obs.metrics import dump_interval_s

    interval = dump_interval_s()
    stop = threading.Event()

    def write_once() -> None:
        write_artifact_bytes(
            path, [server.metrics_text().encode("utf-8")], "metrics"
        )

    def loop() -> None:
        while not stop.wait(interval):
            write_once()

    t = threading.Thread(target=loop, name="fa-metrics-dump", daemon=True)
    t.start()

    def finish() -> None:
        stop.set()
        t.join(10.0)
        write_once()

    return finish


def _run_serve(args) -> int:
    from fastapriori_tpu.errors import InputError

    if args.save_serving and not args.output:
        raise InputError(
            "--save-serving writes <output>serving.npz and therefore "
            "needs an output prefix"
        )
    if args.platform == "cpu":
        import jax

        jax.config.update("jax_platforms", "cpu")
        if jax.default_backend() != "cpu":
            print(
                "--platform cpu requested but JAX backends were already "
                f"initialized ({jax.default_backend()}); start a fresh "
                "process",
                file=sys.stderr,
            )
            return 2
    from fastapriori_tpu.utils.compile_cache import enable_compile_cache

    enable_compile_cache()
    from fastapriori_tpu.config import MinerConfig
    from fastapriori_tpu.io.reader import tokenize_line
    from fastapriori_tpu.obs import flight, trace
    from fastapriori_tpu.serve import RecommendServer, ServingState
    from fastapriori_tpu.utils.logging import phase_timer

    trace.maybe_enable(bool(args.trace))
    flight.set_dump_prefix(args.output or args.input)

    config = MinerConfig(
        min_support=args.min_support,
        num_devices=args.num_devices,
        log_metrics=args.metrics,
        retain_csr=False,
    )
    from fastapriori_tpu.serve.router import hosts_from_env

    n_hosts = args.hosts if args.hosts is not None else hosts_from_env()
    if n_hosts < 1:
        raise InputError(f"--hosts must be >= 1, got {n_hosts}")
    t0 = time.perf_counter()
    with phase_timer("serve model mount", enabled=False):
        if args.from_serving:
            state = ServingState.load(
                args.from_serving, config=config, engine=args.serve_engine
            )
        else:
            state = ServingState.from_mine(
                args.input + "D.dat", config=config,
                engine=args.serve_engine,
            )
        if args.save_serving:
            state.save(args.output)

        def _mk_server(st):
            return RecommendServer(
                st,
                batch_rows=args.batch_rows,
                linger_ms=args.linger_ms,
                queue_depth=args.queue_depth,
                pipeline_depth=args.pipeline_depth,
            ).start()

        if n_hosts > 1:
            # Mesh mode (ISSUE 19): each host mounts its OWN state (no
            # shared device table — per-host scan state is what the
            # hot-swap signature discipline protects), loaded from the
            # serving checkpoint; a mined model is checkpointed to a
            # scratch prefix first.
            import os
            import tempfile

            from fastapriori_tpu.serve import LocalHost, MeshRouter

            if args.from_serving:
                prefix = args.from_serving
            elif args.save_serving:
                prefix = args.output
            else:
                prefix = os.path.join(
                    tempfile.mkdtemp(prefix="fa_mesh_cli_"), "m_"
                )
                state.save(prefix)
            states = [state] + [
                ServingState.load(
                    prefix, config=config, engine=args.serve_engine
                )
                for _ in range(n_hosts - 1)
            ]
            server = MeshRouter(
                [
                    LocalHost(f"host{i}", _mk_server(st))
                    for i, st in enumerate(states)
                ]
            )
        else:
            server = _mk_server(state)
    print(
        "==== Total time for serve model mount "
        f"{int((time.perf_counter() - t0) * 1e3)}",
        file=sys.stderr,
    )
    dump_stop = _start_metrics_dump(server, args.metrics_dump)

    req_path = args.requests or (args.input + "U.dat")
    if req_path == "-":
        lines = (tokenize_line(l) for l in sys.stdin)
    else:
        from fastapriori_tpu.io.reader import read_dat

        lines = iter(read_dat(req_path))

    t1 = time.perf_counter()
    reqs = []
    if args.rate is not None:
        # Open-loop: materialize the pool, drive the seeded schedule.
        from fastapriori_tpu.serve import run_open_loop

        pool = list(lines)
        if pool:
            # run_open_loop submits request i = pool[i % len] in order,
            # so responses align with input rows.
            result = run_open_loop(
                server,
                pool,
                rate_rps=args.rate,
                n_requests=len(pool),
                seed=args.seed,
                requests_out=reqs,
            )
            import json

            print(json.dumps({"serve_open_loop": result}), file=sys.stderr)
    else:
        for tokens in lines:
            if n_hosts > 1:
                # The router's closed-loop shape: admission spills
                # across hosts and sheds (never blocks) at mesh-full.
                reqs.append(server.submit(tokens))
            else:
                reqs.append(server.submit_wait(tokens))
    completed = server.wait_for(reqs, timeout_s=600.0)
    served_wall = time.perf_counter() - t1
    stats = server.stats()
    if n_hosts > 1:
        stopped = server.drain() and server.stop()
    else:
        stopped = server.stop(drain=True)
    if dump_stop is not None:
        dump_stop()  # final metrics snapshot, thread joined (bounded)
    if args.trace:
        path = trace.TRACER.export(args.trace)
        print(
            f"trace written: {path} "
            f"({len(trace.TRACER.events())} events; load in Perfetto)",
            file=sys.stderr,
        )
    if not completed or not stopped:
        # A wedged dispatcher must be a LOUD failure (the server's own
        # stop() contract) — writing a clean-looking artifact of "0"
        # rows with exit 0 is exactly the silent degradation the
        # serving tier forbids.
        pending = sum(1 for r in reqs if not r.done)
        print(
            f"error: serve did not complete inside the bound "
            f"({pending} of {len(reqs)} requests unfinished, "
            f"dispatcher {'stopped' if stopped else 'STILL RUNNING'}) — "
            "no output written",
            file=sys.stderr,
        )
        return 1

    recommends = [
        (i, r.item if r.item is not None else "0")
        for i, r in enumerate(reqs)
    ]
    if args.output:
        from fastapriori_tpu.io.writer import write_manifest
        from fastapriori_tpu.reliability import quorum

        manifest = {}
        save_recommends(args.output, recommends, manifest=manifest)
        # Fence discipline (G020): None without an active quorum domain
        # or on a non-writer rank; the domain writer stamps its epoch,
        # and a superseded one raises StaleFenceError instead.
        write_manifest(args.output, manifest,
                       fence=quorum.writer_fence())
    else:
        for _, item in recommends:
            print(item)
    avg_batch = stats.get("avg_batch")
    if avg_batch is None:  # mesh stats aggregate; derive the average
        avg_batch = round(stats["served"] / max(stats["batches"], 1), 1)
    engine = (stats.get("model") or {}).get("engine")
    if engine is None:
        ph = stats.get("per_host") or [{}]
        engine = (ph[0].get("model") or {}).get("engine", "?")
    mesh_note = (
        f"{stats['hosts']} hosts ({stats.get('router_shed', 0)} "
        f"router-shed), " if n_hosts > 1 else ""
    )
    print(
        f"==== serve: {stats['served']} served, {stats['shed']} shed, "
        f"{mesh_note}"
        f"{stats['batches']} batches (avg {avg_batch} rows), "
        f"engine {engine}, "
        f"{int(served_wall * 1e3)} ms",
        file=sys.stderr,
    )
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    """Parse args and run; user-correctable problems (missing input
    files, malformed resume artifacts — InputError/FileNotFoundError)
    print a one-line actionable message and return 2 instead of dumping a
    traceback (the reference stack-traces on all of these).  A first
    argument of ``serve`` routes to the serving-tier subcommand
    (:func:`_serve_main`) — the batch contract's positionals are
    untouched for every other spelling."""
    from fastapriori_tpu.errors import InputError

    if argv is None:
        argv = sys.argv[1:]
    if argv and argv[0] == "serve":
        return _serve_main(list(argv[1:]))
    args = build_parser().parse_args(argv)
    try:
        return _run(args)
    except InputError as e:
        # Classified failure: ship the flight-recorder post-mortem (the
        # last N span/ledger/watchdog events) next to the run's other
        # artifacts before the friendly one-liner.
        from fastapriori_tpu.obs import flight

        flight.auto_dump(
            "classified_error", extra={"error": f"InputError: {e}"[:400]}
        )
        print(f"error: {e}", file=sys.stderr)
        return 2
    except RuntimeError as e:
        # Fault-domain failures (ISSUE 12) are CLASSIFIED, not
        # tracebacks: a dead peer / divergent mesh names the rank and
        # exits 3 (distinct from the user-fixable 2), with the
        # consensus epoch trail already in the flight dump the quorum
        # layer shipped when the error classified.  Any other
        # RuntimeError keeps propagating unchanged.
        from fastapriori_tpu.reliability import quorum

        if not isinstance(
            e,
            (
                quorum.PeerLost,
                quorum.MeshDivergence,
                # Defensive: an elastic abort that escapes every rejoin
                # arm (it should not) is still a classified fault-domain
                # exit, never a traceback.
                quorum.MeshEpochAbort,
            ),
        ):
            raise
        print(f"error: {e}", file=sys.stderr)
        return 3
    except FileNotFoundError as e:
        missing = e.filename if e.filename else str(e)
        # The D.dat/U.dat hint only fits the two ingest reads; a
        # FileNotFoundError from elsewhere in the run (--profile-dir,
        # output writes — which may share the input prefix) must name
        # its actual path, not blame the input prefix.  Matched by
        # basename, not full path: remote (fsspec) backends report
        # scheme-stripped paths that never equal args.input + "D.dat".
        if missing.endswith(("D.dat", "U.dat")):
            print(
                f"error: input file {missing!r} not found — the input "
                "prefix must point at D.dat and U.dat (prefix + 'D.dat', "
                "trailing slash matters, as with the reference)",
                file=sys.stderr,
            )
        else:
            print(f"error: file {missing!r} not found", file=sys.stderr)
        return 2


def _run(args) -> int:
    config = MinerConfig(
        min_support=args.min_support,
        num_devices=args.num_devices,
        cand_devices=args.cand_devices,
        log_metrics=args.metrics,
        engine=args.engine,
        # The CLI never reads the basket CSR back (the bitmap is built
        # block-by-block at ingest); skipping it saves ~0.7 GB of host
        # copies at webdocs scale.
        retain_csr=False,
        checkpoint_prefix=(
            args.output if args.checkpoint_every_level else None
        ),
        checkpoint_every_levels=max(args.checkpoint_cadence, 1),
    )
    if args.platform == "cpu":
        import jax

        jax.config.update("jax_platforms", "cpu")
        if args.num_devices and args.num_devices > 1:
            # Provision that many virtual CPU devices so the sharded
            # paths (and 2-D meshes) run for real without an accelerator.
            # Raises if backends already initialized — fall through to the
            # default_backend guard below for the friendly diagnostic.
            try:
                jax.config.update("jax_num_cpu_devices", args.num_devices)
            except (RuntimeError, AttributeError):
                # AttributeError: jax < 0.5 has no such option — there
                # the XLA_FLAGS device-count split (conftest/bench
                # convention) is the only mechanism; the env var is the
                # caller's job and the guard below still verifies the
                # platform.
                pass
        # The config only takes effect at backend init; if a caller already
        # initialized backends in this process, fail loudly rather than
        # silently running on the accelerator anyway.
        if jax.default_backend() != "cpu":
            print(
                "--platform cpu requested but JAX backends were already "
                f"initialized ({jax.default_backend()}); start a fresh "
                "process",
                file=sys.stderr,
            )
            return 2
    n_proc, proc_id = 1, 0
    if args.distributed:
        from fastapriori_tpu.parallel.mesh import initialize_distributed

        try:
            initialize_distributed()
        except RuntimeError as e:
            # "should only be called once" = the launcher already
            # initialized jax.distributed — fine, proceed.  Any OTHER
            # RuntimeError (e.g. "must be called before any JAX
            # computations") means a real multi-process launch would
            # silently degrade to N independent runs racing on the same
            # output files — fail loudly instead.
            if "once" not in str(e):
                print(
                    f"error: --distributed initialization failed: {e}",
                    file=sys.stderr,
                )
                return 2
        except ValueError as e:
            # Incomplete/absent coordinator config — surface jax's own
            # message (it names the missing piece) and proceed
            # single-process.
            print(
                f"--distributed: {e} — running single-process "
                "(initialize jax.distributed in the launcher, or set "
                "the cluster environment it auto-detects)",
                file=sys.stderr,
            )
        import jax

        n_proc, proc_id = jax.process_count(), jax.process_index()

    # Imports deferred so --help works without initializing a backend.
    from fastapriori_tpu.utils.compile_cache import enable_compile_cache

    enable_compile_cache()
    from fastapriori_tpu.models.apriori import FastApriori
    from fastapriori_tpu.models.recommender import AssociationRules

    from fastapriori_tpu.io.reader import read_dat
    from fastapriori_tpu.obs import flight, trace
    from fastapriori_tpu.utils.logging import phase_timer

    # Observability (ISSUE 11): span recording on --trace/FA_TRACE, and
    # the flight recorder's post-mortem dumps target this run's output
    # prefix (process 0 — one writer, like every other artifact).  On a
    # multi-process fault domain (ISSUE 12) EVERY rank dumps, under a
    # rank-suffixed prefix so per-process post-mortems never clobber
    # (tools/flight_merge.py reassembles them into one ordered trail).
    from fastapriori_tpu.reliability import quorum

    dom = quorum.active()
    multi_rank = dom is not None and dom.nprocs > 1
    trace.maybe_enable(bool(args.trace))
    if multi_rank:
        flight.set_dump_prefix(args.output + f"rank{dom.rank}.")
    elif proc_id == 0:
        flight.set_dump_prefix(args.output)
    # Fault-domain rendezvous (ISSUE 12): all ranks up before any work
    # — a peer that never starts surfaces here as a classified
    # PeerLost, bounded by attempts x FA_QUORUM_TIMEOUT_S.  The
    # sync_or_rejoin form (ISSUE 17) lets a rank blocked here while a
    # peer elastically aborts the mesh rejoin under the new epoch
    # instead of misclassifying the alive peer as lost; with elastic
    # continuation off (the default) it is exactly sync.
    quorum.sync_or_rejoin("run.start", wait=True)

    u_lines = read_dat(args.input + "U.dat")

    # The run root span + reference-style phase walls (phase_timer now
    # routes through the tracer and the active MetricsLogger — ISSUE 11
    # satellite).  Entered explicitly: the phase boundaries interleave
    # with this function's early returns, and a propagating error is
    # the flight recorder's job, not the trace exporter's.
    run_span = trace.span("run", cmd="mine")
    run_span.__enter__()
    phase = phase_timer("get freqItemsets", enabled=False)
    phase.__enter__()
    t1 = time.perf_counter()
    levels = item_counts = None
    resume_ckpt = None
    if args.resume_from:
        from fastapriori_tpu.errors import InputError
        from fastapriori_tpu.io.checkpoint import (
            checkpoint_available,
            load_checkpoint,
        )
        from fastapriori_tpu.io.resume import load_phase1, phase1_available

        if phase1_available(args.resume_from):
            # Complete phase-1 artifacts: recommendation-only restart
            # (the reference's Utils.getAll path).
            try:
                freq_itemsets, item_to_rank, freq_items = load_phase1(
                    args.resume_from
                )
            except InputError:
                # A torn phase-1 set (crash between the freqItems write
                # and its aux artifacts, or a failed validation) must
                # not wedge resume when a valid mid-mine checkpoint
                # exists under the same prefix.
                if not checkpoint_available(args.resume_from):
                    raise
                resume_ckpt = load_checkpoint(args.resume_from)
        elif checkpoint_available(args.resume_from):
            # Mid-mine checkpoint only: re-ingest D.dat and restart the
            # level loop from the deepest completed level.
            resume_ckpt = load_checkpoint(args.resume_from)
        else:
            raise InputError(
                f"--resume-from {args.resume_from!r}: found neither the "
                "phase-1 artifacts a --save-counts run writes "
                "(freqItems, FreqItems, ItemsToRank) nor the mid-mine "
                "checkpoint.npz a --checkpoint-every-level run writes"
            )
    if args.resume_from and resume_ckpt is None:
        pass  # phase-1 resume: skip mining entirely
    else:
        profiler = None
        if args.profile_dir:
            import jax.profiler as profiler

            profiler.start_trace(args.profile_dir)
        # Matrix-form pipeline: mining result stays as level matrices all
        # the way into the writer and rule generator — no per-itemset
        # Python objects (multi-second at 10^6-itemset scale).
        miner = FastApriori(args.min_support, config=config)
        if resume_ckpt is not None:
            ck_levels, ck_meta = resume_ckpt
            miner.set_resume_levels(
                ck_levels, ck_meta, label=args.resume_from
            )
        # lint: waive G015 -- lockstep: n_proc is jax.process_count(), identical on every rank of the mesh, so all peers take the same branch and issue the same collectives
        if n_proc > 1:
            # Multi-host: each process preprocesses only its own byte
            # range of D.dat (sharded ingest); results are replicated.
            levels, data = miner.run_file_sharded(args.input + "D.dat")
        else:
            levels, data = miner.run_file_raw(args.input + "D.dat")
        item_to_rank, freq_items = data.item_to_rank, data.freq_items
        item_counts = data.item_counts
        freq_itemsets = []
        if profiler is not None:
            profiler.stop_trace()
        if proc_id == 0:  # one writer, like the reference's driver
            from fastapriori_tpu.io.writer import (
                save_freq_itemsets_levels,
                write_manifest,
            )

            manifest = {}
            save_freq_itemsets_levels(
                args.output, levels, item_counts, freq_items,
                with_counts_path=args.save_counts,
                manifest=manifest,
            )
            if args.save_counts:
                from fastapriori_tpu.io.resume import save_phase1_aux

                save_phase1_aux(
                    args.output, freq_items, item_to_rank,
                    manifest=manifest,
                )
            write_manifest(args.output, manifest,
                           fence=quorum.writer_fence())
    phase.__exit__(None, None, None)
    print(
        "==== Total time for get freqItemsets "
        f"{int((time.perf_counter() - t1) * 1e3)}",
        file=sys.stderr,
    )
    # End-of-mine rendezvous: fused and per-level ranks take different
    # numbers of level boundaries, but every rank arrives HERE — a rank
    # killed mid-mine is detected by its survivors within the bound,
    # never waited on forever.  Rejoin-armed (ISSUE 17): a rank already
    # done mining must pair with survivors that aborted to a newer
    # mesh epoch mid-mine.
    quorum.sync_or_rejoin("mine.end", wait=True)

    phase = phase_timer("get recommends", enabled=False)
    phase.__enter__()
    t2 = time.perf_counter()
    # Phase 2 runs on EVERY process: the containment kernel shards the
    # (deduplicated) user baskets over the global mesh, so each process
    # computes only its own rows and one allgather reassembles the
    # result — the work is genuinely divided, not duplicated.  Process 0
    # writes, like the reference's driver.
    recommender = AssociationRules(
        freq_itemsets, freq_items, item_to_rank, config=config,
        levels=levels, item_counts=item_counts,
    )
    recommends = recommender.run(u_lines)
    if proc_id == 0:
        from fastapriori_tpu.io.writer import write_manifest

        manifest = {}
        save_recommends(args.output, recommends, manifest=manifest)
        write_manifest(args.output, manifest,
                       fence=quorum.writer_fence())
    phase.__exit__(None, None, None)
    print(
        "==== Total time for get recommends "
        f"{int((time.perf_counter() - t2) * 1e3)}",
        file=sys.stderr,
    )
    run_span.__exit__(None, None, None)
    # Final rendezvous: no rank exits while a peer still needs its
    # heartbeats — the survivors' last bounded wait (rejoin-armed, so
    # an elastic abort between mine.end and here still pairs).
    quorum.sync_or_rejoin("run.end", wait=True)
    if args.trace and (multi_rank or proc_id == 0):
        # Multi-rank runs export per-rank traces (rank suffix before
        # the extension — no clobbering; ISSUE 12 satellite).
        path = trace.TRACER.export(quorum.rank_path(args.trace))
        print(
            f"trace written: {path} "
            f"({len(trace.TRACER.events())} events; load in Perfetto)",
            file=sys.stderr,
        )
    return 0


if __name__ == "__main__":
    sys.exit(main())
