"""Native (C++) fast paths for host-side preprocessing.

The reference's host-side work runs on the JVM inside Spark; here the
Python fallback is numpy/dicts (fastapriori_tpu/preprocess.py) and the
fast path is a C++ shared library (tokenize + count + dedup in one pass
over the raw bytes — preprocess.cc) built by ``make -C
fastapriori_tpu/native`` (attempted automatically on first use) and loaded
via ctypes.  Selection logic lives in preprocess._use_native; this module
only answers availability."""

from __future__ import annotations


def native_available() -> bool:
    try:
        from fastapriori_tpu.native.loader import get_lib

        return get_lib() is not None
    except (OSError, AttributeError):
        # get_lib converts CDLL load failures to None (and a ledger
        # event); a filesystem-level surprise (OSError) or a stale .so
        # missing a hard-bound symbol (AttributeError from the restype/
        # argtypes binding) still means "no native path" here.
        return False
