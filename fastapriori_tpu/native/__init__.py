"""Native (C++) fast paths for host-side preprocessing.

The reference's host-side work runs on the JVM inside Spark; here the
Python fallback is numpy/dicts and the fast path is a C++ extension
(tokenize + count + dedup in one pass) built by ``make -C
fastapriori_tpu/native`` and loaded via ctypes.  Import never fails: if the
shared library is absent, ``maybe_native_preprocess`` returns None and the
Python path runs.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple


def native_available() -> bool:
    try:
        from fastapriori_tpu.native.loader import get_lib

        return get_lib() is not None
    except Exception:
        return False


def maybe_native_preprocess(
    transactions: Sequence[Sequence[str]],
    min_count: int,
    force: Optional[bool],
):
    """Return preprocess results from the C++ path, or None to use Python.

    ``force``: True = require native (raise if unavailable); False = never
    use native; None = use native when built and the input is large enough
    to amortize the FFI boundary."""
    if force is False:
        return None
    try:
        from fastapriori_tpu.native.loader import preprocess_native, get_lib

        available = get_lib() is not None
    except ImportError:
        available = False
    if not available:
        if force:
            raise RuntimeError(
                "native preprocessing requested but the extension is not "
                "built; run `make -C fastapriori_tpu/native`"
            )
        return None
    if force is None and len(transactions) < 50_000:
        return None
    return preprocess_native(transactions, min_count)
