"""ctypes bindings for the native preprocessing library.

Builds lazily on first use if g++ is available (``make -C
fastapriori_tpu/native``); absence is non-fatal — callers fall back to the
Python path (see fastapriori_tpu/native/__init__.py).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import List, Optional, Sequence, Tuple

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_SO = os.path.join(_DIR, "libfa_native.so")
_lib = None
_build_attempted = False


class _FaResult(ctypes.Structure):
    _fields_ = [
        ("n_raw", ctypes.c_int64),
        ("min_count", ctypes.c_int64),
        ("n_items", ctypes.c_int32),
        # void* (not c_char_p): the buffer is length-delimited, not
        # NUL-terminated, and c_char_p field access would scan for NUL.
        ("items_buf", ctypes.c_void_p),
        ("items_buf_len", ctypes.c_int64),
        ("item_counts", ctypes.POINTER(ctypes.c_int64)),
        ("n_baskets", ctypes.c_int64),
        ("basket_offsets", ctypes.POINTER(ctypes.c_int64)),
        ("basket_items", ctypes.POINTER(ctypes.c_int32)),
        ("weights", ctypes.POINTER(ctypes.c_int32)),
    ]


def _try_build() -> None:
    global _build_attempted
    if _build_attempted:
        return
    _build_attempted = True
    try:
        subprocess.run(
            ["make", "-C", _DIR, "-s"],
            check=True,
            capture_output=True,
            timeout=120,
        )
    except (OSError, subprocess.SubprocessError) as e:
        # Best-effort build: absence falls back to the Python path — but
        # that fallback is a real slowdown at scale, so it is a recorded
        # degradation, not a silent one.
        from fastapriori_tpu.reliability import ledger

        ledger.record(
            "native_unavailable",
            once_key="build",
            stage="build",
            error=f"{type(e).__name__}: {e}"[:200],
        )


def get_lib() -> Optional[ctypes.CDLL]:
    global _lib
    if _lib is not None:
        return _lib
    if not os.path.exists(_SO):
        _try_build()
    if not os.path.exists(_SO):
        return None
    try:
        from fastapriori_tpu.reliability import failpoints

        failpoints.fire("native.load")
        lib = ctypes.CDLL(_SO)
    except OSError as e:
        # A present-but-unloadable .so (stale build, injected
        # native.load failpoint): same contract as absence — callers
        # fall back to the Python path, loudly.
        from fastapriori_tpu.reliability import ledger

        ledger.record(
            "native_unavailable",
            once_key="load",
            stage="load",
            error=f"{type(e).__name__}: {e}"[:200],
        )
        return None
    lib.fa_preprocess_buffer.restype = ctypes.POINTER(_FaResult)
    lib.fa_preprocess_buffer.argtypes = [
        ctypes.c_char_p,
        ctypes.c_int64,
        ctypes.c_double,
    ]
    lib.fa_free_result.argtypes = [ctypes.POINTER(_FaResult)]
    lib.fa_free_result.restype = None
    # Stale prebuilt .so (from before this symbol existed) must not break
    # the other native entry points — probe instead of hard-binding.
    fill = getattr(lib, "fa_fill_packed_bitmap", None)
    if fill is not None:
        fill.argtypes = [
            ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int32),
            ctypes.c_int64,
            ctypes.c_int64,
            ctypes.POINTER(ctypes.c_uint8),
        ]
        fill.restype = None
    count = getattr(lib, "fa_count_buffer", None)
    if count is not None:
        count.restype = ctypes.POINTER(_FaCounts)
        count.argtypes = [ctypes.c_char_p, ctypes.c_int64]
        lib.fa_free_counts.argtypes = [ctypes.POINTER(_FaCounts)]
        lib.fa_free_counts.restype = None
        cwr = lib.fa_compress_with_ranks
        cwr.restype = ctypes.POINTER(_FaResult)
        cwr.argtypes = [
            ctypes.c_char_p,
            ctypes.c_int64,
            ctypes.c_char_p,
            ctypes.c_int64,
            ctypes.c_int32,
        ]
    blocks_fn = getattr(lib, "fa_preprocess_buffer_blocks", None)
    if blocks_fn is not None:
        blocks_fn.restype = ctypes.POINTER(_FaResult)
        blocks_fn.argtypes = [
            ctypes.c_char_p,
            ctypes.c_int64,
            ctypes.c_double,
            ctypes.c_int32,
            ctypes.c_int32,
            _FA_BLOCK_CB,
            ctypes.c_void_p,
        ]
    blocks2_fn = getattr(lib, "fa_preprocess_buffer_blocks2", None)
    if blocks2_fn is not None:
        blocks2_fn.restype = ctypes.POINTER(_FaResult)
        blocks2_fn.argtypes = [
            ctypes.c_char_p,
            ctypes.c_int64,
            ctypes.c_double,
            ctypes.c_int32,
            ctypes.c_int32,
            _FA_PASS1_CB,
            _FA_BLOCK_CB,
            ctypes.c_void_p,
        ]
    cand = getattr(lib, "fa_gen_candidates", None)
    if cand is not None:
        cand.restype = ctypes.POINTER(_FaCandidates)
        cand.argtypes = [
            ctypes.POINTER(ctypes.c_int32),
            ctypes.c_int64,
            ctypes.c_int32,
        ]
        lib.fa_free_candidates.argtypes = [ctypes.POINTER(_FaCandidates)]
        lib.fa_free_candidates.restype = None
    _lib = lib
    return _lib


NativeResult = Tuple[
    int,  # n_raw
    int,  # min_count
    List[str],  # freq_items
    np.ndarray,  # item_counts int64[F]
    np.ndarray,  # basket_indices int32 (CSR data)
    np.ndarray,  # basket_offsets int64[T'+1]
    np.ndarray,  # weights int32[T']
]


# void cb(ctx, f, n_baskets, offsets*, items*, weights*)
_FA_BLOCK_CB = ctypes.CFUNCTYPE(
    None,
    ctypes.c_void_p,
    ctypes.c_int32,
    ctypes.c_int64,
    ctypes.POINTER(ctypes.c_int64),
    ctypes.POINTER(ctypes.c_int32),
    ctypes.POINTER(ctypes.c_int32),
)

# void pass1_cb(ctx, n_raw, min_count, f, counts*) — fires once after
# pass 1 / rank assignment, before any block replays.
_FA_PASS1_CB = ctypes.CFUNCTYPE(
    None,
    ctypes.c_void_p,
    ctypes.c_int64,
    ctypes.c_int64,
    ctypes.c_int32,
    ctypes.POINTER(ctypes.c_int64),
)


class _FaCandidates(ctypes.Structure):
    _fields_ = [
        ("n", ctypes.c_int64),
        ("x_idx", ctypes.POINTER(ctypes.c_int64)),
        ("y", ctypes.POINTER(ctypes.c_int32)),
    ]


class _FaCounts(ctypes.Structure):
    _fields_ = [
        ("n_lines", ctypes.c_int64),
        ("n_tokens", ctypes.c_int64),
        ("tokens_buf", ctypes.c_void_p),
        ("tokens_buf_len", ctypes.c_int64),
        ("counts", ctypes.POINTER(ctypes.c_int64)),
    ]


def count_buffer(data: bytes) -> Tuple[int, List[str], np.ndarray]:
    """Sharded-ingest phase 1: (line count, distinct tokens, occurrence
    counts) for one byte range.  Raises if the native library (or a stale
    build of it) is unavailable."""
    lib = get_lib()
    if lib is None or getattr(lib, "fa_count_buffer", None) is None:
        raise RuntimeError(
            "native sharded-ingest entry points unavailable; rebuild with "
            "`make -C fastapriori_tpu/native`"
        )
    res_ptr = lib.fa_count_buffer(data, len(data))
    if not res_ptr:
        raise MemoryError("fa_count_buffer failed")
    try:
        res = res_ptr.contents
        n = int(res.n_tokens)
        raw = ctypes.string_at(res.tokens_buf, res.tokens_buf_len)
        tokens = raw.decode("utf-8").split("\n") if n else []
        assert len(tokens) == n, (len(tokens), n)
        counts = np.ctypeslib.as_array(res.counts, shape=(max(n, 1),))[
            :n
        ].copy()
        return int(res.n_lines), tokens, counts
    finally:
        lib.fa_free_counts(res_ptr)


def compress_with_ranks(
    data: bytes, freq_items: List[str]
) -> Tuple[int, np.ndarray, np.ndarray, np.ndarray]:
    """Sharded-ingest phase 2: compress one byte range against the GLOBAL
    rank table.  Returns (local line count, basket_indices,
    basket_offsets, weights) — CSR over this shard's distinct baskets."""
    lib = get_lib()
    if lib is None or getattr(lib, "fa_compress_with_ranks", None) is None:
        raise RuntimeError(
            "native sharded-ingest entry points unavailable; rebuild with "
            "`make -C fastapriori_tpu/native`"
        )
    ranks_blob = "\n".join(freq_items).encode("utf-8")
    res_ptr = lib.fa_compress_with_ranks(
        data, len(data), ranks_blob, len(ranks_blob), len(freq_items)
    )
    if not res_ptr:
        raise MemoryError("fa_compress_with_ranks failed")
    free_now = True
    try:
        res = res_ptr.contents
        t = int(res.n_baskets)
        offsets = np.ctypeslib.as_array(
            res.basket_offsets, shape=(t + 1,)
        ).copy()
        nnz = int(offsets[-1]) if t else 0
        if nnz:
            import weakref

            base = np.ctypeslib.as_array(res.basket_items, shape=(nnz,))
            base.flags.writeable = False
            weakref.finalize(base, lib.fa_free_result, res_ptr)
            indices = base[:nnz]
            free_now = False
        else:
            indices = np.empty(0, dtype=np.int32)
        weights = np.ctypeslib.as_array(res.weights, shape=(max(t, 1),))[
            :t
        ].copy()
        return int(res.n_raw), indices, offsets, weights
    finally:
        if free_now:
            lib.fa_free_result(res_ptr)


def preprocess_buffer(data: bytes, min_support: float) -> NativeResult:
    """Run the one-pass native preprocessing over raw file bytes."""
    lib = get_lib()
    if lib is None:
        raise RuntimeError(
            "native preprocessing library is not built; run "
            "`make -C fastapriori_tpu/native`"
        )
    res_ptr = lib.fa_preprocess_buffer(
        data, len(data), ctypes.c_double(min_support)
    )
    if not res_ptr:
        raise MemoryError("fa_preprocess_buffer failed")
    free_now = True
    try:
        res = res_ptr.contents
        f = int(res.n_items)
        t = int(res.n_baskets)
        items_raw = ctypes.string_at(res.items_buf, res.items_buf_len)
        # Keyed on f, not the byte length: a frequent EMPTY token (a
        # dataset with >= min_count blank lines) makes the items string
        # legitimately empty while f == 1 — split still yields [""].
        freq_items = (
            [] if f == 0 else items_raw.decode("utf-8").split("\n")
        )
        assert len(freq_items) == f, (len(freq_items), f)
        item_counts = np.ctypeslib.as_array(res.item_counts, shape=(max(f, 1),))[
            :f
        ].copy()
        offsets = np.ctypeslib.as_array(
            res.basket_offsets, shape=(t + 1,)
        ).copy()
        nnz = int(offsets[-1]) if t else 0
        if nnz:
            # Zero-copy: view the native CSR arena directly (~0.6 GB at
            # Webdocs scale — the .copy() was a full extra second on this
            # host).  The native result is freed when the LAST view dies:
            # slices hold the parent array via .base, and the finalizer
            # hangs off the parent.
            import weakref

            base = np.ctypeslib.as_array(res.basket_items, shape=(nnz,))
            base.flags.writeable = False
            weakref.finalize(base, lib.fa_free_result, res_ptr)
            indices = base[:nnz]
            free_now = False
        else:
            indices = np.empty(0, dtype=np.int32)
        weights = np.ctypeslib.as_array(res.weights, shape=(max(t, 1),))[
            :t
        ].copy()
        return (
            int(res.n_raw),
            int(res.min_count),
            freq_items,
            item_counts,
            indices,
            offsets,
            weights,
        )
    finally:
        if free_now:
            lib.fa_free_result(res_ptr)


def fill_packed_bitmap(
    indices: np.ndarray, offsets: np.ndarray, out: np.ndarray
) -> bool:
    """Set CSR basket bits into a zeroed bit-packed bitmap ``out``
    (uint8[t_pad, f_pad//8], MSB-first like numpy packbits).  Returns
    False when the native library is unavailable (caller falls back)."""
    lib = get_lib()
    if lib is None or getattr(lib, "fa_fill_packed_bitmap", None) is None:
        return False
    assert out.dtype == np.uint8 and out.flags["C_CONTIGUOUS"]
    offsets = np.ascontiguousarray(offsets, dtype=np.int64)
    indices = np.ascontiguousarray(indices, dtype=np.int32)
    n_baskets = len(offsets) - 1
    assert out.shape[0] >= n_baskets
    if len(indices):
        # The C filler does no bounds checks (the numpy fallback's fancy
        # indexing would raise); fence inconsistent CSR input here.  A
        # real exception, not an assert: under `python -O` an assert
        # vanishes and out-of-range indices would corrupt the heap.
        lo, hi = int(indices.min()), int(indices.max())
        if lo < 0 or hi >= out.shape[1] * 8:
            raise ValueError(
                f"CSR item index out of range for the packed bitmap: "
                f"min={lo}, max={hi}, columns={out.shape[1] * 8}"
            )
    lib.fa_fill_packed_bitmap(
        offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        indices.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        ctypes.c_int64(n_baskets),
        ctypes.c_int64(out.shape[1]),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
    )
    return True


def preprocess_file(path: str, min_support: float) -> NativeResult:
    with open(path, "rb") as fh:
        return preprocess_buffer(fh.read(), min_support)


def has_preprocess_buffer_blocks() -> bool:
    lib = get_lib()
    return (
        lib is not None
        and getattr(lib, "fa_preprocess_buffer_blocks", None) is not None
    )


def has_pass1_probe() -> bool:
    """True when the .so exports the pass-1-callback flavor
    (``fa_preprocess_buffer_blocks2``) — a stale build without it keeps
    the probe-less capture path."""
    lib = get_lib()
    return (
        lib is not None
        and getattr(lib, "fa_preprocess_buffer_blocks2", None) is not None
    )


def preprocess_buffer_blocks(
    data: bytes, min_support: float, n_blocks: int, on_block,
    n_threads: int = 1, copy_items: bool = True, on_pass1=None,
):
    """Capture-replay pipelined preprocessing: pass 1 + rank assignment +
    per-block pass-2 id replay in ONE native call (the raw bytes are
    tokenized exactly once).  ``n_threads > 1`` replays blocks on
    std::threads; ``on_block(f, offsets int64[t+1], items int32[nnz],
    weights int32[t])`` fires per block mid-call — always from the
    calling thread, always in block order — with COPIES the callee
    owns, EXCEPT ``items`` when ``copy_items=False``: then it is a view
    into the native block arena, valid ONLY for the duration of the
    callback (the copy is ~0.7 GB of memcpy at webdocs scale; callers
    that consume items inside the callback — bitmap packing, heavy-row
    extraction — skip it).  Returns the global tables
    ``(n_raw, min_count, freq_items, item_counts)``.

    ``on_pass1(n_raw, min_count, f, item_counts int64[f])`` fires ONCE
    after pass 1 / rank assignment and before any block replays — the
    hook the mining-engine density probe rides (models/apriori.py) so a
    layout choice can steer the block callbacks without re-tokenizing;
    requires the ``fa_preprocess_buffer_blocks2`` export
    (:func:`has_pass1_probe`)."""
    from fastapriori_tpu.reliability import failpoints

    failpoints.fire("native.blocks")
    lib = get_lib()
    if lib is None or getattr(lib, "fa_preprocess_buffer_blocks", None) is None:
        raise RuntimeError(
            "native block-preprocess entry point unavailable; rebuild "
            "with `make -C fastapriori_tpu/native`"
        )
    if on_pass1 is not None and getattr(
        lib, "fa_preprocess_buffer_blocks2", None
    ) is None:
        raise RuntimeError(
            "native pass-1-probe entry point unavailable; rebuild with "
            "`make -C fastapriori_tpu/native` (or call without on_pass1)"
        )
    # Accept bytes OR any readonly buffer (an mmap'd file via a numpy
    # view — the caller avoids copying a GB-scale file into a bytes
    # object just to hand the native scan a pointer).  bytearray goes
    # through the buffer branch: ctypes' c_char_p accepts only bytes.
    if isinstance(data, bytes):
        data_arg: object = data
        data_len = len(data)
    else:
        arr = (
            data
            if isinstance(data, np.ndarray)
            else np.frombuffer(data, dtype=np.uint8)
        )
        # Real exceptions, not asserts (python -O), and contiguity is
        # load-bearing: ctypes.data ignores strides, so a strided view
        # would scan the WRONG bytes silently.
        if arr.dtype != np.uint8 or arr.ndim != 1:
            raise TypeError(
                "buffer input must be 1-D uint8 (or bytes); got "
                f"{arr.dtype} ndim={arr.ndim}"
            )
        if not arr.flags["C_CONTIGUOUS"]:
            raise ValueError("buffer input must be C-contiguous")
        data_arg = arr.ctypes.data_as(ctypes.c_char_p)
        data_len = arr.size
    errs: list = []

    @_FA_BLOCK_CB
    def cb(_ctx, f, t, offs_p, items_p, w_p):
        # Once any block's consumer has failed, stop producing side
        # effects (device uploads, queued futures) for the remaining
        # blocks — the native call keeps compressing either way (no
        # abort channel in the C ABI), but its results are discarded and
        # the first error re-raises after it returns (ADVICE r3).
        if errs:
            return
        try:
            t = int(t)
            offsets = np.ctypeslib.as_array(offs_p, shape=(t + 1,)).copy()
            nnz = int(offsets[-1])
            items = np.ctypeslib.as_array(items_p, shape=(max(nnz, 1),))[
                :nnz
            ]
            if copy_items:
                items = items.copy()
            else:
                # The view dies with this callback (the native arena is
                # reused for the next block); freeze it so a consumer
                # that tries to mutate a stored dangling view fails
                # loudly instead of scribbling on recycled memory
                # (ADVICE r5 #3).  Reads of a stored view are still
                # dangling — hence the retaining callers assert
                # copy_items=True (models/apriori.py).
                items.flags.writeable = False
            weights = np.ctypeslib.as_array(w_p, shape=(max(t, 1),))[
                :t
            ].copy()
            on_block(int(f), offsets, items, weights)
        # lint: waive G006 -- captured into errs and re-raised after the C call
        except BaseException as e:  # never unwind through the C frame
            errs.append(e)

    if on_pass1 is not None:

        @_FA_PASS1_CB
        def p1cb(_ctx, n_raw, min_count, f, counts_p):
            if errs:
                return
            try:
                f = int(f)
                counts = (
                    np.ctypeslib.as_array(counts_p, shape=(f,)).copy()
                    if f > 0
                    else np.empty(0, dtype=np.int64)
                )
                on_pass1(int(n_raw), int(min_count), f, counts)
            # lint: waive G006 -- captured into errs and re-raised after the C call
            except BaseException as e:  # never unwind through the C frame
                errs.append(e)

        res_ptr = lib.fa_preprocess_buffer_blocks2(
            data_arg, data_len, ctypes.c_double(min_support), n_blocks,
            max(n_threads, 1), p1cb, cb, None
        )
    else:
        res_ptr = lib.fa_preprocess_buffer_blocks(
            data_arg, data_len, ctypes.c_double(min_support), n_blocks,
            max(n_threads, 1), cb, None
        )
    if not res_ptr:
        if errs:
            raise errs[0]
        raise MemoryError("fa_preprocess_buffer_blocks failed")
    try:
        # A callback error still frees the native result (finally below).
        if errs:
            raise errs[0]
        res = res_ptr.contents
        f = int(res.n_items)
        items_raw = ctypes.string_at(res.items_buf, res.items_buf_len)
        # Keyed on f, not the byte length: a frequent EMPTY token (a
        # dataset with >= min_count blank lines) makes the items string
        # legitimately empty while f == 1 — split still yields [""].
        freq_items = (
            [] if f == 0 else items_raw.decode("utf-8").split("\n")
        )
        assert len(freq_items) == f, (len(freq_items), f)
        item_counts = np.ctypeslib.as_array(
            res.item_counts, shape=(max(f, 1),)
        )[:f].copy()
        return int(res.n_raw), int(res.min_count), freq_items, item_counts
    finally:
        lib.fa_free_result(res_ptr)


def gen_candidates_native(level: np.ndarray):
    """Prefix join + Apriori subset prune over a lex-sorted int32 [M, s]
    level matrix (reference C7).  Returns ``(x_idx int64[C], y int32[C])``
    in global (x_idx, y) order — identical to
    models/candidates.gen_candidates_arrays.  Raises if the native
    library (or a stale build) lacks the entry point."""
    lib = get_lib()
    if lib is None or getattr(lib, "fa_gen_candidates", None) is None:
        raise RuntimeError(
            "native candidate-gen entry point unavailable; rebuild with "
            "`make -C fastapriori_tpu/native`"
        )
    level = np.ascontiguousarray(level, dtype=np.int32)
    m, s = level.shape
    res_ptr = lib.fa_gen_candidates(
        level.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)), m, s
    )
    if not res_ptr:
        raise MemoryError("fa_gen_candidates failed")
    try:
        res = res_ptr.contents
        n = int(res.n)
        x_idx = np.ctypeslib.as_array(res.x_idx, shape=(max(n, 1),))[
            :n
        ].copy()
        y = np.ctypeslib.as_array(res.y, shape=(max(n, 1),))[:n].copy()
        return x_idx, y
    finally:
        lib.fa_free_candidates(res_ptr)


def join_transactions(transactions: Sequence[Sequence[str]]) -> bytes:
    """Re-serialize token lists so the buffer path can run on in-memory
    data (tokens contain no whitespace, so this round-trips exactly).

    The trailing newline is load-bearing: without it a final [""] line
    (the empty-line form) would serialize to a buffer ending in "\\n"
    with nothing after it and be silently dropped by the scanner,
    shifting n_raw and therefore minCount."""
    if not transactions:
        return b""
    return ("\n".join(" ".join(t) for t in transactions) + "\n").encode(
        "utf-8"
    )
