// Native preprocessing: tokenize + item count + rank + basket dedup in one
// pass over the raw bytes (reference components C3/C4, FastApriori.scala:
// 52-85 — there they are Spark shuffle passes; here a single C++ scan).
//
// Semantics contract (must match fastapriori_tpu/preprocess.py exactly;
// tests/test_native.py enforces equality):
//   - lines split on '\n'; each line trimmed then split on ASCII whitespace
//     runs; an empty (trimmed) line yields ONE empty token (Java
//     String.split("\\s+") semantics, Utils.scala:21);
//   - item occurrence counts: every token occurrence counts, duplicates
//     within a line included (FastApriori.scala:55);
//   - minCount = ceil(min_support * raw_line_count) (FastApriori.scala:39);
//   - frequent items sorted by (-count, numeric-if-integer asc, token asc)
//     (utils/order.py item_sort_key), dense ranks 0..F-1;
//   - baskets: per line, frequent tokens -> ranks, dedup within line, drop
//     size <= 1, dedupe identical baskets with int32 multiplicity
//     (FastApriori.scala:66-79); first-seen order.
//
// Three entry points share the helpers below (ONE tokenizer, ONE dedup):
//   - fa_preprocess_buffer: the whole pipeline for a single host;
//   - fa_count_buffer + fa_compress_with_ranks: the split phases of the
//     multi-host SHARDED ingest (preprocess.py preprocess_file_sharded) —
//     each process counts and compresses only its own byte range against
//     globally merged rank tables.  Identical baskets in different shards
//     stay separate rows with their own multiplicities; weighted counts
//     are unaffected, so cross-shard dedup is unnecessary.
//
// C ABI only (loaded via ctypes): fa_preprocess_buffer / fa_count_buffer /
// fa_compress_with_ranks / fa_fill_packed_bitmap / fa_free_*.

#include <algorithm>
#include <atomic>
#include <cctype>
#include <chrono>
#include <cmath>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <deque>
#include <mutex>
#include <new>
#include <string>
#include <thread>
#include <string_view>
#include <unordered_map>
#include <vector>

#if defined(__AVX512BW__) && defined(__BMI__)
#define FA_HAVE_AVX512 1
#include <immintrin.h>
#endif

#ifdef __linux__
#include <sys/mman.h>
#endif

namespace {
// Ask the kernel for transparent huge pages on a large heap range (THP
// policy "madvise" needs the hint): the GB-scale capture/arena buffers
// otherwise fault in ~4 KB at a time — ~220K soft faults (~0.2 s) per
// GB on first touch.  Best-effort; errors are ignored.
inline void advise_hugepages(void* ptr, size_t bytes) {
#if defined(__linux__) && defined(MADV_HUGEPAGE)
  if (!ptr || bytes < (8u << 20)) return;
  uintptr_t lo = (reinterpret_cast<uintptr_t>(ptr) + 4095) & ~uintptr_t(4095);
  uintptr_t hi =
      (reinterpret_cast<uintptr_t>(ptr) + bytes) & ~uintptr_t(4095);
  if (hi > lo) madvise(reinterpret_cast<void*>(lo), hi - lo, MADV_HUGEPAGE);
#else
  (void)ptr;
  (void)bytes;
#endif
}
}  // namespace

// FA_NATIVE_TIMING=1 prints per-phase wall times to stderr (diagnostics
// for the single-core preprocess budget; no effect on results).
namespace {
struct PhaseTimer {
  bool on;
  std::chrono::steady_clock::time_point t0;
  PhaseTimer() : on(std::getenv("FA_NATIVE_TIMING") != nullptr) {
    t0 = std::chrono::steady_clock::now();
  }
  void mark(const char* name) {
    if (!on) return;
    auto t1 = std::chrono::steady_clock::now();
    std::fprintf(
        stderr, "fa_native[%s]: %.3f s\n", name,
        std::chrono::duration<double>(t1 - t0).count());
    t0 = t1;
  }
};
}  // namespace

namespace {

// Byte classes for the tokenizer hot loop: one table load replaces the
// six-way whitespace comparison chain per byte.  bit0 = Java \s
// (ASCII ws the tokenizer splits on), bit1 = decimal digit.
constexpr uint8_t kWs = 1, kDigit = 2;
struct ByteClass {
  uint8_t t[256] = {};
  constexpr ByteClass() {
    t[' '] = t['\t'] = t['\n'] = t['\v'] = t['\f'] = t['\r'] = kWs;
    for (int c = '0'; c <= '9'; ++c) t[c] = kDigit;
  }
};
constexpr ByteClass kByteClass;

inline bool is_ws(unsigned char c) { return kByteClass.t[c] & kWs; }

// Dense fast path: most datasets use small decimal item ids.  A token in
// CANONICAL decimal form (single "0", or leading digit 1-9, all digits, at
// most 7 of them) maps to a slot in a dense array, bypassing the string
// hash maps.  Canonical-form only: "007", "+7" and "7" are DIFFERENT
// tokens for counting purposes and must not collide.
constexpr int64_t kDenseCap = 10'000'000;  // ids 0..9,999,999 (<= 7 digits)

inline int64_t fast_id(std::string_view s) {
  size_t n = s.size();
  if (n == 0 || n > 7) return -1;
  unsigned char c0 = static_cast<unsigned char>(s[0]) - '0';
  if (c0 > 9 || (c0 == 0 && n > 1)) return -1;  // non-digit or leading zero
  int64_t v = c0;
  for (size_t i = 1; i < n; ++i) {
    unsigned char c = static_cast<unsigned char>(s[i]) - '0';
    if (c > 9) return -1;
    v = v * 10 + c;
  }
  return v;
}

// Matches Python int(token) on ASCII: optional sign, all digits.  Python
// ints are arbitrary precision, so the value is kept as a normalized
// (negative, digits-without-leading-zeros) pair and compared by
// (sign, magnitude-length, magnitude-lexical) — exact for any size.
struct BigInt {
  bool negative = false;
  std::string_view digits;  // no leading zeros; empty means 0
};

bool parse_int(std::string_view s, BigInt* out) {
  if (s.empty()) return false;
  size_t i = 0;
  bool neg = false;
  if (s[0] == '+' || s[0] == '-') {
    neg = s[0] == '-';
    if (s.size() == 1) return false;
    i = 1;
  }
  size_t first = i;
  for (; i < s.size(); ++i) {
    if (s[i] < '0' || s[i] > '9') return false;
  }
  while (first < s.size() - 1 && s[first] == '0') ++first;
  std::string_view digits = s.substr(first);
  if (digits == "0") digits = std::string_view();
  out->negative = neg && !digits.empty();  // -0 == 0
  out->digits = digits;
  return true;
}

// v < w as integers.
bool bigint_less(const BigInt& v, const BigInt& w) {
  if (v.negative != w.negative) return v.negative;
  bool less;
  if (v.digits.size() != w.digits.size()) {
    less = v.digits.size() < w.digits.size();
  } else {
    less = v.digits < w.digits;
  }
  return v.negative ? (v.digits != w.digits && !less) : less;
}

// ---- shared scan machinery (ONE copy for all three entry points) -----

// Split on '\n' (last line may lack it), trim with Java String.trim
// semantics (chars <= 0x20), call fn(trimmed_line) per line.
template <class Fn>
inline void for_each_trimmed_line(std::string_view buf, Fn&& fn) {
  size_t pos = 0;
  while (pos <= buf.size()) {
    size_t nl = buf.find('\n', pos);
    size_t end = (nl == std::string_view::npos) ? buf.size() : nl;
    if (nl == std::string_view::npos && pos == buf.size()) break;
    std::string_view line = buf.substr(pos, end - pos);
    size_t b = 0, e = line.size();
    while (b < e && static_cast<unsigned char>(line[b]) <= 0x20) ++b;
    while (e > b && static_cast<unsigned char>(line[e - 1]) <= 0x20) --e;
    fn(line.substr(b, e - b));
    if (nl == std::string_view::npos) break;
    pos = nl + 1;
  }
}

// Tokenize one trimmed NON-EMPTY line on whitespace runs; per token call
// fn(token_view, dense_id_or_minus1).  The canonical-decimal value
// accumulates during the walk, so classification costs no second scan;
// semantics identical to splitting then testing fast_id().  (Empty lines
// are the caller's business: Java split("") yields one empty token.)
template <class Fn>
inline void for_each_token(std::string_view line, Fn&& fn) {
  const char* p = line.data();
  const char* end = p + line.size();
  while (p < end) {
    while (p < end && is_ws(static_cast<unsigned char>(*p))) ++p;
    if (p >= end) break;
    const char* start = p;
    int64_t v = 0;
    bool digits_only = true;
    while (p < end) {
      const uint8_t cls = kByteClass.t[static_cast<unsigned char>(*p)];
      if (cls & kWs) break;
      if (!(cls & kDigit)) {
        digits_only = false;
      } else if (p - start < 7) {  // beyond 7 digits: non-dense anyway
        v = v * 10 + (static_cast<unsigned char>(*p) - '0');
      }
      ++p;
    }
    size_t n = static_cast<size_t>(p - start);
    bool dense = digits_only && n <= 7 && !(start[0] == '0' && n > 1);
    fn(std::string_view(start, n), dense ? v : -1);
  }
}

// Malloc-backed growable int32 buffer whose ownership can transfer into
// a result struct with NO copy (the dedup arena is ~0.6 GB at Webdocs
// scale and the marshal memcpy alone was ~2.5 s on a single-core host).
struct I32Buf {
  int32_t* p = nullptr;
  size_t n = 0, cap = 0;
  bool reserve(size_t want) {
    if (want <= cap) return true;
    size_t nc = cap ? cap * 2 : (1u << 20);
    while (nc < want) nc *= 2;
    auto* np_ = static_cast<int32_t*>(std::realloc(p, nc * sizeof(int32_t)));
    if (!np_) return false;
    p = np_;
    cap = nc;
    advise_hugepages(p, nc * sizeof(int32_t));
    return true;
  }
  bool append(const int32_t* src, size_t k) {
    if (!reserve(n + k)) return false;
    std::memcpy(p + n, src, k * sizeof(int32_t));
    n += k;
    return true;
  }
  // std::vector-style accessors for the pass-1 token capture (which
  // uses this buffer for its UNINITIALIZED growth: a value-initializing
  // vector resize would memset the ~1 GB webdocs-scale capture just to
  // overwrite it).  push_back matches the vector's OOM behavior.
  inline void push_back(int32_t v) {
    if (n == cap && !reserve(n + 1)) throw std::bad_alloc();
    p[n++] = v;
  }
  size_t size() const { return n; }
  int32_t operator[](size_t i) const { return p[i]; }
  void free_buf() {
    std::free(p);
    p = nullptr;
    n = cap = 0;
  }
};

// Distinct-basket accumulator: open-addressing index over (hash, arena
// slice) — no per-basket heap node, no rehash-time key copies; the final
// marshal hands the arena over pointer-for-pointer.  Insertion order =
// first-seen order (FastApriori.scala:74 zipWithIndex over the deduped
// RDD).
struct BasketDeduper {
  I32Buf arena;                  // concatenated sorted rank lists
  std::vector<int64_t> b_off;    // [t] arena offset per basket
  std::vector<int32_t> b_len;    // [t]
  std::vector<int32_t> b_weight; // [t] multiplicity
  std::vector<uint64_t> b_hash;  // [t] cached for table growth
  size_t table_size = 1 << 12;   // power of two
  std::vector<int64_t> table = std::vector<int64_t>(1 << 12, -1);


  void grow_table() {
    table_size *= 2;
    std::fill(table.begin(), table.end(), -1);
    table.resize(table_size, -1);
    const size_t mask = table_size - 1;
    for (size_t id = 0; id < b_off.size(); ++id) {
      size_t slot = static_cast<size_t>(b_hash[id]) & mask;
      while (table[slot] != -1) slot = (slot + 1) & mask;
      table[slot] = static_cast<int64_t>(id);
    }
  }

  // Probe/commit for a rank list ALREADY written at the arena cursor
  // (the fused bitset walk in insert_from_bitset writes there
  // directly).  On a new basket the cursor advances; on a duplicate it
  // stays put — an implicit rollback of the speculative write.
  void commit_at_cursor(size_t n, uint64_t h) {
    const size_t mask = table_size - 1;
    size_t slot = static_cast<size_t>(h) & mask;
    const int32_t* ranks = arena.p + arena.n;
    while (true) {
      int64_t id = table[slot];
      if (id == -1) {  // new distinct basket: commit the written span
        table[slot] = static_cast<int64_t>(b_off.size());
        b_off.push_back(static_cast<int64_t>(arena.n));
        b_len.push_back(static_cast<int32_t>(n));
        b_weight.push_back(1);
        b_hash.push_back(h);
        arena.n += n;
        if (b_off.size() * 10 >= table_size * 7) grow_table();
        return;
      }
      if (b_hash[id] == h && b_len[id] == static_cast<int32_t>(n) &&
          std::memcmp(arena.p + b_off[id], ranks,
                      n * sizeof(int32_t)) == 0) {
        ++b_weight[id];  // duplicate: cursor untouched (rollback)
        return;
      }
      slot = (slot + 1) & mask;
    }
  }

  // Insert one sorted, deduplicated rank list (n >= 2) with its hash
  // (RankCollector.finish computes it during the collection walk — the
  // hash function lives THERE; all inserts must use it).  False on OOM.
  bool insert(const int32_t* ranks, size_t n, uint64_t h) {
    const size_t mask = table_size - 1;
    size_t slot = static_cast<size_t>(h) & mask;
    while (true) {
      int64_t id = table[slot];
      if (id == -1) {  // new distinct basket
        table[slot] = static_cast<int64_t>(b_off.size());
        b_off.push_back(static_cast<int64_t>(arena.n));
        b_len.push_back(static_cast<int32_t>(n));
        b_weight.push_back(1);
        b_hash.push_back(h);
        if (!arena.append(ranks, n)) return false;
        // Load factor <= 0.7 keeps linear probes short.
        if (b_off.size() * 10 >= table_size * 7) grow_table();
        return true;
      }
      if (b_hash[id] == h && b_len[id] == static_cast<int32_t>(n) &&
          std::memcmp(arena.p + b_off[id], ranks,
                      n * sizeof(int32_t)) == 0) {
        ++b_weight[id];
        return true;
      }
      slot = (slot + 1) & mask;
    }
  }
};

// Per-line sorted-unique rank collection.  Small-F fast path: an F-bit
// set makes dedup free and a ctz walk emits sorted ranks in O(F/64 + n)
// instead of sort+unique's O(n log n); F is minSupport-bounded
// (hundreds on the benchmark corpora), so the per-line clear is a few
// words.  Ranks arrive as rank+1 (0 = not frequent, ignored).
struct RankCollector {
  std::vector<int32_t> scratch;
  std::vector<uint64_t> bits;
  size_t n_words = 0;
  bool use_bitset = false;

  explicit RankCollector(int32_t f) {
    n_words = (static_cast<size_t>(f) + 63) / 64;
    use_bitset = f > 0 && f <= 4096;
    if (use_bitset) bits.assign(n_words, 0);
  }
  inline void add(int32_t r_plus_1) {
    if (!r_plus_1) return;
    if (use_bitset) {
      uint32_t rr = static_cast<uint32_t>(r_plus_1 - 1);
      bits[rr >> 6] |= 1ull << (rr & 63);
    } else {
      scratch.push_back(r_plus_1 - 1);
    }
  }
  // Returns the sorted unique ranks for the current line (and clears
  // the bitset for the next one).  ``hash`` is the deduper's basket
  // hash: on the bitset fast path it folds into the ctz walk itself
  // (the ranks are register-hot there, saving the deduper a second
  // pass over every basket); the sort path (F > 4096) hashes in its
  // own pass after sort+unique.
  uint64_t hash = 0;
  static inline uint64_t mix_rank(uint64_t h, int32_t r) {
    h ^= static_cast<uint32_t>(r);
    h *= 0x9E3779B97F4A7C15ull;
    h ^= h >> 29;
    return h;
  }
  inline const std::vector<int32_t>& finish() {
    uint64_t h = 0x243F6A8885A308D3ull;
    if (use_bitset) {
      scratch.clear();
      for (size_t wi = 0; wi < n_words; ++wi) {
        uint64_t w = bits[wi];
        if (!w) continue;
        bits[wi] = 0;
        do {
          const int32_t r = static_cast<int32_t>(
              (wi << 6) + static_cast<size_t>(__builtin_ctzll(w)));
          scratch.push_back(r);
          h = mix_rank(h, r);
          w &= w - 1;
        } while (w);
      }
    } else {
      std::sort(scratch.begin(), scratch.end());
      scratch.erase(std::unique(scratch.begin(), scratch.end()),
                    scratch.end());
      for (int32_t r : scratch) h = mix_rank(h, r);
    }
    hash = h ^ scratch.size();
    return scratch;
  }
  inline void reset_list() {
    if (!use_bitset) scratch.clear();
  }
};

// Fused bitset-walk + dedup insert: emits the line's sorted ranks
// straight into the deduper's arena at the cursor (the arena IS the
// output CSR), so the scratch intermediate and the insert-time memcpy —
// a second pass over every basket's ranks, ~1 GB of cumulative traffic
// at webdocs scale — disappear; the basket hash folds into the same
// walk (same constants as RankCollector::finish).  Caller must have
// reserved arena capacity for all remaining tokens (the replay loops
// do); bitset path only.
inline void walk_insert_bitset(RankCollector& rc, BasketDeduper& dd) {
  int32_t* dst = dd.arena.p + dd.arena.n;
  uint64_t h = 0x243F6A8885A308D3ull;
  size_t n = 0;
  for (size_t wi = 0; wi < rc.n_words; ++wi) {
    uint64_t w = rc.bits[wi];
    if (!w) continue;
    rc.bits[wi] = 0;
    do {
      const int32_t r = static_cast<int32_t>(
          (wi << 6) + static_cast<size_t>(__builtin_ctzll(w)));
      dst[n++] = r;
      h = RankCollector::mix_rank(h, r);
      w &= w - 1;
    } while (w);
  }
  if (n <= 1) return;  // size<=1 baskets are dropped (reference C4)
  dd.commit_at_cursor(n, h ^ n);
}

}  // namespace

extern "C" {

struct FaResult {
  int64_t n_raw;      // raw transaction (line) count
  int64_t min_count;  // ceil(min_support * n_raw)
  int32_t n_items;    // F
  // Frequent item tokens in rank order, '\n'-joined (no trailing newline).
  char* items_buf;
  int64_t items_buf_len;
  int64_t* item_counts;  // [F] occurrence counts by rank
  int64_t n_baskets;     // T'
  int64_t* basket_offsets;  // [T'+1] CSR offsets into basket_items
  int32_t* basket_items;    // flattened sorted ranks
  int32_t* weights;         // [T'] multiplicities
};

void fa_free_result(FaResult* res);

}  // extern "C"

namespace {

// Marshal a BasketDeduper into an FaResult (zero-copy arena handoff —
// on success the arena pointer belongs to the result).  Returns false on
// OOM, leaving the arena owned by the deduper for the caller to free.
bool marshal_baskets(BasketDeduper& dd, FaResult* res) {
  const int64_t t = static_cast<int64_t>(dd.b_off.size());
  const int64_t total_items = static_cast<int64_t>(dd.arena.n);
  res->n_baskets = t;
  res->basket_offsets =
      static_cast<int64_t*>(std::malloc(sizeof(int64_t) * (t + 1)));
  res->basket_items = total_items
      ? dd.arena.p
      : static_cast<int32_t*>(std::malloc(sizeof(int32_t)));
  res->weights =
      static_cast<int32_t*>(std::malloc(sizeof(int32_t) * (t ? t : 1)));
  if (!res->basket_offsets || !res->basket_items || !res->weights) {
    if (res->basket_items == dd.arena.p) res->basket_items = nullptr;
    return false;
  }
  for (int64_t i = 0; i < t; ++i) {
    res->basket_offsets[i] = dd.b_off[i];
    res->weights[i] = dd.b_weight[i];
  }
  res->basket_offsets[t] = total_items;
  if (total_items) dd.arena.p = nullptr;  // ownership transferred
  else dd.arena.free_buf();
  return true;
}

}  // namespace


namespace {

// ---- shared pass-1 capture + rank assignment ------------------------
// ONE copy for both whole-buffer entry points (fa_preprocess_buffer and
// fa_preprocess_buffer_blocks); the sharded fa_count_buffer /
// fa_compress_with_ranks pair keeps its own split-phase contract.
//
// Pass 1: dense array for canonical small-integer tokens (the
// overwhelmingly common case), string hash map for everything else
// (calloc pages lazily, so untouched id ranges cost no physical
// memory).  Every token is also recorded once in parsed form
// (``tok_ids``, line-major with ``tok_offsets`` line boundaries): a
// dense id >= 0, or ``-(side_index+1)`` for non-dense tokens.  Pass 2
// then never touches the raw bytes again — on a 1 GB file a second
// tokenize+parse scan was half the preprocessing cost; the parsed form
// replays at memory bandwidth.

struct FreqItem {
  std::string_view tok;
  int64_t count;
  bool numeric;
  BigInt value;
};


#ifdef FA_HAVE_AVX512
// True when EVERY byte of the buffer is a decimal digit or one of the six
// Java \s whitespace chars — the shape of every integer-id transaction
// file (the reference's own datasets are exactly this).  The vectorized
// pass-1 scan below only handles that alphabet; anything else (letters,
// signs, control bytes that Java trims but does not split on) takes the
// scalar path with its full edge-case semantics.  One read of the buffer
// at memory bandwidth (~100 ms/GB) buys a ~2x faster tokenize pass.
inline bool pass1_fast_supported(std::string_view buf) {
  if (std::getenv("FA_NO_SIMD")) return false;
  const char* p = buf.data();
  size_t size = buf.size();
  const __m512i zero_ch = _mm512_set1_epi8('0');
  const __m512i nine = _mm512_set1_epi8(9);
  const __m512i tab = _mm512_set1_epi8(9);  // '\t'
  const __m512i four = _mm512_set1_epi8(4);
  const __m512i space = _mm512_set1_epi8(' ');
  uint64_t bad = 0;
  for (size_t off = 0; off < size; off += 64) {
    size_t rem = size - off;
    __mmask64 lm = rem >= 64 ? ~0ULL : ((1ULL << rem) - 1);
    __m512i v = _mm512_maskz_loadu_epi8(lm, p + off);
    uint64_t digit =
        _mm512_cmple_epu8_mask(_mm512_sub_epi8(v, zero_ch), nine);
    uint64_t ws =
        _mm512_cmpeq_epi8_mask(v, space) |
        _mm512_cmple_epu8_mask(_mm512_sub_epi8(v, tab), four);
    bad |= lm & ~(digit | ws);
    if (bad) return false;
  }
  return true;
}
#endif  // FA_HAVE_AVX512

// One pass-1 scan unit: a line-aligned byte range captured independently
// so pass 1 parallelizes across cores (VERDICT r5 next #3 — the 2.4 s
// single-core webdocs scan was ~28-40% of the best wall).  Each segment
// owns its token capture and its LOCAL side-token table; the global
// merge (counts, ranks) happens once after the scan threads join, and a
// tiny per-segment ``side_rank`` remap resolves local side indexes to
// global ranks — the same merge argument as the multi-host sharded
// ingest's count tables.
struct Pass1Segment {
  int64_t n_raw = 0;
  I32Buf tok_ids;                    // dense id >= 0, or -(side_index+1)
  std::vector<int64_t> tok_offsets;  // [n_raw+1] line boundaries (local)
  std::vector<std::string_view> side_toks;   // local side index -> token
  std::unordered_map<std::string_view, std::pair<int64_t, int32_t>> counts;
  int64_t* dense_counts = nullptr;   // [kDenseCap] occurrence counts
  int64_t max_dense_id = -1;
  std::vector<int32_t> side_rank;    // rank+1 by LOCAL side index

  ~Pass1Segment() {
    std::free(dense_counts);
    tok_ids.free_buf();  // I32Buf is manually managed (ownership moves)
  }
};

struct Pass1Capture {
  int64_t n_raw = 0;
  int64_t min_count = 0;
  int32_t f = 0;
  std::deque<Pass1Segment> segs;     // 1 segment unless n_threads > 1
  std::vector<FreqItem> freq;        // rank order
  int32_t* dense_rank = nullptr;     // rank+1 by dense id (may be null)
  // Backing storage freq's string_views may point into:
  std::unordered_map<std::string_view, std::pair<int64_t, int32_t>> counts;
  std::deque<std::string> dense_tok_arena;

  ~Pass1Capture() { std::free(dense_rank); }

  inline int32_t rank_plus_1(const Pass1Segment& seg, int32_t id) const {
    return id >= 0 ? dense_rank[id] : seg.side_rank[-id - 1];
  }

  // Scan ONE line-aligned range into ``seg``.  False on allocation
  // failure.  Thread-safe across distinct segments (no shared state).
  static bool scan_segment(std::string_view buf, Pass1Segment& seg) {
    int64_t* dense_counts =
        static_cast<int64_t*>(std::calloc(kDenseCap, sizeof(int64_t)));
    auto& counts = seg.counts;
    counts.reserve(1 << 16);
    auto& side_toks = seg.side_toks;
    auto& tok_ids = seg.tok_ids;
    auto& tok_offsets = seg.tok_offsets;
    int64_t& n_raw = seg.n_raw;
    tok_ids.reserve(buf.size() / 4 + 16);
    tok_offsets.reserve(buf.size() / 64 + 16);
    // Count a non-dense token and return its encoded id (-(index+1));
    // the two scan paths append it with their own write discipline.
    auto side_id = [&](std::string_view tok) -> int32_t {
      auto [it, inserted] = counts.try_emplace(
          tok, 0, static_cast<int32_t>(side_toks.size()));
      if (inserted) side_toks.push_back(tok);
      ++it->second.first;
      return -(it->second.second + 1);
    };
    auto side_token = [&](std::string_view tok) {
      tok_ids.push_back(side_id(tok));
    };
    int64_t max_dense_id = -1;
    bool fast = false;
#ifdef FA_HAVE_AVX512
    // Vectorized scan for digits+whitespace buffers: 64-byte blocks are
    // classified into digit/newline masks; tokens are maximal digit
    // runs iterated via trailing-zero counts (a token is a contiguous
    // byte span of the buffer, so runs crossing block boundaries carry
    // only a (start, length) pair and parse at emit time).  Line
    // semantics are identical to for_each_trimmed_line on this
    // alphabet: trim == whitespace-strip, and a line with no digits
    // yields the single empty token (Java split("") -> [""]).
    if (dense_counts && pass1_fast_supported(buf)) {
      fast = true;
      const char* base = buf.data();
      size_t size = buf.size();
      size_t line_start = 0;
      bool line_open = false;
      bool line_had_token = false;
      // Unchecked writes through a raw cursor: push_back's per-element
      // size check + bump was ~30% of the whole scan (0.5 s over 226M
      // webdocs tokens).  Capacity is re-guaranteed once per 64-byte
      // BLOCK (bounded appends per block: <= 33 token emits + <= 64
      // newline empty-tokens), and the buffer's logical size is set
      // once at the end.
      int32_t* tok_raw = tok_ids.p;
      size_t tn = 0;
      auto open_line = [&] {
        if (!line_open) {
          ++n_raw;
          tok_offsets.push_back(static_cast<int64_t>(tn));
          line_open = true;
          line_had_token = false;
        }
      };
      auto close_line = [&] {
        open_line();  // whitespace-only lines still count
        if (!line_had_token) {
          tok_raw[tn++] = side_id(std::string_view(""));
        }
        line_open = false;
      };
      const char* buf_end = base + size;
      auto emit_run = [&](const char* s, size_t n) {
        open_line();
        line_had_token = true;
        if (n <= 7 && !(n > 1 && s[0] == '0')) {  // canonical decimal
          int64_t v;
          if (buf_end - s >= 8) {  // full 8-byte load stays in bounds
            // SWAR parse (simdjson-style): low byte is the most
            // significant digit; shifting the masked load left pads
            // with leading zero digits, so one multiply tree replaces
            // the n-step serial multiply-add chain (the chain's ~4
            // cycles per digit dominated the per-token cost).
            uint64_t raw;
            std::memcpy(&raw, s, 8);
            raw &= 0x0F0F0F0F0F0F0F0FULL;
            raw <<= (8 - n) * 8;
            raw = (raw * 2561) >> 8;
            raw = (raw & 0x00FF00FF00FF00FFULL) * 6553601 >> 16;
            v = static_cast<int64_t>(
                (raw & 0x0000FFFF0000FFFFULL) * 42949672960001ULL >> 32);
          } else {  // within 8 bytes of the buffer end: no overread
            v = 0;
            for (size_t i = 0; i < n; ++i) {
              v = v * 10 + static_cast<unsigned char>(s[i] - '0');
            }
          }
          ++dense_counts[v];
          if (v > max_dense_id) max_dense_id = v;
          tok_raw[tn++] = static_cast<int32_t>(v);
        } else {  // >7 digits or leading zero: non-dense token
          tok_raw[tn++] = side_id(std::string_view(s, n));
        }
      };
      const __m512i zero_ch = _mm512_set1_epi8('0');
      const __m512i nine = _mm512_set1_epi8(9);
      const __m512i newline = _mm512_set1_epi8('\n');
      const char* run_start = nullptr;  // digit run spanning blocks
      size_t run_len = 0;
      for (size_t off = 0; off < size; off += 64) {
        if (tn + 160 > tok_ids.cap) {  // per-block append bound
          if (!tok_ids.reserve(std::max(tok_ids.cap * 2, tn + 1024))) {
            throw std::bad_alloc();  // like the scalar path's push_back
          }
          tok_raw = tok_ids.p;
        }
        size_t rem = size - off;
        __mmask64 lm = rem >= 64 ? ~0ULL : ((1ULL << rem) - 1);
        __m512i v = _mm512_maskz_loadu_epi8(lm, base + off);
        uint64_t d =
            _mm512_cmple_epu8_mask(_mm512_sub_epi8(v, zero_ch), nine) & lm;
        uint64_t nl = _mm512_cmpeq_epi8_mask(v, newline) & lm;
        if (run_len) {  // run carried in from the previous block
          if (d == ~0ULL) {  // whole block digits: keep carrying
            run_len += 64;
            continue;
          }
          size_t ext = static_cast<size_t>(_tzcnt_u64(~d));
          run_len += ext;
          emit_run(run_start, run_len);
          run_len = 0;
          if (ext) d &= ~((1ULL << ext) - 1);
        }
        uint64_t starts = d & ~(d << 1);
        while (starts | nl) {
          unsigned s_pos =
              starts ? static_cast<unsigned>(_tzcnt_u64(starts)) : 64;
          unsigned n_pos =
              nl ? static_cast<unsigned>(_tzcnt_u64(nl)) : 64;
          if (n_pos < s_pos) {
            close_line();
            line_start = off + n_pos + 1;
            nl &= nl - 1;
          } else {
            uint64_t rest = d >> s_pos;
            size_t len = rest == ~0ULL
                             ? 64
                             : static_cast<size_t>(_tzcnt_u64(~rest));
            if (s_pos + len >= 64) {  // run reaches the block edge
              run_start = base + off + s_pos;
              run_len = 64 - s_pos;
            } else {
              emit_run(base + off + s_pos, len);
            }
            starts &= starts - 1;
          }
        }
      }
      if (run_len) emit_run(run_start, run_len);
      if (line_start < size) close_line();  // final line without '\n'
      tok_ids.n = tn;  // commit the cursor as the logical size
    }
#endif  // FA_HAVE_AVX512
    if (!fast) {
      for_each_trimmed_line(buf, [&](std::string_view line) {
        ++n_raw;
        tok_offsets.push_back(static_cast<int64_t>(tok_ids.size()));
        if (line.empty()) {
          side_token(std::string_view(""));  // Java split("") -> [""]
          return;
        }
        for_each_token(line, [&](std::string_view tok, int64_t dense_id) {
          if (dense_id >= 0 && dense_counts) {
            ++dense_counts[dense_id];
            if (dense_id > max_dense_id) max_dense_id = dense_id;
            tok_ids.push_back(static_cast<int32_t>(dense_id));
          } else {
            side_token(tok);
          }
        });
      });
    }
    tok_offsets.push_back(static_cast<int64_t>(tok_ids.size()));
    seg.dense_counts = dense_counts;
    seg.max_dense_id = max_dense_id;
    return true;
  }

  // False on allocation failure.  ``n_threads > 1`` scans line-aligned
  // segments on std::threads (pass 1 parallelized); 1 is the exact
  // legacy single-segment scan.
  bool run(std::string_view buf, double min_support, PhaseTimer& timer,
           int32_t n_threads = 1) {
    // Line-aligned segment boundaries (same rule as the Python side's
    // split_buffer_ranges: nominal cut advanced past the next '\n';
    // the straddling line belongs to the earlier segment).
    std::vector<size_t> cuts{0};
    const size_t size = buf.size();
    const int32_t n_segs = n_threads > 1 ? n_threads : 1;
    for (int32_t i = 1; i < n_segs; ++i) {
      size_t b = (size * static_cast<size_t>(i)) / n_segs;
      size_t prev = cuts.back();
      if (b <= prev) {
        cuts.push_back(prev);
        continue;
      }
      if (buf[b - 1] == '\n') {
        cuts.push_back(b);
      } else {
        size_t j = buf.find('\n', b);
        cuts.push_back(j == std::string_view::npos ? size : j + 1);
      }
    }
    cuts.push_back(size);
    for (size_t i = 0; i + 1 < cuts.size(); ++i) segs.emplace_back();
    std::atomic<bool> ok{true};
    if (segs.size() == 1) {
      ok = scan_segment(buf, segs[0]);
    } else {
      std::vector<std::thread> threads;
      threads.reserve(segs.size());
      for (size_t s = 0; s < segs.size(); ++s) {
        threads.emplace_back([&, s] {
          if (!scan_segment(buf.substr(cuts[s], cuts[s + 1] - cuts[s]),
                            segs[s])) {
            ok = false;
          }
        });
      }
      for (auto& th : threads) th.join();
    }
    if (!ok) return false;
    timer.mark("pass1_tokenize_count");

    // ---- merge (tiny next to the scans: count tables only) ----------
    n_raw = 0;
    int64_t max_dense_id = -1;
    for (auto& seg : segs) {
      n_raw += seg.n_raw;
      if (seg.max_dense_id > max_dense_id) max_dense_id = seg.max_dense_id;
    }
    // Dense counts merge into segment 0's array (untouched id ranges
    // cost no physical pages; the loop runs only to the global max id).
    // Multi-segment requires the dense arrays uniformly present: a
    // token dense-counted in one segment but side-counted in another
    // (one calloc failed) would split its count across two tables and
    // silently mis-threshold — treat that as the OOM it is.
    if (segs.size() > 1) {
      for (auto& seg : segs) {
        if (!seg.dense_counts) return false;
      }
    }
    int64_t* dense_counts = segs[0].dense_counts;
    for (size_t s = 1; s < segs.size(); ++s) {
      int64_t* dc = segs[s].dense_counts;
      for (int64_t id = 0; id <= segs[s].max_dense_id; ++id) {
        dense_counts[id] += dc[id];
      }
    }
    if (segs.size() == 1) {
      counts = std::move(segs[0].counts);
    } else {
      for (auto& seg : segs) {
        for (const auto& [tok, cs] : seg.counts) {
          auto [it, inserted] = counts.try_emplace(tok, cs.first, -1);
          if (!inserted) it->second.first += cs.first;
        }
      }
    }
    min_count = static_cast<int64_t>(
        std::ceil(min_support * static_cast<double>(n_raw)));

    for (int64_t id = 0; id <= max_dense_id; ++id) {
      int64_t c = dense_counts ? dense_counts[id] : 0;
      if (c > 0 && c >= min_count) {  // c > 0: only tokens actually seen
        dense_tok_arena.push_back(std::to_string(id));
        std::string_view tok = dense_tok_arena.back();
        BigInt v;
        parse_int(tok, &v);
        freq.push_back({tok, c, true, v});
      }
    }
    for (const auto& [tok, cs] : counts) {
      if (cs.first >= min_count) {
        BigInt v;
        bool num = parse_int(tok, &v);
        freq.push_back({tok, cs.first, num, v});
      }
    }
    std::sort(freq.begin(), freq.end(),
              [](const FreqItem& a, const FreqItem& b) {
                if (a.count != b.count) return a.count > b.count;
                if (a.numeric != b.numeric) return a.numeric;
                if (a.numeric) {
                  if (bigint_less(a.value, b.value)) return true;
                  if (bigint_less(b.value, a.value)) return false;
                }
                return a.tok < b.tok;
              });
    f = static_cast<int32_t>(freq.size());
    // Rank tables (rank+1; 0 = not frequent) keyed the same way pass 1
    // recorded the tokens: dense id -> GLOBAL dense_rank, local side
    // index -> per-segment side_rank remap.  Pass 2's per-token lookup
    // is one array read either way.
    if (dense_counts && max_dense_id >= 0) {
      dense_rank = static_cast<int32_t*>(
          std::calloc(max_dense_id + 1, sizeof(int32_t)));
      if (!dense_rank) return false;  // dense tok_ids unresolvable
    }
    std::unordered_map<std::string_view, int32_t> side_of;  // tok->rank+1
    for (int32_t r = 0; r < f; ++r) {
      int64_t id = freq[r].numeric ? fast_id(freq[r].tok) : -1;
      // A canonical-decimal token lands in dense_rank only if SOME
      // segment dense-tracked it; with per-segment dense alloc failures
      // it may live in the side tables instead — route it there too.
      bool in_dense = dense_rank && id >= 0 && id <= max_dense_id &&
                      dense_counts && dense_counts[id] > 0;
      if (in_dense) {
        dense_rank[id] = r + 1;
      }
      if (!in_dense || segs.size() > 1) {
        // Multi-segment: a token can be dense in one segment and
        // side-tracked in another (alloc failure); publish both.
        side_of[freq[r].tok] = r + 1;
      }
    }
    for (auto& seg : segs) {
      seg.side_rank.assign(seg.side_toks.size(), 0);
      for (size_t i = 0; i < seg.side_toks.size(); ++i) {
        auto it = side_of.find(seg.side_toks[i]);
        if (it != side_of.end()) seg.side_rank[i] = it->second;
      }
      std::free(seg.dense_counts);
      seg.dense_counts = nullptr;
    }
    dense_counts = nullptr;  // freed via segs[0]
    timer.mark("rank_assign");
    return true;
  }
};

// Collect one line's ranks from the captured token ids into the
// collector.  AVX-512 fast path for the dominant shape (all-dense ids,
// bitset-sized F): 16 rank lookups ride one gather — the serial
// load -> rank lookup -> bit set chain was ~14 cycles/token and pass-2
// replay is one rank lookup per captured token (226M on webdocs).
// Frequent lanes compress into a register-packed buffer and set bits
// scalar (f <= 4096 keeps the words in L1).  Any negative (side-table)
// lane falls back to the scalar path for that group.
inline void collect_line_ranks(
    const Pass1Capture& p1, const Pass1Segment& seg, RankCollector& rc,
    int64_t ti, int64_t ti_end) {
#ifdef FA_HAVE_AVX512
  const int32_t* ids = seg.tok_ids.p;
  const int32_t* dr = p1.dense_rank;
  if (dr && rc.use_bitset) {
    uint64_t* bits = rc.bits.data();
    for (; ti + 16 <= ti_end; ti += 16) {
      __m512i v = _mm512_loadu_si512(
          reinterpret_cast<const void*>(ids + ti));
      __mmask16 neg =
          _mm512_cmplt_epi32_mask(v, _mm512_setzero_si512());
      if (neg) {  // rare: side-table tokens in this group
        for (int i = 0; i < 16; ++i) {
          rc.add(p1.rank_plus_1(seg, ids[ti + i]));
        }
        continue;
      }
      __m512i ranks = _mm512_i32gather_epi32(v, dr, 4);  // rank+1
      __mmask16 freq =
          _mm512_cmpgt_epi32_mask(ranks, _mm512_setzero_si512());
      alignas(64) int32_t rbuf[16];
      _mm512_store_si512(
          rbuf,
          _mm512_maskz_compress_epi32(
              freq, _mm512_sub_epi32(ranks, _mm512_set1_epi32(1))));
      const int n = __builtin_popcount(freq);
      for (int i = 0; i < n; ++i) {
        const uint32_t rr = static_cast<uint32_t>(rbuf[i]);
        bits[rr >> 6] |= 1ull << (rr & 63);
      }
    }
  }
#endif  // FA_HAVE_AVX512
  for (; ti < ti_end; ++ti) rc.add(p1.rank_plus_1(seg, seg.tok_ids[ti]));
}

// Marshal the global tables (items in rank order + counts) into res.
// False on allocation failure.
bool marshal_tables(const Pass1Capture& p1, FaResult* res) {
  res->n_raw = p1.n_raw;
  res->min_count = p1.min_count;
  res->n_items = p1.f;
  int64_t items_len = 0;
  for (const auto& item : p1.freq) items_len += item.tok.size() + 1;
  res->items_buf =
      static_cast<char*>(std::malloc(items_len ? items_len : 1));
  res->items_buf_len = items_len ? items_len - 1 : 0;  // drop trailing \n
  res->item_counts =
      static_cast<int64_t*>(std::malloc(sizeof(int64_t) * (p1.f ? p1.f : 1)));
  if (!res->items_buf || !res->item_counts) return false;
  char* p = res->items_buf;
  for (const auto& item : p1.freq) {
    std::memcpy(p, item.tok.data(), item.tok.size());
    p += item.tok.size();
    *p++ = '\n';
  }
  for (int32_t r = 0; r < p1.f; ++r) res->item_counts[r] = p1.freq[r].count;
  return true;
}

}  // namespace

extern "C" {

// data/len: raw file bytes.  Not nul-terminated.  Returns a heap-allocated
// result (free with fa_free_result) or nullptr on allocation failure.
FaResult* fa_preprocess_buffer(const char* data, int64_t len,
                               double min_support) {
  PhaseTimer timer;
  std::string_view buf(data, static_cast<size_t>(len));

  Pass1Capture p1;
  if (!p1.run(buf, min_support, timer)) return nullptr;
  const Pass1Segment& seg = p1.segs[0];  // single-segment entry point

  // ---- pass 2: basket dedup with multiplicity --------------------------
  // Replays the parsed tokens captured in pass 1 (tok_ids) — no second
  // scan of the raw bytes.
  BasketDeduper dd;
  // Upper bound: one rank per captured token.  Reserving up front keeps
  // realloc from copying the growing arena (~1.2 GB of cumulative copy
  // at Webdocs scale); pages are committed lazily, so over-reservation
  // costs virtual space only.
  if (!dd.arena.reserve(seg.tok_ids.size() + 1)) return nullptr;
  RankCollector rc(p1.f);
  if (rc.use_bitset) {
    // Fused walk+insert straight into the arena (no scratch pass).
    for (int64_t li = 0; li < p1.n_raw; ++li) {
      collect_line_ranks(
          p1, seg, rc, seg.tok_offsets[li], seg.tok_offsets[li + 1]);
      walk_insert_bitset(rc, dd);
    }
  } else {
    for (int64_t li = 0; li < p1.n_raw; ++li) {
      rc.reset_list();
      collect_line_ranks(
          p1, seg, rc, seg.tok_offsets[li], seg.tok_offsets[li + 1]);
      const auto& ranks = rc.finish();
      if (ranks.size() <= 1) continue;
      if (!dd.insert(ranks.data(), ranks.size(), rc.hash)) {
        dd.arena.free_buf();
        return nullptr;
      }
    }
  }
  timer.mark("pass2_dedup");

  // ---- marshal ---------------------------------------------------------
  auto* res = static_cast<FaResult*>(std::calloc(1, sizeof(FaResult)));
  if (!res) {
    dd.arena.free_buf();
    return nullptr;
  }
  bool ok = marshal_tables(p1, res) && marshal_baskets(dd, res);
  if (!ok) {
    // fa_free_result tolerates the partially-filled struct
    // (free(nullptr) is a no-op); the arena is still the deduper's.
    dd.arena.free_buf();
    fa_free_result(res);
    return nullptr;
  }
  timer.mark("marshal");
  return res;
}

// Fill a caller-allocated bit-packed vertical bitmap (MSB-first within
// each byte, matching numpy packbits / ops/fused.py pack_bitmap) straight
// from the CSR baskets: out[row, col>>3] |= 0x80 >> (col&7).  Replaces
// the host-side dense [T, F] int8 intermediate + packbits pass (~0.5 GB
// of traffic at Webdocs scale).  ``out`` must be zeroed, with
// ``row_stride`` bytes per row (= padded F / 8).
void fa_fill_packed_bitmap(const int64_t* offsets, const int32_t* items,
                           int64_t n_baskets, int64_t row_stride,
                           uint8_t* out) {
  for (int64_t i = 0; i < n_baskets; ++i) {
    uint8_t* row = out + i * row_stride;
    for (int64_t j = offsets[i]; j < offsets[i + 1]; ++j) {
      int32_t col = items[j];
      row[col >> 3] |= static_cast<uint8_t>(0x80u >> (col & 7));
    }
  }
}

// ---- sharded-ingest split phases -------------------------------------

struct FaCounts {
  int64_t n_lines;
  int64_t n_tokens;    // distinct tokens seen in this buffer
  char* tokens_buf;    // '\n'-joined distinct tokens (arbitrary order)
  int64_t tokens_buf_len;
  int64_t* counts;     // [n_tokens] occurrence counts
};

void fa_free_counts(FaCounts* res) {
  if (!res) return;
  std::free(res->tokens_buf);
  std::free(res->counts);
  std::free(res);
}

FaCounts* fa_count_buffer(const char* data, int64_t len) {
  std::string_view buf(data, static_cast<size_t>(len));
  int64_t* dense_counts =
      static_cast<int64_t*>(std::calloc(kDenseCap, sizeof(int64_t)));
  std::unordered_map<std::string_view, int64_t> side;
  side.reserve(1 << 14);
  int64_t max_dense_id = -1;
  int64_t n_lines = 0;
  for_each_trimmed_line(buf, [&](std::string_view line) {
    ++n_lines;
    if (line.empty()) {
      ++side[std::string_view("")];  // Java split("") -> [""]
      return;
    }
    for_each_token(line, [&](std::string_view tok, int64_t dense_id) {
      if (dense_id >= 0 && dense_counts) {
        ++dense_counts[dense_id];
        if (dense_id > max_dense_id) max_dense_id = dense_id;
      } else {
        ++side[tok];
      }
    });
  });

  auto* res = static_cast<FaCounts*>(std::calloc(1, sizeof(FaCounts)));
  if (!res) {
    std::free(dense_counts);
    return nullptr;
  }
  res->n_lines = n_lines;
  std::vector<std::pair<std::string, int64_t>> items;
  for (int64_t id = 0; id <= max_dense_id; ++id) {
    if (dense_counts[id] > 0) {
      items.emplace_back(std::to_string(id), dense_counts[id]);
    }
  }
  for (const auto& [tok, c] : side) {
    items.emplace_back(std::string(tok), c);
  }
  std::free(dense_counts);
  res->n_tokens = static_cast<int64_t>(items.size());
  int64_t buf_len = 0;
  for (const auto& [tok, c] : items) buf_len += tok.size() + 1;
  res->tokens_buf = static_cast<char*>(std::malloc(buf_len ? buf_len : 1));
  res->counts = static_cast<int64_t*>(
      std::malloc(sizeof(int64_t) * (items.empty() ? 1 : items.size())));
  if (!res->tokens_buf || !res->counts) {
    fa_free_counts(res);
    return nullptr;
  }
  res->tokens_buf_len = buf_len ? buf_len - 1 : 0;  // drop trailing '\n'
  char* w = res->tokens_buf;
  for (size_t i = 0; i < items.size(); ++i) {
    std::memcpy(w, items[i].first.data(), items[i].first.size());
    w += items[i].first.size();
    *w++ = '\n';
    res->counts[i] = items[i].second;
  }
  return res;
}

// ranks_buf: '\n'-joined item tokens in GLOBAL rank order (f of them).
// Returns an FaResult whose baskets/weights cover only this buffer's
// lines; item_counts is zeroed and items_buf empty (the caller owns the
// global tables).
FaResult* fa_compress_with_ranks(const char* data, int64_t len,
                                 const char* ranks_buf, int64_t ranks_len,
                                 int32_t f) {
  std::string_view buf(data, static_cast<size_t>(len));
  // Rank lookup tables keyed like the tokenizer classifies: canonical
  // small decimals through a dense array, everything else via the map.
  int64_t max_dense_id = -1;
  std::vector<std::pair<std::string_view, int32_t>> side_entries;
  std::vector<std::pair<int64_t, int32_t>> dense_entries;
  {
    std::string_view rb(ranks_buf, static_cast<size_t>(ranks_len));
    size_t pos = 0;
    int32_t r = 0;
    while (r < f) {
      size_t nl = rb.find('\n', pos);
      size_t end = (nl == std::string_view::npos) ? rb.size() : nl;
      std::string_view tok = rb.substr(pos, end - pos);
      int64_t id = fast_id(tok);
      if (id >= 0) {
        dense_entries.emplace_back(id, r + 1);
        if (id > max_dense_id) max_dense_id = id;
      } else {
        side_entries.emplace_back(tok, r + 1);
      }
      ++r;
      if (nl == std::string_view::npos) break;
      pos = nl + 1;
    }
    if (r != f) return nullptr;  // malformed rank table
  }
  int32_t* dense_rank = nullptr;
  if (max_dense_id >= 0) {
    dense_rank = static_cast<int32_t*>(
        std::calloc(max_dense_id + 1, sizeof(int32_t)));
    if (!dense_rank) return nullptr;
    for (const auto& [id, r] : dense_entries) dense_rank[id] = r;
  }
  std::unordered_map<std::string_view, int32_t> side_rank;
  side_rank.reserve(side_entries.size() * 2 + 8);
  for (const auto& [tok, r] : side_entries) side_rank[tok] = r;

  // One pass over this buffer (re-tokenizes; there is no pass-1 capture
  // here — the extra scan is per-shard and parallel across processes).
  BasketDeduper dd;
  RankCollector rc(f);
  int64_t n_lines = 0;
  bool oom = false;
  // On dedup OOM the remaining lines are still split/trimmed (the
  // callback just skips their work) — a known, accepted cost: OOM here
  // is terminal for the shard anyway, and a bool-returning line walker
  // isn't worth complicating the shared helper for.
  for_each_trimmed_line(buf, [&](std::string_view line) {
    if (oom) return;
    ++n_lines;
    rc.reset_list();
    if (line.empty()) {
      auto it = side_rank.find(std::string_view(""));
      if (it != side_rank.end()) rc.add(it->second);
    } else {
      for_each_token(line, [&](std::string_view tok, int64_t dense_id) {
        if (dense_id >= 0) {
          if (dense_rank && dense_id <= max_dense_id) {
            rc.add(dense_rank[dense_id]);
          }
        } else {
          auto it = side_rank.find(tok);
          if (it != side_rank.end()) rc.add(it->second);
        }
      });
    }
    const auto& ranks = rc.finish();
    if (ranks.size() <= 1) return;
    if (!dd.insert(ranks.data(), ranks.size(), rc.hash)) oom = true;
  });
  if (oom) {
    dd.arena.free_buf();
    std::free(dense_rank);
    return nullptr;
  }

  auto* res = static_cast<FaResult*>(std::calloc(1, sizeof(FaResult)));
  if (!res) {
    dd.arena.free_buf();
    std::free(dense_rank);
    return nullptr;
  }
  res->n_raw = n_lines;
  res->min_count = 0;
  res->n_items = f;
  res->items_buf = static_cast<char*>(std::malloc(1));
  res->items_buf_len = 0;
  res->item_counts =
      static_cast<int64_t*>(std::calloc(f ? f : 1, sizeof(int64_t)));
  bool ok =
      res->items_buf && res->item_counts && marshal_baskets(dd, res);
  if (!ok) {
    dd.arena.free_buf();
    std::free(dense_rank);
    fa_free_result(res);
    return nullptr;
  }
  std::free(dense_rank);
  return res;
}

void fa_free_result(FaResult* res) {
  if (!res) return;
  std::free(res->items_buf);
  std::free(res->item_counts);
  std::free(res->basket_offsets);
  std::free(res->basket_items);
  std::free(res->weights);
  std::free(res);
}

}  // extern "C"

// ---------------------------------------------------------------------------
// Apriori candidate generation (reference C7, FastApriori.scala:167-193):
// prefix join + subset prune over a lex-sorted [M, s] level matrix.  The
// numpy implementation (models/candidates.py) spends ~99% of its time in
// the prune's per-subset searchsorted passes (it cannot early-exit per
// candidate); this native version prunes each candidate with early exit
// and a per-(group, drop-position) narrowed binary-search range, making
// host candidate generation a non-factor next to device counting.

namespace {

// Rows [lo, hi) of `level` whose first `plen` ints equal `key` (binary
// search twice over the lex-sorted matrix).
struct RowRange {
  int64_t lo, hi;
};

inline int cmp_prefix(const int32_t* a, const int32_t* key, int32_t plen) {
  for (int32_t d = 0; d < plen; ++d) {
    if (a[d] != key[d]) return a[d] < key[d] ? -1 : 1;
  }
  return 0;
}

RowRange prefix_range(const int32_t* level, int64_t m, int32_t s,
                      const int32_t* key, int32_t plen) {
  int64_t lo = 0, hi = m;
  while (lo < hi) {  // first row with prefix >= key
    int64_t mid = (lo + hi) >> 1;
    if (cmp_prefix(level + mid * s, key, plen) < 0) lo = mid + 1;
    else hi = mid;
  }
  int64_t lo2 = lo, hi2 = m;
  while (lo2 < hi2) {  // first row with prefix > key
    int64_t mid = (lo2 + hi2) >> 1;
    if (cmp_prefix(level + mid * s, key, plen) <= 0) lo2 = mid + 1;
    else hi2 = mid;
  }
  return {lo, lo2};
}

// Is (a_last, y) present as the last two elements of a row inside
// [r.lo, r.hi) (rows there share the first s-2 ints already)?
inline bool tail_exists(const int32_t* level, int32_t s, RowRange r,
                        int32_t a_last, int32_t y) {
  int64_t lo = r.lo, hi = r.hi;
  while (lo < hi) {
    int64_t mid = (lo + hi) >> 1;
    const int32_t* row = level + mid * s + (s - 2);
    bool lt = row[0] != a_last ? row[0] < a_last : row[1] < y;
    if (lt) lo = mid + 1;
    else hi = mid;
  }
  if (lo >= r.hi) return false;
  const int32_t* row = level + lo * s + (s - 2);
  return row[0] == a_last && row[1] == y;
}

}  // namespace

extern "C" {

struct FaCandidates {
  int64_t n;
  int64_t* x_idx;  // [n] prefix row index into the level matrix
  int32_t* y;      // [n] extension rank
};

void fa_free_candidates(FaCandidates* c);

// level: lex-sorted int32 [m, s] row-major.  Returns survivors of the
// prefix join + Apriori subset prune in global (x_idx, y) order, or
// nullptr on allocation failure.  Free with fa_free_candidates.
FaCandidates* fa_gen_candidates(const int32_t* level, int64_t m, int32_t s) {
  auto* res = static_cast<FaCandidates*>(std::malloc(sizeof(FaCandidates)));
  if (!res) return nullptr;
  res->n = 0;
  res->x_idx = nullptr;
  res->y = nullptr;
  if (m < 2 || s < 1) {
    res->x_idx = static_cast<int64_t*>(std::malloc(sizeof(int64_t)));
    res->y = static_cast<int32_t*>(std::malloc(sizeof(int32_t)));
    if (!res->x_idx || !res->y) {
      fa_free_candidates(res);
      return nullptr;
    }
    return res;
  }
  std::vector<int64_t> xs;
  std::vector<int32_t> ys;
  std::vector<int32_t> sub(s);
  // Per-(group, drop-position) narrowed range: rows matching the
  // candidate subset's first s-2 ints (= group prefix minus one element,
  // plus x's last for the deepest position).  Reused across the group's
  // pairs, so each pair's membership test is a short tail search.
  std::vector<RowRange> ranges(s > 1 ? s - 1 : 1);

  auto row = [&](int64_t i) { return level + i * s; };
  int64_t g0 = 0;
  for (int64_t i = 1; i <= m; ++i) {
    bool boundary =
        (i == m) ||
        (s > 1 &&
         std::memcmp(row(i), row(i - 1), sizeof(int32_t) * (s - 1)) != 0);
    if (s == 1) boundary = (i == m);  // single group when s == 1
    if (!boundary) continue;
    const int64_t gn = i - g0;
    if (gn >= 2) {
      const int32_t* shared = row(g0);  // first s-1 ints shared
      if (s == 1) {
        // Level 1 never reaches here in the mining engine (level 2 is
        // the pair matmul) but keep the join semantics total: no prune
        // (candidates have no (s-1)-subsets beyond the joined rows).
        for (int64_t a = g0; a < i; ++a)
          for (int64_t b = a + 1; b < i; ++b) {
            xs.push_back(a);
            ys.push_back(row(b)[0]);
          }
      } else {
        // Precompute, per drop position d in the shared prefix, the row
        // range matching (shared minus position d) as a first-(s-2)
        // prefix.  The candidate subset for (a, b, d) is that prefix +
        // (x_last, y): membership is a tail search in the range.
        for (int32_t d = 0; d + 1 < s; ++d) {
          int32_t w = 0;
          for (int32_t e = 0; e + 1 < s; ++e)
            if (e != d) sub[w++] = shared[e];
          ranges[d] = prefix_range(level, m, s, sub.data(), s - 2);
        }
        for (int64_t a = g0; a < i; ++a) {
          const int32_t a_last = row(a)[s - 1];
          for (int64_t b = a + 1; b < i; ++b) {
            const int32_t yv = row(b)[s - 1];
            bool ok = true;
            for (int32_t d = 0; d + 1 < s; ++d) {
              if (!tail_exists(level, s, ranges[d], a_last, yv)) {
                ok = false;
                break;
              }
            }
            if (ok) {
              xs.push_back(a);
              ys.push_back(yv);
            }
          }
        }
      }
    }
    g0 = i;
  }
  const int64_t n = static_cast<int64_t>(xs.size());
  res->n = n;
  res->x_idx = static_cast<int64_t*>(std::malloc(sizeof(int64_t) * (n ? n : 1)));
  res->y = static_cast<int32_t*>(std::malloc(sizeof(int32_t) * (n ? n : 1)));
  if (!res->x_idx || !res->y) {
    fa_free_candidates(res);
    return nullptr;
  }
  if (n) {
    std::memcpy(res->x_idx, xs.data(), sizeof(int64_t) * n);
    std::memcpy(res->y, ys.data(), sizeof(int32_t) * n);
  }
  return res;
}

void fa_free_candidates(FaCandidates* c) {
  if (!c) return;
  std::free(c->x_idx);
  std::free(c->y);
  std::free(c);
}

}  // extern "C"

// ---------------------------------------------------------------------------
// Pipelined single-host ingest, capture-replay form: the whole
// fa_preprocess_buffer pipeline (pass-1 capture, rank assignment, pass-2
// id replay — never re-tokenizing the raw bytes) but with pass 2 split
// into ``n_blocks`` contiguous line ranges, each handed to the caller
// through ``cb`` AS SOON as it is deduplicated — the Python side starts
// that block's device upload while this function compresses the next
// block.  Per-block dedup only (cross-block duplicate baskets stay
// separate weighted rows; weighted counts are identical — the multi-host
// sharded-ingest correctness argument).  The returned FaResult carries
// the global tables (n_raw, min_count, items, counts) with ZERO baskets;
// the caller assembles the basket CSR from the callback copies.

extern "C" {

typedef void (*FaBlockCb)(void* ctx, int32_t f, int64_t n_baskets,
                          const int64_t* offsets, const int32_t* items,
                          const int32_t* weights);

// Pass-1-complete callback (fa_preprocess_buffer_blocks2): fires once
// after the global tables exist and BEFORE any block replays — the
// caller's chance to pick a layout (e.g. the vertical-engine density
// probe, models/apriori.py) while keeping the capture pipeline's
// tokenize-once property.  ``counts`` are the [f] occurrence counts in
// rank order, valid only for the duration of the callback.
typedef void (*FaPass1Cb)(void* ctx, int64_t n_raw, int64_t min_count,
                          int32_t f, const int64_t* counts);

}  // extern "C"

static FaResult* preprocess_buffer_blocks_impl(
    const char* data, int64_t len, double min_support, int32_t n_blocks,
    int32_t n_threads, FaPass1Cb pass1_cb, FaBlockCb cb, void* cb_ctx) {
  PhaseTimer timer;
  std::string_view buf(data, static_cast<size_t>(len));

  Pass1Capture p1;
  // Pass 1 itself parallelizes across n_threads line-aligned segments
  // (scan_segment) — the OVERLAPPED two-pass ingest: on a multi-core
  // host the tokenize+count scan and the per-block replay below each
  // run at ~n_threads the single-core rate, and replay workers overlap
  // the main thread's callback/packing/upload work.
  if (!p1.run(buf, min_support, timer, n_threads)) return nullptr;
  if (pass1_cb) {
    std::vector<int64_t> cnts(static_cast<size_t>(p1.f));
    for (int32_t r = 0; r < p1.f; ++r) {
      cnts[static_cast<size_t>(r)] = p1.freq[static_cast<size_t>(r)].count;
    }
    pass1_cb(cb_ctx, p1.n_raw, p1.min_count, p1.f,
             cnts.empty() ? nullptr : cnts.data());
  }

  // ---- pass 2: per-block replay + dedup + callback --------------------
  // Blocks split by TOKEN count (not line count) so work per block is
  // even regardless of line-length skew, distributed across pass-1
  // segments by token share (a block never spans segments — the
  // capture buffers are per-segment).  With n_threads > 1 the blocks
  // replay on std::threads (each block has its own deduper; cross-block
  // duplicates stay separate weighted rows) while the MAIN thread
  // invokes cb strictly in block order — the caller sees the same
  // deterministic stream either way.
  if (n_blocks < 1) n_blocks = 1;
  if (n_threads < 1) n_threads = 1;
  struct Range {
    const Pass1Segment* seg;
    int64_t lo, hi;
  };
  std::vector<Range> ranges;
  {
    int64_t total_tok = 0;
    for (const auto& seg : p1.segs) {
      total_tok += static_cast<int64_t>(seg.tok_ids.size());
    }
    for (const auto& seg : p1.segs) {
      if (seg.n_raw == 0) continue;
      const int64_t n_tok = static_cast<int64_t>(seg.tok_ids.size());
      int32_t blocks_s =
          total_tok > 0
              ? static_cast<int32_t>(
                    (static_cast<int64_t>(n_blocks) * n_tok + total_tok - 1) /
                    total_tok)
              : 1;
      if (blocks_s < 1) blocks_s = 1;
      int64_t line_lo = 0;
      for (int32_t b = 0; b < blocks_s && line_lo < seg.n_raw; ++b) {
        const int64_t tok_target = (n_tok * (b + 1)) / blocks_s;
        int64_t line_hi = seg.n_raw;
        if (b != blocks_s - 1) {
          line_hi = std::upper_bound(seg.tok_offsets.begin() + line_lo,
                                     seg.tok_offsets.begin() + seg.n_raw,
                                     tok_target - 1)
                    - seg.tok_offsets.begin();
          if (line_hi <= line_lo) line_hi = line_lo + 1;
          if (line_hi > seg.n_raw) line_hi = seg.n_raw;
        }
        ranges.push_back({&seg, line_lo, line_hi});
        line_lo = line_hi;
      }
    }
  }

  // Replay one segment's lines [lo, hi) into a fresh deduper.  False
  // on OOM.
  auto replay_block = [&p1](const Range& r, BasketDeduper& dd) {
    const Pass1Segment& seg = *r.seg;
    if (!dd.arena.reserve(
            static_cast<size_t>(seg.tok_offsets[r.hi] -
                                seg.tok_offsets[r.lo]) +
            1)) {
      return false;
    }
    RankCollector rc(p1.f);
    if (rc.use_bitset) {
      // Fused walk+insert straight into the arena (no scratch pass);
      // capacity for every remaining token is reserved above.
      for (int64_t li = r.lo; li < r.hi; ++li) {
        collect_line_ranks(
            p1, seg, rc, seg.tok_offsets[li], seg.tok_offsets[li + 1]);
        walk_insert_bitset(rc, dd);
      }
      return true;
    }
    for (int64_t li = r.lo; li < r.hi; ++li) {
      rc.reset_list();
      collect_line_ranks(
          p1, seg, rc, seg.tok_offsets[li], seg.tok_offsets[li + 1]);
      const auto& ranks = rc.finish();
      if (ranks.size() <= 1) continue;
      if (!dd.insert(ranks.data(), ranks.size(), rc.hash)) return false;
    }
    return true;
  };

  bool oom = false;
  std::vector<int64_t> offs;
  auto emit = [&](BasketDeduper& dd) {  // main thread only
    const int64_t t = static_cast<int64_t>(dd.b_off.size());
    if (t > 0) {
      offs.resize(t + 1);
      for (int64_t i = 0; i < t; ++i) offs[i] = dd.b_off[i];
      offs[t] = static_cast<int64_t>(dd.arena.n);
      cb(cb_ctx, p1.f, t, offs.data(), dd.arena.p, dd.b_weight.data());
    }
    dd.arena.free_buf();
  };

  if (n_threads == 1 || ranges.size() <= 1) {
    double replay_s = 0.0, cb_s = 0.0;  // FA_NATIVE_TIMING sub-phases
    for (const Range& r : ranges) {
      BasketDeduper dd;
      auto t_replay0 = std::chrono::steady_clock::now();
      bool ok = replay_block(r, dd);
      replay_s += std::chrono::duration<double>(
                      std::chrono::steady_clock::now() - t_replay0)
                      .count();
      if (!ok) {
        dd.arena.free_buf();
        oom = true;
        break;
      }
      auto t_cb0 = std::chrono::steady_clock::now();
      emit(dd);
      cb_s += std::chrono::duration<double>(
                  std::chrono::steady_clock::now() - t_cb0)
                  .count();
    }
    if (timer.on) {
      std::fprintf(stderr, "fa_native[pass2.replay_dedup]: %.3f s\n",
                   replay_s);
      std::fprintf(stderr, "fa_native[pass2.callback]: %.3f s\n", cb_s);
    }
  } else {
    struct BlockOut {
      BasketDeduper dd;
      bool ok = false;
      bool ready = false;
    };
    std::vector<BlockOut> outs(ranges.size());
    std::mutex mu;
    std::condition_variable cv;
    std::atomic<size_t> next{0};
    auto worker = [&]() {
      while (true) {
        const size_t b = next.fetch_add(1);
        if (b >= ranges.size()) break;
        BlockOut& o = outs[b];
        o.ok = replay_block(ranges[b], o.dd);
        {
          std::lock_guard<std::mutex> lk(mu);
          o.ready = true;
        }
        cv.notify_all();
      }
    };
    const size_t nt = std::min<size_t>(n_threads, ranges.size());
    std::vector<std::thread> threads;
    threads.reserve(nt);
    for (size_t i = 0; i < nt; ++i) threads.emplace_back(worker);
    for (size_t b = 0; b < outs.size(); ++b) {
      {
        std::unique_lock<std::mutex> lk(mu);
        cv.wait(lk, [&] { return outs[b].ready; });
      }
      if (!outs[b].ok) {
        outs[b].dd.arena.free_buf();
        oom = true;
        continue;  // drain remaining blocks' buffers below
      }
      if (!oom) emit(outs[b].dd);
      else outs[b].dd.arena.free_buf();
    }
    for (auto& th : threads) th.join();
  }
  timer.mark("pass2_dedup_blocks");
  if (oom) return nullptr;

  // ---- marshal (tables only; baskets live in the callback copies) -----
  auto* res = static_cast<FaResult*>(std::calloc(1, sizeof(FaResult)));
  if (!res) return nullptr;
  res->n_baskets = 0;
  res->basket_offsets =
      static_cast<int64_t*>(std::calloc(1, sizeof(int64_t)));
  res->basket_items = static_cast<int32_t*>(std::malloc(sizeof(int32_t)));
  res->weights = static_cast<int32_t*>(std::malloc(sizeof(int32_t)));
  if (!marshal_tables(p1, res) || !res->basket_offsets ||
      !res->basket_items || !res->weights) {
    fa_free_result(res);
    return nullptr;
  }
  timer.mark("marshal");
  return res;
}

extern "C" {

FaResult* fa_preprocess_buffer_blocks(const char* data, int64_t len,
                                      double min_support, int32_t n_blocks,
                                      int32_t n_threads, FaBlockCb cb,
                                      void* cb_ctx) {
  return preprocess_buffer_blocks_impl(data, len, min_support, n_blocks,
                                       n_threads, nullptr, cb, cb_ctx);
}

FaResult* fa_preprocess_buffer_blocks2(const char* data, int64_t len,
                                       double min_support, int32_t n_blocks,
                                       int32_t n_threads, FaPass1Cb pass1_cb,
                                       FaBlockCb cb, void* cb_ctx) {
  return preprocess_buffer_blocks_impl(data, len, min_support, n_blocks,
                                       n_threads, pass1_cb, cb, cb_ctx);
}

}  // extern "C"
