// Native preprocessing: tokenize + item count + rank + basket dedup in one
// pass over the raw bytes (reference components C3/C4, FastApriori.scala:
// 52-85 — there they are Spark shuffle passes; here a single C++ scan).
//
// Semantics contract (must match fastapriori_tpu/preprocess.py exactly;
// tests/test_native.py enforces equality):
//   - lines split on '\n'; each line trimmed then split on ASCII whitespace
//     runs; an empty (trimmed) line yields ONE empty token (Java
//     String.split("\\s+") semantics, Utils.scala:21);
//   - item occurrence counts: every token occurrence counts, duplicates
//     within a line included (FastApriori.scala:55);
//   - minCount = ceil(min_support * raw_line_count) (FastApriori.scala:39);
//   - frequent items sorted by (-count, numeric-if-integer asc, token asc)
//     (utils/order.py item_sort_key), dense ranks 0..F-1;
//   - baskets: per line, frequent tokens -> ranks, dedup within line, drop
//     size <= 1, dedupe identical baskets with int32 multiplicity
//     (FastApriori.scala:66-79); first-seen order.
//
// C ABI only (loaded via ctypes): fa_preprocess_buffer / fa_free_result.

#include <algorithm>
#include <cctype>
#include <cmath>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <deque>
#include <string>
#include <string_view>
#include <unordered_map>
#include <vector>

namespace {

struct VecHash {
  size_t operator()(const std::vector<int32_t>& v) const {
    // FNV-1a over the rank bytes.
    uint64_t h = 1469598103934665603ull;
    for (int32_t x : v) {
      for (int i = 0; i < 4; ++i) {
        h ^= static_cast<uint8_t>(x >> (i * 8));
        h *= 1099511628211ull;
      }
    }
    return static_cast<size_t>(h);
  }
};

inline bool is_ws(unsigned char c) {
  return c == ' ' || c == '\t' || c == '\n' || c == '\v' || c == '\f' ||
         c == '\r';
}

// Dense fast path: most datasets use small decimal item ids.  A token in
// CANONICAL decimal form (single "0", or leading digit 1-9, all digits, at
// most 7 of them) maps to a slot in a dense array, bypassing the string
// hash maps in both passes.  Canonical-form only: "007", "+7" and "7" are
// DIFFERENT tokens for counting purposes and must not collide.  Returns
// -1 when the token doesn't qualify (string-map path).
constexpr int64_t kDenseCap = 10'000'000;  // ids 0..9,999,999 (<= 7 digits)

inline int64_t fast_id(std::string_view s) {
  size_t n = s.size();
  if (n == 0 || n > 7) return -1;
  unsigned char c0 = static_cast<unsigned char>(s[0]) - '0';
  if (c0 > 9 || (c0 == 0 && n > 1)) return -1;  // non-digit or leading zero
  int64_t v = c0;
  for (size_t i = 1; i < n; ++i) {
    unsigned char c = static_cast<unsigned char>(s[i]) - '0';
    if (c > 9) return -1;
    v = v * 10 + c;
  }
  return v;
}

// Matches Python int(token) on ASCII: optional sign, all digits.  Python
// ints are arbitrary precision, so the value is kept as a normalized
// (negative, digits-without-leading-zeros) pair and compared by
// (sign, magnitude-length, magnitude-lexical) — exact for any size.
struct BigInt {
  bool negative = false;
  std::string_view digits;  // no leading zeros; empty means 0
};

bool parse_int(std::string_view s, BigInt* out) {
  if (s.empty()) return false;
  size_t i = 0;
  bool neg = false;
  if (s[0] == '+' || s[0] == '-') {
    neg = s[0] == '-';
    if (s.size() == 1) return false;
    i = 1;
  }
  size_t first = i;
  for (; i < s.size(); ++i) {
    if (s[i] < '0' || s[i] > '9') return false;
  }
  while (first < s.size() - 1 && s[first] == '0') ++first;
  std::string_view digits = s.substr(first);
  if (digits == "0") digits = std::string_view();
  out->negative = neg && !digits.empty();  // -0 == 0
  out->digits = digits;
  return true;
}

// v < w as integers.
bool bigint_less(const BigInt& v, const BigInt& w) {
  if (v.negative != w.negative) return v.negative;
  bool less;
  if (v.digits.size() != w.digits.size()) {
    less = v.digits.size() < w.digits.size();
  } else {
    less = v.digits < w.digits;
  }
  return v.negative ? (v.digits != w.digits && !less) : less;
}

}  // namespace

extern "C" {

struct FaResult {
  int64_t n_raw;      // raw transaction (line) count
  int64_t min_count;  // ceil(min_support * n_raw)
  int32_t n_items;    // F
  // Frequent item tokens in rank order, '\n'-joined (no trailing newline).
  char* items_buf;
  int64_t items_buf_len;
  int64_t* item_counts;  // [F] occurrence counts by rank
  int64_t n_baskets;     // T'
  int64_t* basket_offsets;  // [T'+1] CSR offsets into basket_items
  int32_t* basket_items;    // flattened sorted ranks
  int32_t* weights;         // [T'] multiplicities
};

// data/len: raw file bytes.  Not nul-terminated.  Returns a heap-allocated
// result (free with fa_free_result) or nullptr on allocation failure.
FaResult* fa_preprocess_buffer(const char* data, int64_t len,
                               double min_support) {
  std::string_view buf(data, static_cast<size_t>(len));

  // ---- split into trimmed lines (last line may lack '\n') --------------
  std::vector<std::string_view> lines;
  {
    size_t pos = 0;
    while (pos <= buf.size()) {
      size_t nl = buf.find('\n', pos);
      size_t end = (nl == std::string_view::npos) ? buf.size() : nl;
      if (nl == std::string_view::npos && pos == buf.size()) break;
      std::string_view line = buf.substr(pos, end - pos);
      // trim (Java String.trim: chars <= 0x20)
      size_t b = 0, e = line.size();
      while (b < e && static_cast<unsigned char>(line[b]) <= 0x20) ++b;
      while (e > b && static_cast<unsigned char>(line[e - 1]) <= 0x20) --e;
      lines.push_back(line.substr(b, e - b));
      if (nl == std::string_view::npos) break;
      pos = nl + 1;
    }
  }
  const int64_t n_raw = static_cast<int64_t>(lines.size());
  const int64_t min_count =
      static_cast<int64_t>(std::ceil(min_support * static_cast<double>(n_raw)));

  // ---- pass 1: occurrence counts ---------------------------------------
  // Dense array for canonical small-integer tokens (the overwhelmingly
  // common case), string hash map for everything else.  calloc pages
  // lazily, so untouched id ranges cost no physical memory.
  int64_t* dense_counts =
      static_cast<int64_t*>(std::calloc(kDenseCap, sizeof(int64_t)));
  std::unordered_map<std::string_view, int64_t> counts;
  counts.reserve(1 << 16);
  auto for_each_token = [](std::string_view line, auto&& fn) {
    if (line.empty()) {
      fn(std::string_view(""));  // Java split("") -> [""]
      return;
    }
    size_t i = 0;
    while (i < line.size()) {
      while (i < line.size() && is_ws(line[i])) ++i;
      size_t start = i;
      while (i < line.size() && !is_ws(line[i])) ++i;
      if (i > start) fn(line.substr(start, i - start));
    }
  };
  int64_t max_dense_id = -1;
  if (dense_counts) {
    for (auto line : lines) {
      for_each_token(line, [&](std::string_view tok) {
        int64_t id = fast_id(tok);
        if (id >= 0) {
          ++dense_counts[id];
          if (id > max_dense_id) max_dense_id = id;
        } else {
          ++counts[tok];
        }
      });
    }
  } else {  // allocation failed: everything through the map
    for (auto line : lines) {
      for_each_token(line, [&](std::string_view tok) { ++counts[tok]; });
    }
  }

  // ---- rank assignment -------------------------------------------------
  struct Item {
    std::string_view tok;
    int64_t count;
    bool numeric;
    BigInt value;
  };
  // Owned storage for tokens materialized from dense ids (deque: stable
  // addresses so string_views into it survive growth).
  std::deque<std::string> dense_tok_arena;
  std::vector<Item> freq;
  for (int64_t id = 0; id <= max_dense_id; ++id) {
    int64_t c = dense_counts ? dense_counts[id] : 0;
    if (c > 0 && c >= min_count) {  // c > 0: only tokens actually seen
      dense_tok_arena.push_back(std::to_string(id));
      std::string_view tok = dense_tok_arena.back();
      BigInt v;
      parse_int(tok, &v);
      freq.push_back({tok, c, true, v});
    }
  }
  for (const auto& [tok, c] : counts) {
    if (c >= min_count) {
      BigInt v;
      bool num = parse_int(tok, &v);
      freq.push_back({tok, c, num, v});
    }
  }
  std::sort(freq.begin(), freq.end(), [](const Item& a, const Item& b) {
    if (a.count != b.count) return a.count > b.count;
    if (a.numeric != b.numeric) return a.numeric;  // numeric first
    if (a.numeric) {
      if (bigint_less(a.value, b.value)) return true;
      if (bigint_less(b.value, a.value)) return false;
    }
    return a.tok < b.tok;
  });
  const int32_t f = static_cast<int32_t>(freq.size());
  std::unordered_map<std::string_view, int32_t> rank;
  rank.reserve(freq.size() * 2);
  // Dense rank table (rank+1; 0 = not frequent) mirrors the counting fast
  // path so pass 2's per-token lookup is one array read.
  int32_t* dense_rank = nullptr;
  if (dense_counts && max_dense_id >= 0) {
    dense_rank = static_cast<int32_t*>(
        std::calloc(max_dense_id + 1, sizeof(int32_t)));
  }
  for (int32_t r = 0; r < f; ++r) {
    int64_t id = freq[r].numeric ? fast_id(freq[r].tok) : -1;
    if (dense_rank && id >= 0 && id <= max_dense_id) {
      dense_rank[id] = r + 1;
    } else {
      rank.emplace(freq[r].tok, r);
    }
  }
  std::free(dense_counts);

  // ---- pass 2: basket dedup with multiplicity --------------------------
  std::unordered_map<std::vector<int32_t>, int32_t, VecHash> mult;
  mult.reserve(1 << 16);
  std::vector<const std::vector<int32_t>*> order;
  std::vector<int32_t> scratch;
  int64_t total_items = 0;
  for (auto line : lines) {
    scratch.clear();
    for_each_token(line, [&](std::string_view tok) {
      int64_t id;
      // Without dense_rank (dense path unused or alloc failed) every
      // frequent token is in the string map — fall through.
      if (dense_rank && (id = fast_id(tok)) >= 0) {
        if (id <= max_dense_id) {  // beyond: unseen in pass 1 => infrequent
          int32_t r = dense_rank[id];
          if (r) scratch.push_back(r - 1);
        }
        return;
      }
      auto it = rank.find(tok);
      if (it != rank.end()) scratch.push_back(it->second);
    });
    std::sort(scratch.begin(), scratch.end());
    scratch.erase(std::unique(scratch.begin(), scratch.end()), scratch.end());
    if (scratch.size() <= 1) continue;
    auto [it, inserted] = mult.emplace(scratch, 1);
    if (inserted) {
      order.push_back(&it->first);
      total_items += static_cast<int64_t>(scratch.size());
    } else {
      ++it->second;
    }
  }
  const int64_t t = static_cast<int64_t>(order.size());

  // ---- marshal ---------------------------------------------------------
  auto* res = static_cast<FaResult*>(std::calloc(1, sizeof(FaResult)));
  if (!res) return nullptr;
  res->n_raw = n_raw;
  res->min_count = min_count;
  res->n_items = f;

  int64_t items_len = 0;
  for (const auto& item : freq) items_len += item.tok.size() + 1;
  res->items_buf = static_cast<char*>(std::malloc(items_len ? items_len : 1));
  res->items_buf_len = items_len ? items_len - 1 : 0;  // drop trailing '\n'
  {
    char* p = res->items_buf;
    for (const auto& item : freq) {
      std::memcpy(p, item.tok.data(), item.tok.size());
      p += item.tok.size();
      *p++ = '\n';
    }
  }
  res->item_counts =
      static_cast<int64_t*>(std::malloc(sizeof(int64_t) * (f ? f : 1)));
  for (int32_t r = 0; r < f; ++r) res->item_counts[r] = freq[r].count;

  res->n_baskets = t;
  res->basket_offsets =
      static_cast<int64_t*>(std::malloc(sizeof(int64_t) * (t + 1)));
  res->basket_items = static_cast<int32_t*>(
      std::malloc(sizeof(int32_t) * (total_items ? total_items : 1)));
  res->weights =
      static_cast<int32_t*>(std::malloc(sizeof(int32_t) * (t ? t : 1)));
  int64_t off = 0;
  for (int64_t i = 0; i < t; ++i) {
    const auto& basket = *order[i];
    res->basket_offsets[i] = off;
    std::memcpy(res->basket_items + off, basket.data(),
                basket.size() * sizeof(int32_t));
    off += static_cast<int64_t>(basket.size());
    res->weights[i] = mult.find(basket)->second;
  }
  res->basket_offsets[t] = off;
  std::free(dense_rank);
  return res;
}

void fa_free_result(FaResult* res) {
  if (!res) return;
  std::free(res->items_buf);
  std::free(res->item_counts);
  std::free(res->basket_offsets);
  std::free(res->basket_items);
  std::free(res->weights);
  std::free(res);
}

}  // extern "C"
