"""fastapriori_tpu — a TPU-native frequent-itemset-mining and
association-rule-recommendation framework (JAX / XLA / shard_map / Pallas).

Brand-new implementation with the capabilities of relife957/FastApriori
(Spark-based parallel Apriori; see SURVEY.md for the structural map).  Where
the reference broadcasts a vertical transaction bitmap to every Spark
executor and parallelizes support counting over the candidate space
(FastApriori.scala:97-100, 140-157), this framework shards the bitmap over
the transaction axis of a TPU mesh and turns counting into weighted int32
bitmap matmuls on the MXU, reduced with ``jax.lax.psum`` over ICI.
"""

__version__ = "0.1.0"

from fastapriori_tpu.config import MinerConfig  # noqa: F401
from fastapriori_tpu.models.apriori import FastApriori  # noqa: F401
from fastapriori_tpu.models.recommender import AssociationRules  # noqa: F401
from fastapriori_tpu.serve import (  # noqa: F401
    RecommendServer,
    ServingState,
)
