"""ServingState — the explicit "serve model" half of the API split
(ISSUE 10 tentpole; ROADMAP item 1).

The batch pipeline conflates "build model" (mine + generate rules) with
"serve model" (scan baskets against the sorted rule table) inside one
``AssociationRules.run`` call.  A long-lived serving tier needs the
second half as a first-class, checkpointable object:

- :meth:`ServingState.build` wraps a mining result (level matrices +
  item tables) into a serving artifact: rules generated + priority-
  sorted ONCE, the device scan table mounted through
  :meth:`~fastapriori_tpu.models.recommender.AssociationRules.serve_scan`
  — the resident sharded table from the phase-2 join state
  (``rules/gen.py DeviceRuleState`` / ``ops/contain.py
  rule_scan_build``) when the mesh built one, uploaded once and reused
  across every request batch.
- :meth:`save` / :meth:`load` persist the model through PR 2's
  committer + MANIFEST machinery (``<prefix>serving.npz``, atomic write,
  size+sha256 manifest entry), so a serving process warm-restarts from
  checkpoint and — rule generation being deterministic in the mining
  result — serves byte-identical recommendations (test-pinned).
- :meth:`recommend_batch` is the serving data path: one fixed-shape
  micro-batch per scan dispatch (``config.rec_batch_rows`` /
  ``FA_REC_BATCH`` — the same knob the batch recommender caps its
  micro-batches with), padding rows excluded from the kernel's early
  exit, the result fetch audited under the serving tier's own
  ``fetch.serve_match`` site (failpoint-armable, watchdog-bounded,
  retried — the standard audited-fetch discipline).  A device scan
  whose transient failures survive the retry budget walks the
  ``rule_scan`` cascade to the host oracle scan instead of killing the
  server.

Model identity: :attr:`signature` (sha256 over the level matrices,
counts and item vocabulary) names the model a response was served from
— the hot-swap tests pin that no response ever mixes tables.
"""

from __future__ import annotations

import hashlib
import io
import time
import zipfile
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from fastapriori_tpu.config import MinerConfig
from fastapriori_tpu.errors import InputError
from fastapriori_tpu.io.reader import _open_bytes
from fastapriori_tpu.io.writer import write_artifact_bytes, write_manifest
from fastapriori_tpu.obs import trace
from fastapriori_tpu.ops.bitmap import build_bitmap, pad_axis
from fastapriori_tpu.preprocess import dedup_user_baskets
from fastapriori_tpu.reliability import failpoints, ledger, retry, watchdog

SERVING_NAME = "serving.npz"

Level = Tuple[np.ndarray, np.ndarray]


class PackedBatch:
    """Stage-1 output of the pipelined dispatcher (ISSUE 19): one
    request micro-batch with the HOST half done — dedup + fixed-shape
    bitmap packing — and the device dispatch NOT yet issued.  The
    two-stage server packs batch k+1 on its pack thread while batch k's
    scan fetch is in flight on the dispatch thread; ``state`` pins the
    model the batch was packed against (the hot-swap barrier guarantees
    the scan stage serves it from that same state, so a response can
    never mix tables).

    ``deferred`` marks a batch whose state had ``recommend_batch``
    overridden on the instance (the test gating seam): packing cannot
    assume the default scan path, so the raw lines ride to the scan
    stage and the override serves there."""

    __slots__ = (
        "state", "n_lines", "baskets", "indexes", "blocks",
        "rows", "f", "lines", "deferred",
    )

    def __init__(self, state: "ServingState", n_lines: int):
        self.state = state
        self.n_lines = n_lines
        self.baskets: Optional[List[np.ndarray]] = None
        self.indexes = None
        # blocks: [(b0, n, bitmap, blen)] numpy, one per scan dispatch.
        self.blocks: Optional[list] = None
        self.rows = 0
        self.f = 0
        self.lines = None
        self.deferred = False


def model_signature(
    levels: Sequence[Level],
    item_counts: np.ndarray,
    freq_items: Sequence[str],
) -> str:
    """Deterministic model identity: sha256 over the level matrices,
    their counts, the 1-itemset counts and the item vocabulary.  Two
    mines of the same corpus at the same support produce the same
    signature; any rule-visible difference changes it."""
    h = hashlib.sha256()
    h.update(np.int64(len(levels)).tobytes())
    for mat, cnt in levels:
        h.update(np.ascontiguousarray(mat, dtype=np.int32).tobytes())
        h.update(np.ascontiguousarray(cnt, dtype=np.int64).tobytes())
    h.update(np.ascontiguousarray(item_counts, dtype=np.int64).tobytes())
    h.update("\x00".join(freq_items).encode("utf-8"))
    return h.hexdigest()[:16]


class ServingState:
    """A resident, checkpointable recommend model (module docstring).

    Construction is cheap; the expensive pieces — rule generation, the
    device table build, the scan compile — run in :meth:`warm` (or
    lazily on the first batch).  One instance serves many batches; a
    model refresh builds a NEW instance and hot-swaps it through
    :meth:`~fastapriori_tpu.serve.server.RecommendServer.swap`, then
    :meth:`release`\\ s this one."""

    def __init__(
        self,
        levels: Sequence[Level],
        item_counts: np.ndarray,
        freq_items: Sequence[str],
        item_to_rank: Optional[Dict[str, int]] = None,
        config: Optional[MinerConfig] = None,
        context=None,
        engine: str = "auto",
        source: str = "build",
    ):
        if engine not in ("auto", "device", "host"):
            # The FA_NO_PALLAS strictness contract: a typo'd engine
            # silently serving the host scan is an invisible downgrade.
            raise InputError(
                f"unrecognized ServingState engine {engine!r}: use one "
                "of auto/device/host"
            )
        from fastapriori_tpu.models.recommender import AssociationRules

        self.levels = [
            (
                np.ascontiguousarray(m, dtype=np.int32),
                np.ascontiguousarray(c, dtype=np.int64),
            )
            for m, c in levels
        ]
        self.item_counts = np.ascontiguousarray(item_counts, np.int64)
        self.freq_items = list(freq_items)
        self.item_to_rank = (
            dict(item_to_rank)
            if item_to_rank is not None
            else {item: r for r, item in enumerate(self.freq_items)}
        )
        self.config = config or MinerConfig()
        self.signature = model_signature(
            self.levels, self.item_counts, self.freq_items
        )
        self.source = source
        self._rec = AssociationRules(
            [], self.freq_items, self.item_to_rank, config=self.config,
            context=context, levels=self.levels,
            item_counts=self.item_counts,
        )
        self._engine_req = engine
        self._engine: Optional[str] = None  # resolved at warm()
        self._handle = None
        self._batch_rows_override: Optional[int] = None
        self._released = False
        self.warm_ms = 0.0
        # Serving-run counters (cumulative per instance; the server's
        # stats() folds them into the record).
        self.scan_dispatches = 0
        self.scan_rows = 0
        # The acceptance contract (ISSUE 10): rule-table bytes crossing
        # the host link AFTER the model is mounted — identically zero on
        # both device forms (resident: built on device; replicated:
        # uploaded once inside warm(), before serving starts).
        self.rule_table_host_bytes = 0

    # -- build/load entry points ---------------------------------------
    @classmethod
    def from_mine(
        cls,
        d_path: str,
        config: Optional[MinerConfig] = None,
        engine: str = "auto",
        source: str = "mine",
    ) -> "ServingState":
        """Mine ``d_path`` and wrap the result — the one-call "build
        model" path the CLI ``serve`` subcommand and bench use."""
        from fastapriori_tpu.models.apriori import FastApriori

        config = config or MinerConfig()
        miner = FastApriori(config=config)
        levels, data = miner.run_file_raw(d_path)
        return cls(
            levels, data.item_counts, data.freq_items, data.item_to_rank,
            config=config, context=miner.context, engine=engine,
            source=source,
        )

    def save(self, prefix: str) -> str:
        """Persist ``<prefix>serving.npz`` through the crash-safe
        committer + run manifest (PR 2 machinery): a killed save leaves
        either the old artifact or the new one, never a torn file, and
        a truncated artifact fails manifest validation at load."""
        arrays = {
            "meta": np.array(
                [1, len(self.levels), len(self.freq_items)], dtype=np.int64
            ),
            "item_counts": self.item_counts,
            # lint: host-data -- item vocabulary is a host string list
            "freq_items": np.asarray(self.freq_items, dtype=np.str_),
            "signature": np.asarray([self.signature], dtype=np.str_),
        }
        for i, (mat, cnt) in enumerate(self.levels):
            arrays[f"mat_{i}"] = mat
            arrays[f"cnt_{i}"] = cnt
        buf = io.BytesIO()
        np.savez(buf, **arrays)
        manifest: Dict[str, dict] = {}
        path = write_artifact_bytes(
            prefix + SERVING_NAME, [buf.getvalue()], SERVING_NAME, manifest
        )
        from fastapriori_tpu.reliability import quorum

        write_manifest(prefix, manifest,
                       fence=quorum.writer_fence())
        return path

    @classmethod
    def load(
        cls,
        prefix: str,
        config: Optional[MinerConfig] = None,
        context=None,
        engine: str = "auto",
    ) -> "ServingState":
        """Warm restart: load ``<prefix>serving.npz`` (manifest-validated
        — a truncated/corrupt artifact is an InputError naming the file,
        never a silently different model) and rebuild the serving state.
        Rule generation is deterministic in the stored mining result, so
        the restarted state serves byte-identical recommendations
        (test-pinned); the stored signature cross-checks the recomputed
        one."""
        from fastapriori_tpu.io.resume import validate_artifact_bytes

        failpoints.fire("serving.load")
        path = prefix + SERVING_NAME
        try:
            with _open_bytes(path) as f:
                raw = f.read()
        except FileNotFoundError:
            raise InputError(
                f"serving checkpoint {path!r} not found — write one "
                "with ServingState.save (CLI: serve --save-serving)"
            ) from None
        validate_artifact_bytes(prefix, SERVING_NAME, raw)
        try:
            with np.load(io.BytesIO(raw)) as z:
                meta = z["meta"]
                if int(meta[0]) != 1:
                    raise ValueError(f"unknown version {int(meta[0])}")
                n_levels = int(meta[1])
                freq_items = [str(s) for s in z["freq_items"]]
                item_counts = z["item_counts"]
                stored_sig = str(z["signature"][0])
                levels = [
                    (z[f"mat_{i}"], z[f"cnt_{i}"]) for i in range(n_levels)
                ]
        except (KeyError, ValueError, OSError, zipfile.BadZipFile) as e:
            raise InputError(
                f"corrupt serving checkpoint {path!r}: {e} — regenerate "
                "it with ServingState.save"
            ) from None
        state = cls(
            levels, item_counts, freq_items, config=config,
            context=context, engine=engine, source="restart",
        )
        if state.signature != stored_sig:
            raise InputError(
                f"serving checkpoint {path!r} signature mismatch "
                f"(stored {stored_sig}, recomputed {state.signature}) — "
                "the artifact does not describe the model it claims"
            )
        ledger.record(
            "serving_restart", once_key=state.signature,
            signature=state.signature, n_levels=len(levels),
        )
        return state

    # -- model facts ----------------------------------------------------
    @property
    def n_rules(self) -> int:
        self._rec._ensure_rules()
        return self._rec.n_rules or 0

    def batch_rows(self) -> int:
        """The serving micro-batch row count — the recommender's shared
        ``rec_batch_rows`` knob (config + FA_REC_BATCH, pow2-bucketed),
        unless a server pinned its own batch bound here
        (:meth:`set_batch_rows`): the scan's fixed compile shape and the
        micro-batcher's collection bound must be the SAME number, or
        every dispatch pads a small batch up to the config default."""
        if self._batch_rows_override is not None:
            return self._batch_rows_override
        return self._rec.rec_batch_rows()

    def set_batch_rows(self, rows: int) -> None:
        """Pin the scan micro-batch shape (the shared bucketing
        contract, models/recommender.py bucket_batch_rows) — called by
        the server with its resolved batch knob before warm()."""
        from fastapriori_tpu.models.recommender import bucket_batch_rows

        self._batch_rows_override = bucket_batch_rows(rows)

    def describe(self) -> dict:
        """Model facts for the serving record / stats stream."""
        out = {
            "signature": self.signature,
            "source": self.source,
            "engine": self._engine or self._engine_req,
            "n_rules": self.n_rules,
            "n_items": len(self.freq_items),
            "batch_rows": self.batch_rows(),
            "scan_dispatches": self.scan_dispatches,
            "rule_table_host_bytes": self.rule_table_host_bytes,
            "warm_ms": round(self.warm_ms, 1),
        }
        if self._handle is not None:
            out["resident_table"] = bool(self._handle.resident)
            out["scan_shards"] = self._handle.shards
            out["table_bytes"] = self._handle.table_bytes
        return out

    # -- serving --------------------------------------------------------
    def _resolve_engine(self) -> str:
        if self._engine is not None:
            return self._engine
        eng = self._engine_req
        rec = self._rec
        n_rules = self.n_rules  # generates the rules (and, on the
        # sharded engine, the resident scan state the auto rule reads)
        if eng == "auto":
            if (
                rec._scan_state is not None or rec._scan_table is not None
            ) and n_rules:
                # Phase 2 left a device-resident (or already-built) scan
                # table — the serving tier's whole point; mount it.
                eng = "device"
            else:
                # Mirror the batch path's auto rule against ONE
                # micro-batch (deterministic in the model, not the
                # traffic): tiny models scan faster on the host than one
                # dispatch round-trips.
                eng = (
                    "device"
                    if self.n_rules
                    and self.batch_rows() * self.n_rules >= 30_000_000
                    else "host"
                )
        if eng == "device" and not self.n_rules:
            eng = "host"
        if eng == "host" and rec._scan_state is not None:
            # The host scan never consumes the resident join state —
            # free the per-level device tables (the batch path's rule).
            rec._scan_state.release()
            rec._scan_state = None
        self._engine = eng
        ledger.record(
            "serve_engine", once_key=f"{self.signature}:{eng}",
            engine=eng, signature=self.signature, rules=self.n_rules,
        )
        return eng

    def warm(self) -> None:
        """Resolve the engine, mount the device table and pre-compile
        the fixed-shape scan (one dummy micro-batch), so the first real
        request pays dispatch latency, not XLA compile latency.  The
        replicated form's one-time table upload happens HERE — after
        warm() returns, no rule-table byte crosses the host link
        (``rule_table_host_bytes`` stays 0 across the serving run)."""
        t0 = time.perf_counter()
        eng = self._resolve_engine()
        if eng == "device" and self._handle is None:
            self._handle = self._rec.serve_scan()
            self._scan_blocks([np.zeros(1, dtype=np.int32)])
        elif eng == "host":
            self._rec._ensure_rules()
        self.warm_ms = (time.perf_counter() - t0) * 1e3

    def device_ready(self) -> bool:
        """Swap-path readiness barrier (the router worker calls this
        BEFORE handing a new table to ``server.swap``): prove the
        table is device-resident and the fixed-shape scan compiled by
        running one dummy micro-batch end to end.  ``warm()`` compiles;
        this VERIFIES — the result crosses the link through the
        audited ``serve_swap_ready`` fetch, so a table that cannot
        actually serve surfaces as a classified fetch failure on the
        swap path instead of a latency cliff (or a crash) mid-batch
        after the barrier commits.  Host engine: nothing device-side
        to prove; returns False."""
        if self._resolve_engine() != "device":
            return False
        if self._handle is None:
            self.warm()
        h = self._handle
        rows = self.batch_rows()
        bm = build_bitmap(
            [np.zeros(1, dtype=np.int32)], h.f, rows,
            self.config.item_tile,
        )
        blen = np.zeros(rows, dtype=np.int32)
        blen[0] = 1
        best, _cons, _chunks = h.scan(bm, blen)
        retry.fetch(lambda: np.asarray(best), "serve_swap_ready")
        return True

    def _pack_blocks(self, baskets: List[np.ndarray], rows: int,
                     base: int = 0) -> list:
        """HOST half of the scan: chunk distinct baskets into fixed-
        shape [rows, F_pad] bitmap blocks — pure numpy, no device work,
        safe to run on the pipelined server's pack thread while the
        previous batch's scan is in flight."""
        h = self._handle
        cfg = self.config
        mb = self.batch_rows()
        blocks = []
        for b0 in range(0, len(baskets), mb):
            block = baskets[b0 : b0 + mb]
            bm = build_bitmap(block, h.f, rows, cfg.item_tile)
            blen = np.zeros(rows, dtype=np.int32)
            blen[: len(block)] = [len(b) for b in block]
            blocks.append((base + b0, len(block), bm, blen))
        return blocks

    def _dispatch_packed(self, blocks: list) -> list:
        """DEVICE dispatch of pre-packed bitmap blocks: issue the
        compiled scan + the audited async fetch per block, return the
        in-flight fetch handles without blocking on results."""
        import jax.numpy as jnp

        h = self._handle
        fetches = []
        for b0, n, bm, blen in blocks:
            best, cons, _chunks = h.scan(bm, blen)
            arr = best if cons is None else jnp.stack([best, cons])
            fetches.append(
                (b0, n, retry.fetch_async(arr, "serve_match"))
            )
            self.scan_dispatches += 1
            self.scan_rows += bm.shape[0]
        return fetches

    def _fetch_blocks(self, fetches: list, total: int) -> np.ndarray:
        """Block on the audited fetches and assemble the consequent
        index vector (-1 = no match) across all blocks."""
        h = self._handle
        cons_out = np.full(total, -1, dtype=np.int64)
        for b0, n, fetch in fetches:
            arr = fetch.result()
            if h.decode is not None:
                # lint: host-data -- arr is the already-fetched numpy result
                ranks = np.asarray(arr[:n], dtype=np.int64)
                cons_out[b0 : b0 + n] = h.decode(ranks)
            else:
                cons_out[b0 : b0 + n] = arr[1][:n]
        return cons_out

    def _scan_rows(self) -> int:
        h = self._handle
        mb = self.batch_rows()
        return pad_axis(mb, h.row_multiple) if h.row_multiple > 1 else mb

    def _scan_blocks(self, baskets: List[np.ndarray]) -> np.ndarray:
        """Device scan of distinct baskets in fixed-shape micro-batches:
        every dispatch is [rows, F_pad] — ONE compiled program serves
        any traffic mix, short batches ride as padding rows (0-length,
        excluded from the kernel's early exit).  Each batch's audited
        fetch (``fetch.serve_match``) overlaps the next batch's
        dispatch.  Returns consequent indexes (-1 = no match)."""
        mb = self.batch_rows()
        rows = self._scan_rows()
        fetches = []
        # Trace split (ISSUE 11 acceptance): serve.pack is the HOST side
        # (bitmap build + dispatch issue), serve.scan the DEVICE side
        # (the audited result fetches — each an inner fetch.serve_match
        # span) — a Perfetto timeline separates the two directly.
        with trace.span("serve.pack", baskets=len(baskets)):
            for b0 in range(0, len(baskets), mb):
                # Block-at-a-time so block k's dispatch overlaps block
                # k+1's bitmap build (the intra-call pipelining the
                # closed-batch capacity numbers rest on).
                blocks = self._pack_blocks(
                    baskets[b0 : b0 + mb], rows, base=b0
                )
                fetches.extend(self._dispatch_packed(blocks))
        with trace.span("serve.scan", dispatches=len(fetches)):
            return self._fetch_blocks(fetches, len(baskets))

    def pack_batch(self, lines: Sequence[Sequence[str]]) -> PackedBatch:
        """Stage 1 of the two-stage serving pipeline (ISSUE 19): dedup
        the request micro-batch and — on the device engine — build the
        fixed-shape bitmap blocks, WITHOUT issuing the device scan.
        Pure host work: the pipelined server runs it on its pack thread
        while stage 2 consumes the previous batch's fetch.

        ``recommend_batch(lines)`` is exactly
        ``scan_packed(pack_batch(lines))``; a state whose
        ``recommend_batch`` was overridden on the INSTANCE (the test
        gating seam) defers the batch — the raw lines ride to
        :meth:`scan_packed`, which serves them through the override."""
        if self.__dict__.get("recommend_batch") is not None:
            packed = PackedBatch(self, len(lines))
            packed.deferred = True
            packed.lines = [list(ln) for ln in lines]
            return packed
        return self._pack_real(lines)

    def _pack_real(self, lines: Sequence[Sequence[str]]) -> PackedBatch:
        """The real stage-1 body, bypassing the override seam — the
        class-default :meth:`recommend_batch` enters here so an
        instance override that calls the captured original method
        cannot re-defer into itself."""
        if self._released:
            raise InputError(
                "ServingState was released (hot-swapped out); build or "
                "load a fresh state to serve"
            )
        with trace.span("serve.dedup", rows=len(lines)) as sp:
            baskets, indexes, _empty = dedup_user_baskets(
                lines, self.item_to_rank
            )
            sp.update(distinct=len(baskets))
        packed = PackedBatch(self, len(lines))
        packed.baskets = baskets
        packed.indexes = indexes
        if not baskets or not self.n_rules:
            return packed
        if self._resolve_engine() == "device":
            if self._handle is None:
                self.warm()
            rows = self._scan_rows()
            with trace.span("serve.pack", baskets=len(baskets)):
                packed.blocks = self._pack_blocks(baskets, rows)
            packed.rows = rows
            packed.f = self._handle.f
        return packed

    def _scan_device(self, packed: PackedBatch) -> np.ndarray:
        """Stage-2 device path: consume pre-packed blocks when their
        shape still matches the mounted handle; a stale pack (a cascade
        or batch-shape change landed between the stages) rebuilds from
        the retained baskets instead of feeding the wrong shape."""
        h = self._handle
        if (
            packed.blocks is not None
            and packed.rows == self._scan_rows()
            and packed.f == h.f
        ):
            with trace.span(
                "serve.scan", dispatches=len(packed.blocks)
            ):
                fetches = self._dispatch_packed(packed.blocks)
                return self._fetch_blocks(fetches, len(packed.baskets))
        return self._scan_blocks(packed.baskets)

    def recommend_batch(self, lines: Sequence[Sequence[str]]) -> List[str]:
        """Serve one request micro-batch: dedup within the batch (the
        reference's C10 — identical concurrent baskets scan once),
        scan distinct baskets on the resolved engine, fan out.  Returns
        one recommended item string (or "0") per input line, in input
        order.  A device scan whose transient failures exhausted their
        retry budget walks the ``rule_scan`` cascade to the host oracle
        for this AND later batches (forward-only, ledger-recorded) —
        the serving loop degrades, it does not die."""
        return self.scan_packed(self._pack_real(lines))

    def scan_packed(self, packed: PackedBatch) -> List[str]:
        """Stage 2 of the two-stage serving pipeline: scan a
        :class:`PackedBatch` on the resolved engine and fan the
        consequents back out to input order.  All serving cascades live
        here, identical to the unsplit path: serve_scan pallas→xla
        first (handle drop + re-warm + one retry), then rule_scan
        device→host — the retries rebuild from ``packed.baskets``, so a
        mid-flight engine change never feeds stale block shapes."""
        if packed.deferred:
            return self.recommend_batch(packed.lines)
        if self._released:
            raise InputError(
                "ServingState was released (hot-swapped out); build or "
                "load a fresh state to serve"
            )
        baskets, indexes = packed.baskets, packed.indexes
        out = ["0"] * packed.n_lines
        if not baskets or not self.n_rules:
            return out
        eng = self._resolve_engine()
        if eng == "device":
            if self._handle is None:
                self.warm()
            try:
                cons = self._scan_device(packed)
            except Exception as exc:
                if not watchdog.transient(exc):
                    raise
                cons = None
                h = self._handle
                if h is not None and h.pallas:
                    # A Pallas-kernel scan walks serve_scan pallas→xla
                    # FIRST: drop only the compiled handle (the device
                    # table stays mounted), sticky-disable the kernel
                    # tier, re-warm on the XLA while_loop body and retry
                    # this batch once — abandoning the device table for
                    # the host oracle is the LAST resort, not the first.
                    watchdog.downgrade(
                        "serve_scan", "pallas", "xla",
                        reason="serve_transient_exhausted",
                        once_key=f"serve_kernel:{self.signature}",
                        error=f"{type(exc).__name__}: {exc}"[:200],
                    )
                    self._rec.context.disable_serve_pallas()
                    self._handle = None
                    try:
                        self.warm()
                        cons = self._scan_blocks(baskets)
                    except Exception as exc2:
                        if not watchdog.transient(exc2):
                            raise
                        exc = exc2
                        cons = None
                if cons is None:
                    watchdog.downgrade(
                        "rule_scan", "device", "host",
                        reason="serve_transient_exhausted",
                        once_key=f"serve:{self.signature}",
                        error=f"{type(exc).__name__}: {exc}"[:200],
                    )
                    self._engine = "host"
                    # The cascade is forward-only — the device engine
                    # never serves this state again, so free its table
                    # instead of pinning HBM for the degraded server's
                    # lifetime.
                    self._drop_device_table()
                    # lint: host-data -- host-scan result list, no device fetch
                    cons = np.asarray(
                        self._rec._host_first_match(baskets),
                        dtype=np.int64,
                    )
        else:
            with trace.span("serve.host_scan", baskets=len(baskets)):
                # lint: host-data -- host-scan result list, no device fetch
                cons = np.asarray(
                    self._rec._host_first_match(baskets), dtype=np.int64
                )
        for rows, c in zip(indexes, cons):
            if c >= 0:
                item = self.freq_items[int(c)]
                for i in rows:
                    out[i] = item
        return out

    def _drop_device_table(self) -> None:
        """Free every device reference this state holds (the scan
        handle, the resident join state, the built/uploaded tables) —
        shared by :meth:`release` and the device→host serve cascade."""
        self._handle = None
        rec = self._rec
        if rec._scan_state is not None:
            rec._scan_state.release()
            rec._scan_state = None
        rec._scan_table = None
        rec._rule_dev = None

    def release(self) -> None:
        """Drop the device table references (a hot-swapped-out model
        must not pin HBM for the process lifetime).  Further
        recommend_batch calls raise — a swapped-out model never serves
        again (the no-table-mixing contract)."""
        self._released = True
        self._drop_device_table()

    def resident_device_bytes(self) -> int:
        """HBM currently pinned by the mounted table (+ any not-yet-
        consumed phase-2 join state — ``DeviceRuleState.device_bytes``),
        for the serving record: a hot-swap transiently doubles this."""
        total = (
            self._handle.table_bytes if self._handle is not None else 0
        )
        state = self._rec._scan_state
        if state is not None:
            total += state.device_bytes()
        return total
