"""RecommendServer — the resident, admission-controlled request loop
(ISSUE 10 tentpole; two-stage pipelined dispatcher, ISSUE 19).

The dispatcher turns an open-loop request stream into the fixed-shape
micro-batches the device scan serves best (arxiv 1309.0215's pipelined
micro-batching, with the buffer/latency trade-off as two explicit
knobs):

- **batch_rows** (``config.rec_batch_rows`` / ``FA_REC_BATCH``): the
  micro-batch size — throughput side.  The dispatcher collects at most
  this many queued requests per scan dispatch.
- **linger** (``config.serve_linger_ms``): the max time a PARTIAL batch
  waits to fill before dispatching anyway — latency side.  0 dispatches
  immediately.

**Admission control.**  The queue is bounded (``serve_queue_depth``; 0 =
auto 4× batch_rows).  :meth:`submit` on a full queue SHEDS the request:
it is answered ``"0"`` immediately (the reference's no-recommendation
value, AssociationRules.scala:49) and counted, and the accept→shed
transition of each overload episode is recorded on the degradation
cascade (``watchdog.CHAINS["serving"]``) — so offered load past
capacity degrades to bounded latency plus *recorded* sheds, never an
unbounded queue, and a shed run can never masquerade as a clean one.
:meth:`submit_wait` is the closed-loop flavor (file/stdin sources):
bounded blocking for space instead of shedding.

**Two-stage pipeline** (``FA_SERVE_PIPELINE_DEPTH``, default 2).  The
PR 10 dispatcher pipelined one-deep: host-side dedup/pack serialized
against the device scan, so sustained acceptance stalled at ~0.67× the
closed-batch capacity.  At depth >= 1 the dispatcher splits into two
threads joined by a bounded hand-off ring: **stage 1**
(``fa-serve-pack``) collects + dedups + packs batch k+1 into fixed-
shape bitmap blocks (:meth:`ServingState.pack_batch`, pure host work)
while **stage 2** (``fa-serve-dispatch``) is still inside batch k's
device scan fetch (:meth:`ServingState.scan_packed`) — the scan kernel
never waits on host work.  The ring holds at most ``pipeline_depth``
batches (double-buffered at the default 2); a full ring back-pressures
the pack stage, it never grows.  Depth 0 keeps the serial one-thread
loop (the one-deep baseline — the serve bench's pipelining control).

**Hot-swap.**  :meth:`swap` enqueues a barrier marker: every request
enqueued before it is served by the OLD state (a batch never straddles
the marker), requests after it by the new — responses never mix tables
(test-pinned via model signatures).  The old state is released at the
barrier.  Under the pipeline the marker rides queue → ring in FIFO
order and the PACK-side state pointer advances when the marker is
forwarded, so post-barrier batches pack (and are then scanned) against
the incoming model while pre-barrier batches — pinned to the old state
at pack time — finish ahead of them.

The scan fetches inside the state are the standard audited sites
(``fetch.serve_match`` → retry + dispatch watchdog), so a wedged device
runtime surfaces as classified errors/cascade walks, never a hung
dispatcher; every wait in this module is timeout-bounded.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Optional, Sequence

from fastapriori_tpu.errors import InputError
from fastapriori_tpu.obs import metrics as obs_metrics
from fastapriori_tpu.obs import trace
from fastapriori_tpu.obs.metrics import MetricsRegistry
from fastapriori_tpu.reliability import ledger, watchdog
from fastapriori_tpu.serve.state import PackedBatch, ServingState
from fastapriori_tpu.utils.env import env_int

# Batch-fill histogram bounds: pow2 rows up to the largest bucketed
# micro-batch (models/recommender.py bucket_batch_rows ceiling is 4096).
_FILL_BUCKETS = (1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024, 2048, 4096)

# Pack-stage shutdown sentinel: pushed to the ring after the last drained
# batch so the scan stage exits in order.
_STOP = object()

_PIPELINE_DEPTH: Optional[int] = None


def pipeline_depth_from_env() -> int:
    """``FA_SERVE_PIPELINE_DEPTH`` — hand-off ring capacity between the
    pack stage and the scan stage of the two-stage dispatcher.  0 = the
    serial one-thread dispatcher (the one-deep PR 10 baseline, kept as
    the serve bench's pipelining control); >= 1 pipelines, double-
    buffered at the default 2.  Strict int >= 0 — a typo'd value raises
    InputError rather than silently serving serial."""
    global _PIPELINE_DEPTH
    if _PIPELINE_DEPTH is None:
        _PIPELINE_DEPTH = env_int("FA_SERVE_PIPELINE_DEPTH", 2, minimum=0)
    return _PIPELINE_DEPTH


def reload_from_env() -> None:
    """Drop the memoized knob reads (tests repoint the environment)."""
    global _PIPELINE_DEPTH
    _PIPELINE_DEPTH = None


class ServeRequest:
    """One in-flight request.  ``t_sched`` is the open-loop intended
    arrival time (defaults to submit time) — latency is measured from
    it, so generator lag cannot hide queueing delay (no coordinated
    omission)."""

    __slots__ = (
        "tokens", "t_sched", "t_enq", "t_done", "item", "shed", "model"
    )

    def __init__(self, tokens, t_sched: Optional[float], t_enq: float):
        self.tokens = tokens
        self.t_sched = t_enq if t_sched is None else t_sched
        self.t_enq = t_enq
        self.t_done: Optional[float] = None
        self.item: Optional[str] = None
        self.shed = False
        self.model: Optional[str] = None

    @property
    def done(self) -> bool:
        return self.t_done is not None

    def latency_ms(self) -> Optional[float]:
        if self.t_done is None:
            return None
        return max(self.t_done - self.t_sched, 0.0) * 1e3


class _SwapMarker:
    __slots__ = ("state", "release_old", "event", "t_enq")

    def __init__(self, state: ServingState, release_old: bool):
        self.state = state
        self.release_old = release_old
        self.event = threading.Event()
        self.t_enq = time.monotonic()


class RecommendServer:
    def __init__(
        self,
        state: ServingState,
        batch_rows: Optional[int] = None,
        linger_ms: Optional[float] = None,
        queue_depth: Optional[int] = None,
        metrics: bool = True,
        pipeline_depth: Optional[int] = None,
    ):
        from fastapriori_tpu.models.recommender import bucket_batch_rows

        self._state = state
        cfg = state.config
        rows = batch_rows if batch_rows else state.batch_rows()
        # The state's set_batch_rows applies the SAME shared bucketing,
        # so the compiled scan shape equals this collection bound.
        self._batch_rows = bucket_batch_rows(rows)
        self._linger_s = (
            cfg.serve_linger_ms if linger_ms is None else linger_ms
        ) / 1e3
        depth = queue_depth if queue_depth else cfg.serve_queue_depth
        self._depth = int(depth) if depth else 4 * self._batch_rows
        if pipeline_depth is None:
            pipeline_depth = pipeline_depth_from_env()
        if pipeline_depth < 0:
            raise InputError(
                f"pipeline_depth must be >= 0, got {pipeline_depth}"
            )
        self._pipeline_depth = int(pipeline_depth)
        self._q: deque = deque()
        self._cond = threading.Condition()
        self._running = False
        self._in_flight = 0  # requests popped but not yet completed
        self._thread: Optional[threading.Thread] = None
        self._shedding = False
        self._pending_swaps = 0  # markers riding the queue
        # Two-stage hand-off ring (pipeline_depth >= 1): stage 1 packs
        # into it, stage 2 drains it in FIFO order; bounded, so a slow
        # scan back-pressures packing instead of buffering unboundedly.
        self._ring: deque = deque()
        self._ring_cond = threading.Condition()
        self._ring_cap = max(self._pipeline_depth, 1)
        self._ring_peak = 0
        self._pack_state = state  # stage-1 model pointer (pack thread)
        self._pack_thread: Optional[threading.Thread] = None
        # Counters (under _cond).
        self._submitted = 0
        self._served = 0
        self._shed = 0
        self._batches = 0
        self._batch_rows_served = 0
        self._swaps = 0
        self._max_depth = 0
        self._scan_wall_s = 0.0
        # Live serving metrics registry (ISSUE 11): fixed-bucket
        # histograms + counters/gauges updated on the hot path,
        # scrapeable MID-RUN through metrics_text() and the periodic
        # `serve --metrics-dump` snapshots.  ``metrics=False`` is the
        # no-obs control the serve bench uses to bound the
        # instrumentation overhead (< 2% acceptance).
        self._obs = metrics
        self.registry = MetricsRegistry()
        reg = self.registry
        self._m_submitted = reg.counter(
            "fa_serve_submitted_total", "requests submitted"
        )
        self._m_served = reg.counter(
            "fa_serve_served_total", "requests answered by a scan batch"
        )
        self._m_shed = reg.counter(
            "fa_serve_shed_total", "requests shed by admission control"
        )
        self._m_errors = reg.counter(
            "fa_serve_errors_total", "batches answered '0' on a fatal error"
        )
        self._m_swaps = reg.counter(
            "fa_serve_swaps_total", "hot-swap barriers committed"
        )
        self._m_queue = reg.gauge(
            "fa_serve_queue_depth", "admission queue depth (and peak)"
        )
        self._m_ring = reg.gauge(
            "fa_serve_ring_depth",
            "pack-to-scan hand-off ring depth (and peak)",
        )
        self._m_fill = reg.histogram(
            "fa_serve_batch_fill", _FILL_BUCKETS,
            "rows per dispatched micro-batch",
        )
        self._m_linger = reg.histogram(
            "fa_serve_linger_ms",
            help="first-request wait from enqueue to batch dispatch",
        )
        self._m_batch_ms = reg.histogram(
            "fa_serve_batch_ms", help="per-batch serve wall (scan incl.)"
        )
        self._m_swap_ms = reg.histogram(
            "fa_serve_swap_barrier_ms",
            help="swap-marker wait from enqueue to barrier commit",
        )

    # -- lifecycle ------------------------------------------------------
    def start(self, warm: bool = True) -> "RecommendServer":
        if self._thread is not None:
            raise InputError("RecommendServer.start called twice")
        # The scan's fixed compile shape must equal the micro-batcher's
        # collection bound, or every partial batch pads up to the config
        # default.
        self._state.set_batch_rows(self._batch_rows)
        if warm:
            self._state.warm()
        self._running = True
        self._pack_state = self._state
        if self._pipeline_depth > 0:
            # Two-stage pipeline: pack thread feeds the bounded ring,
            # dispatch thread consumes it (thread names key the
            # tracer's per-stage root spans).
            self._pack_thread = threading.Thread(
                target=self._pack_loop, name="fa-serve-pack",
                daemon=True,
            )
            self._pack_thread.start()
            self._thread = threading.Thread(
                target=self._scan_loop, name="fa-serve-dispatch",
                daemon=True,
            )
        else:
            self._thread = threading.Thread(
                target=self._dispatch_loop, name="fa-serve-dispatch",
                daemon=True,
            )
        self._thread.start()
        return self

    def stop(self, drain: bool = True, timeout_s: float = 30.0) -> bool:
        """Stop the dispatcher (optionally draining queued work first,
        bounded).  Returns True when every stage thread exited inside
        the bound — callers assert it, so a wedged dispatcher is a loud
        failure, not a leaked zombie."""
        if drain:
            self.drain(timeout_s=timeout_s)
        with self._cond:
            self._running = False
            self._cond.notify_all()
        with self._ring_cond:
            self._ring_cond.notify_all()
        deadline = time.monotonic() + timeout_s
        ok = True
        for t in (self._pack_thread, self._thread):
            if t is not None:
                t.join(max(deadline - time.monotonic(), 0.001))
                ok = ok and not t.is_alive()
        return ok

    def alive(self) -> bool:
        """Liveness probe for the mesh router's failure detector: the
        scan-stage dispatcher thread is still serving."""
        t = self._thread
        return bool(self._running and t is not None and t.is_alive())

    def drain(self, timeout_s: float = 30.0) -> bool:
        """Wait (bounded) until the queue is empty and nothing is in
        flight.  False on timeout — never a hang."""
        deadline = time.monotonic() + timeout_s
        with self._cond:
            while self._q or self._in_flight:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._cond.wait(min(remaining, 0.1))
        return True

    # -- request admission ---------------------------------------------
    def submit(
        self,
        tokens: Sequence[str],
        t_sched: Optional[float] = None,
    ) -> ServeRequest:
        """Open-loop admission: enqueue, or SHED immediately ("0",
        counted, episode cascade-recorded) when the bounded queue is
        full or the server is not running."""
        now = time.monotonic()
        req = ServeRequest(tokens, t_sched, now)
        with self._cond:
            self._submitted += 1
            if self._obs:
                self._m_submitted.inc()
            if not self._running or len(self._q) >= self._depth:
                return self._shed_locked(req, now)
            if self._shedding:
                self._shedding = False  # overload episode over
            self._q.append(req)
            depth = len(self._q)
            if self._obs:
                self._m_queue.set(depth)
            if depth > self._max_depth:
                self._max_depth = depth
            self._cond.notify_all()
        return req

    def try_submit(
        self,
        tokens: Sequence[str],
        t_sched: Optional[float] = None,
    ) -> Optional[ServeRequest]:
        """Mesh-router admission probe (serve/router.py): enqueue like
        :meth:`submit`, but return None — counting nothing — when the
        queue is full or the server stopped.  The router spills the
        request to another host first and sheds GLOBALLY only when every
        host refused, so a spilled request never double-counts in
        per-host submitted/shed."""
        now = time.monotonic()
        with self._cond:
            if not self._running or len(self._q) >= self._depth:
                return None
            req = ServeRequest(tokens, t_sched, now)
            self._submitted += 1
            if self._obs:
                self._m_submitted.inc()
            if self._shedding:
                self._shedding = False
            self._q.append(req)
            depth = len(self._q)
            if self._obs:
                self._m_queue.set(depth)
            if depth > self._max_depth:
                self._max_depth = depth
            self._cond.notify_all()
        return req

    def submit_wait(
        self,
        tokens: Sequence[str],
        t_sched: Optional[float] = None,
        timeout_s: float = 30.0,
    ) -> ServeRequest:
        """Closed-loop admission (file/stdin sources): block — bounded —
        for queue space instead of shedding.  Sheds only on timeout or a
        stopped server."""
        deadline = time.monotonic() + timeout_s
        now = time.monotonic()
        req = ServeRequest(tokens, t_sched, now)
        with self._cond:
            self._submitted += 1
            if self._obs:
                self._m_submitted.inc()
            while self._running and len(self._q) >= self._depth:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                self._cond.wait(min(remaining, 0.1))
            if not self._running or len(self._q) >= self._depth:
                return self._shed_locked(req, time.monotonic())
            if self._shedding:
                self._shedding = False
            req.t_enq = time.monotonic()
            self._q.append(req)
            depth = len(self._q)
            if self._obs:
                self._m_queue.set(depth)
            if depth > self._max_depth:
                self._max_depth = depth
            self._cond.notify_all()
        return req

    def _shed_locked(self, req: ServeRequest, now: float) -> ServeRequest:
        """Complete ``req`` as shed (caller holds the lock).  One
        cascade event per overload EPISODE (accept→shed transition) —
        per-request ledger events at tens of kilohertz would be their
        own memory overload; the per-request count rides stats()."""
        req.item = "0"
        req.shed = True
        req.t_done = now
        self._shed += 1
        if self._obs:
            self._m_shed.inc()
        if not self._shedding:
            self._shedding = True
            watchdog.downgrade(
                "serving", "accept", "shed",
                reason="queue_full" if self._running else "not_running",
                once_key="serving:accept>shed",
                depth=self._depth,
                shed_so_far=self._shed,
            )
        return req

    # -- waiting --------------------------------------------------------
    def wait_for(
        self, reqs: Sequence[ServeRequest], timeout_s: float = 30.0
    ) -> bool:
        """Bounded wait until every request in ``reqs`` completed."""
        deadline = time.monotonic() + timeout_s
        with self._cond:
            while not all(r.done for r in reqs):
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._cond.wait(min(remaining, 0.1))
        return True

    # -- hot swap -------------------------------------------------------
    def swap(
        self, new_state: ServingState, release_old: bool = True
    ) -> threading.Event:
        """Hot-swap the model: requests enqueued BEFORE this call are
        served by the current state (the barrier marker rides the queue;
        a batch never straddles it), requests after it by ``new_state``.
        Returns the barrier event (set when the swap committed).  The
        outgoing state is released at the barrier unless
        ``release_old=False`` (caller keeps it — e.g. a planned
        swap-back)."""
        marker = _SwapMarker(new_state, release_old)
        with self._cond:
            if not self._running:
                raise InputError("cannot swap a stopped server")
            self._q.append(marker)
            self._pending_swaps += 1
            self._cond.notify_all()
        return marker.event

    @property
    def state(self) -> ServingState:
        return self._state

    # -- dispatcher -----------------------------------------------------
    def _collect_batch(self) -> Optional[list]:
        """Form one micro-batch under the lock: up to batch_rows
        requests, stopping early at a swap marker or when the first
        request's linger deadline passes.  Returns None when stopped and
        empty."""
        with self._cond:
            while self._running and not self._q:
                self._cond.wait(0.05)
            if not self._q:
                return None  # stopped and drained
            if isinstance(self._q[0], _SwapMarker):
                self._in_flight += 1
                self._pending_swaps -= 1
                return [self._q.popleft()]
            deadline = self._q[0].t_enq + self._linger_s
            while (
                self._running
                and len(self._q) < self._batch_rows
                and not self._pending_swaps
            ):
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                self._cond.wait(min(remaining, 0.05))
            batch = []
            while self._q and len(batch) < self._batch_rows:
                if isinstance(self._q[0], _SwapMarker):
                    break  # the barrier: next batch handles it
                batch.append(self._q.popleft())
            self._in_flight += len(batch)
            return batch

    def _commit_swap(self, marker: _SwapMarker) -> None:
        """Commit a hot-swap barrier on the scan stage: repoint the
        serving state, ledger the transition, release the outgoing
        model, wake the barrier waiters."""
        old = self._state
        marker.state.set_batch_rows(self._batch_rows)
        with self._cond:
            # The install itself is published under the lock: submit
            # paths and stats() read the table concurrently, and the
            # swap counter pairs with it.
            self._state = marker.state
            self._swaps += 1
        if self._obs:
            self._m_swaps.inc()
            self._m_swap_ms.observe(
                (time.monotonic() - marker.t_enq) * 1e3
            )
        ledger.record(
            "serve_swap",
            once_key=marker.state.signature,
            frm=old.signature,
            to=marker.state.signature,
        )
        if marker.release_old:
            old.release()
        marker.event.set()
        with self._cond:
            self._in_flight -= 1
            self._cond.notify_all()

    def _serve_batch(
        self,
        batch: list,
        packed: Optional[PackedBatch],
        state: ServingState,
    ) -> None:
        """Serve one collected micro-batch and complete its requests.
        ``state`` is the model the batch is pinned to (the pack-time
        pointer under the pipeline; ``self._state`` on the serial
        path); ``packed`` is its stage-1 output, or None to run the
        whole unsplit path here."""
        t0 = time.monotonic()
        # The per-batch span is the serving trace's unit of work:
        # its children (serve.dedup / serve.pack on the pack stage,
        # serve.scan here) separate host time from device time, the
        # admission wait rides as an annotation, and the queue/shed
        # counter track samples at batch rate.
        with trace.span("serve.batch", rows=len(batch)) as sp:
            sp.update(
                admission_wait_ms=round(
                    (t0 - batch[0].t_enq) * 1e3, 3
                )
            )
            try:
                if packed is not None:
                    items = state.scan_packed(packed)
                else:
                    items = state.recommend_batch(
                        [r.tokens for r in batch]
                    )
            # The dispatcher must survive anything the scan raises past
            # its own cascade (a fatal error serves "0" to THIS batch,
            # classified on the ledger; the next batch gets a fresh
            # attempt) — a dead dispatcher would hang every later
            # waiter, the one outcome the serving tier forbids.
            # lint: waive G006 -- answered "0" + ledger serve_error; next batch retries
            except Exception as exc:
                ledger.record(
                    "serve_error",
                    once_key=type(exc).__name__,
                    error=f"{type(exc).__name__}: {exc}"[:200],
                    rows=len(batch),
                )
                items = ["0"] * len(batch)
                if self._obs:
                    self._m_errors.inc()
            now = time.monotonic()
            sig = state.signature
            with trace.span("serve.respond", rows=len(batch)):
                with self._cond:
                    for r, item in zip(batch, items):
                        r.item = item
                        r.model = sig
                        r.t_done = now
                    self._served += len(batch)
                    self._batches += 1
                    self._batch_rows_served += len(batch)
                    self._scan_wall_s += now - t0
                    self._in_flight -= len(batch)
                    depth = len(self._q)
                    shed = self._shed
                    # Registry updates BEFORE the waiters wake: a
                    # scrape racing wait_for() must never see the
                    # last batch missing from the instruments (the
                    # bench cross-check compares them to loadgen's
                    # own counts; cheap int adds under the lock).
                    if self._obs:
                        self._m_served.inc(len(batch))
                        self._m_fill.observe(len(batch))
                        self._m_linger.observe(
                            (t0 - batch[0].t_enq) * 1e3
                        )
                        self._m_batch_ms.observe((now - t0) * 1e3)
                        self._m_queue.set(depth)
                    self._cond.notify_all()
            trace.counter("serve_queue", depth=depth, shed=shed)

    def _dispatch_loop(self) -> None:
        """Serial (pipeline_depth=0) dispatcher: collect, scan, respond
        on one thread — the one-deep baseline."""
        while True:
            batch = self._collect_batch()
            if batch is None:
                return
            if len(batch) == 1 and isinstance(batch[0], _SwapMarker):
                self._commit_swap(batch[0])
                continue
            self._serve_batch(batch, None, self._state)

    # -- two-stage pipeline (pipeline_depth >= 1) -----------------------
    def _ring_push(self, item) -> None:
        """Bounded hand-off: block while the ring is at capacity (the
        back-pressure that keeps the pipeline's buffering at
        pipeline_depth batches); sentinel and shutdown pushes always
        land so the scan stage drains in order."""
        with self._ring_cond:
            while (
                self._running
                and item is not _STOP
                and len(self._ring) >= self._ring_cap
            ):
                self._ring_cond.wait(0.05)
            self._ring.append(item)
            depth = len(self._ring)
            if depth > self._ring_peak:
                self._ring_peak = depth
            if self._obs:
                self._m_ring.set(depth)
            self._ring_cond.notify_all()

    def _pack_loop(self) -> None:
        """Stage 1: collect + dedup + bitmap-pack micro-batches on the
        host while stage 2 scans the previous ones.  A swap marker
        advances the pack-side model pointer immediately — later
        batches pack against the incoming model; the marker itself
        commits downstream in ring order, behind every batch pinned to
        the old state."""
        try:
            while True:
                batch = self._collect_batch()
                if batch is None:
                    return
                if len(batch) == 1 and isinstance(batch[0], _SwapMarker):
                    marker = batch[0]
                    marker.state.set_batch_rows(self._batch_rows)
                    self._pack_state = marker.state
                    self._ring_push(marker)
                    continue
                state = self._pack_state
                try:
                    packed = state.pack_batch(
                        [r.tokens for r in batch]
                    )
                # A failed pack replays in stage 2: scan_packed-less
                # batches run the whole unsplit path there, where the
                # serve_error contract answers "0".
                except Exception:  # lint: waive G006 -- pack failure replays on stage 2's unsplit path
                    packed = None
                self._ring_push((batch, packed, state))
        finally:
            # Always deliver the shutdown sentinel — even on a pack-
            # thread crash — so stage 2 never waits on a dead feeder.
            self._ring_push(_STOP)

    def _scan_loop(self) -> None:
        """Stage 2: drain the ring in FIFO order — swap barriers commit
        between batches exactly as on the serial path."""
        while True:
            with self._ring_cond:
                while not self._ring:
                    self._ring_cond.wait(0.05)
                item = self._ring.popleft()
                if self._obs:
                    self._m_ring.set(len(self._ring))
                self._ring_cond.notify_all()
            if item is _STOP:
                return
            if isinstance(item, _SwapMarker):
                self._commit_swap(item)
                continue
            batch, packed, state = item
            self._serve_batch(batch, packed, state)

    # -- observability --------------------------------------------------
    def metrics_text(self) -> str:
        """The scrapeable Prometheus-text snapshot (ISSUE 11): this
        server's registry plus the process-global instruments (per-site
        audited-fetch latency).  Safe to call mid-run from any thread —
        instruments are single-writer ints; a torn read costs one
        sample, never a crash."""
        return self.registry.render() + obs_metrics.GLOBAL.render()

    def metrics_snapshot(self) -> dict:
        """Structured form of :meth:`metrics_text` for records/tests:
        the bench's per-scenario snapshot cross-checks these against
        the load generator's own shed/queue counts."""
        return {
            "server": self.registry.snapshot(),
            "global": obs_metrics.GLOBAL.snapshot(),
        }

    def reset_max_queue(self) -> int:
        """Reset the queue-depth peak to the CURRENT depth and return
        the old peak — run_open_loop calls it at scenario start so each
        record reports its own peak, not the server-lifetime maximum."""
        with self._cond:
            old = self._max_depth
            self._max_depth = len(self._q)
            return old

    def stats(self) -> dict:
        with self._cond:
            out = {
                "batch_rows": self._batch_rows,
                "linger_ms": round(self._linger_s * 1e3, 3),
                "queue_depth": self._depth,
                "submitted": self._submitted,
                "served": self._served,
                "shed": self._shed,
                "batches": self._batches,
                "avg_batch": round(
                    self._batch_rows_served / max(self._batches, 1), 1
                ),
                "max_queue": self._max_depth,
                "swaps": self._swaps,
                "scan_wall_s": round(self._scan_wall_s, 3),
                "pipeline_depth": self._pipeline_depth,
                "ring_peak": self._ring_peak,
            }
        out["model"] = self._state.describe()
        return out
