"""MeshRouter — the multi-host serving mesh (ISSUE 19 tentpole, part b).

One RecommendServer saturates at one host's scan capacity; the north
star's "millions of users" needs the serving tier to shard the way the
batch path shards transactions.  The router is that shard layer: it
fans an open-loop request stream across a mesh of serving hosts — each
a full admission-queue + two-stage-dispatcher + device-scan stack —
and presents the SAME surface a single server does (submit / wait_for /
stats / metrics / swap), so the load generator, bench, CLI and smoke
drive a mesh exactly like one server.

**Hosts.**  Two host forms behind one duck-typed face:

- :class:`LocalHost` — an in-process ``RecommendServer`` (virtual-host
  scaling on one machine; the bench's 1/2/4-host ladder).
- :class:`ProcHost` — a subprocess worker (``python -m
  fastapriori_tpu.serve.router --worker``) owning its own JAX runtime
  and serving from a checkpoint prefix; the router talks to it through
  an atomic-rename file protocol (the quorum FileTransport discipline:
  ``tmp`` + ``os.replace``, so a reader never sees a torn file) with
  heartbeat liveness under the SAME knobs the consensus substrate uses
  (``FA_HEARTBEAT_MS`` publish interval, age judged against
  ``FA_QUORUM_TIMEOUT_S``).

**Routing + global shed.**  Requests round-robin across live hosts;
a host that refuses admission (:meth:`RecommendServer.try_submit` —
full queue, counts nothing) spills along
:func:`~fastapriori_tpu.parallel.hier.spill_order` (pod-local first).
Only when EVERY live host refuses does the router shed globally —
answered "0" immediately, counted once at the router, one ``serving``
accept→shed cascade event per overload episode.  A request is counted
by exactly one host or by the router, never both — shed accounting
stays exact under overload (test-pinned).

**Mesh hot-swap.**  :meth:`swap` holds admission while it enqueues the
barrier marker on every host in order, then releases; each host's
barrier preserves the single-server contract (a batch never straddles
the marker), so every response carries either the old or the new model
signature and every request admitted after :meth:`swap` returns is
served by the new — a response never mixes rule tables across the
router (test-pinned via per-response signatures).

**PeerLost-driven rerouting.**  A monitor thread runs the failure
detector (thread liveness for LocalHost, process exit + heartbeat age
for ProcHost).  A dead host walks the ``serve_mesh`` full→degraded
cascade once (HOST-LOCAL: the router is one process observing files —
no collective shape change, hence not consensus-registered), its
in-flight requests are answered "0" as recorded sheds, and its share
drains to the survivors through ordinary routing — degraded, recorded,
never a hang.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Sequence

from fastapriori_tpu.errors import InputError
from fastapriori_tpu.obs import metrics as obs_metrics
from fastapriori_tpu.obs.metrics import MetricsRegistry
from fastapriori_tpu.parallel.hier import spill_order
from fastapriori_tpu.reliability import failpoints, ledger, quorum, watchdog
from fastapriori_tpu.serve.server import RecommendServer, ServeRequest
from fastapriori_tpu.serve.state import ServingState

_HOSTS: Optional[int] = None


def hosts_from_env() -> int:
    """``FA_SERVE_HOSTS`` — serving-mesh host count for the CLI/bench
    entry points (strict int >= 1, default 1 = no mesh, the plain
    single-server path).  The router itself takes an explicit host
    list; this knob only sizes the default mesh the entry points
    build."""
    global _HOSTS
    if _HOSTS is None:
        from fastapriori_tpu.utils.env import env_int

        _HOSTS = env_int("FA_SERVE_HOSTS", 1, minimum=1)
    return _HOSTS


def reload_from_env() -> None:
    """Drop the memoized knob reads (tests repoint the environment)."""
    global _HOSTS
    _HOSTS = None


def _write_json_atomic(path: str, obj) -> None:
    """The FileTransport write discipline: full content to a tmp name,
    one atomic rename — a concurrent reader sees the old file or the
    new one, never a torn prefix."""
    tmp = f"{path}.tmp.{os.getpid()}"
    # lint: waive G009 -- this IS the atomic discipline: tmp + os.replace; write_artifact would drag manifest machinery into a per-request protocol file
    with open(tmp, "w", encoding="utf-8") as f:
        f.write(json.dumps(obj))
    os.replace(tmp, path)


def _read_json(path: str):
    try:
        with open(path, "r", encoding="utf-8") as f:
            return json.loads(f.read())
    except (FileNotFoundError, json.JSONDecodeError, OSError):
        return None


class LocalHost:
    """An in-process mesh host: one started :class:`RecommendServer`.
    The bench's virtual-host ladder and the router tests use these —
    same admission/pipeline/swap machinery as a real host, zero
    transport."""

    def __init__(self, name: str, server: RecommendServer):
        self.name = name
        self.server = server
        self._failed = False
        # Requests accepted by this host and possibly still in flight —
        # the router answers them as sheds if the host dies (pruned
        # lazily; bounded by queue depth + pipeline buffering).
        self._outstanding: deque = deque()

    def try_submit(
        self, tokens: Sequence[str], t_sched: Optional[float] = None
    ) -> Optional[ServeRequest]:
        if self._failed:
            return None
        req = self.server.try_submit(tokens, t_sched)
        if req is not None:
            out = self._outstanding
            while out and out[0].done:
                out.popleft()
            out.append(req)
        return req

    def alive(self) -> bool:
        return not self._failed and self.server.alive()

    def swap(self, payload: ServingState) -> threading.Event:
        return self.server.swap(payload)

    def fail_outstanding(self) -> int:
        """Answer every not-yet-served request as a recorded shed (the
        dead host's in-flight share) — called by the router's failure
        detector, never a hang for the waiters."""
        now = time.monotonic()
        n = 0
        while self._outstanding:
            r = self._outstanding.popleft()
            if not r.done:
                r.item = "0"
                r.shed = True
                r.t_done = now
                n += 1
        return n

    def kill(self) -> None:
        """Chaos/test hook: abrupt host death — the admission queue and
        hand-off ring are dropped on the floor (their requests are the
        router's to answer), the stage threads exit without drain."""
        self._failed = True
        srv = self.server
        with srv._cond:
            srv._q.clear()
            srv._running = False
            srv._cond.notify_all()
        with srv._ring_cond:
            srv._ring.clear()
            srv._ring_cond.notify_all()

    def stats(self) -> dict:
        return self.server.stats()

    def metrics_snapshot(self) -> dict:
        snap = self.server.metrics_snapshot()
        return {**snap["server"], **snap["global"]}

    def reset_max_queue(self) -> None:
        self.server.reset_max_queue()

    def stop(self, timeout_s: float = 30.0) -> bool:
        if self._failed:
            return True
        return self.server.stop(timeout_s=timeout_s)


class ProcHost:
    """A subprocess mesh host: spawns ``python -m
    fastapriori_tpu.serve.router --worker`` serving a checkpoint
    prefix, and proxies admission through the file protocol.

    Router-side shape: :meth:`try_submit` bounds in-flight requests at
    the worker's queue depth (admission back-pressure without a
    round-trip); a flusher thread packs pending requests into
    ``req-<seq>.json`` batches; a poller thread completes them from
    ``rsp-<seq>.json``.  Swap barriers ride the SAME seq stream —
    ``swap-<seq>.json`` is written only after every request admitted
    before the swap, so the worker observes router order."""

    def __init__(
        self,
        name: str,
        workdir: str,
        serving_prefix: str,
        *,
        batch_rows: int = 0,
        linger_ms: float = -1.0,
        queue_depth: int = 0,
        engine: str = "auto",
        pipeline_depth: Optional[int] = None,
        start_timeout_s: float = 120.0,
        env: Optional[dict] = None,
    ):
        self.name = name
        self.dir = workdir
        os.makedirs(workdir, exist_ok=True)
        self._cap = queue_depth if queue_depth else 4 * (batch_rows or 256)
        self._lock = threading.Condition()
        self._pending: deque = deque()  # ServeRequest | _SwapCmd
        self._outstanding: Dict[int, ServeRequest] = {}
        self._next_id = 0
        self._next_seq = 0
        self._swap_events: Dict[int, threading.Event] = {}
        self._swap_sigs: Dict[int, str] = {}
        self._failed = False
        self._running = True
        self._stats_cache: dict = {}
        self._batch_cap = max(batch_rows or 256, 1)
        cmd = [
            sys.executable, "-m", "fastapriori_tpu.serve.router",
            "--worker", "--dir", workdir, "--serving", serving_prefix,
            "--engine", engine,
            "--batch-rows", str(batch_rows),
            "--linger-ms", str(linger_ms),
            "--queue-depth", str(queue_depth),
        ]
        if pipeline_depth is not None:
            cmd += ["--pipeline-depth", str(pipeline_depth)]
        penv = dict(os.environ)
        if env:
            penv.update(env)
        self.proc = subprocess.Popen(
            cmd, env=penv,
            stdout=subprocess.DEVNULL, stderr=subprocess.STDOUT,
        )
        deadline = time.monotonic() + start_timeout_s
        ready = None
        while time.monotonic() < deadline:
            ready = _read_json(os.path.join(workdir, "ready.json"))
            if ready is not None:
                break
            if self.proc.poll() is not None:
                break
            time.sleep(0.02)
        if ready is None:
            self.proc.kill()
            raise InputError(
                f"mesh host {name}: worker failed to become ready "
                f"within {start_timeout_s}s (exit="
                f"{self.proc.poll()})"
            )
        self.signature = ready["signature"]
        self._flusher = threading.Thread(
            target=self._flush_loop, name=f"fa-mesh-flush-{name}",
            daemon=True,
        )
        self._poller = threading.Thread(
            target=self._poll_loop, name=f"fa-mesh-poll-{name}",
            daemon=True,
        )
        self._flusher.start()
        self._poller.start()

    # -- admission ------------------------------------------------------
    def try_submit(
        self, tokens: Sequence[str], t_sched: Optional[float] = None
    ) -> Optional[ServeRequest]:
        if self._failed:
            return None
        with self._lock:
            if (
                len(self._pending) + len(self._outstanding) >= self._cap
            ):
                return None
            req = ServeRequest(list(tokens), t_sched, time.monotonic())
            self._pending.append(req)
            self._lock.notify_all()
        return req

    def swap(self, payload: str) -> threading.Event:
        """Enqueue a swap barrier carrying a checkpoint PREFIX; it is
        flushed behind every previously admitted request."""
        ev = threading.Event()
        with self._lock:
            self._pending.append(("swap", payload, ev))
            self._lock.notify_all()
        return ev

    # -- router-side threads --------------------------------------------
    def _flush_loop(self) -> None:
        while self._running:
            with self._lock:
                if not self._pending:
                    self._lock.wait(0.005)
                    continue
                batch: List[ServeRequest] = []
                swap_cmd = None
                while self._pending and len(batch) < self._batch_cap:
                    item = self._pending[0]
                    if isinstance(item, tuple):
                        if batch:
                            break  # flush admitted requests first
                        swap_cmd = self._pending.popleft()
                        break
                    batch.append(self._pending.popleft())
                ids = []
                for r in batch:
                    self._outstanding[self._next_id] = r
                    ids.append(self._next_id)
                    self._next_id += 1
                seq = self._next_seq
                self._next_seq += 1
                if swap_cmd is not None:
                    # Register the barrier event under the lock: a
                    # concurrent fail_outstanding must either see it
                    # (and release it) or miss the whole swap.
                    self._swap_events[seq] = swap_cmd[2]
            if swap_cmd is not None:
                _, prefix, _ev = swap_cmd
                _write_json_atomic(
                    os.path.join(self.dir, f"swap-{seq:08d}.json"),
                    {"prefix": prefix},
                )
                continue
            _write_json_atomic(
                os.path.join(self.dir, f"req-{seq:08d}.json"),
                {"ids": ids, "baskets": [list(r.tokens) for r in batch]},
            )

    def _poll_loop(self) -> None:
        done_rsp = set()
        while self._running:
            progressed = False
            try:
                names = os.listdir(self.dir)
            except OSError:
                names = []
            for fn in sorted(names):
                if fn.startswith("rsp-") and fn.endswith(".json"):
                    if fn in done_rsp:
                        continue
                    data = _read_json(os.path.join(self.dir, fn))
                    if data is None:
                        continue
                    done_rsp.add(fn)
                    now = time.monotonic()
                    with self._lock:
                        for i, rid in enumerate(data["ids"]):
                            r = self._outstanding.pop(rid, None)
                            if r is None:
                                continue
                            r.item = data["items"][i]
                            r.model = data["models"][i]
                            r.shed = bool(data["shed"][i])
                            r.t_done = now
                        self._lock.notify_all()
                    progressed = True
                elif fn.startswith("swapped-") and fn.endswith(".json"):
                    seq = int(fn[8:-5])
                    ev = self._swap_events.get(seq)
                    if ev is not None and not ev.is_set():
                        data = _read_json(os.path.join(self.dir, fn))
                        if data is not None:
                            self._swap_sigs[seq] = data.get("to", "")
                            ev.set()
                            progressed = True
                elif fn == "stats.json":
                    data = _read_json(os.path.join(self.dir, fn))
                    if data is not None:
                        with self._lock:
                            self._stats_cache = data
            if not progressed:
                time.sleep(0.003)

    # -- health / teardown ----------------------------------------------
    def alive(self) -> bool:
        if self._failed:
            return False
        if self.proc.poll() is not None:
            return False
        try:
            age = time.time() - os.path.getmtime(
                os.path.join(self.dir, "hb")
            )
        except OSError:
            return True  # not yet published; process liveness covers it
        return age <= quorum.quorum_timeout_s()

    def fail_outstanding(self) -> int:
        self._failed = True
        now = time.monotonic()
        n = 0
        with self._lock:
            for r in list(self._pending) + list(
                self._outstanding.values()
            ):
                if not isinstance(r, tuple) and not r.done:
                    r.item = "0"
                    r.shed = True
                    r.t_done = now
                    n += 1
            self._pending.clear()
            self._outstanding.clear()
            self._lock.notify_all()
            # Snapshot under the lock: the flusher registers swap
            # events concurrently, and iterating the live dict races
            # that insert.
            events = list(self._swap_events.values())
        for ev in events:
            ev.set()  # a dead host cannot hold the mesh barrier
        return n

    def kill(self) -> None:
        """Chaos/test hook: hard-kill the worker process."""
        self.proc.kill()

    def stats(self) -> dict:
        return dict(self._stats_cache)

    def metrics_snapshot(self) -> dict:
        snap = _read_json(os.path.join(self.dir, "metrics.json"))
        return snap or {}

    def reset_max_queue(self) -> None:
        # Worker-side peak reset rides the stop-free control file; the
        # seq is allocated under the lock like every other protocol
        # file, so two resets can never share a name.
        with self._lock:
            seq = self._next_seq
            self._next_seq += 1
        _write_json_atomic(
            os.path.join(self.dir, f"reset-{seq}.json"), {}
        )

    def stop(self, timeout_s: float = 60.0) -> bool:
        with self._lock:
            self._running = False
            self._lock.notify_all()
        if self._failed or self.proc.poll() is not None:
            return True
        _write_json_atomic(os.path.join(self.dir, "stop"), {})
        try:
            self.proc.wait(timeout_s)
        except subprocess.TimeoutExpired:
            self.proc.kill()
            return False
        # Final worker state lands before exit; fold it in.
        data = _read_json(os.path.join(self.dir, "stats.json"))
        if data is not None:
            with self._lock:
                self._stats_cache = data
        return True


class MeshRouter:
    """Routes an open-loop request stream across serving hosts (module
    docstring).  Duck-types the single-server surface the load
    generator drives: submit / wait_for / stats / reset_max_queue /
    metrics_text."""

    def __init__(self, hosts: Sequence, metrics: bool = True):
        if not hosts:
            raise InputError("MeshRouter needs at least one host")
        self._hosts = list(hosts)
        self._lock = threading.Condition()
        self._admit_lock = threading.Lock()
        self._rr = 0
        self._submitted = 0
        self._shed = 0          # router-global sheds (all hosts full)
        self._lost_shed = 0     # dead-host in-flight answered as shed
        self._rerouted = 0      # primary dead, survivor accepted
        self._swaps = 0
        self._lost: set = set()
        self._shedding = False
        self._obs = metrics
        self.registry = MetricsRegistry()
        reg = self.registry
        self._m_submitted = reg.counter(
            "fa_mesh_submitted_total", "requests routed by the mesh"
        )
        self._m_shed = reg.counter(
            "fa_mesh_shed_total",
            "requests shed at the router (every live host refused)",
        )
        self._m_lost = reg.counter(
            "fa_mesh_lost_shed_total",
            "dead-host in-flight requests answered as sheds",
        )
        self._m_rerouted = reg.counter(
            "fa_mesh_rerouted_total",
            "requests rerouted off a dead primary host",
        )
        self._m_swaps = reg.counter(
            "fa_mesh_swaps_total", "mesh-wide hot-swap barriers"
        )
        self._m_hosts = reg.gauge(
            "fa_mesh_hosts_live", "live serving hosts"
        )
        self._m_hosts.set(len(self._hosts))
        self._running = True
        self._monitor = threading.Thread(
            target=self._monitor_loop, name="fa-mesh-monitor",
            daemon=True,
        )
        self._monitor.start()

    # -- routing --------------------------------------------------------
    def submit(
        self,
        tokens: Sequence[str],
        t_sched: Optional[float] = None,
    ) -> ServeRequest:
        """Round-robin admission with pod-local spill; global shed only
        when every live host refused (counted once, HERE)."""
        with self._admit_lock:
            with self._lock:
                self._submitted += 1
                if self._obs:
                    self._m_submitted.inc()
                primary = self._rr % len(self._hosts)
                self._rr += 1
            rerouted = False
            for idx in spill_order(primary, len(self._hosts)):
                host = self._hosts[idx]
                if host.name in self._lost:
                    if idx == primary:
                        rerouted = True
                    continue
                if not host.alive():
                    self._on_host_lost(host)
                    if idx == primary:
                        rerouted = True
                    continue
                req = host.try_submit(tokens, t_sched)
                if req is not None:
                    if rerouted:
                        with self._lock:
                            self._rerouted += 1
                            if self._obs:
                                self._m_rerouted.inc()
                    if self._shedding:
                        self._shedding = False
                    return req
            return self._shed_global(tokens, t_sched)

    def _shed_global(self, tokens, t_sched) -> ServeRequest:
        now = time.monotonic()
        req = ServeRequest(list(tokens), t_sched, now)
        req.item = "0"
        req.shed = True
        req.t_done = now
        with self._lock:
            self._shed += 1
            if self._obs:
                self._m_shed.inc()
        if not self._shedding:
            self._shedding = True
            watchdog.downgrade(
                "serving", "accept", "shed",
                reason="mesh_queue_full",
                once_key="mesh:accept>shed",
                hosts=len(self._hosts),
                lost=len(self._lost),
            )
        return req

    # -- failure detector -----------------------------------------------
    def _monitor_loop(self) -> None:
        interval = max(quorum.heartbeat_ms() / 1e3, 0.02)
        while self._running:
            for host in self._hosts:
                if host.name not in self._lost and not host.alive():
                    self._on_host_lost(host)
            time.sleep(interval)

    def _on_host_lost(self, host) -> None:
        with self._lock:
            if host.name in self._lost:
                return
            self._lost.add(host.name)
            live = len(self._hosts) - len(self._lost)
        watchdog.downgrade(
            "serve_mesh", "full", "degraded",
            reason="host_lost",
            once_key=f"serve_mesh:{host.name}",
            host=host.name,
            survivors=live,
        )
        n = host.fail_outstanding()
        with self._lock:
            self._lost_shed += n
            self._shed += n
            if self._obs:
                self._m_lost.inc(n)
                self._m_shed.inc(n)
                self._m_hosts.set(live)
            self._lock.notify_all()
        ledger.record(
            "serve_host_lost",
            once_key=f"host:{host.name}",
            host=host.name,
            survivors=live,
            inflight_shed=n,
        )
        if live == 0:
            # Total mesh loss: admission flips to permanent global
            # shed; the downgrade above already recorded degraded.
            ledger.record(
                "serve_mesh_empty", once_key="serve_mesh_empty"
            )

    # -- waiting / swap -------------------------------------------------
    def wait_for(
        self, reqs: Sequence[ServeRequest], timeout_s: float = 30.0
    ) -> bool:
        """Bounded completion wait.  Polls: requests complete on host
        threads (LocalHost) or the poller (ProcHost); the monitor
        answers a dead host's share — every path sets ``t_done``, so
        this converges or times out, never hangs."""
        deadline = time.monotonic() + timeout_s
        while not all(r.done for r in reqs):
            if time.monotonic() >= deadline:
                return False
            time.sleep(0.002)
        return True

    def swap(
        self, payloads: Sequence, timeout_s: Optional[float] = None
    ) -> bool:
        """Mesh-wide hot-swap, barrier-ordered across hosts: admission
        is held while every live host enqueues its barrier marker (so a
        request admitted after this returns is served by the new model
        on whichever host it lands), then all barriers are awaited,
        bounded.  ``payloads[i]`` is host i's swap payload — a
        ServingState for a LocalHost, a checkpoint prefix for a
        ProcHost."""
        if len(payloads) != len(self._hosts):
            raise InputError(
                f"swap needs one payload per host "
                f"({len(payloads)} != {len(self._hosts)})"
            )
        bound = (
            quorum.quorum_timeout_s() if timeout_s is None else timeout_s
        )
        events = []
        with self._admit_lock:
            for host, payload in zip(self._hosts, payloads):
                if host.name in self._lost:
                    continue
                events.append(host.swap(payload))
        deadline = time.monotonic() + bound
        ok = True
        for ev in events:
            ok = ev.wait(max(deadline - time.monotonic(), 0.001)) and ok
        with self._lock:
            self._swaps += 1
            if self._obs:
                self._m_swaps.inc()
        ledger.record("serve_mesh_swap", hosts=len(events), ok=ok)
        return ok

    # -- observability ---------------------------------------------------
    def metrics_snapshot(self) -> dict:
        """The mesh-merged snapshot (counters sum, gauges max,
        histograms bucket-wise add) of every host registry plus the
        router's own instruments."""
        snaps = [self.registry.snapshot()]
        snaps += [h.metrics_snapshot() for h in self._hosts]
        return obs_metrics.merge_snapshots(snaps)

    def metrics_text(self) -> str:
        """One scrapeable Prometheus text for the whole mesh."""
        return obs_metrics.render_snapshot(self.metrics_snapshot())

    def stats(self) -> dict:
        per_host = []
        served = batches = shed_hosts = submitted_hosts = 0
        max_queue = 0
        for h in self._hosts:
            s = h.stats()
            per_host.append(
                {"host": h.name, "lost": h.name in self._lost, **s}
            )
            served += s.get("served", 0)
            batches += s.get("batches", 0)
            shed_hosts += s.get("shed", 0)
            submitted_hosts += s.get("submitted", 0)
            max_queue = max(max_queue, s.get("max_queue", 0))
        with self._lock:
            return {
                "hosts": len(self._hosts),
                "hosts_lost": len(self._lost),
                "submitted": self._submitted,
                "served": served,
                "shed": self._shed + shed_hosts,
                "router_shed": self._shed,
                "lost_shed": self._lost_shed,
                "rerouted": self._rerouted,
                "swaps": self._swaps,
                "batches": batches,
                "max_queue": max_queue,
                "per_host": per_host,
            }

    def reset_max_queue(self) -> None:
        for h in self._hosts:
            if h.name not in self._lost:
                h.reset_max_queue()

    def drain(self, timeout_s: float = 60.0) -> bool:
        deadline = time.monotonic() + timeout_s
        for h in self._hosts:
            if h.name in self._lost:
                continue
            if isinstance(h, LocalHost):
                if not h.server.drain(
                    max(deadline - time.monotonic(), 0.001)
                ):
                    return False
        return True

    def stop(self, timeout_s: float = 60.0) -> bool:
        with self._admit_lock:
            # The hb monitor thread polls this flag; publish the store
            # under the admission lock it already synchronizes on.
            self._running = False
        ok = True
        for h in self._hosts:
            ok = h.stop(timeout_s=timeout_s) and ok
        return ok


# ---------------------------------------------------------------------
# Worker process: one serving host behind the file protocol.
# ---------------------------------------------------------------------

def _worker_serve(args) -> int:
    from fastapriori_tpu.obs import trace

    trace.maybe_enable(explicit=False)
    state = ServingState.load(args.serving, engine=args.engine)
    server = RecommendServer(
        state,
        batch_rows=args.batch_rows or None,
        linger_ms=None if args.linger_ms < 0 else args.linger_ms,
        queue_depth=args.queue_depth or None,
        pipeline_depth=args.pipeline_depth,
    ).start()
    d = args.dir
    hb_s = quorum.heartbeat_ms() / 1e3

    def _publish() -> None:
        # The heartbeat rides the same atomic committer as every other
        # protocol file; only its mtime is consulted (ProcHost.alive),
        # and os.replace refreshes that either way.
        _write_json_atomic(os.path.join(d, "hb"), {"t": time.time()})
        snap = server.metrics_snapshot()
        _write_json_atomic(
            os.path.join(d, "metrics.json"),
            {**snap["server"], **snap["global"]},
        )
        _write_json_atomic(os.path.join(d, "stats.json"), server.stats())

    _publish()
    _write_json_atomic(
        os.path.join(d, "ready.json"),
        {"signature": state.signature, "pid": os.getpid()},
    )
    processed: set = set()
    outstanding: deque = deque()  # (seq, ids, reqs)
    swaps_pending: Dict[int, object] = {}  # seq -> (event, signature)
    last_hb = time.monotonic()
    stopping = False
    while True:
        now = time.monotonic()
        if now - last_hb >= hb_s:
            last_hb = now
            _publish()
        progressed = False
        try:
            names = sorted(os.listdir(d))
        except OSError:
            names = []
        for fn in names:
            if fn.startswith("req-") and fn.endswith(".json"):
                seq = int(fn[4:-5])
                if seq in processed:
                    continue
                data = _read_json(os.path.join(d, fn))
                if data is None:
                    continue
                processed.add(seq)
                failpoints.fire("router.req")
                reqs = [server.submit(b) for b in data["baskets"]]
                outstanding.append((seq, data["ids"], reqs))
                progressed = True
            elif fn.startswith("swap-") and fn.endswith(".json"):
                seq = int(fn[5:-5])
                if seq in processed:
                    continue
                data = _read_json(os.path.join(d, fn))
                if data is None:
                    continue
                processed.add(seq)
                failpoints.fire("router.swap")
                new_state = ServingState.load(
                    data["prefix"], engine=args.engine
                )
                # Readiness barrier: compile + device-load the new
                # table BEFORE it enters the swap ring, so the scan
                # stage never stalls on a cold XLA cache mid-batch
                # (the audited fetch inside pins device residency).
                new_state.device_ready()
                ev = server.swap(new_state)
                swaps_pending[seq] = (ev, new_state.signature)
                progressed = True
            elif fn.startswith("reset-"):
                server.reset_max_queue()
                try:
                    os.remove(os.path.join(d, fn))
                except OSError:
                    pass
        while outstanding and all(r.done for r in outstanding[0][2]):
            seq, ids, reqs = outstanding.popleft()
            _write_json_atomic(
                os.path.join(d, f"rsp-{seq:08d}.json"),
                {
                    "ids": ids,
                    "items": [r.item for r in reqs],
                    "models": [r.model for r in reqs],
                    "shed": [bool(r.shed) for r in reqs],
                },
            )
            # Counters must be current the moment the response is
            # visible — a scrape at drain is exact, not hb-stale.
            _publish()
            last_hb = time.monotonic()
            progressed = True
        for seq in list(swaps_pending):
            ev, sig = swaps_pending[seq]
            if ev.is_set():
                del swaps_pending[seq]
                _write_json_atomic(
                    os.path.join(d, f"swapped-{seq:08d}.json"),
                    {"to": sig},
                )
                progressed = True
        if os.path.exists(os.path.join(d, "stop")):
            if not stopping:
                stopping = True
            if not outstanding and not swaps_pending:
                break
        if not progressed:
            time.sleep(0.002)
    server.stop(drain=True)
    _publish()
    return 0


def main(argv: Optional[Sequence[str]] = None) -> int:
    import argparse

    p = argparse.ArgumentParser(
        prog="fastapriori_tpu.serve.router",
        description="serving-mesh worker host (spawned by ProcHost)",
    )
    p.add_argument("--worker", action="store_true", required=True)
    p.add_argument("--dir", required=True)
    p.add_argument("--serving", required=True,
                   help="checkpoint prefix to serve from")
    p.add_argument("--engine", default="auto")
    p.add_argument("--batch-rows", type=int, default=0)
    p.add_argument("--linger-ms", type=float, default=-1.0)
    p.add_argument("--queue-depth", type=int, default=0)
    p.add_argument("--pipeline-depth", type=int, default=None)
    args = p.parse_args(argv)
    return _worker_serve(args)


if __name__ == "__main__":
    sys.exit(main())
