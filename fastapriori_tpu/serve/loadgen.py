"""Open-loop load generation for the serving tier (ISSUE 10).

A CLOSED benchmark (submit, wait, repeat) measures only service time —
its arrival rate slows down whenever the server does, so queueing and
overload never show.  The north star's "heavy traffic" claim needs the
open-loop shape: arrivals follow a schedule INDEPENDENT of completions
(a Poisson process here — seeded, so the schedule is deterministic and
reproducible), latency is measured from each request's *scheduled*
arrival (no coordinated omission: generator lag counts against the
server, not for it), and offered load past capacity surfaces as
queueing + recorded sheds rather than a silently stretched run.

:func:`run_open_loop` drives a :class:`~fastapriori_tpu.serve.server.
RecommendServer` with one such schedule and aggregates the serving
record fields: offered/achieved rates, p50/p95/p99 latency, queue
depth, shed counts.  Every wait is timeout-bounded — a wedged server
yields ``drained=False`` plus partial counters, never a hung bench.
"""

from __future__ import annotations

import time
from typing import List, Optional, Sequence

import numpy as np

from fastapriori_tpu.errors import InputError


def arrival_offsets(
    n_requests: int, rate_rps: float, seed: int
) -> np.ndarray:
    """Deterministic Poisson arrival schedule: ``n`` cumulative offsets
    (seconds from t0) with exponential inter-arrivals at ``rate_rps``.
    Same (n, rate, seed) -> byte-identical schedule (test-pinned)."""
    if rate_rps <= 0:
        raise InputError(f"rate_rps must be positive, got {rate_rps}")
    rng = np.random.default_rng(seed)
    gaps = rng.exponential(1.0 / rate_rps, size=n_requests)
    return np.cumsum(gaps)


def percentiles_ms(latencies_ms: Sequence[float]) -> dict:
    if not len(latencies_ms):
        return {"p50_ms": None, "p95_ms": None, "p99_ms": None}
    # lint: host-data -- latency floats computed on host, no device fetch
    arr = np.asarray(latencies_ms, dtype=np.float64)
    return {
        "p50_ms": round(float(np.percentile(arr, 50)), 2),
        "p95_ms": round(float(np.percentile(arr, 95)), 2),
        "p99_ms": round(float(np.percentile(arr, 99)), 2),
    }


def run_open_loop(
    server,
    baskets: Sequence[Sequence[str]],
    *,
    rate_rps: float,
    n_requests: int,
    seed: int,
    drain_timeout_s: float = 60.0,
    label: str = "open_loop",
    requests_out: Optional[List] = None,
) -> dict:
    """Drive ``server`` with a seeded open-loop burst: request i is the
    (i mod len(baskets))-th basket, submitted at its scheduled offset
    (all due arrivals submit in one sweep — at tens of kHz a per-request
    sleep cannot keep the schedule, the batched sweep can).  Returns the
    serving record: offered/achieved rates, percentile latencies over
    SERVED requests (sheds answer immediately and are counted
    separately), queue/shed counters, and the model's scan facts."""
    if not baskets:
        raise InputError("run_open_loop needs a non-empty basket pool")
    offsets = arrival_offsets(n_requests, rate_rps, seed)
    # Each scenario reports ITS OWN queue peak (`batches` below is
    # differenced the same way).
    server.reset_max_queue()
    before = server.stats()
    reqs: List = []
    t0 = time.monotonic()
    i = 0
    while i < n_requests:
        now = time.monotonic() - t0
        # Submit every arrival whose scheduled time has passed.
        while i < n_requests and offsets[i] <= now:
            reqs.append(
                server.submit(
                    baskets[i % len(baskets)], t_sched=t0 + offsets[i]
                )
            )
            i += 1
        if i < n_requests:
            time.sleep(min(max(offsets[i] - (time.monotonic() - t0), 0.0),
                           0.002))
    if requests_out is not None:
        requests_out.extend(reqs)
    drained = server.wait_for(reqs, timeout_s=drain_timeout_s)
    t_end = time.monotonic()
    served = [r for r in reqs if r.done and not r.shed]
    shed = sum(1 for r in reqs if r.shed)
    lat = [r.latency_ms() for r in served]
    last_done = max((r.t_done for r in served), default=t_end)
    wall = max(last_done - t0, 1e-9)
    after = server.stats()
    out = {
        "label": label,
        "seed": seed,
        "n_requests": n_requests,
        "offered_rps": round(rate_rps, 1),
        # Offered rate as realized by the schedule (== rate_rps up to
        # sampling noise; recorded so the row is self-describing).
        "scheduled_rps": round(float(n_requests / offsets[-1]), 1),
        "achieved_rps": round(len(served) / wall, 1),
        "served": len(served),
        "shed": shed,
        "drained": drained,
        "wall_s": round(t_end - t0, 3),
        "max_queue": after["max_queue"],
        "batches": after["batches"] - before["batches"],
        **percentiles_ms(lat),
    }
    n_batches = out["batches"]
    out["avg_batch"] = round(len(served) / n_batches, 1) if n_batches else 0
    return out
