"""Serving tier (ISSUE 10): the "serve model" half of the API split.

- :class:`~fastapriori_tpu.serve.state.ServingState` — the explicit,
  checkpointable model artifact mounting the device-resident rule scan
  table (build / save / load / recommend_batch / release).
- :class:`~fastapriori_tpu.serve.server.RecommendServer` — the
  resident request loop: bounded-queue admission control, fixed-shape
  micro-batching behind the batch-size/linger knobs, ledger-recorded
  shed mode, barrier-ordered hot-swap.
- :mod:`~fastapriori_tpu.serve.loadgen` — seeded open-loop load
  generation + the sustained-load record fields (bench / smoke / CLI).
- :class:`~fastapriori_tpu.serve.router.MeshRouter` — the multi-host
  serving mesh (ISSUE 19): request routing + global shed across
  in-process (:class:`~fastapriori_tpu.serve.router.LocalHost`) or
  subprocess (:class:`~fastapriori_tpu.serve.router.ProcHost`) hosts,
  mesh-ordered hot-swap, PeerLost-driven rerouting, merged metrics.
"""

from fastapriori_tpu.serve.loadgen import (  # noqa: F401
    arrival_offsets,
    percentiles_ms,
    run_open_loop,
)
from fastapriori_tpu.serve.router import (  # noqa: F401
    LocalHost,
    MeshRouter,
    ProcHost,
)
from fastapriori_tpu.serve.server import (  # noqa: F401
    RecommendServer,
    ServeRequest,
)
from fastapriori_tpu.serve.state import (  # noqa: F401
    SERVING_NAME,
    ServingState,
    model_signature,
)
