"""User-facing error type.

The reference surfaces every user mistake as a raw JVM stack trace (missing
HDFS path, malformed resume file — nothing in Main.scala/Utils.scala guards
inputs).  Here user-correctable problems raise :class:`InputError`, which
the CLI renders as a one-line actionable message (exit code 2) instead of a
traceback; programmatic callers can still catch it like any exception.
"""

from __future__ import annotations


class InputError(Exception):
    """A problem the user can fix (missing file, malformed artifact,
    inconsistent input data) — message is the full, actionable text."""
