from fastapriori_tpu.models.apriori import FastApriori  # noqa: F401
from fastapriori_tpu.models.recommender import AssociationRules  # noqa: F401
