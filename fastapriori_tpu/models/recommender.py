"""Association-rule recommender (reference C10 + C12,
AssociationRules.scala:17-113).

API mirrors the reference class:
``AssociationRules(freqItemsets, freqItems, itemToRank).run(user_lines)``
returns ``[(original row index, recommended item string or "0"), ...]``.

Pipeline (run, :23-31): dedupe user baskets keeping original row indexes
(C10, preprocess.dedup_user_baskets); generate + prune rules (C11,
rules/gen.py); sort by (confidence desc, consequent-as-int asc) (:74);
first-match per distinct basket (C12) on device via the containment matmul
kernel (ops/contain.py) or a host loop for tiny inputs; fan results out to
all original rows (:104-105); empty baskets get "0" (:49).
"""

from __future__ import annotations

from typing import Dict, FrozenSet, List, Optional, Sequence, Tuple

import numpy as np

from fastapriori_tpu.config import MinerConfig
from fastapriori_tpu.ops.bitmap import (
    build_bitmap,
    next_pow2 as _next_pow2,
    pad_axis,
)
from fastapriori_tpu.parallel.mesh import DeviceContext
from fastapriori_tpu.preprocess import dedup_user_baskets
from fastapriori_tpu.rules.gen import (
    Rule,
    gen_rule_arrays_levels,
    gen_rules,
    rule_objects_from_arrays,
    sort_rule_arrays,
    sort_rules,
)
from fastapriori_tpu.utils.logging import MetricsLogger


def bucket_batch_rows(rows: int) -> int:
    """THE bucketing contract for scan micro-batch rows: pow2 bucket
    (G011 — the scan compiles per batch shape) with a floor of 32.
    Shared by :meth:`AssociationRules.rec_batch_rows`, the serving
    state's pinned override and the server's collection bound — the
    compiled scan shape and the micro-batcher's batch bound must be the
    SAME number, which only holds while they share this one function."""
    return max(_next_pow2(max(int(rows), 1)), 32)


class ServeScanHandle:
    """What the serving tier needs from the recommender's device scan
    (:meth:`AssociationRules.serve_scan`): the fixed-shape micro-batch
    ``scan`` callable over whichever table form is mounted, plus the
    layout facts the micro-batcher sizes its batches with.

    ``scan(bitmap, blen) -> (best_rank, consequent_or_None, chunks)``
    returns device arrays; the caller owns the (audited) fetch.  On the
    replicated form the kernel returns only the winning global rank —
    ``decode(best_np)`` maps fetched ranks to consequent indexes (-1 =
    no match) through the host consequent table; on the resident form
    the consequent array comes back from the device directly and
    ``decode`` is None.  ``row_multiple`` is the basket-row divisibility
    the scan's sharding needs (1 on the resident form, whose micro-batch
    is replicated)."""

    __slots__ = (
        "scan", "f", "f_pad", "resident", "shards", "table_bytes",
        "decode", "row_multiple", "pallas",
    )

    def __init__(self, scan, *, f, f_pad, resident, shards, table_bytes,
                 row_multiple, decode=None, pallas=False):
        self.scan = scan
        self.f = f
        self.f_pad = f_pad
        self.resident = resident
        self.shards = shards
        self.table_bytes = table_bytes
        self.decode = decode
        self.row_multiple = row_multiple
        # True when the mounted local scan body is the Pallas first-
        # match kernel (serve/state.py's serve_scan cascade attribution:
        # a transient-exhausted scan walks pallas→xla and re-warms
        # before abandoning the device table).
        self.pallas = pallas


class AssociationRules:
    def __init__(
        self,
        freq_itemsets: Sequence[Tuple[FrozenSet[int], int]],
        freq_items: Sequence[str],
        item_to_rank: Dict[str, int],
        config: Optional[MinerConfig] = None,
        context: Optional[DeviceContext] = None,
        levels=None,
        item_counts=None,
    ):
        """``levels``/``item_counts``: matrix-form mining result
        (FastApriori.run_file_raw) — rule generation then skips the
        frozenset round trip entirely (rules/gen.py gen_rules_levels);
        ``freq_itemsets`` may be empty in that case."""
        self.freq_itemsets = list(freq_itemsets)
        self.freq_items = list(freq_items)
        self.item_to_rank = dict(item_to_rank)
        self.config = config or MinerConfig()
        self._context = context
        self._levels = levels
        self._item_counts = item_counts
        self.metrics = MetricsLogger(enabled=self.config.log_metrics)
        # Rules depend only on the (immutable) mining result — built once
        # per instance, like the reference's single genRules pass
        # (AssociationRules.scala:72), not once per run() call.  The
        # matrix-form path (``levels`` given) keeps them as sorted
        # ARRAYS (ant [R, k_max] 0-padded, lens, cons, conf) — at
        # webdocs/minSupport=0.092 scale there are 16M rules and the
        # object form cost minutes of pure materialization; the object
        # list is built lazily only for the host-scan fallback and
        # API-parity callers.
        self._sorted_rules: Optional[List[Rule]] = None
        self._rule_arrays: Optional[tuple] = None
        # Device-resident compact rule table (the reference broadcasts
        # the sorted rules once, AssociationRules.scala:76-78): uploaded
        # on the first device run, reused by every later run() — repeat
        # scans pay only the basket upload + result fetch.
        self._rule_dev: Optional[tuple] = None
        self._rule_dev_key: Optional[tuple] = None
        # Sharded device rule engine residue (ISSUE 8): when phase 2 ran
        # on the mesh, its per-level device state stays resident and the
        # priority scan table is BUILT on device (ops/contain.py
        # rule_scan_build — conf-desc 49-bit key sort, rank-strided
        # shard layout); the 16M-rule table then never crosses the host
        # link at all.  None = host-built table path (host rule engine,
        # multi-process meshes).
        self._scan_state = None
        self._scan_table: Optional[tuple] = None

    @property
    def context(self) -> DeviceContext:
        if self._context is None:
            self._context = DeviceContext(
                num_devices=self.config.num_devices,
                cand_devices=self.config.cand_devices,
            )
        return self._context

    @property
    def n_rules(self) -> Optional[int]:
        """Sorted-rule count, whichever form holds them (None before the
        first run() generates them)."""
        if self._rule_arrays is not None:
            return len(self._rule_arrays[1])
        if self._sorted_rules is not None:
            return len(self._sorted_rules)
        return None

    # ------------------------------------------------------------------
    def run(
        self,
        user_lines: Sequence[Sequence[str]],
        use_device: Optional[bool] = None,
    ) -> List[Tuple[int, str]]:
        """``use_device=None`` auto-selects: the containment-matmul path
        for real workloads, the host first-match scan when the problem is
        small (distinct-baskets × rules below 3·10^7 — the host scan
        early-exits per user so its true cost is far below that product,
        while the device path carries ~seconds of fixed dispatch and
        transfer costs, especially on tunneled chips).  Deterministic in
        the inputs, so every process of a multi-host run picks the same
        path."""
        with self.metrics.timed("user_dedup") as m:
            baskets, indexes, empty = dedup_user_baskets(
                user_lines, self.item_to_rank
            )
            m.update(
                users=len(user_lines), distinct=len(baskets), empty=len(empty)
            )
        n_rules = self._ensure_rules()

        out: List[Tuple[int, str]] = [(i, "0") for i in empty]
        if not baskets:
            return out
        if not n_rules:
            for rows in indexes:
                out.extend((i, "0") for i in rows)
            return out

        if use_device is None:
            # The host scan early-exits at each user's first match, so
            # its real cost is far below users × rules; the device path
            # carries ~seconds of fixed dispatch/transfer cost on
            # tunneled chips.  3e7 keeps small jobs on the host while
            # movielens-scale (16K users × 10^5 rules) goes on device.
            use_device = len(baskets) * n_rules >= 30_000_000
        if not use_device and self._scan_state is not None:
            # The host scan never consumes the sharded engine's resident
            # join state — free the per-level device tables instead of
            # pinning replicated HBM for the instance lifetime.  A later
            # device run takes the host-built-table upload path; the
            # compact scan table, if already built, stays resident.
            self._scan_state.release()
            self._scan_state = None
        with self.metrics.timed("first_match", device=use_device) as m:
            if use_device:
                recs, stats = self._device_first_match(baskets)
                m.update(**stats)
            else:
                recs = self._host_first_match(baskets)

        for rows, rec in zip(indexes, recs):
            item = self.freq_items[rec] if rec >= 0 else "0"
            out.extend((i, item) for i in rows)
        return out

    # ------------------------------------------------------------------
    def _ensure_rules(self) -> int:
        """Generate + priority-sort the rules once per instance; returns
        the rule count.  Matrix-form mining input stays in ARRAY form;
        the object-API input (freq_itemsets) keeps the object pipeline."""
        n = self.n_rules
        if n is not None:
            return n
        with self.metrics.timed("gen_rules") as m:
            if self._levels is not None:
                # Device-eligible path (rules/gen.py device engine): the
                # level-wise joins + dominance prune run on the SAME
                # context the first-match scan uses, so phase 2 shares
                # one mesh and the rule tables upload once per instance.
                # The sharded engine additionally leaves its per-level
                # device state resident (DeviceRuleState) so the scan
                # table below is built on device, never uploaded.
                from fastapriori_tpu.rules.gen import DeviceRuleState

                state = DeviceRuleState()
                surv = gen_rule_arrays_levels(
                    self._levels,
                    self._item_counts,
                    context=self.context,
                    config=self.config,
                    metrics=self.metrics,
                    scan_state=state,
                )
                self._scan_state = state if state.ready else None
                self._rule_arrays = sort_rule_arrays(surv, self.freq_items)
                n = len(self._rule_arrays[1])
            else:
                self._sorted_rules = sort_rules(
                    gen_rules(self.freq_itemsets), self.freq_items
                )
                n = len(self._sorted_rules)
            m.update(rules=n)
        return n

    def _rule_objects(self) -> List[Rule]:
        """Object form of the sorted rules (host scan / parity callers);
        materialized lazily from the arrays on the matrix path."""
        if self._sorted_rules is None:
            assert self._rule_arrays is not None
            self._sorted_rules = rule_objects_from_arrays(*self._rule_arrays)
        return self._sorted_rules

    def _host_rule_table(self) -> tuple:
        """Padded priority-ordered rule arrays for the host scan —
        straight from the matrix pipeline when present, else built once
        from the object list.  Antecedent padding points at the always-
        present sentinel column F (see `_host_first_match`)."""
        f = len(self.freq_items)
        if self._rule_arrays is not None:
            ant0, lens, cons, _ = self._rule_arrays
            r, k_max = ant0.shape if ant0.size else (len(cons), 1)
            ant = np.full((len(cons), max(k_max, 1)), f, dtype=np.int64)
            if len(cons):
                mask = np.arange(ant.shape[1])[None, :] < lens[:, None]
                ant[mask] = ant0[mask]
            return ant, lens.astype(np.int64), np.asarray(cons), f
        rules = self._sorted_rules or []
        lens = np.fromiter(
            (len(a) for a, _, _ in rules), np.int64, count=len(rules)
        )
        k_max = int(lens.max()) if len(rules) else 1
        ant = np.full((len(rules), k_max), f, dtype=np.int64)
        for i, (a, _, _) in enumerate(rules):
            ant[i, : len(a)] = sorted(a)
        cons = np.fromiter(
            (c for _, c, _ in rules), np.int64, count=len(rules)
        )
        return ant, lens, cons, f

    def _host_first_match(self, baskets: List[np.ndarray]) -> List[int]:
        """Reference-semantics scan (AssociationRules.scala:88-102)
        vectorized with numpy — the same priority-ordered chunked
        early-exit structure as the device kernel, run per basket block:
        containment is a boolean gather+all over the padded antecedent
        table, first match the argmax over the chunk's eligibility.
        Exactness: chunks are priority-ordered and argmax-of-bool returns
        the FIRST eligible index, so the result equals the per-rule
        scalar scan rule for rule.  Fast enough that the bench's
        recommend baseline runs the FULL user population (real, non-
        estimated ``vs_baseline`` — VERDICT r5 weak #5) where the old
        per-rule Python loop had to subsample."""
        ant, lens, cons, f = self._host_rule_table()
        r = len(cons)
        recs = np.full(len(baskets), -1, dtype=np.int64)
        if r == 0:
            return recs.tolist()
        blen = np.fromiter((len(b) for b in baskets), np.int64, len(baskets))
        rule_chunk = 8192
        for b0 in range(0, len(baskets), 2048):
            rows = range(b0, min(b0 + 2048, len(baskets)))
            member = np.zeros((len(rows), f + 1), dtype=bool)
            member[:, f] = True  # antecedent-padding sentinel column
            for i, bi in enumerate(rows):
                member[i, np.asarray(baskets[bi], dtype=np.int64)] = True
            best = np.full(len(rows), -1, dtype=np.int64)
            unmatched = np.arange(len(rows))
            bl = blen[b0 : b0 + len(rows)]
            for base in range(0, r, rule_chunk):
                a = ant[base : base + rule_chunk]
                sub = member[unmatched]
                contained = sub[
                    np.arange(len(unmatched))[:, None, None], a[None, :, :]
                ].all(axis=2)
                eligible = (
                    contained
                    & (lens[None, base : base + rule_chunk] <= bl[unmatched][:, None])
                    & ~sub[:, cons[base : base + rule_chunk]]
                )
                hit = eligible.any(axis=1)
                first = np.argmax(eligible, axis=1)
                best[unmatched[hit]] = base + first[hit]
                unmatched = unmatched[~hit]
                if unmatched.size == 0:
                    break
            matched = best >= 0
            recs[b0 : b0 + len(rows)][matched] = cons[best[matched]]
        return recs.tolist()

    def _rule_table_device(self, f_pad: int) -> tuple:
        """Compact device-resident rule table — built and uploaded ONCE
        per instance (the sorted table is immutable; the reference
        broadcasts it once, AssociationRules.scala:76-78).  Antecedents
        travel as [R_pad, k_max] column indexes (padding positions point
        at the guaranteed all-zero bitmap column) and scatter to one-hot
        on device; the dense [R, F] form was ~30x the bytes at movielens
        scale.  Built straight from the sorted rule ARRAYS on the
        matrix path (a per-rule Python loop cost minutes at 10^7-rule
        scale); the object path keeps the list form."""
        n_rules = self.n_rules or 0
        if self._rule_dev is not None:
            # The cache is keyed on nothing because both inputs are
            # instance-invariant today (rules built once per instance,
            # f_pad from the fixed item count) — assert that rather
            # than silently serving a stale table if run() ever starts
            # filtering rules per call (ADVICE r3).
            assert self._rule_dev_key == (n_rules, f_pad), (
                self._rule_dev_key, n_rules, f_pad
            )
            return self._rule_dev
        self._rule_dev_key = (n_rules, f_pad)
        ctx = self.context
        cfg = self.config
        f = len(self.freq_items)
        r = n_rules
        # Lane-aligned chunk, scaled so the on-device scan targets ~256
        # while-loop iterations: each iteration carries fixed overhead,
        # and a no-match basket walks the WHOLE table — at 16M rules the
        # default chunk meant 2000 iterations (~35 s) where 256 bigger
        # ones do the same MACs.  Early-exit resolution only coarsens
        # for matched users, whose wasted partial chunk is device noise.
        # The absolute cap bounds the per-step [Nb, chunk] overlap
        # buffer: without it the chunk grows linearly with the rule
        # count ON TOP of the basket count.  Chunk AND chunk count round
        # to powers of two: the scan compiles per (r_pad, chunk) and a
        # data-exact rule count compiled a fresh program per dataset —
        # part of r5's primed-cache misses (VERDICT r5 next #5).
        chunk = min(
            _next_pow2(max(1, cfg.rule_chunk, -(-r // 256))), 1 << 16
        )
        chunk = pad_axis(chunk, 128)
        r_pad = chunk * _next_pow2(max(-(-r // chunk), 1))
        zcol = f_pad - 1  # guaranteed all-zero column (ops/bitmap.py)
        if self._rule_arrays is not None:
            ant0, lens, cons_vals, _conf = self._rule_arrays
            k_max = ant0.shape[1] if r else 1
            ant = np.full((r_pad, k_max), zcol, dtype=np.int32)
            if r > 0:
                mask = np.arange(k_max)[None, :] < lens[:, None]
                ant[:r][mask] = ant0[mask]
        else:
            rules = self._sorted_rules or []
            ant_rows = [
                np.asarray(sorted(a), dtype=np.int32) for a, _, _ in rules
            ]
            lens = np.fromiter((len(a) for a in ant_rows), np.int64, count=r)
            cons_vals = [c for _, c, _ in rules]
            k_max = int(lens.max()) if r else 1
            ant = np.full((r_pad, k_max), zcol, dtype=np.int32)
            if r > 0:
                rows = np.repeat(np.arange(r, dtype=np.int64), lens)
                cols = np.concatenate(
                    [np.arange(n, dtype=np.int64) for n in lens]
                )
                ant[rows, cols] = np.concatenate(ant_rows)
        size = np.full(r_pad, f + 1, dtype=np.int32)  # pad rows never hit
        size[:r] = lens
        consequent = np.zeros(r_pad, dtype=np.int32)
        consequent[:r] = cons_vals
        self._rule_dev = (
            ctx.replicate(ant),
            ctx.replicate(size),
            ctx.replicate(consequent),
            chunk,
            r_pad,
            consequent,
            ant.nbytes + size.nbytes + consequent.nbytes,
        )
        return self._rule_dev

    def rec_batch_rows(self) -> int:
        """Scan micro-batch rows: ``config.rec_batch_rows`` overridden by
        strictly-parsed ``FA_REC_BATCH``, pow2-bucketed with a floor of
        32 (the scan compiles per batch shape — G011).  ONE knob shared
        by the batch path's resident scan below and the serving tier's
        request micro-batcher (serve/server.py), replacing the static 4K
        constant (PR 8 residue / ISSUE 10)."""
        from fastapriori_tpu.utils.env import env_int

        rows = env_int("FA_REC_BATCH", 0, minimum=0)
        if rows == 0:
            rows = self.config.rec_batch_rows
        return bucket_batch_rows(rows)

    def _ensure_scan_table(self) -> tuple:
        """Build the priority-sorted compact scan table ON DEVICE from
        the sharded rule engine's resident state (once per instance; one
        dispatch): conf-desc 49-bit key sort + rank-strided shard layout
        (ops/contain.py rule_scan_build).  The table never exists on the
        host — the host's sorted arrays remain the differential oracle.
        Returns ``(ant, size, cons, chunk, r_pad, shards, build_ms)``."""
        if self._scan_table is not None:
            return self._scan_table
        import time

        import jax.numpy as jnp

        from fastapriori_tpu.rules.gen import _consequent_priority

        t0 = time.perf_counter()
        state = self._scan_state
        ctx = self.context
        cfg = self.config
        f = len(self.freq_items)
        f_pad = pad_axis(f + 1, cfg.item_tile)
        zcol = f_pad - 1  # guaranteed all-zero basket column
        s = state.shards
        r = state.total
        # Same chunk policy as the replicated table (scaled to the
        # PER-SHARD slice): ~256 while-loop iterations for a no-match
        # walk of the whole table, chunk and chunk count pow2-bucketed —
        # and capped at the per-shard SLICE size, so a small table on a
        # big mesh pads to ~R/S rows per shard (each shard's one chunk
        # shrinks with S) instead of a full rule_chunk of padding per
        # shard (which made total scan work GROW with the mesh).
        per_shard = _next_pow2(max(-(-r // s), 1))
        chunk = min(
            _next_pow2(max(1, cfg.rule_chunk, -(-r // (256 * s)))),
            max(per_shard, 128),
            1 << 16,
        )
        chunk = pad_axis(chunk, 128)
        r_loc = chunk * _next_pow2(max(-(-r // (chunk * s)), 1))
        r_pad = r_loc * s
        k_max = max(max(state.ks) - 1, 1)
        pr = ctx.replicate_rule_table(
            _consequent_priority(self.freq_items).astype(np.int32)
        )
        build = ctx.rule_scan_build(
            state.ks, state.n_pads, r_pad, k_max, zcol
        )
        ant_s, size_s, cons_s = build(
            tuple(state.arrays),
            jnp.asarray(state.offsets, dtype=jnp.int32),
            pr,
        )
        # The join state's only remaining consumer is this build — free
        # the per-level tables, keep the (sharded) scan table resident.
        state.release()
        build_ms = (time.perf_counter() - t0) * 1e3
        self._scan_table = (
            ant_s, size_s, cons_s, chunk, r_pad, s, round(build_ms, 1),
        )
        return self._scan_table

    def _device_first_match_resident(
        self, baskets: List[np.ndarray]
    ) -> Tuple[List[int], dict]:
        """Sharded resident-table scan (ISSUE 8 part b): rules
        rank-strided across the mesh (R/S rows of HBM per shard instead
        of a full replica), baskets streamed as replicated micro-batches,
        per-shard argmin-over-rank merged by one pmin/pmax exchange per
        batch (ops/contain.py local_strided_match_scan).  The rule table
        was BUILT on device (:meth:`_ensure_scan_table`) and its bytes
        never cross the host link — each batch costs one basket upload
        and one [2, mb] result fetch, which overlaps the next batch's
        dispatch."""
        import time

        import jax.numpy as jnp

        from fastapriori_tpu.reliability import retry

        ctx = self.context
        cfg = self.config
        f = len(self.freq_items)
        f_pad = pad_axis(f + 1, cfg.item_tile)
        nb = len(baskets)
        first_build = self._scan_table is None
        ant_s, size_s, cons_s, chunk, r_pad, shards, build_ms = (
            self._ensure_scan_table()
        )
        scan_fn = ctx.strided_first_match_scan(chunk)
        mb = max(min(_next_pow2(max(nb, 1)), self.rec_batch_rows()), 32)
        t_s0 = time.perf_counter()
        fetches = []
        upload_bytes = 0
        chunk_refs = []
        for b0 in range(0, nb, mb):
            block = baskets[b0 : b0 + mb]
            bm = build_bitmap(block, f, mb, cfg.item_tile)
            blen = np.zeros(mb, dtype=np.int32)
            blen[: len(block)] = [len(b) for b in block]
            bm_dev = ctx.replicate(bm)
            blen_dev = ctx.replicate(blen)
            # Replicated micro-batch: the host link pushes one copy per
            # device (the heavy-row accounting convention).
            upload_bytes += (bm.nbytes + blen.nbytes) * ctx.n_devices
            best, cons, chunks = scan_fn(
                bm_dev, blen_dev, ant_s, size_s, cons_s
            )
            # Non-blocking audited fetch; consumed after the last batch
            # dispatches, so transfers ride under later scan work.
            fetches.append(
                (b0, len(block),
                 retry.fetch_async(jnp.stack([best, cons]), "rec_match"))
            )
            chunk_refs.append(chunks)
        # Attribution barrier (the replicated path's convention, VERDICT
        # r5 weak #5): batches dispatch in submission order on the same
        # devices, so blocking on the LAST batch's tiny chunk counter
        # puts all device scan work in scan_ms — a scan-bound run must
        # not read as link-bound (fetch_ms is then the real link term).
        if chunk_refs:
            chunk_refs[-1].block_until_ready()
        recs = np.full(nb, -1, dtype=np.int64)
        t_f0 = time.perf_counter()
        for b0, nrows, fetch in fetches:
            arr = fetch.result()  # [2, mb] int32: global rank, consequent
            recs[b0 : b0 + nrows] = arr[1][:nrows]
        fetch_ms = (time.perf_counter() - t_f0) * 1e3
        chunks_run = max((int(c) for c in chunk_refs), default=0)
        n_rules = self.n_rules or 0
        stats = {
            "rules": n_rules,
            "resident_table": True,
            # The acceptance contract: the rule table's bytes crossing
            # the host link after upload — identically zero here (it was
            # built on device and is consumed on device).
            "rule_table_host_bytes": 0,
            "dispatches": len(fetches) + (1 if first_build else 0),
            "scan_dispatches": len(fetches),
            "rule_upload_ms": build_ms if first_build else 0.0,
            "scan_ms": round((t_f0 - t_s0) * 1e3, 1),
            "fetch_ms": round(fetch_ms, 1),
            "chunks_run": chunks_run,
            "chunks_total": r_pad // (chunk * shards),
            "shards": shards,
            "macs": chunks_run * mb * chunk * f_pad * shards
            * len(fetches),
            # Two [mb]-int32 collectives (pmin + consequent pmax) per
            # micro-batch, received by every shard.
            "psum_bytes": 2 * 4 * mb * shards * len(fetches),
            "upload_bytes": upload_bytes,
        }
        if first_build:
            stats["table_build_ms"] = build_ms
        return [int(x) for x in recs], stats

    def serve_scan(self):
        """Serving-tier device-scan entry (ISSUE 10): the serving
        subsystem mounts the SAME device rule table the batch path owns
        — the resident sharded table when phase 2 left one (scanned
        rank-strided, consequent selected on device), else the
        replicated compact table (scanned row-sharded, the winning rank
        decoded through the host consequent map).  Returns a
        :class:`ServeScanHandle` whose ``scan(bitmap, blen)`` runs ONE
        fixed-shape micro-batch ([mb, F_pad] int8 basket bitmap + [mb]
        int32 lengths; 0-length rows are padding, excluded from the
        kernel's early exit) and returns DEVICE arrays — the CALLER owns
        the audited fetch, so serving transfers land on the serving
        tier's own ``fetch.serve_match`` site instead of the batch
        path's ``fetch.rec_match``."""
        from fastapriori_tpu.ops.contain import NO_MATCH

        self._ensure_rules()
        ctx = self.context
        cfg = self.config
        f = len(self.freq_items)
        f_pad = pad_axis(f + 1, cfg.item_tile)
        if self._scan_state is not None or self._scan_table is not None:
            ant_s, size_s, cons_s, chunk, r_pad, shards, _ = (
                self._ensure_scan_table()
            )
            scan_fn = ctx.strided_first_match_scan(chunk)

            def scan(bm, blen):
                return scan_fn(
                    ctx.replicate(bm), ctx.replicate(blen),
                    ant_s, size_s, cons_s,
                )

            tbytes = int(
                ant_s.nbytes + size_s.nbytes + cons_s.nbytes
            )
            return ServeScanHandle(
                scan, f=f, f_pad=f_pad, resident=True, shards=shards,
                table_bytes=tbytes, row_multiple=1,
                pallas=ctx.serve_pallas_active(),
            )

        ant_dev, size_dev, cons_dev, chunk, r_pad, consequent, rbytes = (
            self._rule_table_device(f_pad)
        )

        def scan(bm, blen):
            best, chunks = ctx.first_match_scan(
                ctx.shard_rows_local(bm), ctx.shard_rows_local(blen),
                ant_dev, size_dev, cons_dev, chunk,
            )
            return best, None, chunks

        def decode(best_np):
            found = best_np < int(NO_MATCH)
            return np.where(
                found, consequent[np.minimum(best_np, r_pad - 1)], -1
            )

        return ServeScanHandle(
            scan, f=f, f_pad=f_pad, resident=False, shards=1,
            table_bytes=int(rbytes), decode=decode,
            row_multiple=max(cfg.txn_tile, 32) * ctx.txn_shards,
        )

    def _device_first_match(
        self, baskets: List[np.ndarray]
    ) -> Tuple[List[int], dict]:
        """Containment-matmul path (ops/contain.py), baskets sharded over
        the mesh, the rule table resident and replicated.

        The whole priority scan runs as ONE dispatch — an on-device
        ``lax.while_loop`` over rule chunks with the early exit on device
        (local_first_match_scan), the batch analog of the reference's
        scan stopping at the first hit (AssociationRules.scala:95-102).
        Most users match within the highest-confidence chunks, so
        usually only a fraction of the table is ever counted, and the
        [Nb, R] eligibility matrix never materializes at full R.
        Returns ``(recommended consequents, stats for the metrics
        stream)``.

        When the sharded rule engine left its device state resident
        (``self._scan_state``), the scan instead runs the
        resident-table strided path — the rule table was built on
        device and is sharded, not replicated
        (:meth:`_device_first_match_resident`)."""
        from fastapriori_tpu.ops.contain import NO_MATCH

        if self._scan_state is not None or self._scan_table is not None:
            return self._device_first_match_resident(baskets)

        ctx = self.context
        f = len(self.freq_items)
        nb = len(baskets)
        cfg = self.config

        basket_mat = build_bitmap(
            baskets, f, max(cfg.txn_tile, 32) * ctx.txn_shards, cfg.item_tile
        )
        nb_pad, f_pad = basket_mat.shape
        # Pow2 row bucket (when it stays shard-divisible): a data-exact
        # basket count compiled a fresh scan per user population — the
        # same primed-cache-miss class the mining shapes already bucket
        # (VERDICT r5 next #5).  Padding rows have basket_len 0 and are
        # excluded from the on-device early exit.
        nb_pow2 = _next_pow2(nb_pad)
        if nb_pow2 > nb_pad and nb_pow2 % ctx.txn_shards == 0:
            basket_mat = np.concatenate(
                [
                    basket_mat,
                    np.zeros((nb_pow2 - nb_pad, f_pad), basket_mat.dtype),
                ]
            )
            nb_pad = nb_pow2
        basket_len = np.zeros(nb_pad, dtype=np.int32)
        basket_len[:nb] = [len(b) for b in baskets]

        # Multi-process: every process has the full (replicated) user
        # table but places only ITS row slice of the sharded arrays; the
        # scan kernel has no collectives inside the loop, so processes
        # may stop at different chunks — one process_allgather at the
        # end reassembles the global best vector.
        import jax

        n_proc = jax.process_count()
        # local_row_slice guards the sharding invariants itself
        # (InputError on a non-divisible or 2-D-across-processes mesh).
        row = ctx.local_row_slice(nb_pad) if n_proc > 1 else slice(None)

        import time

        first_upload = self._rule_dev is None
        t_up0 = time.perf_counter()
        ant_dev, size_dev, cons_dev, chunk, r_pad, consequent, rule_bytes = (
            self._rule_table_device(f_pad)
        )
        # Rule-table build + upload-SUBMISSION wall (device_put is async
        # on some backends; the host-side table build dominates — ≈0
        # after the first run, the table is instance-cached).  The
        # recommend path's analog of bitmap_build: with scan_ms and
        # fetch_ms below, a regression attributes to upload vs scan vs
        # fetch (VERDICT r5 weak #5).
        upload_ms = (time.perf_counter() - t_up0) * 1e3

        baskets_dev = ctx.shard_rows_local(basket_mat[row])
        basket_len_dev = ctx.shard_rows_local(basket_len[row])
        t_s0 = time.perf_counter()
        best, chunks_run = ctx.first_match_scan(
            baskets_dev, basket_len_dev, ant_dev, size_dev, cons_dev, chunk
        )
        # The dispatch is async: block on DEVICE completion first so the
        # scan wall and the transfer wall attribute separately (a
        # scan-bound run must not read as link-bound — VERDICT r5
        # weak #5 is exactly about distinguishing the two).
        # lint: fetch-site -- device-completion barrier for scan-vs-fetch attribution
        best.block_until_ready()
        scan_ms = (time.perf_counter() - t_s0) * 1e3
        t_f0 = time.perf_counter()
        best_np = ctx.local_rows(best)
        fetch_ms = (time.perf_counter() - t_f0) * 1e3
        chunks_run = int(chunks_run)
        stats = {
            "rules": self._rule_dev_key[0],
            "dispatches": 1,  # the whole priority scan is one dispatch
            "rule_upload_ms": round(upload_ms, 1),
            "scan_ms": round(scan_ms, 1),
            "fetch_ms": round(fetch_ms, 1),
            "chunks_run": chunks_run,
            "chunks_total": r_pad // chunk,
            # Containment matmul per chunk over the padded global shapes
            # (deepest shard; shards that exited earlier did less).
            "macs": chunks_run * nb_pad * chunk * f_pad,
            "psum_bytes": 4 * nb_pad if n_proc > 1 else 0,
            # Per-process bytes actually pushed over the link (the
            # mining phases' convention): this process's basket rows
            # plus the one-time replicated rule table.
            "upload_bytes": basket_mat[row].nbytes
            + basket_len[row].nbytes
            + (rule_bytes if first_upload else 0),
        }
        if n_proc > 1:
            # Reassemble the global vector (one collective; every
            # process reaches here exactly once).
            from jax.experimental import multihost_utils

            best_np = multihost_utils.process_allgather(
                best_np
            ).reshape(-1)
        best_np = best_np[:nb]
        found = best_np < int(NO_MATCH)
        rec = np.where(found, consequent[np.minimum(best_np, r_pad - 1)], -1)
        return [int(x) for x in rec], stats
