"""Association-rule recommender (reference C10 + C12,
AssociationRules.scala:17-113).

API mirrors the reference class:
``AssociationRules(freqItemsets, freqItems, itemToRank).run(user_lines)``
returns ``[(original row index, recommended item string or "0"), ...]``.

Pipeline (run, :23-31): dedupe user baskets keeping original row indexes
(C10, preprocess.dedup_user_baskets); generate + prune rules (C11,
rules/gen.py); sort by (confidence desc, consequent-as-int asc) (:74);
first-match per distinct basket (C12) on device via the containment matmul
kernel (ops/contain.py) or a host loop for tiny inputs; fan results out to
all original rows (:104-105); empty baskets get "0" (:49).
"""

from __future__ import annotations

from typing import Dict, FrozenSet, List, Optional, Sequence, Tuple

import numpy as np

from fastapriori_tpu.config import MinerConfig
from fastapriori_tpu.ops.bitmap import build_bitmap, pad_axis
from fastapriori_tpu.parallel.mesh import DeviceContext
from fastapriori_tpu.preprocess import dedup_user_baskets
from fastapriori_tpu.rules.gen import (
    Rule,
    gen_rules,
    gen_rules_levels,
    sort_rules,
)
from fastapriori_tpu.utils.logging import MetricsLogger


class AssociationRules:
    def __init__(
        self,
        freq_itemsets: Sequence[Tuple[FrozenSet[int], int]],
        freq_items: Sequence[str],
        item_to_rank: Dict[str, int],
        config: Optional[MinerConfig] = None,
        context: Optional[DeviceContext] = None,
        levels=None,
        item_counts=None,
    ):
        """``levels``/``item_counts``: matrix-form mining result
        (FastApriori.run_file_raw) — rule generation then skips the
        frozenset round trip entirely (rules/gen.py gen_rules_levels);
        ``freq_itemsets`` may be empty in that case."""
        self.freq_itemsets = list(freq_itemsets)
        self.freq_items = list(freq_items)
        self.item_to_rank = dict(item_to_rank)
        self.config = config or MinerConfig()
        self._context = context
        self._levels = levels
        self._item_counts = item_counts
        self.metrics = MetricsLogger(enabled=self.config.log_metrics)
        # Rules depend only on the (immutable) mining result — built once
        # per instance, like the reference's single genRules pass
        # (AssociationRules.scala:72), not once per run() call.
        self._sorted_rules: Optional[List[Rule]] = None

    @property
    def context(self) -> DeviceContext:
        if self._context is None:
            self._context = DeviceContext(
                num_devices=self.config.num_devices,
                cand_devices=self.config.cand_devices,
            )
        return self._context

    # ------------------------------------------------------------------
    def run(
        self,
        user_lines: Sequence[Sequence[str]],
        use_device: Optional[bool] = None,
    ) -> List[Tuple[int, str]]:
        """``use_device=None`` auto-selects: the containment-matmul path
        for real workloads, the host first-match scan when the problem is
        small (distinct-baskets × rules below 3·10^7 — the host scan
        early-exits per user so its true cost is far below that product,
        while the device path carries ~seconds of fixed dispatch and
        transfer costs, especially on tunneled chips).  Deterministic in
        the inputs, so every process of a multi-host run picks the same
        path."""
        with self.metrics.timed("user_dedup") as m:
            baskets, indexes, empty = dedup_user_baskets(
                user_lines, self.item_to_rank
            )
            m.update(
                users=len(user_lines), distinct=len(baskets), empty=len(empty)
            )
        if self._sorted_rules is None:
            with self.metrics.timed("gen_rules") as m:
                if self._levels is not None:
                    raw_rules = gen_rules_levels(
                        self._levels, self._item_counts
                    )
                else:
                    raw_rules = gen_rules(self.freq_itemsets)
                self._sorted_rules = sort_rules(raw_rules, self.freq_items)
                m.update(rules=len(self._sorted_rules))
        rules = self._sorted_rules

        out: List[Tuple[int, str]] = [(i, "0") for i in empty]
        if not baskets:
            return out
        if not rules:
            for rows in indexes:
                out.extend((i, "0") for i in rows)
            return out

        if use_device is None:
            # The host scan early-exits at each user's first match, so
            # its real cost is far below users × rules; the device path
            # carries ~seconds of fixed dispatch/transfer cost on
            # tunneled chips.  3e7 keeps small jobs on the host while
            # movielens-scale (16K users × 10^5 rules) goes on device.
            use_device = len(baskets) * len(rules) >= 30_000_000
        with self.metrics.timed("first_match", device=use_device):
            if use_device:
                recs = self._device_first_match(baskets, rules)
            else:
                recs = self._host_first_match(baskets, rules)

        for rows, rec in zip(indexes, recs):
            item = self.freq_items[rec] if rec >= 0 else "0"
            out.extend((i, item) for i in rows)
        return out

    # ------------------------------------------------------------------
    def _host_first_match(
        self, baskets: List[np.ndarray], rules: List[Rule]
    ) -> List[int]:
        """Reference-shaped scan (AssociationRules.scala:88-102); used for
        tiny inputs and as the device kernel's cross-check in tests."""
        prepared = [(frozenset(a), c, len(a)) for a, c, _ in rules]
        recs = []
        for b in baskets:
            basket = frozenset(int(x) for x in b)
            n = len(basket)
            rec = -1
            for ant, cons, size in prepared:
                if size <= n and cons not in basket and ant <= basket:
                    rec = cons
                    break
            recs.append(rec)
        return recs

    def _device_first_match(
        self, baskets: List[np.ndarray], rules: List[Rule]
    ) -> List[int]:
        """Containment-matmul path (ops/contain.py), baskets sharded over
        the mesh, rule tables replicated.

        Rules are processed in priority-ordered chunks with a running
        per-basket best index and an early exit once every basket has
        matched — the batch analog of the reference's scan stopping at
        the first hit (AssociationRules.scala:95-102).  Most users match
        within the highest-confidence chunk, so usually only a fraction
        of the rule table is ever uploaded or counted, and the [Nb, R]
        eligibility matrix never materializes at full R."""
        from fastapriori_tpu.ops.contain import NO_MATCH

        ctx = self.context
        f = len(self.freq_items)
        nb = len(baskets)
        cfg = self.config

        basket_mat = build_bitmap(
            baskets, f, max(cfg.txn_tile, 32) * ctx.txn_shards, cfg.item_tile
        )
        nb_pad, f_pad = basket_mat.shape
        basket_len = np.zeros(nb_pad, dtype=np.int32)
        basket_len[:nb] = [len(b) for b in baskets]

        # Multi-process: every process has the full (replicated) user
        # table but places only ITS row slice of the sharded arrays; the
        # chunk kernel has no collectives, so processes may even stop at
        # different chunks — one process_allgather at the end reassembles
        # the global best vector.
        import jax

        n_proc = jax.process_count()
        # local_row_slice guards the sharding invariants itself
        # (InputError on a non-divisible or 2-D-across-processes mesh).
        row = ctx.local_row_slice(nb_pad) if n_proc > 1 else slice(None)

        r = len(rules)
        chunk = pad_axis(max(1, cfg.rule_chunk), 128)  # lane-aligned
        r_pad = pad_axis(r, chunk)
        ant_rows = [np.asarray(sorted(a), dtype=np.int32) for a, _, _ in rules]
        lens = np.fromiter((len(a) for a in ant_rows), np.int64, count=r)
        k_max = int(lens.max()) if r else 1
        consequent = np.zeros(r_pad, dtype=np.int32)
        consequent[:r] = [c for _, c, _ in rules]

        baskets_dev = ctx.shard_rows_local(basket_mat[row])
        basket_len_dev = ctx.shard_rows_local(basket_len[row])
        best = ctx.shard_rows_local(
            np.full(nb_pad, int(NO_MATCH), dtype=np.int32)[row]
        )
        # The early exit (and its lagged fetch) watches only THIS
        # process's rows; rows this process can check are its local ones.
        local_hi = min(row.stop, nb) if n_proc > 1 else nb
        local_done = (
            slice(row.start, local_hi) if n_proc > 1 else slice(0, nb)
        )
        best_np = None
        prev = None  # previous chunk's best (async copy in flight)
        zcol = f_pad - 1  # guaranteed all-zero column (ops/bitmap.py)
        # The lagged early-exit fetch is a host<->device round trip
        # (~65 ms on tunneled chips); checking every chunk made a
        # 100-chunk scan round-trip-bound.  Check every CHECK_EVERY
        # chunks: at most that many extra chunks dispatch past the match
        # point, while fetch round trips drop by the same factor.
        CHECK_EVERY = 8
        for step, c0 in enumerate(range(0, r_pad, chunk)):
            hi = min(c0 + chunk, r)
            n_c = hi - c0  # real rules in this chunk (0 for pure padding)
            # Compact [chunk, k_max] column-index form (padding -> the
            # zero column); the kernel scatters to one-hot on device.
            ant_c = np.full((chunk, k_max), zcol, dtype=np.int32)
            if n_c > 0:
                rows = np.repeat(
                    np.arange(n_c, dtype=np.int64), lens[c0:hi]
                )
                cols = np.concatenate(
                    [np.arange(n, dtype=np.int64) for n in lens[c0:hi]]
                )
                ant_c[rows, cols] = np.concatenate(ant_rows[c0:hi])
            size_c = np.full(chunk, f + 1, dtype=np.int32)  # pad: never hits
            size_c[:n_c] = lens[c0:hi]
            cons_c = np.zeros(chunk, dtype=np.int32)
            cons_c[:n_c] = consequent[c0:hi]
            best = ctx.first_match_chunk(
                baskets_dev,
                basket_len_dev,
                ctx.replicate(ant_c),
                ctx.replicate(size_c),
                ctx.replicate(cons_c),
                c0,
                best,
            )
            if (step + 1) % CHECK_EVERY == 0:
                # Start the D2H copy only for the state the NEXT check
                # will actually read — copying every chunk wasted 7/8 of
                # the transfers on the same link the chunk uploads use.
                try:
                    best.copy_to_host_async()
                except (AttributeError, NotImplementedError):
                    pass
            # Early-exit on the PREVIOUS chunk's (already in-flight)
            # result: lagging the check by one chunk keeps consecutive
            # dispatches overlapped instead of paying a blocking
            # host<->device round trip per chunk.  Exiting on the lagged
            # state is exact — later chunks hold only larger rule
            # indices, so once every basket has matched the running min
            # cannot change.  Multi-process: each process watches only
            # its own rows (the chunk kernel has no collectives, so
            # processes may stop at different chunks safely).
            if prev is not None and step % CHECK_EVERY == 0:
                prev_np = ctx.local_rows(prev)
                # Clamped: a tail process whose entire slice is padding
                # has n_real == 0 and exits after its first chunk.
                n_real = max(0, local_done.stop - local_done.start)
                if (prev_np[:n_real] < int(NO_MATCH)).all():
                    best_np = prev_np
                    break
            prev = best
        if best_np is None:
            best_np = ctx.local_rows(best)
        if n_proc > 1:
            # Reassemble the global vector (one collective; every
            # process reaches here exactly once).
            from jax.experimental import multihost_utils

            best_np = multihost_utils.process_allgather(
                best_np
            ).reshape(-1)
        best_np = best_np[:nb]
        found = best_np < int(NO_MATCH)
        rec = np.where(found, consequent[np.minimum(best_np, r_pad - 1)], -1)
        return [int(x) for x in rec]
