"""The mining engine: level-synchronous Apriori with TPU counting kernels
(reference C9, FastApriori.scala:31-44, 88-130).

Control flow mirrors the reference exactly — a host-driven level loop with
the same termination rule (``while kItems.length >= k``,
FastApriori.scala:111) and the same minCount semantics
(``ceil(minSupport × rawCount)``, :38-39) — but each level's counting runs
as sharded MXU matmuls instead of Spark candidate-space tasks:

- level 2: one weighted Gram matmul over the whole bitmap (ops/count.py);
- level k>=3: candidate prefixes are padded into power-of-two buckets
  (static shapes for jit; SURVEY.md §7 "padding/bucketing discipline"),
  each bucket one prefix-product + matmul kernel launch, extension
  validity applied as a host-side mask on the returned counts.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Dict, FrozenSet, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from fastapriori_tpu.config import MinerConfig
from fastapriori_tpu.models.candidates import gen_candidates_stream
from fastapriori_tpu.ops.bitmap import (
    build_packed_bitmap_csr,
    next_pow2 as _next_pow2,
    weight_digits,
)
from fastapriori_tpu.parallel.mesh import DeviceContext
from fastapriori_tpu.preprocess import CompressedData, preprocess
from fastapriori_tpu.reliability import (
    failpoints,
    ledger,
    quorum,
    retry,
    watchdog,
)
from fastapriori_tpu.obs import trace
from fastapriori_tpu.utils.logging import MetricsLogger

ItemsetWithCount = Tuple[FrozenSet[int], int]

# Concrete types the device-probe calls below can raise: backends
# without the probe (AttributeError/NotImplementedError) and the XLA
# runtime's own error types (reliability/retry.py) — a bare Exception
# here once hid real engine bugs behind the 16 GB default (ADVICE r5).
_PROBE_ERRORS: Tuple[type, ...] = (
    AttributeError,
    NotImplementedError,
) + retry.xla_runtime_error_types()


class _MineEngineClamp(RuntimeError):
    """Control-flow signal for the mid-mine ``mine_engine`` consensus
    clamp (ISSUE 17 satellite): a level-boundary adoption walked
    vertical→bitmap while THIS rank was mid-lattice in the vertical
    loop.  Carries the completed levels so :meth:`FastApriori.
    _mine_vertical_safe` re-seeds the bitmap loop from the boundary
    instead of re-mining from scratch.  The leading ``ABORTED`` keeps
    it transient-classified — the safety arm's walk-the-chain contract
    (``watchdog.transient``) holds unchanged."""

    def __init__(self, levels: list, k: int):
        self.levels = levels
        self.k = k
        super().__init__(
            f"ABORTED: mine_engine clamped vertical->bitmap at level "
            f"{k} by quorum consensus"
        )


def _fused_m_cap_memory_limit(
    cfg: MinerConfig,
    ctx: DeviceContext,
    t_pad: int,
    f_pad: int,
    n_chunks: int,
    unpacked_resident: bool = False,
    cap: Optional[int] = None,
    tail_chunked: bool = False,
) -> int:
    """Largest power-of-two row budget whose fused program provably fits
    the per-device HBM budget — so an oversized m_cap is never compiled
    only to OOM (VERDICT weak #5: the [m_cap, m_cap] f32 candidate-gen
    intermediates alone are 8 GB at m_cap=32768).

    Per-device byte model of ops/fused.py at row budget m (conservative —
    assumes the big intermediates coexist rather than getting fused):
    candidate gen ``d_mat``+``e_mat`` 2·4·m², ``s_f``+``cand_cnt``+counts
    acc 3·4·m·f, S and S_next 2·m·f int8, per-chunk ``overlap``+``common``
    5·t_c·m, outputs (3·l_max+1)·m·4, plus the fixed packed bitmap +
    weights."""
    dev = ctx.mesh.devices.flat[0]
    budget = cfg.fused_hbm_budget_bytes
    if budget is None:
        try:
            stats = dev.memory_stats()
        except _PROBE_ERRORS:
            # Backends without memory_stats fall to the 16 GB default.
            stats = None
        hbm = (stats or {}).get("bytes_limit") or 16 * 2**30
        budget = int(cfg.fused_hbm_fraction * hbm)
    t_loc = t_pad // ctx.txn_shards
    t_c = t_loc // max(n_chunks, 1)
    if unpacked_resident:
        # Resident-bitmap variant (pipelined-ingest sharing): the full
        # unpacked int8 bitmap lives in HBM instead of the packed form +
        # transient per-chunk unpack.
        fixed = t_loc * f_pad + t_loc * 4
    else:
        fixed = t_loc * f_pad // 8 + t_loc * 4 + t_c * f_pad  # bitmap+w+unpack
    m = _next_pow2(cfg.fused_l_max + 2)

    def bytes_at(m: int) -> int:
        # Tail folds chunk the [m, m] candidate-gen intermediates
        # (ops/fused.py tail_cand_row_chunks caps each block at 512 MB),
        # so their peak is bounded; the fused engine runs unchunked.
        cand = 8 * m * m
        if tail_chunked:
            cand = min(cand, 2 * (512 << 20))
        return (
            cand
            + 14 * m * f_pad
            + 5 * t_c * m
            + (3 * cfg.fused_l_max + 1) * m * 4
        )

    # ``cap`` bounds the search (default: the fused engine's row cap);
    # the shallow-tail fold passes its own need — its budget is sized
    # from the seed level, not from fused_m_cap_max.
    if cap is None:
        cap = cfg.fused_m_cap_max
    if fixed + bytes_at(m) > budget:
        return 0  # even the floor budget cannot fit: fused is infeasible
    while 2 * m <= cap and fixed + bytes_at(2 * m) <= budget:
        m *= 2
    return m


class FastApriori:
    """Mining engine.  API mirrors the reference class
    (``FastApriori(minSupport, numPartitions).run(...)`` →
    ``FastApriori(min_support, num_devices).run(...)``), with fluent
    setters for parity with FastApriori.scala:21-29."""

    def __init__(
        self,
        min_support: Optional[float] = None,
        num_devices: Optional[int] = None,
        config: Optional[MinerConfig] = None,
        context: Optional[DeviceContext] = None,
    ):
        # Copy the config so explicit arguments never mutate the caller's
        # object; explicit arguments win over config fields.
        self.config = (
            dataclasses.replace(config) if config is not None else MinerConfig()
        )
        if min_support is not None:
            self.config.min_support = min_support
        if num_devices is not None:
            self.config.num_devices = num_devices
        self._context = context
        self.metrics = MetricsLogger(
            enabled=self.config.log_metrics
        ).bind_global_ledger()
        # One-shot W_s cross-check latch + exchanged-totals cache
        # (ISSUE 15): the mine.start weight-total rendezvous fires once
        # per miner / once per (t_pad, n_proc).
        self._wstotals_verified = False
        self._wstotals_cache: Dict[Tuple[int, int], np.ndarray] = {}
        # Mid-mine resume state (io/checkpoint.py): levels already
        # counted by an interrupted run, consumed by the first mine.
        self._resume_levels: Optional[list] = None
        self._resume_meta: Optional[Dict[str, int]] = None
        self._resume_label = "checkpoint"
        # Last-committed-levels stash (ISSUE 17): kept on EVERY rank so
        # whichever rank holds writership after an elastic rejoin can
        # re-commit the checkpoint under the re-derived fence.
        self._ckpt_stash: Optional[Tuple[list, Dict[str, int]]] = None

    # Fluent setters (FastApriori.scala:21-29).
    def set_min_support(self, min_support: float) -> "FastApriori":
        self.config.min_support = min_support
        return self

    def set_num_devices(self, num_devices: Optional[int]) -> "FastApriori":
        self.config.num_devices = num_devices
        self._context = None
        return self

    def set_resume_levels(
        self,
        levels: list,
        meta: Optional[Dict[str, int]] = None,
        label: str = "checkpoint",
    ) -> "FastApriori":
        """Seed the next mine with levels an interrupted run already
        completed (``--resume-from`` a ``--checkpoint-every-level``
        checkpoint, io/checkpoint.py): the level loop restarts from the
        deepest one instead of recounting.  ``meta`` (``n_raw`` /
        ``min_count`` / ``num_items``) pins the levels to their dataset;
        a mismatch with the freshly ingested data raises InputError
        rather than silently grafting one dataset's lattice onto
        another."""
        self._resume_levels = levels
        self._resume_meta = meta
        self._resume_label = label
        return self

    def _take_resume(self, data: CompressedData) -> Optional[list]:
        levels = self._resume_levels
        if not levels:
            return None
        # One-shot: a later mine() on this instance must never silently
        # re-graft the stale lattice (check_meta pins only three ints —
        # a different dataset could collide on all of them).
        meta, label = self._resume_meta, self._resume_label
        self._resume_levels = None
        self._resume_meta = None
        if meta is not None:
            from fastapriori_tpu.io.checkpoint import check_meta

            check_meta(
                meta,
                n_raw=data.n_raw,
                min_count=data.min_count,
                num_items=data.num_items,
                prefix=label,
            )
        return levels

    def _checkpoint_levels(self, levels: list, data: CompressedData) -> None:
        """Crash-safe per-level checkpoint (config.checkpoint_prefix):
        atomic rewrite of ``<prefix>checkpoint.npz`` + manifest after a
        completed level, then the ``level.<k>`` failpoint — so tests can
        kill the run at exactly the point where the checkpoint exists
        but nothing after it does."""
        if not levels:
            return
        prefix = self.config.checkpoint_prefix
        k = int(levels[-1][0].shape[1])
        if prefix:
            # Stash on every rank (not just the writer): writership can
            # move to THIS rank at an elastic rejoin, and the new writer
            # must be able to re-commit under the re-derived fence.
            self._ckpt_stash = (
                list(levels),
                {
                    "n_raw": data.n_raw,
                    "min_count": data.min_count,
                    "num_items": data.num_items,
                },
            )
            dom = quorum.active()
            if dom is not None:
                dom.add_rejoin_hook(self._recommit_checkpoint)
        if prefix and jax.process_index() == 0 and quorum.is_writer():
            from fastapriori_tpu.io.checkpoint import save_checkpoint

            with self.metrics.timed("checkpoint", levels=len(levels), k=k):
                # Fenced commit (ISSUE 12): on a multi-process domain
                # the writer stamps its monotonic fence epoch into the
                # checkpoint meta + MANIFEST.json; a superseded writer
                # (split-brain after a coordinator flap) is REJECTED
                # here (StaleFenceError, classified) instead of
                # publishing a mixed-epoch artifact.  0 without a
                # domain (single-process, unfenced — the default).
                save_checkpoint(
                    prefix,
                    levels,
                    {
                        "n_raw": data.n_raw,
                        "min_count": data.min_count,
                        "num_items": data.num_items,
                        "fence": quorum.checkpoint_fence(),
                    },
                )
        # lint: waive G013 -- level.<k> site family: depth-indexed (k is the mining level), bounded by the lattice depth and armed per-level by the chaos kill-mid-level schedules
        failpoints.fire(f"level.{k}")
        # Level-boundary consensus exchange (ISSUE 12): publish this
        # process's cascade positions, adopt any peer's more-degraded
        # ones BEFORE the next level's dispatch, and surface a dead
        # peer (stale heartbeat) as a classified PeerLost instead of a
        # collective hang.  Non-blocking; no-op without a domain.
        quorum.sync(f"level.{k}")

    def _recommit_checkpoint(self) -> None:
        """Elastic-rejoin hook (ISSUE 17): re-commit the last committed
        levels under the re-derived fence.  Runs after EVERY completed
        rejoin — including ones absorbed outside the level loop (the
        post-mine ``mine.end``/``run.end`` rendezvous) where no further
        per-level commit would otherwise refresh the npz, leaving it
        stranded at the pre-abort fence while the end-of-run manifest
        advances.  Pure local file I/O: no failpoint, no quorum sync."""
        stash = self._ckpt_stash
        prefix = self.config.checkpoint_prefix
        if stash is None or not prefix:
            return
        if jax.process_index() != 0 or not quorum.is_writer():
            return
        from fastapriori_tpu.io.checkpoint import save_checkpoint

        levels, meta = stash
        save_checkpoint(
            prefix, levels, dict(meta, fence=quorum.checkpoint_fence())
        )

    # -- count-reduction engine (ROADMAP item 2: sparse allreduce) -----
    _COUNT_REDUCE = ("auto", "dense", "sparse")

    def _count_reduce_engine(
        self, data: CompressedData
    ) -> Tuple[str, str]:
        """Resolve the count-reduction engine for this mine:
        ``FA_COUNT_REDUCE`` (strict) overrides
        ``config.count_reduce`` (validated just as strictly — a typo'd
        config silently running the dense path would be invisible in a
        record).  Returns ``(engine, requested)`` where engine is
        "dense" or "sparse": the sparse exchange is defined only on
        multi-device single-process 1-D txn meshes — elsewhere "auto"
        quietly stays dense and a forced "sparse" falls back WITH a
        ledger event (the engine-choice pattern of rules/gen.py
        ``_pick_rule_engine``)."""
        from fastapriori_tpu.utils.env import env_choice

        req = env_choice("FA_COUNT_REDUCE", self._COUNT_REDUCE)
        if req is None:
            req = self.config.count_reduce
            if req not in self._COUNT_REDUCE:
                from fastapriori_tpu.errors import InputError

                raise InputError(
                    f"unrecognized MinerConfig.count_reduce value "
                    f"{req!r}: use one of {'/'.join(self._COUNT_REDUCE)}"
                )
        if req == "dense":
            return "dense", req
        ctx = self.context
        reason = None
        if ctx.txn_shards < 2:
            reason = "one_txn_shard"
        elif ctx.cand_shards != 1:
            reason = "cand_mesh"
        elif not self._wstotals_available(data):
            # The blanket multi-process refusal is GONE (ISSUE 15):
            # the mine.start W_s exchange supplies the cross-host
            # shard weight totals, so only a sharded CompressedData
            # with no transport spanning its ingest world still
            # forces dense.
            reason = "no_wstotals_transport"
        elif not quorum.stage_allowed("count_reduce", "sparse"):
            # Cross-process consensus floor (ISSUE 12): a peer already
            # degraded this chain — start at the agreed position so
            # this process never issues the more-capable collective.
            reason = "quorum"
        if reason is not None:
            if req == "sparse":
                ledger.record(
                    "count_reduce_fallback", once_key=reason,
                    reason=reason,
                )
                watchdog.downgrade(
                    "count_reduce", "sparse", "dense", reason=reason
                )
            return "dense", req
        ledger.record(
            "count_reduce_engine", once_key="sparse", engine="sparse"
        )
        return "sparse", req

    def _sparse_cap(self, n_valid: int, hint_key=None) -> int:
        """Union-compaction slot budget for one sparse reduction
        (ops/count.py sparse_union_cap — pow2 buckets), with the
        config/env override and, when ``hint_key`` is given, the grown
        budget a previous overflow of this profile recorded (the
        pair-cap-hint pattern: repeat runs never re-pay the dense
        redo)."""
        from fastapriori_tpu.ops.count import sparse_union_cap
        from fastapriori_tpu.utils.env import env_int

        override = env_int(
            "FA_COUNT_SPARSE_CAP", 0, minimum=0
        ) or self.config.count_sparse_cap
        cap = sparse_union_cap(n_valid, override)
        if hint_key is not None:
            hint = self.context.pair_cap_hint(hint_key)
            if hint:
                cap = min(max(cap, hint), _next_pow2(max(n_valid, 8)))
        return cap

    def _sparse_thresholds(
        self, data: CompressedData, t_pad: int, heavy: bool
    ) -> np.ndarray:
        """Per-shard local-prune thresholds for the sparse exchange
        (int32[S], replicated into the kernels): the weighted pigeonhole
        over the STATIC shard weight totals — a candidate whose local
        count sits below ``max(1, ceil(min_count · W_s / W))`` on every
        shard provably sums below min_count, so per-shard pruning at
        these thresholds loses no frequent candidate.  ``heavy``: the
        single-low-digit weight split is active — the main kernels
        count with ``w % 128`` and shard 0 adds the exact heavy-row
        remainder (ops/count.py ``_heavy_gate``), so shard 0's budget
        carries the remainder total."""
        s = self.context.txn_shards
        shard = data.shard
        if shard is not None and shard.num_processes > 1:
            # (cached per (t_pad, n_proc) — see _shard_weight_totals)
            # Multi-process sharded ingest: this process knows only ITS
            # rows' weights — the per-shard totals cross hosts ONCE at
            # the mine.start rendezvous (ISSUE 15, the PR-6 "W_s never
            # crosses hosts" residue).  The multi-host path never uses
            # the heavy split (heavy is None there by construction).
            per = self._shard_weight_totals(data, t_pad)
        else:
            if heavy:
                w = np.zeros(t_pad, dtype=np.int64)
                w[: data.total_count] = data.weights
                low = w % 128
                per = low.reshape(s, -1).sum(axis=1)
                per[0] += int((w - low).sum())
            else:
                per = self._per_shard_row_totals(data, t_pad, s)
            # Full-replica fault domains (every rank mines the whole
            # corpus on its own mesh — the chaos --procs shape): the
            # W_s vector SHAPES the sparse collectives via the prune
            # thresholds, so divergent ingests must surface at the
            # rendezvous, not as silently divergent counts.  The
            # exchange carries the CANONICAL raw totals (no heavy
            # split) so every rank posts the same payload regardless
            # of which engine path reached here first.
            self._verify_wstotals(data, t_pad)
        total = int(per.sum())
        if total <= 0:
            return np.ones(s, dtype=np.int32)
        thr = -(-(int(data.min_count) * per) // total)  # exact ceil
        return np.maximum(1, thr).astype(np.int32)

    @staticmethod
    def _per_shard_row_totals(
        data: CompressedData, pad: int, n_slices: int
    ) -> np.ndarray:
        """The ONE canonical per-slice weight-total computation (pad
        with zero rows, reshape into ``n_slices`` contiguous row
        ranges, sum) — shared by the local threshold path, the W_s
        exchange payload, and the advisory cross-check, so the vector
        the rendezvous verifies can never drift from the vector the
        thresholds are built from."""
        w = np.zeros(pad, dtype=np.int64)
        # lint: host-data -- multiplicity weights are host numpy
        w[: data.total_count] = data.weights
        return w.reshape(n_slices, -1).sum(axis=1)

    def _wstotals_available(self, data: CompressedData) -> bool:
        """True when the per-shard weight totals the sparse thresholds
        need are computable on this mesh: always single-process; on a
        multi-process ingest, whenever a transport spans every ingest
        process (the jax.distributed world itself, or a quorum file
        domain of the same width) for the one-time mine.start W_s
        exchange.  The ONE gate both engine resolutions consult — a
        sharded CompressedData with no transport is the only remaining
        dense/bitmap forcer (PR 6/7 residue closed otherwise)."""
        shard = data.shard
        n_proc = (
            shard.num_processes if shard is not None
            else jax.process_count()
        )
        if n_proc == 1:
            return True
        if shard is None:
            # Non-sharded data on a multi-process mesh: every process
            # holds the full weights — totals are local arithmetic.
            return True
        # The MESH itself must span the ingest world: the count
        # collectives (psum/union) only cover all shards when jax's
        # process world matches the ingest's.  A quorum file domain is
        # a W_s TRANSPORT, not a mesh — unlocking mining on its say-so
        # would count each rank's local rows against the global
        # min_count (review finding on the first cut of this gate);
        # _shard_weight_totals still prefers it for the exchange
        # itself when both are present.
        return jax.process_count() == n_proc

    def _shard_weight_totals(self, data: CompressedData, t_pad: int):
        """The one-time cross-host W_s exchange (fixed shape: this
        process's [S_local] per-shard weight totals; S_local =
        txn_shards / num_processes), at the existing mine.start quorum
        rendezvous — over the quorum domain's transport when one spans
        the ingest processes, else the jax.distributed tiny-table
        channel sharded ingest already uses (mesh.allgather_bytes).
        Concatenation in process order IS shard order (the mesh's
        device order is process-major), so the result drops into the
        weighted-pigeonhole formula unchanged."""
        from fastapriori_tpu.parallel import mesh as mesh_mod

        shard = data.shard
        n_proc = shard.num_processes
        s = self.context.txn_shards
        cache_key = (t_pad, n_proc)
        cached = self._wstotals_cache.get(cache_key)
        if cached is not None:
            # One rendezvous per mine: the fused setup and the level
            # loop both need the thresholds, and the exchanged totals
            # are static for a given padding — re-running the bounded
            # cross-host round trip would also desynchronize the
            # per-site round counters if one rank's engine path
            # resolved differently.
            return cached
        local_shards = s // n_proc
        local_pad = t_pad // n_proc
        per_local = self._per_shard_row_totals(
            data, local_pad, local_shards
        )
        # lint: host-data -- per-shard totals are host numpy (weights never touch the device here)
        gathered = quorum.exchange("mine.wstotals", per_local.tolist())
        if gathered is not None and len(gathered) == n_proc:
            per = np.concatenate(
                [
                    # lint: host-data -- exchanged payloads are python int lists
                    np.asarray(gathered[r], dtype=np.int64)
                    for r in range(n_proc)
                ]
            )
        else:
            blobs = mesh_mod.allgather_bytes(
                per_local.astype("<i8").tobytes()
            )
            per = np.concatenate(
                [np.frombuffer(b, dtype="<i8") for b in blobs]
            )
        if per.size != s:
            from fastapriori_tpu.errors import InputError

            raise InputError(
                f"W_s exchange returned {per.size} shard totals for a "
                f"{s}-shard mesh ({n_proc} ingest processes) — the "
                "transport does not span the ingest world"
            )
        ledger.record(
            "wstotals_exchange", once_key="mine", procs=n_proc,
            shards=s,
        )
        self._wstotals_cache[cache_key] = per
        return per

    def _verify_wstotals(self, data: CompressedData, t_pad: int) -> None:
        """Advisory W_s cross-check on full-replica file-transport
        domains (tools/chaos.py --procs: every rank mines the same
        corpus): exchange the locally-computed totals at the same
        mine.start rendezvous site and classify any mismatch as a
        MeshDivergence naming both ranks — thresholds derived from
        divergent ingests would issue sparse collectives whose unions
        never match, the exact failure mode the consensus layer exists
        to bound.  One-shot per miner, and the payload is the
        CANONICAL raw per-shard totals (never the heavy-split
        redistribution), so every rank posts an identical vector no
        matter which engine path reaches the check first.  No-op
        without a file domain (the real-mesh transport's
        collective-count discipline does not admit an optional
        exchange)."""
        dom = quorum.active()
        if (
            self._wstotals_verified
            or dom is None
            or dom.nprocs == 1
            or not isinstance(dom.transport, quorum.FileTransport)
        ):
            return
        self._wstotals_verified = True
        per = self._per_shard_row_totals(
            data, t_pad, self.context.txn_shards
        )
        # lint: host-data -- raw weight totals are host numpy
        gathered = dom.exchange("mine.wstotals", per.tolist())
        mine = [int(v) for v in per]
        for rank in sorted(gathered):
            if rank != dom.rank and gathered[rank] != mine:
                raise quorum.MeshDivergence(
                    "ABORTED: mesh divergence at 'mine.wstotals': rank "
                    f"{dom.rank} derived shard weight totals {mine} "
                    f"while rank {rank} derived {gathered[rank]} — "
                    "sparse prune thresholds from divergent ingests "
                    "can never issue matching collectives"
                )
        ledger.record(
            "wstotals_exchange", once_key="verify", procs=dom.nprocs,
            verified=True,
        )

    # -- exchange topology (ISSUE 15: pod-scale hierarchical exchange) --
    def _exchange_spec(self):
        """Resolve the two-level exchange topology for this mine's
        sparse collectives (parallel/hier.py resolve_spec):
        ``FA_EXCHANGE_GROUPS`` (strict) over ``config.exchange_groups``
        — 0 = auto (process boundaries on real multi-host meshes, the
        divisor nearest √S on single-process virtual ones, flat where
        the hierarchy cannot strictly win), 1 = flat, any other value
        must divide the txn axis (InputError).  The quorum consensus
        floor clamps hier→flat — a peer that walked the exchange chain
        already issues the flat collectives, and matching their
        shape/count is mandatory.  The resolved topology lands on the
        ledger so a record always names which exchange moved its
        bytes."""
        from fastapriori_tpu.parallel.hier import resolve_active_spec

        spec = resolve_active_spec(
            self.context.txn_shards, self.config, unclamped=True
        )
        if spec is not None and not quorum.stage_allowed(
            "exchange", "hier"
        ):
            # Consensus floor (the _count_reduce_engine pattern): the
            # adoption already recorded the cascade walk; this is the
            # local clamp honoring it.  Recorded ONLY when hier would
            # otherwise have run — a mine that resolves flat anyway
            # (knob, small mesh) was never clamped by anyone.
            ledger.record(
                "exchange_fallback", once_key="quorum", reason="quorum"
            )
            spec = None
        ledger.record(
            "exchange_engine",
            once_key=f"spec:{spec}",
            engine="hier" if spec is not None else "flat",
            groups=spec[0] if spec is not None else 1,
            per_group=spec[1] if spec is not None else (
                self.context.txn_shards
            ),
        )
        return spec

    # -- mining-engine layout choice (ROADMAP item 3: vertical Eclat) --
    _MINE_ENGINES = ("auto", "bitmap", "vertical")

    @staticmethod
    def _has_csr(data: CompressedData) -> bool:
        return (
            data.total_count == 0
            or len(data.basket_offsets) == data.total_count + 1
        )

    @staticmethod
    def _density_from_tables(
        n_raw: int, num_items: int, occ_total: float
    ) -> float:
        """The ONE density definition (frequent-item occurrence mass
        over the full ``T × F`` bitmap) — shared by the post-ingest
        estimate and the pass-1 pipeline probe so the two sites can
        never drift."""
        if num_items <= 0 or n_raw <= 0:
            return 1.0
        return float(occ_total) / (float(n_raw) * num_items)

    @staticmethod
    def _density_estimate(data: CompressedData) -> float:
        """Pair-phase density estimate: frequent-item occurrence mass
        over the full ``T × F`` bitmap — the fraction of bitmap cells
        the Gram matmul multiplies that are actually set.  Computed
        from the ingest's own tables (item_counts are the raw per-rank
        occurrence counts), so the choice costs no device work."""
        return FastApriori._density_from_tables(
            data.n_raw, data.num_items,
            # lint: host-data -- item counts are host numpy
            float(np.sum(data.item_counts)),
        )

    def _pipeline_engine_probe(
        self, n_raw: int, num_items: int, occ_total: float
    ) -> str:
        """Mining-engine LAYOUT choice from pass-1 tables alone (ISSUE 8
        satellite: the density probe folded into pass-1 ingest, so
        auto-vertical no longer forfeits the pipelined capture overlap —
        the choice lands BEFORE any block commits to the bitmap
        layout).  Same decision rule as :meth:`_mine_engine` (which
        remains the post-ingest resolution for the non-pipelined
        paths); the chosen path is ledger-recorded with the density the
        probe saw."""
        req = self._requested_mine_engine()
        if req == "bitmap":
            return "bitmap"
        if not quorum.stage_allowed("mine_engine", "vertical"):
            # Consensus floor (ISSUE 12): same clamp as _mine_engine —
            # the probe must never commit blocks to a layout a peer has
            # already abandoned.
            if req == "vertical":
                ledger.record(
                    "mine_engine_fallback", once_key="quorum",
                    reason="quorum",
                )
                watchdog.downgrade(
                    "mine_engine", "vertical", "bitmap", reason="quorum"
                )
            return "bitmap"
        if req == "vertical":
            ledger.record(
                "mine_engine", once_key="vertical", engine="vertical",
                probe="pass1",
            )
            return "vertical"
        cfg = self.config
        density = self._density_from_tables(n_raw, num_items, occ_total)
        if (
            num_items >= cfg.vertical_min_items
            and density <= cfg.vertical_density_max
        ):
            ledger.record(
                "mine_engine", once_key="auto_vertical",
                engine="vertical", density=round(density, 6),
                probe="pass1",
            )
            return "vertical"
        return "bitmap"

    def _requested_mine_engine(self) -> str:
        """The strictly-parsed mining-engine REQUEST (``FA_MINE_ENGINE``
        over ``config.mine_engine``, a typo in either -> InputError) —
        ONE definition shared by the pipeline-ingest probe and the
        mine-time resolution, so the two sites can never drift."""
        from fastapriori_tpu.utils.env import env_choice

        req = env_choice("FA_MINE_ENGINE", self._MINE_ENGINES)
        if req is None:
            req = self.config.mine_engine
            if req not in self._MINE_ENGINES:
                from fastapriori_tpu.errors import InputError

                raise InputError(
                    f"unrecognized MinerConfig.mine_engine value "
                    f"{req!r}: use one of {'/'.join(self._MINE_ENGINES)}"
                )
        return req

    def _mine_engine(self, data: CompressedData) -> Tuple[str, str]:
        """Resolve the mining-engine LAYOUT for this mine:
        ``FA_MINE_ENGINE`` (strict) overrides ``config.mine_engine``
        (validated just as strictly).  Returns ``(engine, requested)``
        with engine "bitmap" or "vertical".  The vertical tid-lane
        engine is defined on single-process 1-D txn meshes over a
        CSR-bearing CompressedData — elsewhere "auto" quietly stays
        bitmap and a forced "vertical" falls back WITH a ledger event
        (the ``_count_reduce_engine`` pattern).  Auto picks vertical on
        sparse wide-item corpora: density below
        ``config.vertical_density_max`` with at least
        ``config.vertical_min_items`` frequent items — and records the
        choice (plus the density it saw) on the ledger, so a record
        always names which engine counted it."""
        req = self._requested_mine_engine()
        if req == "bitmap":
            return "bitmap", req
        ctx = self.context
        reason = None
        if ctx.cand_shards != 1:
            reason = "cand_mesh"
        elif data.shard is None and jax.process_count() != 1:
            # Non-sharded data on a multi-process mesh: there is no
            # local-row slice to build a lane block from.
            reason = "multi_process"
        elif data.shard is not None and not self._wstotals_available(
            data
        ):
            reason = "no_wstotals_transport"
        elif data.shard is not None and (
            ctx.txn_shards % data.shard.num_processes != 0
        ):
            reason = "mesh_split"
        elif not self._has_csr(data):
            reason = "no_csr"
        elif not quorum.stage_allowed("mine_engine", "vertical"):
            # Consensus floor (ISSUE 12): a peer already fell back to
            # the bitmap layout — lane collectives would never match.
            reason = "quorum"
        if reason is not None:
            if req == "vertical":
                ledger.record(
                    "mine_engine_fallback", once_key=reason, reason=reason
                )
                watchdog.downgrade(
                    "mine_engine", "vertical", "bitmap", reason=reason
                )
            return "bitmap", req
        if req == "vertical":
            ledger.record(
                "mine_engine", once_key="vertical", engine="vertical"
            )
            return "vertical", req
        density = self._density_estimate(data)
        cfg = self.config
        if (
            data.num_items >= cfg.vertical_min_items
            and density <= cfg.vertical_density_max
        ):
            ledger.record(
                "mine_engine", once_key="auto_vertical",
                engine="vertical", density=round(density, 6),
            )
            return "vertical", req
        return "bitmap", req

    def _vertical_chunk(self, c_cap: int) -> int:
        """Candidate scan-chunk for the vertical kernels: the config/env
        knob pow2-bucketed, then halved until it DIVIDES this
        dispatch's candidate budget (the scan reshape needs an exact
        divisor, and c_cap can clamp to f_pad — a 128-multiple like
        384 that is not a power of two).  The [chunk, NL] gathered
        intersection lanes are the kernel's HBM intermediate."""
        from fastapriori_tpu.utils.env import env_int

        chunk = env_int(
            "FA_VERTICAL_CHUNK", 0, minimum=0
        ) or self.config.vertical_cand_chunk
        chunk = min(_next_pow2(max(int(chunk), 8)), _next_pow2(c_cap))
        while chunk > 1 and c_cap % chunk:
            chunk //= 2
        return max(chunk, 1)

    def _vertical_lane_tile(self) -> int:
        """Lane-slab width for the vertical level kernels: the
        config/env knob pow2-bucketed (G011 — one compiled program per
        bucket, not per observed lane count).  Bounds the
        [P_cap, lane_tile] prefix intermediate on the XLA path and
        ceilings the Pallas kernel's lane tile, so big-T corpora stream
        the lane axis on BOTH tiers instead of hitting the old ~50K
        [P_cap, NL] ceiling."""
        from fastapriori_tpu.utils.env import env_int

        tile = env_int(
            "FA_VERTICAL_LANE_TILE", 0, minimum=0
        ) or self.config.vertical_lane_tile
        return _next_pow2(max(int(tile), 128))

    def _mine_vertical(
        self, data: CompressedData
    ) -> List[Tuple[np.ndarray, np.ndarray]]:
        """Vertical (Eclat-style) mining: per-item tid-lists as packed
        uint32 lanes sharded over the txn mesh axis, level-k support by
        lane-wise AND + popcount (ops/vertical.py), the SAME level loop
        driving it (``_level_loop(vertical=True)`` — candidate
        generation, deferred counts, drains, checkpoints and resume all
        shared with the bitmap engine, which stays the differential
        oracle)."""
        from fastapriori_tpu.ops import vertical as vops

        from fastapriori_tpu.preprocess import ingest_thread_count

        cfg = self.config
        ctx = self.context
        resume = self._take_resume(data)
        self._require_csr(data)
        # Same thread pool policy as the segmented pass-1 ingest scan
        # (FA_INGEST_THREADS): the arena build's reduceat pass splits
        # run-aligned across it (PR-7 residue — it was single-threaded).
        n_threads = ingest_thread_count(cfg.ingest_threads)
        shard = data.shard
        multi = shard is not None and shard.num_processes > 1
        with self.metrics.timed("arena_build") as m:
            if multi:
                # Multi-process lane sharding (ISSUE 15, the PR-7
                # "vertical falls back to bitmap on multi-process
                # ingest" residue): each process builds ONLY its rows'
                # lanes, padded to the SAME local row count (max over
                # shards, 32·local_devices-aligned so lanes split
                # evenly over this process's devices), and the global
                # arena assembles with zero cross-host data movement —
                # the lane twin of the bitmap path's sharded branch.
                # The bit-plane count derives from the ingest-exchanged
                # GLOBAL max weight (SPMD static shapes).
                from fastapriori_tpu.ops.bitmap import pad_axis

                n_proc = shard.num_processes
                local_devices = max(ctx.txn_shards // n_proc, 1)
                local_pad = max(
                    pad_axis(c, 32 * local_devices)
                    for c in shard.local_counts
                )
                arena_np, f_pad, t_local = vops.build_tid_arena_csr(
                    data.basket_indices,
                    data.basket_offsets,
                    data.num_items,
                    local_pad,
                    cfg.item_tile,
                    n_threads=n_threads,
                )
                assert t_local == local_pad, (t_local, local_pad)
                t_pad = local_pad * n_proc
                planes_np, scales = vops.weight_bit_planes(
                    # lint: host-data -- CompressedData weights are host numpy
                    np.asarray(data.weights, dtype=np.int64),
                    local_pad,
                    min_planes=max(
                        int(shard.max_weight).bit_length(), 1
                    ),
                )
                use_compressed = False
                seg_stats = {"occupancy": -1.0}
                arena, upload_bytes = ctx.upload_tid_arena_local(
                    arena_np
                )
                w_planes = ctx.upload_lane_planes_local(planes_np)
            else:
                arena_np, f_pad, t_pad = vops.build_tid_arena_csr(
                    data.basket_indices,
                    data.basket_offsets,
                    data.num_items,
                    32 * ctx.txn_shards,
                    cfg.item_tile,
                    n_threads=n_threads,
                )
                planes_np, scales = vops.weight_bit_planes(
                    # lint: host-data -- CompressedData weights are host numpy
                    np.asarray(data.weights, dtype=np.int64), t_pad
                )
                # Census first (vectorized), bucket fill only when the
                # compressed upload wins: the pow2-bucketed segment
                # lists pay off below ~half occupancy; dense corpora
                # skip both the per-item fill loop and the scatter
                # dispatch.
                _, payload, seg_stats = vops.compress_arena(
                    arena_np, f_pad, build=False
                )
                use_compressed = payload * 2 <= arena_np.nbytes
                buckets = (
                    vops.compress_arena(arena_np, f_pad)[0]
                    if use_compressed
                    else None
                )
                arena, upload_bytes = ctx.upload_tid_arena(
                    arena_np, buckets
                )
                w_planes = ctx.upload_lane_planes(planes_np)
            m.update(
                shape=[f_pad + 1, t_pad // 32],
                planes=len(scales),
                compressed=use_compressed,
                occupancy=seg_stats["occupancy"],
                threads=n_threads,
                upload_bytes=upload_bytes + planes_np.nbytes,
            )
        # The pair phase folds the REASSEMBLED weights into one f32
        # Gram on CPU backends (ops/vertical.py fast_f32) — entries are
        # weighted counts bounded by n_raw, so the gate is the same
        # n_raw < 2^24 bound as :meth:`_fast_f32`; k >= 3 counting is
        # integer popcounts and never needs the gate.
        fast_f32 = self._fast_f32(data.n_raw)
        return self._level_loop(
            data, resume, arena, w_planes, scales, 1, fast_f32, t_pad,
            None, vertical=True,
        )

    def _fused_count_reduce_setup(
        self, data: CompressedData, t_pad: int, f_pad: int,
        n_digits: int, n_chunks: int, fast_f32: bool, packed_input: bool,
    ):
        """Count-reduction setup shared by both fused flavors (packed
        upload and resident bitmap — the same sharing as
        :meth:`_fused_attempt_loop`): resolves the engine, applies the
        tiny-candidate-space floor (with a ledger event — the fused
        program then runs dense end to end), computes the per-shard
        prune thresholds, and returns the ``build(m, reduce) ->
        (program, caps)`` closure whose compaction budgets honor the
        overflow-grown hint from previous runs."""
        cfg = self.config
        ctx = self.context
        count_reduce, _req = self._count_reduce_engine(data)
        if count_reduce == "sparse" and f_pad * f_pad < cfg.count_sparse_min:
            ledger.record(
                "count_reduce_fallback", once_key="tiny_fused",
                reason="tiny_candidate_set", site="fused",
            )
            count_reduce = "dense"  # tiny candidate space: psum wins
        # Exchange topology for the fused program's sparse collectives
        # (ISSUE 15) — this setup is shared by both fused flavors and
        # runs before build(), the one place their compiles are keyed;
        # the packed-upload path never passes _level_loop's install.
        ctx.set_exchange_spec(
            self._exchange_spec() if count_reduce == "sparse" else None
        )
        sparse_thr = (
            self._sparse_thresholds(data, t_pad, heavy=False)
            if count_reduce == "sparse"
            else None
        )
        hint_key = ("sparse_fused", t_pad, f_pad, int(data.min_count))

        def build(m, reduce):
            caps = (
                (
                    self._sparse_cap(f_pad * f_pad, hint_key=hint_key),
                    self._sparse_cap(m * f_pad, hint_key=hint_key),
                )
                if reduce == "sparse"
                else None
            )
            return (
                ctx.fused_miner(
                    m, cfg.fused_l_max, n_digits, n_chunks, fast_f32,
                    packed_input=packed_input, sparse_caps=caps,
                ),
                caps,
            )

        return count_reduce, sparse_thr, build, hint_key

    def _fused_fallback(
        self, partial: Optional[list], reason: str = "row_budget_or_bound"
    ) -> None:
        """One call per fused→level fallback: the legacy metrics event
        (asserted by the engine tests / bench parsers), the
        degradation-ledger entry, and the unified cascade event
        (reliability/watchdog.py — the ONE escalation policy every
        engine fallback now reports through)."""
        n = len(partial) if partial else 0
        self.metrics.emit("fused_fallback", resume_levels=n)
        ledger.record("fused_fallback", resume_levels=n)
        # The unified cascade records DEGRADATIONS, not choices: an
        # engine="auto" run that never attempted the fused program
        # simply chose the level engine (the engine_auto event), while
        # a forced-fused run, a run whose fused ATTEMPT overflowed
        # (partial salvage), or a transient-exhausted attempt genuinely
        # walked the chain.
        if (
            self.config.engine == "fused"
            or partial
            or reason == "transient_exhausted"
        ):
            watchdog.downgrade(
                "engine", "fused", "level", reason=reason,
                resume_levels=n,
            )

    @property
    def context(self) -> DeviceContext:
        if self._context is None:
            self._context = DeviceContext(
                num_devices=self.config.num_devices,
                cand_devices=self.config.cand_devices,
            )
        return self._context

    # ------------------------------------------------------------------
    def run(
        self, transactions: Sequence[Sequence[str]]
    ) -> Tuple[List[ItemsetWithCount], Dict[str, int], List[str]]:
        """Full mining (FastApriori.run, :31-44).

        Returns ``(freqItemsets with counts, itemToRank, freqItems)`` —
        levels >=2 first, then the 1-itemsets with their raw occurrence
        counts (:41,83)."""
        with trace.span("mine", source="transactions"):
            with self.metrics.timed("preprocess") as m:
                data = preprocess(transactions, self.config.min_support)
                m.update(
                    n_raw=data.n_raw,
                    min_count=data.min_count,
                    num_items=data.num_items,
                    total_count=data.total_count,
                )
            freq_itemsets = self.mine_compressed(data)
        return freq_itemsets, data.item_to_rank, data.freq_items

    def run_file(
        self, d_path: str
    ) -> Tuple[List[ItemsetWithCount], Dict[str, int], List[str]]:
        """Like :meth:`run` but ingesting ``D.dat`` directly from disk, so
        the native preprocessor (when built) parses raw bytes without
        Python tokenization (reference ingest: Utils.scala:21)."""
        levels, data = self.run_file_raw(d_path)
        return (
            self._decode_levels(levels, data),
            data.item_to_rank,
            data.freq_items,
        )

    def run_file_raw(
        self, d_path: str
    ) -> Tuple[List[Tuple[np.ndarray, np.ndarray]], CompressedData]:
        """Matrix-form mining: like :meth:`run_file` but the levels >= 2
        come back as ``[(int32[N, k] member matrix, int64[N] counts), ...]``
        with NO per-itemset Python objects (the frozenset materialization
        of 1.35M itemsets was a multi-second host phase at Webdocs scale,
        and every consumer — the writer's line formatting, rule gen's
        size-grouped tables — immediately converts back to arrays anyway).
        1-itemsets live in ``data.item_counts`` by rank.

        The returned ``CompressedData``'s rows are per-ingest-block
        deduplicated under the (default) pipelined ingest — identical
        baskets from different blocks stay separate weighted rows; see
        the CompressedData docstring for the exact contract."""
        from fastapriori_tpu.preprocess import preprocess_file

        # The mining root span (ISSUE 11): phases (preprocess / level /
        # tail_fuse / counts_resolve / checkpoint — every metrics.timed
        # section) nest under it via the tracer's thread-local stack.
        with trace.span("mine", path=d_path):
            if self._can_pipeline_ingest(d_path):
                return self._run_file_pipelined(d_path)
            with self.metrics.timed("preprocess", path=d_path) as m:
                data = preprocess_file(d_path, self.config.min_support)
                m.update(
                    n_raw=data.n_raw,
                    min_count=data.min_count,
                    num_items=data.num_items,
                    total_count=data.total_count,
                )
            return self.mine_levels_raw(data), data

    def _txn_multiple(self, n_chunks: int, total: int) -> int:
        """Padding multiple for the transaction axis: per-chunk rows stay
        whole (the level kernels reshape [T] -> [n_chunks, tc]) and, on
        TPU, t_pad additionally aligns to 4096-row Pallas tiles — an
        unaligned t_pad (e.g. 1660672 = 256·6487) forces the fused level
        kernel down to 256-row tiles whose grid overhead eats the VMEM
        win.  ``total`` is the actual (deduplicated) row count: the
        alignment is taken only when it costs <= 5% extra zero-weight
        rows (an LCM multiple sized far above ``total`` — small or
        heavily-deduplicated datasets — could otherwise inflate every
        level matmul by ~25%+; pick_tile just falls back to smaller
        tiles there)."""
        import math

        from fastapriori_tpu.ops.bitmap import pad_axis

        base = max(self.config.txn_tile, 32) * n_chunks
        if self.context.platform == "tpu":
            aligned = base * 4096 // math.gcd(base, 4096)
            if pad_axis(total, aligned) <= 1.05 * max(
                pad_axis(total, base), 1
            ):
                return aligned
        return base

    def _can_pipeline_ingest(self, d_path: str) -> bool:
        """Pipelined ingest (per-block compress overlapped with the
        device upload) applies to the plain single-process local-file
        path — for EVERY engine: the resulting device bitmap serves the
        level kernels directly and the fused engine through its
        unpacked-input variant (ops/fused.py ``packed_input=False``), so
        the auto choice happens after ingest with zero re-upload.  Every
        other combination keeps the existing flow."""
        cfg = self.config
        if cfg.ingest_pipeline_blocks <= 1 or "://" in d_path:
            return False
        # The capture ingest no longer pre-commits to the bitmap layout:
        # the pass-1 density probe (loader on_pass1 /
        # fa_preprocess_buffer_blocks2) picks the engine BEFORE any
        # block callback fires, and vertical blocks retain their CSR for
        # the arena build instead of packing bitmaps (ISSUE 8 satellite,
        # PR-7 residue).  A forced-vertical mine therefore pipelines too
        # — unless the .so predates the capture entry point, where the
        # classic whole-file path still serves it.
        if self._requested_mine_engine() == "vertical":
            from fastapriori_tpu.native.loader import (
                has_preprocess_buffer_blocks,
            )

            if not has_preprocess_buffer_blocks():
                return False
        import jax

        if jax.process_count() != 1:
            return False
        ctx = self.context
        if ctx.txn_shards != 1 or ctx.cand_shards != 1:
            return False
        from fastapriori_tpu.preprocess import _use_native

        return _use_native(None, 1 << 62)

    def _run_file_pipelined(
        self, d_path: str
    ) -> Tuple[List[Tuple[np.ndarray, np.ndarray]], CompressedData]:
        """Single-host ingest with the bitmap upload hidden behind
        pass-2 compression: pass 1 (token counts) runs over the whole
        buffer, then the buffer is split into line-aligned blocks, each
        compressed against the global rank table (the per-byte-range
        machinery the multi-host sharded ingest already proves correct —
        cross-block duplicate baskets stay separate weighted rows with
        identical weighted counts) and its packed bitmap block uploaded
        asynchronously while the next block compresses on the host.

        The reference's analog is ingest+first-shuffle overlapping on
        Spark executors (FastApriori.scala:52-85); here the overlap is
        host-compress vs host->device link."""
        import math
        from collections import Counter

        import jax.numpy as jnp

        from fastapriori_tpu.native.loader import (
            compress_with_ranks,
            count_buffer,
        )
        from fastapriori_tpu.ops.bitmap import (
            build_packed_bitmap_csr,
            pad_axis,
        )
        from fastapriori_tpu.preprocess import (
            build_rank_map,
            split_buffer_ranges,
        )

        cfg = self.config
        ctx = self.context
        from concurrent.futures import ThreadPoolExecutor

        from fastapriori_tpu.preprocess import ingest_thread_count

        n_threads = ingest_thread_count(cfg.ingest_threads)
        from fastapriori_tpu.native.loader import (
            has_preprocess_buffer_blocks,
        )

        if has_preprocess_buffer_blocks():
            # Capture-replay form for EVERY thread count: pass 1's scan
            # runs as n_threads parallel line-aligned segments and pass
            # 2's replay as n_threads native block workers (both inside
            # the one native call — the raw bytes are tokenized exactly
            # once), with replay overlapping the main thread's per-block
            # packing + upload.  The re-tokenizing ThreadPool path below
            # survives only as the fallback for a stale .so without the
            # blocks entry point.
            return self._run_file_pipelined_capture(d_path, n_threads)
        with self.metrics.timed("preprocess", path=d_path) as m:
            with open(d_path, "rb") as fh:
                buf = fh.read()
            # Pass 1 across threads: each thread counts its own
            # line-aligned byte range (the native call releases the GIL)
            # and the tiny per-range token tables merge on the main
            # thread — the single-host analog of the multi-host sharded
            # ingest's count merge, with the same correctness argument.
            # More ranges than threads, so in-flight block copies are
            # bounded by the POOL size, not the range count (equal counts
            # would put slices covering the whole file in memory at once).
            p1_ranges = [
                r
                for r in split_buffer_ranges(
                    buf, n_threads * 4 if n_threads > 1 else 1
                )
                if r[1] > r[0]
            ]
            if len(p1_ranges) > 1:
                with ThreadPoolExecutor(n_threads) as pool:
                    # Slice INSIDE the worker: block copies in flight are
                    # bounded by the thread count, not the range count.
                    parts = list(
                        pool.map(
                            lambda r: count_buffer(buf[r[0] : r[1]]),
                            p1_ranges,
                        )
                    )
            else:
                parts = [count_buffer(buf)]
            n_raw = sum(p[0] for p in parts)
            merged: Counter = Counter()
            for _, toks, cnts in parts:
                # lint: host-data -- native pass-1 count tables are host numpy
                for tok, c in zip(toks, cnts.tolist()):
                    merged[tok] += c
            min_count = math.ceil(cfg.min_support * n_raw)
            freq_items, item_to_rank, item_counts = build_rank_map(
                merged, min_count
            )
            f = len(freq_items)
            m.update(
                n_raw=n_raw, min_count=min_count, num_items=f,
                pipelined=True, threads=n_threads,
            )

        def empty_data():
            return self._empty_compressed(
                n_raw, min_count, freq_items, item_to_rank, item_counts
            )

        if f < 2:
            return [], empty_data()

        # Pass-1 density probe (ISSUE 8 satellite): this flavor has the
        # merged tables in hand before pass 2, so the layout choice is a
        # direct call — a vertical pick compresses the blocks threaded
        # (the same overlap) and retains the CSR for the arena build
        # instead of packing/uploading bitmaps.
        if self._pipeline_engine_probe(
            n_raw, f, float(np.sum(item_counts))
        ) == "vertical":
            self.metrics.emit(
                "mine_engine", engine="vertical",
                requested=self._requested_mine_engine(), probe="pass1",
            )
            with self.metrics.timed("csr_build") as m:
                blocks = []
                with ThreadPoolExecutor(n_threads) as cpool:
                    ranges = [
                        r
                        for r in split_buffer_ranges(
                            buf, max(cfg.ingest_pipeline_blocks, n_threads)
                        )
                        if r[1] > r[0]
                    ]
                    comp = [
                        cpool.submit(
                            lambda lo=lo, hi=hi: compress_with_ranks(
                                buf[lo:hi], freq_items
                            )
                        )
                        for lo, hi in ranges
                    ]
                    for fu in comp:
                        _, bi, bo, bw = fu.result()
                        if len(bw):
                            blocks.append((bi, bo, bw))
                if not blocks:
                    return [], empty_data()
                indices, offsets, w_np = self._concat_block_csr(blocks)
                m.update(blocks=len(blocks), rows=len(w_np))
            data = CompressedData(
                n_raw=n_raw,
                min_count=min_count,
                freq_items=freq_items,
                item_to_rank=item_to_rank,
                item_counts=item_counts,
                basket_indices=indices,
                basket_offsets=offsets,
                weights=w_np,
            )
            return self._mine_vertical_safe(data), data

        # Static shapes fixed BEFORE the first upload: distinct rows are
        # bounded by n_raw, so an n_chunks derived from it can only be
        # (slightly) finer than the exact-count split — harmless.
        n_chunks = max(1, -(-n_raw // cfg.level_txn_chunk))

        with self.metrics.timed("bitmap_build") as m:
            blocks = []  # (indices, offsets, weights) per block
            dev_futures = []  # in-flight packed uploads
            f_pad = None
            upload_bytes = 0
            dev = ctx.mesh.devices.flat[0]
            # Pass 2 across threads (compression is GIL-free native
            # code), results consumed in block order for deterministic
            # row layout.  device_put is SYNCHRONOUS on some backends
            # (it blocks until the bytes cross the link), so transfers
            # run on their own worker: block i's upload overlaps block
            # i+1's compression even on a 1-core host.
            with ThreadPoolExecutor(
                max_workers=n_threads
            ) as cpool, ThreadPoolExecutor(max_workers=1) as upool:
                ranges = [
                    r
                    for r in split_buffer_ranges(
                        buf, max(cfg.ingest_pipeline_blocks, n_threads)
                    )
                    if r[1] > r[0]
                ]
                # Slice inside the worker: at most n_threads block
                # copies exist at once (eager slicing at submit time
                # would duplicate the whole file next to `buf`).
                comp = [
                    cpool.submit(
                        lambda lo=lo, hi=hi: compress_with_ranks(
                            buf[lo:hi], freq_items
                        )
                    )
                    for lo, hi in ranges
                ]
                for fu in comp:
                    _, bi, bo, bw = fu.result()
                    if len(bw) == 0:
                        continue
                    pk, f_pad = build_packed_bitmap_csr(
                        bi, bo, f, 1, cfg.item_tile
                    )
                    dev_futures.append(
                        upool.submit(jax.device_put, pk, dev)
                    )
                    upload_bytes += pk.nbytes
                    blocks.append((bi, bo, bw))
                if not blocks:
                    return [], empty_data()
                # Host-side assembly (weights, CSR for API parity) runs
                # BEFORE the upload-tail wait so it hides under the last
                # blocks' transfers.
                txn_multiple = self._txn_multiple(
                    n_chunks, sum(len(bw) for _, _, bw in blocks)
                )
                asm = self._assemble_blocks(blocks, txn_multiple, f)
                dev_blocks = [fu.result() for fu in dev_futures]

            (
                total, t_pad, w_np, w_digits_np, scales, indices, offsets,
                heavy_b, heavy_w,
            ) = asm
            bitmap = self._device_concat_unpack(
                dev_blocks, total, t_pad, f_pad
            )
            w_digits = ctx.shard_weight_digits(w_digits_np)
            heavy = self._upload_heavy(heavy_b, heavy_w)
            heavy_rows, heavy_bytes = self._heavy_stats(heavy_b, heavy_w)
            m.update(
                shape=[t_pad, f_pad],
                digits=len(scales),
                blocks=len(blocks),
                heavy_rows=heavy_rows,
                upload_bytes=upload_bytes
                + w_digits_np.nbytes
                + heavy_bytes,
            )

        data = CompressedData(
            n_raw=n_raw,
            min_count=min_count,
            freq_items=freq_items,
            item_to_rank=item_to_rank,
            item_counts=item_counts,
            basket_indices=indices,
            basket_offsets=offsets,
            weights=w_np,
        )
        levels = self._mine_levels(
            data,
            preupload=(
                bitmap, w_digits, scales, n_chunks, t_pad, f_pad, heavy,
            ),
            try_fused=True,
        )
        return levels, data

    def _upload_heavy(self, heavy_b, heavy_w):
        """Replicated device placement of the heavy-row remainder arrays
        (None -> None: legacy multi-digit)."""
        if heavy_b is None:
            return None
        ctx = self.context
        return ctx.replicate(heavy_b), ctx.replicate(heavy_w)

    def _heavy_stats(self, heavy_b, heavy_w):
        """(true heavy-row count, host->device bytes) for the metrics
        stream — the arrays are REPLICATED, so the byte figure scales
        with the device count."""
        if heavy_b is None:
            return 0, 0
        return (
            int(np.count_nonzero(heavy_w)),
            (heavy_b.nbytes + heavy_w.nbytes) * self.context.n_devices,
        )

    # Heavy-row remainder bounds: above either, fall back to the legacy
    # multi-digit weight path (the remainder arrays would no longer be
    # "tiny" — heavy_b is DENSE int8 [Th, f_pad] replicated per device,
    # so the byte bound matters at large item counts).
    HEAVY_SPLIT_CAP = 4096
    HEAVY_SPLIT_BYTES = 16 << 20

    def _split_weights(self, w_np, t_pad, indices, offsets, f,
                       heavy_pre=None):
        """Single-low-digit weight split: the main kernels run ONE int8
        digit (``w % 128``) for every row — halving the counting matmuls
        when any row's multiplicity reaches 128 — and the exact remainder
        ``w - w%128`` rides a tiny separate heavy-row array added as an
        int32 correction (ops/count.py heavy_*_correction).  Returns
        ``(w_digits, scales, heavy_b | None, heavy_w | None)``; heavy
        None = legacy multi-digit (no heavy rows, or too many).

        ``heavy_pre``: the heavy rows' basket arrays extracted at ingest
        callback time (retain_csr=False — no global CSR exists), in the
        same row order ``np.flatnonzero(w >= 128)`` enumerates."""
        from fastapriori_tpu.ops.bitmap import build_bitmap, pad_axis

        heavy_idx = np.flatnonzero(w_np >= 128)
        f_pad = pad_axis(f + 1, self.config.item_tile)
        if (
            heavy_idx.size == 0
            or heavy_idx.size > self.HEAVY_SPLIT_CAP
            or heavy_idx.size * f_pad > self.HEAVY_SPLIT_BYTES
        ):
            w_digits_np, scales = weight_digits(w_np, t_pad)
            return w_digits_np, scales, None, None
        w_digits_np, scales = weight_digits(
            (w_np % 128).astype(np.int32), t_pad
        )
        assert scales == [1], scales  # low digit only, by construction
        if heavy_pre is not None:
            baskets = heavy_pre
            assert len(baskets) == heavy_idx.size, (
                len(baskets), heavy_idx.size,
            )
        else:
            baskets = [
                indices[offsets[i] : offsets[i + 1]] for i in heavy_idx
            ]
        heavy_b = build_bitmap(baskets, f, 8, self.config.item_tile)
        heavy_w = np.zeros(heavy_b.shape[0], dtype=np.int32)
        heavy_w[: heavy_idx.size] = w_np[heavy_idx] - (
            w_np[heavy_idx] % 128
        )
        return w_digits_np, scales, heavy_b, heavy_w

    @staticmethod
    def _require_csr(data: CompressedData) -> None:
        """CSR-consuming paths (the packed fused upload, the plain level
        bitmap build) must fail loudly on a CompressedData produced with
        ``retain_csr=False`` — silently mining an empty CSR would return
        an empty lattice."""
        from fastapriori_tpu.errors import InputError

        if (
            data.total_count > 0
            and len(data.basket_offsets) != data.total_count + 1
        ):
            raise InputError(
                "CompressedData carries no basket CSR (produced by the "
                "pipelined capture ingest with retain_csr=False); "
                "re-ingest with retain_csr=True to mine it through "
                "this path"
            )

    @staticmethod
    def _empty_compressed(
        n_raw, min_count, freq_items, item_to_rank, item_counts
    ) -> CompressedData:
        """Global tables with zero baskets (degenerate ingest outcomes —
        no frequent items, or every basket of size <= 1)."""
        return CompressedData(
            n_raw=n_raw,
            min_count=min_count,
            freq_items=freq_items,
            item_to_rank=item_to_rank,
            item_counts=item_counts,
            basket_indices=np.empty(0, np.int32),
            basket_offsets=np.zeros(1, np.int64),
            weights=np.empty(0, np.int32),
        )

    @staticmethod
    def _concat_block_csr(blocks):
        """Block-order concatenation of per-block ``(indices, offsets,
        weights)`` CSRs into one global CSR — the ONE offset-rebase
        definition (cross-block duplicate baskets stay separate weighted
        rows, the sharded-ingest correctness rule; each block's
        ``offsets[0] == 0``).  Shared by the bitmap assembly and both
        vertical ingest flavors."""
        w_np = np.concatenate([bw for _, _, bw in blocks])
        indices = np.concatenate([bi for bi, _, _ in blocks])
        offs = [np.zeros(1, dtype=np.int64)]
        base = 0
        for _, bo, _ in blocks:
            offs.append(bo[1:].astype(np.int64) + base)
            base += int(bo[-1])
        return indices, np.concatenate(offs), w_np

    def _assemble_blocks(self, blocks, txn_multiple: int, f: int,
                         heavy_pre=None):
        """Host-side assembly of per-block CSRs: concatenated weights +
        weight digits (single-low-digit split when heavy rows are few) +
        the global CSR (API parity).  Shared by both pipelined ingest
        flavors; runs while the upload tail drains.

        ``heavy_pre`` (retain_csr=False): blocks carry ``None`` item
        arrays — the global CSR is skipped entirely (~0.7 GB of copies
        at webdocs scale) and the heavy rows' baskets arrive pre-
        extracted from the ingest callback."""
        from fastapriori_tpu.ops.bitmap import pad_axis

        total = sum(len(bw) for _, _, bw in blocks)
        t_pad = pad_axis(total, txn_multiple)
        if heavy_pre is None:
            indices, offsets, w_np = self._concat_block_csr(blocks)
            w_digits_np, scales, heavy_b, heavy_w = self._split_weights(
                w_np, t_pad, indices, offsets, f
            )
        else:
            w_np = np.concatenate([bw for _, _, bw in blocks])
            indices = np.empty(0, np.int32)
            offsets = np.zeros(1, np.int64)
            w_digits_np, scales, heavy_b, heavy_w = self._split_weights(
                w_np, t_pad, indices, offsets, f, heavy_pre=heavy_pre
            )
        return (
            total, t_pad, w_np, w_digits_np, scales, indices, offsets,
            heavy_b, heavy_w,
        )

    def _device_concat_unpack(self, dev_blocks, total, t_pad, f_pad):
        """Concat uploaded packed blocks on device, pad the tail rows,
        unpack to the resident int8 bitmap."""
        import jax.numpy as jnp

        parts = dev_blocks
        if t_pad > total:
            parts = parts + [
                jnp.zeros((t_pad - total, f_pad // 8), dtype=jnp.uint8)
            ]
        return self.context._unpack_fn()(jnp.concatenate(parts, axis=0))

    def _run_file_pipelined_capture(
        self, d_path: str, n_threads: int = 1
    ) -> Tuple[List[Tuple[np.ndarray, np.ndarray]], CompressedData]:
        """Capture-replay pipelined ingest: one native call runs pass 1
        (capturing parsed token ids — ``n_threads`` parallel segment
        scans), rank assignment, and per-block pass-2 replay
        (native/preprocess.cc fa_preprocess_buffer_blocks — the raw
        bytes are tokenized exactly ONCE); each block's CSR arrives
        through a callback mid-call and its packed bitmap is submitted
        to the upload worker immediately, so transfers ride the link
        while the native side compresses the next block."""
        from concurrent.futures import ThreadPoolExecutor

        from fastapriori_tpu.native.loader import preprocess_buffer_blocks

        cfg = self.config
        ctx = self.context
        dev = ctx.mesh.devices.flat[0]
        blocks = []
        dev_futures = []
        w_futures = []  # raw int32 block weights (ingest-overlapped pair)
        state = {"f_pad": None, "upload_bytes": 0}
        # Mining-engine layout, decided by the PASS-1 probe (ISSUE 8
        # satellite): the native call fires on_pass1 once — after the
        # global tables exist, before any block replays — so the block
        # callbacks commit to bitmap packing OR CSR retention per the
        # probe's choice instead of always pre-committing to the bitmap
        # (the PR-7 residue that forfeited auto-vertical under this
        # ingest).  A stale .so without the probe export keeps the
        # bitmap commit; a FORCED vertical needs no probe at all.
        from fastapriori_tpu.native.loader import has_pass1_probe

        req_engine = self._requested_mine_engine()
        engine_state = {"engine": "bitmap"}
        use_probe = req_engine == "auto" and has_pass1_probe()
        if req_engine == "vertical":
            engine_state["engine"] = self._pipeline_engine_probe(0, 0, 0.0)

        def on_pass1(n_raw_, min_count_, f_, counts_):
            engine_state["engine"] = self._pipeline_engine_probe(
                n_raw_, f_, float(counts_.sum())
            )

        upool = ThreadPoolExecutor(max_workers=1)
        try:
            with self.metrics.timed("preprocess", path=d_path) as m:
                # mmap the file instead of copying ~1 GB of page cache
                # into a bytes object; the native scan reads straight
                # from the mapping (loader accepts any readonly buffer).
                import mmap

                mm = None
                with open(d_path, "rb") as fh:
                    try:
                        mm = mmap.mmap(
                            fh.fileno(), 0, access=mmap.ACCESS_READ
                        )
                        buf = np.frombuffer(mm, dtype=np.uint8)
                    except (ValueError, OSError):  # empty/unsupported
                        buf = fh.read()

                # Phase attribution for the bench record (VERDICT r4
                # weak #1): the native call runs pass 1 (tokenize+count)
                # before the first block callback fires, so
                # time-to-first-block ~= pass 1 + rank assignment and
                # the remainder is pass-2 replay; per-block bitmap
                # packing (host work riding the callback) is timed
                # separately so ingest regressions are attributable to
                # scan vs replay vs packing.
                t_ingest0 = time.perf_counter()

                def on_block(f_, offsets, items, weights):
                    state.setdefault(
                        "t_first_block", time.perf_counter()
                    )
                    if engine_state["engine"] == "vertical":
                        # Vertical layout: retain the block CSR for the
                        # tid-lane arena build instead of packing a
                        # bitmap (items may be a callback-lifetime arena
                        # view under copy_items=False — copy it; the
                        # offsets/weights copies are already owned).
                        items_c = (
                            items if items.flags.writeable else items.copy()
                        )
                        blocks.append((items_c, offsets, weights))
                        return
                    tp0 = time.perf_counter()
                    pk, f_pad = build_packed_bitmap_csr(
                        items, offsets, f_, 1, cfg.item_tile
                    )
                    state["pack_s"] = (
                        state.get("pack_s", 0.0)
                        + time.perf_counter()
                        - tp0
                    )
                    state["f_pad"] = f_pad
                    state["upload_bytes"] += pk.nbytes + weights.nbytes
                    dev_futures.append(
                        upool.submit(jax.device_put, pk, dev)
                    )
                    # Raw int32 weights ride along so the post-ingest
                    # pair program (ingest_pair_miner) can run its exact
                    # f32 Gram before the host finishes the weight-digit
                    # assembly; ~4 bytes/row — noise next to the bitmap.
                    w_futures.append(
                        upool.submit(jax.device_put, weights, dev)
                    )
                    if cfg.retain_csr:
                        # Block-RETAINING caller: storing `items` past
                        # this callback is only legal for the owned copy
                        # copy_items=True produces — the loader freezes
                        # its arena views (writeable=False), so a wiring
                        # mistake that stored a dangling view dies here,
                        # not as corrupted baskets three phases later.
                        assert items.flags.writeable, (
                            "retain_csr requires copy_items=True: `items`"
                            " is a read-only native-arena view valid only"
                            " inside the callback"
                        )
                        blocks.append((items, offsets, weights))
                        return
                    # retain_csr=False: ``items`` is a view into the
                    # native arena, valid only inside this callback —
                    # everything that needs item data (the packed bitmap
                    # above; the heavy rows below) consumes it NOW, and
                    # the ~0.7 GB global-CSR copy is skipped.  Past the
                    # split cap the weight split falls back to the
                    # legacy multi-digit path and never reads these, so
                    # stop re-materializing CSR slices for a heavily-
                    # duplicated dataset.
                    for i in np.flatnonzero(weights >= 128):
                        if len(heavy_pre) > self.HEAVY_SPLIT_CAP:
                            break
                        heavy_pre.append(
                            items[offsets[i] : offsets[i + 1]].copy()
                        )
                    blocks.append((None, offsets, weights))

                heavy_pre: list = []
                n_raw, min_count, freq_items, item_counts = (
                    preprocess_buffer_blocks(
                        buf,
                        cfg.min_support,
                        max(cfg.ingest_pipeline_blocks, 1),
                        on_block,
                        n_threads=n_threads,
                        copy_items=cfg.retain_csr,
                        on_pass1=on_pass1 if use_probe else None,
                    )
                )
                t_ingest1 = time.perf_counter()
                item_to_rank = {t: r for r, t in enumerate(freq_items)}
                f = len(freq_items)
                t_first = state.get("t_first_block", t_ingest1)
                m.update(
                    n_raw=n_raw, min_count=min_count, num_items=f,
                    pipelined=True, capture=True, threads=n_threads,
                    engine=engine_state["engine"],
                    pass1_s=round(t_first - t_ingest0, 3),
                    pass2_s=round(t_ingest1 - t_first, 3),
                    pack_s=round(state.get("pack_s", 0.0), 3),
                )
            if f < 2 or not blocks:
                return [], self._empty_compressed(
                    n_raw, min_count, freq_items, item_to_rank, item_counts
                )
            if engine_state["engine"] == "vertical":
                # Vertical mine off the retained block CSRs: no weight-
                # digit or heavy-row machinery, the lane engine takes
                # raw weights as bit-planes.
                self.metrics.emit(
                    "mine_engine", engine="vertical",
                    requested=req_engine, probe="pass1",
                )
                indices, offsets, w_np = self._concat_block_csr(blocks)
                data = CompressedData(
                    n_raw=n_raw,
                    min_count=min_count,
                    freq_items=freq_items,
                    item_to_rank=item_to_rank,
                    item_counts=item_counts,
                    basket_indices=indices,
                    basket_offsets=offsets,
                    weights=w_np,
                )
                return self._mine_vertical_safe(data), data
            # Same phase accounting as the threaded path: assembly, the
            # upload-tail wait, and the device concat/unpack book under
            # bitmap_build (the native call above is preprocess).
            n_chunks = max(1, -(-n_raw // cfg.level_txn_chunk))
            txn_multiple = self._txn_multiple(
                n_chunks, sum(len(bw) for _, _, bw in blocks)
            )
            with self.metrics.timed("bitmap_build") as m:
                f_pad = state["f_pad"]
                pair_pre = None
                # Ingest-overlapped pair phase (VERDICT r4 next #2): ONE
                # dispatch — concat + unpack + exact f32 Gram over the
                # raw block weights + threshold/gather/census — submitted
                # the moment the last block lands, so C5+C6 execute in
                # the shadow of the host-side weight/CSR assembly below.
                # Gated on f32 exactness (counts < 2^24); the mesh path
                # (txn/cand shards) keeps the classic flow.
                if (
                    n_raw < 2**24
                    and ctx.txn_shards == 1
                    and ctx.cand_shards == 1
                    # A mid-mine resume skips level 2 entirely — don't
                    # burn the overlapped pair dispatch for it.
                    and self._resume_levels is None
                ):
                    from fastapriori_tpu.ops.count import TRI_F_CAP

                    from fastapriori_tpu.ops.bitmap import pad_axis

                    total_rows = sum(len(bw) for _, _, bw in blocks)
                    t_pad_pre = pad_axis(total_rows, txn_multiple)
                    cap_key = ("pair_cap", t_pad_pre, f, min_count)
                    cap = max(
                        cfg.pair_cap, ctx.pair_cap_hint(cap_key) or 0
                    )
                    # Level 3 folded into the same dispatch (VERDICT r5
                    # next #2): valid only when the true pair count fits
                    # the static prefix budget and the level-3 survivors
                    # fit cap3 — the host checks both at fetch time and
                    # falls back to the classic level-3 dispatch,
                    # recording the grown budgets for repeat runs.
                    census = f_pad <= TRI_F_CAP
                    l3_keys = (
                        ("pair_l3p", t_pad_pre, f, min_count),
                        ("pair_l3c", t_pad_pre, f, min_count),
                    )
                    l3 = None
                    if census and cfg.pair_l3_rows > 0:
                        p3 = min(
                            max(
                                cfg.pair_l3_rows,
                                ctx.pair_cap_hint(l3_keys[0]) or 0,
                            ),
                            cap,
                        )
                        cap3 = max(
                            cfg.pair_l3_cap,
                            ctx.pair_cap_hint(l3_keys[1]) or 0,
                        )
                        l3 = (p3, cap3, n_chunks)
                    dev_blocks = [fu.result() for fu in dev_futures]
                    dev_ws = [fu.result() for fu in w_futures]
                    fn = ctx.ingest_pair_miner(
                        tuple(b.shape[0] for b in dev_blocks),
                        t_pad_pre, cap, census, l3=l3,
                    )
                    bitmap, pair_packed, counts_dev = fn(
                        tuple(dev_blocks), tuple(dev_ws),
                        jnp.int32(min_count), jnp.int32(f),
                    )
                    pair_pre = {
                        # Non-blocking audited fetch, consumed one host
                        # phase later (the transfer rides the link while
                        # the host assembles weights/CSR below).
                        "fetch": retry.fetch_async(pair_packed, "pair_pre"),
                        "counts_dev": counts_dev,
                        "cap": cap,
                        "cap_key": cap_key,
                        "l3": l3,
                        "l3_keys": l3_keys,
                    }
                asm = self._assemble_blocks(
                    blocks, txn_multiple, f,
                    heavy_pre=None if cfg.retain_csr else heavy_pre,
                )
                (
                    total, t_pad, w_np, w_digits_np, scales, indices,
                    offsets, heavy_b, heavy_w,
                ) = asm
                if pair_pre is None:
                    dev_blocks = [fu.result() for fu in dev_futures]
                    bitmap = self._device_concat_unpack(
                        dev_blocks, total, t_pad, f_pad
                    )
                    # The block-weight uploads were speculative (n_raw
                    # can only be known after pass 1); unconsumed here,
                    # so they must not skew the attributable upload
                    # figure.
                    state["upload_bytes"] -= sum(
                        bw.nbytes for _, _, bw in blocks
                    )
                else:
                    assert t_pad == t_pad_pre, (t_pad, t_pad_pre)
                w_digits = ctx.shard_weight_digits(w_digits_np)
                heavy = self._upload_heavy(heavy_b, heavy_w)
                heavy_rows, heavy_bytes = self._heavy_stats(heavy_b, heavy_w)
                m.update(
                    shape=[t_pad, f_pad],
                    digits=len(scales),
                    blocks=len(blocks),
                    heavy_rows=heavy_rows,
                    pair_overlapped=pair_pre is not None,
                    upload_bytes=state["upload_bytes"]
                    + w_digits_np.nbytes
                    + heavy_bytes,
                )
        finally:
            upool.shutdown()

        data = CompressedData(
            n_raw=n_raw,
            min_count=min_count,
            freq_items=freq_items,
            item_to_rank=item_to_rank,
            item_counts=item_counts,
            basket_indices=indices,
            basket_offsets=offsets,
            weights=w_np,
        )
        levels = self._mine_levels(
            data,
            preupload=(
                bitmap, w_digits, scales, n_chunks, t_pad, f_pad, heavy,
            ),
            try_fused=True,
            pair_pre=pair_pre,
        )
        return levels, data

    def run_file_sharded(
        self, d_path: str
    ) -> Tuple[List[Tuple[np.ndarray, np.ndarray]], CompressedData]:
        """Multi-host mining: every process calls this (SPMD); each
        preprocesses only its own byte range of ``d_path``
        (preprocess.preprocess_file_sharded) and uploads its rows of the
        global bitmap in place — the bulk data never crosses hosts, the
        distributed analog of the reference's C3/C4 Spark passes.  The
        returned level matrices are replicated (identical on every
        process)."""
        from fastapriori_tpu.preprocess import preprocess_file_sharded

        with trace.span("mine", path=d_path, sharded=True):
            with self.metrics.timed("preprocess", path=d_path) as m:
                data = preprocess_file_sharded(
                    d_path, self.config.min_support
                )
                m.update(
                    n_raw=data.n_raw,
                    min_count=data.min_count,
                    num_items=data.num_items,
                    local_count=data.total_count,
                    global_count=data.shard.global_count,
                )
            return self.mine_levels_raw(data), data

    def mine_levels_raw(
        self, data: CompressedData
    ) -> List[Tuple[np.ndarray, np.ndarray]]:
        """Levels >= 2 as lex-sorted member matrices with counts."""
        levels: List[Tuple[np.ndarray, np.ndarray]] = []
        total = (
            data.shard.global_count if data.shard else data.total_count
        )
        if data.num_items >= 2 and total > 0:
            # Mining-engine LAYOUT first (ROADMAP item 3): the vertical
            # tid-lane engine replaces the whole bitmap pipeline when
            # selected; the bitmap engines below stay the differential
            # oracle (and the fallback for every mesh/ingest shape the
            # vertical path does not cover).
            engine, req = self._mine_engine(data)
            self.metrics.emit("mine_engine", engine=engine, requested=req)
            if engine == "vertical":
                # Transient exhaustion inside falls to the bitmap level
                # loop via the cascade (_mine_vertical_safe), with the
                # consumed resume state restored first.
                return self._mine_vertical_safe(data)
            # Mid-mine resume and per-level checkpointing both force the
            # level engine: the whole-lattice fused dispatch has no
            # mid-points to seed from or checkpoint at (engine="fused"
            # under a checkpoint prefix mines in resumable SEGMENTS
            # inside _level_loop instead).
            if self.config.engine in ("fused", "auto") and not (
                self._resume_levels or self.config.checkpoint_prefix
            ):
                fused_reason = "row_budget_or_bound"
                try:
                    levels, partial = self._mine_fused(
                        data, auto=self.config.engine == "auto"
                    )
                except Exception as exc:
                    # Transient exhaustion at the fused fetch site:
                    # walk the chain to the level engine (its fetches
                    # carry their own retry budgets) rather than dying.
                    if not watchdog.transient(exc):
                        raise
                    levels, partial = None, None
                    fused_reason = "transient_exhausted"
                if levels is None:  # row budget / level bound / auto choice
                    self._fused_fallback(partial, reason=fused_reason)
                    levels = self._mine_levels(data, resume=partial or None)
            else:
                levels = self._mine_levels(data)
        return levels

    def mine_compressed(self, data: CompressedData) -> List[ItemsetWithCount]:
        """Levels >=2 via device kernels, then 1-itemsets appended."""
        return self._decode_levels(self.mine_levels_raw(data), data)

    def _decode_levels(
        self, levels, data: CompressedData
    ) -> List[ItemsetWithCount]:
        """Frozenset form for API-parity callers; the production pipeline
        (CLI) stays in matrix form and never pays this."""
        with self.metrics.timed("decode") as m:
            freq_itemsets: List[ItemsetWithCount] = []
            for mat, cnts in levels:
                freq_itemsets.extend(
                    # lint: host-data -- level matrices are host numpy by here
                    zip(map(frozenset, mat.tolist()), cnts.tolist())
                )
            m.update(n=len(freq_itemsets))
        freq_itemsets.extend(
            (frozenset((r,)), int(c)) for r, c in enumerate(data.item_counts)
        )
        return freq_itemsets

    # ------------------------------------------------------------------
    def _mine_fused(
        self, data: CompressedData, auto: bool = False
    ) -> Tuple[Optional[list], Optional[list]]:
        """Whole-loop on-device engine (ops/fused.py): one dispatch mines
        every level; on overflow retries with a budget sized from the true
        survivor counts.  Returns ``(level matrices, None)`` on success,
        or ``(None, complete_levels)`` when the budget cap or level bound
        is hit — the caller resumes the level engine from the last
        attempt's COMPLETE levels instead of recounting them.

        ``auto``: the engine="auto" policy — run fused only when the
        pre-pass says the whole lattice plausibly fits the row-budget
        ceiling (level-2 survivors with 2x headroom AND the level-3
        candidate census, ops/count.py ``_pair_triangles``); otherwise
        bail out BEFORE compiling a doomed program, so the zero-flag CLI
        path never pays the fused attempt + fallback on webdocs-class
        data (the reference has exactly one path, Main.scala:16-38 — the
        auto choice keeps ours one-path from the user's view)."""
        from fastapriori_tpu.ops import fused

        cfg = self.config
        ctx = self.context
        f = data.num_items

        # The static profile is fully determined by the data shape — compute
        # it BEFORE building or uploading anything so a known-doomed profile
        # skips the bitmap pack and transfer too.  Per-device rows split
        # into n_chunks equal scan chunks; the transaction axis pads to
        # txn_shards * n_chunks * 32.
        from fastapriori_tpu.ops.bitmap import pad_axis

        t0 = len(data.weights)
        shard = data.shard
        if shard is not None:
            # Sharded ingest: this process holds only its shard's baskets.
            # Shapes must be identical on every process (SPMD), so pad
            # each process's rows to the SAME local count (max over
            # shards) and derive the digit count from the GLOBAL max
            # weight.  Rows are process-major, matching the mesh's device
            # order, so the global bitmap assembles with zero cross-host
            # data movement (shard_rows_local) — the fused analog of the
            # level engine's sharded branch.
            n_proc = shard.num_processes
            if ctx.txn_shards % n_proc != 0 or ctx.cand_shards != 1:
                self.metrics.emit("fused_skip", reason="mesh_shape")
                return None, None
            local_devices = max(ctx.txn_shards // n_proc, 1)
            per_dev = -(-max(shard.local_counts) // local_devices)
            n_chunks = max(1, -(-per_dev // cfg.fused_txn_chunk))
            local_multiple = (
                max(cfg.txn_tile, 32) * local_devices * n_chunks
            )
            local_pad = max(
                pad_axis(c, local_multiple) for c in shard.local_counts
            )
            t_pad = local_pad * n_proc
            max_w = shard.max_weight
        else:
            per_dev = -(-t0 // ctx.txn_shards)
            n_chunks = max(1, -(-per_dev // cfg.fused_txn_chunk))
            txn_multiple = max(cfg.txn_tile, 32) * ctx.txn_shards * n_chunks
            local_pad = t_pad = pad_axis(t0, txn_multiple)
            max_w = int(data.weights.max()) if data.total_count else 1
        n_digits = 1
        while 128**n_digits <= max_w:
            n_digits += 1
        # CPU backends: run the counting matmuls in f32 (BLAS path) when
        # every partial sum provably fits f32's exact-integer range; TPU
        # always uses the int8 MXU path (ops/fused.py _weighted_counts).
        fast_f32 = ctx.platform == "cpu" and 127 * t_pad < 2**24
        # Key the hint on the padded data shape as well as the static
        # profile: a budget sized for one dataset must not leak onto a
        # differently-sized one (a large stale hint would compile an
        # oversized program; the [m_cap, m_cap] candidate matrix grows
        # quadratically).  Hints above this instance's cap are unusable.
        # min_count is part of the key because the DECISION inputs (n2,
        # census) depend on it — re-mining the same shape at a different
        # support must re-decide, not reuse a stale choice.
        profile = (
            t_pad, f, cfg.fused_l_max, n_digits, n_chunks, fast_f32,
            data.min_count,
        )
        if ctx.fused_failed(profile):
            # A previous run of this exact profile exhausted the row-budget
            # cap — don't re-pay the doomed attempts.
            self.metrics.emit("fused_skip", reason="known_overflow")
            return None, None
        if auto and ctx.auto_level(profile):
            # The auto choice already picked the level engine for this
            # profile — skip the pack/upload/pre-pass on repeat runs.
            self.metrics.emit("engine_auto", choice="level", memo=True)
            return None, None

        # Row-budget ceiling: the configured cap, clamped to what provably
        # fits the device HBM budget — never compile a program destined to
        # OOM (the fallback would catch it, but only after paying the
        # compile + OOM).
        m_cap_max = min(
            cfg.fused_m_cap_max,
            _fused_m_cap_memory_limit(
                cfg, ctx, t_pad, pad_axis(f + 1, cfg.item_tile), n_chunks
            ),
        )
        if m_cap_max < cfg.fused_m_cap_max:
            self.metrics.emit(
                "fused_m_cap_clamp", memory_limit=m_cap_max,
                configured=cfg.fused_m_cap_max,
            )
        if m_cap_max < _next_pow2(cfg.fused_l_max + 2):
            # Even the minimum viable row budget exceeds the HBM budget —
            # go straight to the (chunked, memory-bounded) level engine.
            self.metrics.emit("fused_skip", reason="memory")
            return None, None

        self._require_csr(data)
        with self.metrics.timed("bitmap_pack") as m:
            # This process's rows only (local_pad == t_pad when not
            # sharded); shard_rows_local assembles the global arrays
            # process-major without moving bulk data across hosts.
            packed_np, f_pad = build_packed_bitmap_csr(
                data.basket_indices,
                data.basket_offsets,
                f,
                local_pad,
                cfg.item_tile,
            )
            assert packed_np.shape[0] == local_pad, (
                packed_np.shape, local_pad
            )
            w_np = np.zeros(local_pad, dtype=np.int32)
            w_np[: data.total_count] = data.weights
            if shard is not None:
                # Process-local rows -> global array, no cross-host bulk.
                packed = ctx.shard_rows_local(packed_np)
                w = ctx.shard_rows_local(w_np)
            else:
                # Replicated ingest: every process holds the FULL arrays
                # (shard_rows_local would mistake them for local slices).
                packed = jax.device_put(packed_np, ctx.sharding_rows())
                w = jax.device_put(w_np, ctx.sharding_vector())
            m.update(
                shape=[t_pad, f_pad],
                digits=n_digits,
                upload_bytes=packed_np.nbytes + w_np.nbytes,
            )

        # Size the row budget from the actual level-2 survivor count (a
        # one-matmul pre-pass over the already-uploaded packed bitmap)
        # instead of guessing.  When a previous run of this process already
        # compiled-and-succeeded at some m_cap for this static profile, skip
        # the prepass entirely and start there — the overflow retry still
        # covers datasets that outgrow the hint, and the prepass's whole
        # purpose (avoiding a wasted multi-second compile) is already met.
        m_cap = ctx.fused_m_cap_hint(profile)
        if m_cap is not None and m_cap > m_cap_max:
            m_cap = None
        if m_cap is None:
            with self.metrics.timed("pair_prepass") as met:
                n2, tri = (
                    int(x)
                    for x in ctx.pair_counter(n_digits, n_chunks, fast_f32)(
                        packed, w, jnp.int32(data.min_count)
                    )
                )
                met.update(
                    n2=n2,
                    cand3=tri,
                    macs=n_digits * t_pad * f_pad * f_pad,
                    psum_bytes=4 * f_pad * f_pad,
                )
            m_cap = self._size_fused_budget(profile, n2, tri, m_cap_max, auto)
            if m_cap is None:  # auto chose the level engine
                return None, None
        # Packed-output meta row needs m_cap > l_max + 1; if the cap can't
        # accommodate that, the fused engine can't run at all.
        m_cap = max(m_cap, _next_pow2(cfg.fused_l_max + 2))

        count_reduce, sparse_thr, build, sp_hint_key = (
            self._fused_count_reduce_setup(
                data, t_pad, f_pad, n_digits, n_chunks, fast_f32,
                packed_input=True,
            )
        )
        return self._fused_attempt_loop(
            profile, build, packed, w, data.min_count, m_cap, m_cap_max,
            t_pad, f_pad, n_digits,
            count_reduce=count_reduce, sparse_thr=sparse_thr,
            sparse_hint_key=sp_hint_key,
        )

    def _size_fused_budget(
        self, profile, n2: int, tri: int, m_cap_max: int, auto: bool
    ) -> Optional[int]:
        """Row budget sized from the pre-pass survivor count — ONE
        definition for both fused flavors (packed upload and resident
        bitmap), so the engines can never drift in how they size or
        choose.  Returns None when the auto gate picks the level
        engine."""
        cfg = self.config
        want = max(
            _next_pow2(2 * max(n2, 1)),
            cfg.fused_m_cap,
            cfg.min_prefix_bucket,
        )
        if auto and not self._auto_fused_ok(profile, n2, tri, want, m_cap_max):
            return None
        return min(want, m_cap_max)

    def _auto_fused_ok(
        self, profile, n2: int, tri: int, want: int, m_cap_max: int
    ) -> bool:
        """The engine="auto" go/no-go: run fused only when the level-2
        survivor budget (2x headroom, same formula that sizes the
        program) fits the memory-derived ceiling AND the level-3
        candidate census does too.  n2 alone cannot see mid-lattice
        blowup — synthetic webdocs has n2=4458 (budget 16384, which FITS
        the ceiling) but 71K level-3 candidates and a 355K-row peak;
        the census catches exactly that class.  tri=-1 (item axis too
        wide for the census matmul) counts as no-objection: such datasets
        have sparse pair graphs.  Records the choice so repeat runs skip
        the pre-pass."""
        if want <= m_cap_max and (tri < 0 or tri <= m_cap_max):
            self.metrics.emit(
                "engine_auto", choice="fused", n2=n2, cand3=tri,
                m_cap_max=m_cap_max,
            )
            return True
        self.metrics.emit(
            "engine_auto", choice="level", n2=n2, cand3=tri,
            m_cap_max=m_cap_max,
        )
        self.context.record_auto_level(profile)
        return False

    def _fused_attempt_loop(
        self, profile, build, bitmap_arg, w, min_count, m_cap: int,
        m_cap_max: int, t_pad: int, f_pad: int, n_digits: int,
        count_reduce: str = "dense", sparse_thr=None,
        sparse_hint_key=None,
    ) -> Tuple[Optional[list], Optional[list]]:
        """The fused engine's overflow-retry loop, shared by the packed
        upload path (:meth:`_mine_fused`) and the resident-bitmap path
        (:meth:`_fused_resident`).  ``build(m_cap, reduce)`` returns
        ``(jitted program, sparse caps or None)``; returns
        ``(levels, None)`` on success or
        ``(None, salvaged_complete_levels_or_None)`` on failure.  A
        sparse union-compaction overflow re-runs the SAME row budget
        with the dense reduction (one ledger event) — exact either
        way."""
        from fastapriori_tpu.ops import fused

        cfg = self.config
        ctx = self.context
        rows = None  # last attempt's output (None if no attempt ran)
        m_cap_run = 0
        reduce = count_reduce
        while m_cap <= m_cap_max:
            with self.metrics.timed(
                "fused_mine", m_cap=m_cap, reduce=reduce
            ) as met:
                fn, caps = build(m_cap, reduce)
                args = [bitmap_arg, w, jnp.int32(min_count)]
                if caps is not None:
                    args.append(jnp.asarray(sparse_thr, dtype=jnp.int32))
                # ONE device->host transfer for the whole mining result.
                packed_out = retry.fetch(
                    # lint: fetch-site -- the fused engine's single audited fetch, retry-wrapped
                    lambda: np.asarray(fn(*args)),
                    "fused",
                )
                (
                    a_rows, a_cols, a_counts, n_lvl, incomplete, overflow,
                    sparse_ovf, sparse_nu,
                ) = fused.unpack_fused_result(packed_out, cfg.fused_l_max)
                if sparse_ovf:
                    # Union compaction overflowed: every level's counts
                    # are unusable (and n_lvl is undefined) — redo this
                    # budget dense.
                    met.update(sparse_overflow=True)
                else:
                    m_cap_run = m_cap
                    rows, cols, counts = a_rows, a_cols, a_counts
                    # MAC estimate for the MFU report: level 2 is D Gram
                    # matmuls over [t_pad, f_pad]; each while-loop
                    # iteration (one per level >= 3, plus the
                    # terminating check's last full iteration) does the
                    # candidate-gen pair of [m_cap, m_cap/f_pad] matmuls
                    # plus the membership + D counting matmuls over
                    # [t_pad, m_cap, f_pad].
                    n_iters = max(int(np.count_nonzero(n_lvl)), 1)
                    if caps is not None:
                        from fastapriori_tpu.ops.count import (
                            sparse_psum_bytes,
                        )

                        g2, p2 = sparse_psum_bytes(
                            f_pad * f_pad, caps[0], ctx.txn_shards,
                            ctx.exchange_spec,
                        )
                        gl, pl = sparse_psum_bytes(
                            m_cap * f_pad, caps[1], ctx.txn_shards,
                            ctx.exchange_spec,
                        )
                        psum_b = p2 + (n_iters - 1) * pl
                        gather_b = g2 + (n_iters - 1) * gl
                    else:
                        psum_b = 4 * f_pad * f_pad + (n_iters - 1) * (
                            4 * m_cap * f_pad
                        )
                        gather_b = 0
                    met.update(
                        incomplete=incomplete,
                        overflow=overflow,
                        macs=n_digits * t_pad * f_pad * f_pad
                        + (n_iters - 1)
                        * (
                            2 * m_cap * m_cap * f_pad
                            + (1 + n_digits) * t_pad * m_cap * f_pad
                        ),
                        psum_bytes=psum_b,
                        gather_bytes=gather_b,
                    )
            if sparse_ovf:
                ledger.record(
                    "count_sparse_overflow", site="fused",
                    m_cap=m_cap, caps=list(caps), n_union=sparse_nu,
                )
                watchdog.downgrade(
                    "count_reduce", "sparse", "dense",
                    reason="union_overflow", site="fused",
                )
                if sparse_hint_key is not None and sparse_nu > 0:
                    # Memoize the true union size (the pair-cap-hint
                    # pattern): repeat runs size the compaction right
                    # instead of re-paying this wasted sparse dispatch
                    # plus the dense redo.
                    ctx.record_pair_cap(
                        sparse_hint_key, _next_pow2(sparse_nu)
                    )
                reduce = "dense"
                continue  # same budget, dense reduction (cannot recurse)
            if not incomplete:
                ctx.record_fused_m_cap(profile, m_cap)
                return (
                    fused.decode_level_matrices(rows, cols, counts, n_lvl),
                    None,
                )
            if not overflow:
                # Stopped by the l_max level bound — a larger row budget
                # cannot help; go straight to the level engine.
                break
            # The meta row holds TRUE (pre-cap) survivor counts, so the
            # overflowing level's need is known exactly — jump straight to
            # a budget that fits it (later levels may need more still; the
            # retry loop covers that).  Each skipped attempt saves a full
            # compile of the next-larger [m_cap, m_cap] program.
            needed = int(max(np.max(n_lvl), m_cap + 1))
            m_cap = max(2 * m_cap, _next_pow2(needed))
        ctx.record_fused_fail(profile)
        if rows is None:  # no attempt ran (budget floor above the cap)
            return None, None
        # Salvage the last attempt's COMPLETE levels (those whose survivor
        # count fit the budget) so the level engine resumes mid-lattice.
        partial = fused.decode_level_matrices(
            rows, cols, counts, n_lvl, max_rows=m_cap_run
        )
        return None, partial

    def _fused_resident(
        self,
        data: CompressedData,
        bitmap,
        n_chunks: int,
        t_pad: int,
        n2: Optional[int] = None,
        tri: int = -1,
    ) -> Tuple[Optional[list], Optional[list], bool]:
        """Fused whole-loop attempt over the RESIDENT unpacked bitmap —
        the pipelined-ingest flavor of :meth:`_mine_fused` (VERDICT r3
        task 1: one ingest, one device bitmap, both engines).  Returns
        ``(levels, salvaged_partial, need_n2)``: levels on success;
        ``need_n2=True`` means the caller should run the level-2 pair
        gather (whose survivor count + level-3 census it needs to size
        the budget / make the auto choice) and call back with ``n2`` and
        ``tri``."""
        cfg = self.config
        ctx = self.context
        f = data.num_items
        f_pad = bitmap.shape[1]
        max_w = int(data.weights.max()) if data.total_count else 1
        n_digits = 1
        while 128**n_digits <= max_w:
            n_digits += 1
        # The fused kernel's own f32-exactness bound (127·T_pad < 2^24;
        # ops/fused.py _weighted_counts), NOT the level kernels' n_raw
        # bound — the two engines' partial-sum shapes differ.
        fast_f32 = ctx.platform == "cpu" and 127 * t_pad < 2**24
        # min_count in the key for the same reason as _mine_fused's
        # profile: the auto choice depends on it.
        profile = (
            "resident", t_pad, f, cfg.fused_l_max, n_digits, n_chunks,
            fast_f32, data.min_count,
        )
        auto = cfg.engine == "auto"
        if ctx.fused_failed(profile):
            self.metrics.emit("fused_skip", reason="known_overflow")
            return None, None, False
        if auto and ctx.auto_level(profile):
            self.metrics.emit("engine_auto", choice="level", memo=True)
            return None, None, False
        m_cap_max = min(
            cfg.fused_m_cap_max,
            _fused_m_cap_memory_limit(
                cfg, ctx, t_pad, f_pad, n_chunks, unpacked_resident=True
            ),
        )
        if m_cap_max < _next_pow2(cfg.fused_l_max + 2):
            self.metrics.emit("fused_skip", reason="memory")
            return None, None, False
        m_cap = ctx.fused_m_cap_hint(profile)
        if m_cap is not None and m_cap > m_cap_max:
            m_cap = None
        if m_cap is None:
            if n2 is None:
                return None, None, True
            m_cap = self._size_fused_budget(profile, n2, tri, m_cap_max, auto)
            if m_cap is None:  # auto chose the level engine
                return None, None, False
        m_cap = max(m_cap, _next_pow2(cfg.fused_l_max + 2))
        # The weights upload this path pays (the ingest uploaded base-128
        # digits for the level kernels, the fused program wants raw
        # int32) is 4·T bytes — noise next to the bitmap, and only paid
        # when fused actually runs.
        w_np = np.zeros(t_pad, dtype=np.int32)
        w_np[: data.total_count] = data.weights
        w = jax.device_put(w_np, ctx.sharding_vector())

        count_reduce, sparse_thr, build, sp_hint_key = (
            self._fused_count_reduce_setup(
                data, t_pad, f_pad, n_digits, n_chunks, fast_f32,
                packed_input=False,
            )
        )
        lv, partial = self._fused_attempt_loop(
            profile, build, bitmap, w, data.min_count, m_cap, m_cap_max,
            t_pad, f_pad, n_digits,
            count_reduce=count_reduce, sparse_thr=sparse_thr,
            sparse_hint_key=sp_hint_key,
        )
        return lv, partial, False

    def _mine_vertical_safe(
        self, data: CompressedData
    ) -> List[Tuple[np.ndarray, np.ndarray]]:
        """:meth:`_mine_vertical` with the transient-exhaustion arm of
        the cascade — EVERY vertical entry point (mine() and both
        file-pipeline ingest paths) goes through here, so the
        walk-the-chain contract holds on the real CLI path too: a
        vertical failure that survived its retry budgets falls to the
        bitmap level loop (bit-exact by the differential contract)
        instead of killing the mine.  The mid-mine resume state the
        vertical attempt consumed (:meth:`_take_resume`) is restored
        first, so a resumed run re-seeds the fallback from its
        checkpoint instead of re-mining the lattice from scratch."""
        resume_state = (
            self._resume_levels, self._resume_meta, self._resume_label
        )
        try:
            return self._mine_vertical(data)
        except _MineEngineClamp as exc:
            # Mid-mine consensus clamp (ISSUE 17 satellite): a peer
            # walked mine_engine vertical→bitmap and the level-boundary
            # adoption clamped this rank at level k.  The adoption
            # already recorded the cascade walk (reason="quorum"); here
            # the completed levels seed the bitmap loop so nothing is
            # recounted (bit-exact by the differential contract).
            self.set_resume_levels(exc.levels, None, "engine_clamp")
            ledger.record(
                "mine_engine_fallback",
                once_key="quorum",
                reason="quorum",
                k=exc.k,
            )
            return self._mine_levels(data)
        except Exception as exc:
            if not watchdog.transient(exc):
                raise
            (
                self._resume_levels,
                self._resume_meta,
                self._resume_label,
            ) = resume_state
            watchdog.downgrade(
                "mine_engine", "vertical", "bitmap",
                reason="transient_exhausted",
                error=f"{type(exc).__name__}: {exc}"[:200],
            )
            ledger.record(
                "mine_engine_fallback",
                once_key="transient_exhausted",
                reason="transient_exhausted",
            )
            return self._mine_levels(data)

    def _fused_resident_safe(self, *args, **kw):
        """:meth:`_fused_resident` with the transient-exhaustion arm of
        the cascade: a fused fetch that survived its retry budget walks
        the chain to the level engine (whose fetches carry their own
        budgets) instead of killing the mine."""
        try:
            return self._fused_resident(*args, **kw)
        except Exception as exc:
            if not watchdog.transient(exc):
                raise
            watchdog.downgrade(
                "engine", "fused", "level",
                reason="transient_exhausted",
                error=f"{type(exc).__name__}: {exc}"[:200],
            )
            return None, None, False

    # ------------------------------------------------------------------
    def _mine_levels(
        self,
        data: CompressedData,
        resume: Optional[list] = None,
        preupload: Optional[tuple] = None,
        try_fused: bool = False,
        pair_pre: Optional[dict] = None,
    ) -> List[Tuple[np.ndarray, np.ndarray]]:
        """Level matrices ``[(int32[N, k], int64[N] counts), ...]`` for
        levels >= 2, lex-sorted.  ``resume``: complete levels salvaged
        from a failed fused attempt — the loop continues from the deepest
        one instead of recounting them.  ``preupload``: device-resident
        ``(bitmap, w_digits, scales, n_chunks, t_pad, f_pad, heavy)``
        from the pipelined ingest — the bitmap build/upload below is
        skipped.  ``pair_pre``: the ingest-overlapped pair program's
        in-flight outputs (ingest_pair_miner) — level 2 becomes a fetch,
        not a dispatch."""
        cfg = self.config
        ctx = self.context
        f = data.num_items
        min_count = data.min_count
        if resume is None:
            # Mid-mine checkpoint resume rides the same mechanism as the
            # fused-salvage resume; every mining entry point funnels
            # through here, so the take happens exactly once.
            resume = self._take_resume(data)

        if preupload is not None:
            bitmap, w_digits, scales, n_chunks, t_pad, f_pad, heavy = (
                preupload
            )
            fast_f32 = self._fast_f32(data.n_raw)
            return self._level_loop(
                data, resume, bitmap, w_digits, scales, n_chunks,
                fast_f32, t_pad, heavy, try_fused=try_fused,
                pair_pre=pair_pre,
            )

        self._require_csr(data)
        with self.metrics.timed("bitmap_build") as m:
            # Pad the txn axis so per-device rows split into n_chunks equal
            # scan chunks (ops/count.py local_level_gather).
            shard = data.shard
            total = shard.global_count if shard else data.total_count
            # Per-device rows are padded to the LARGEST shard in sharded
            # mode, so size the scan chunking from that (an n_chunks
            # derived from the even global split would under-chunk and
            # break the per-chunk HBM bound under shard imbalance).
            if shard is not None:
                # (divisibility is asserted in the sharded branch below)
                per_dev = -(
                    -max(shard.local_counts)
                    // max(ctx.txn_shards // shard.num_processes, 1)
                )
            else:
                per_dev = -(-total // ctx.txn_shards)
            n_chunks = max(1, -(-per_dev // cfg.level_txn_chunk))
            fast_f32 = self._fast_f32(data.n_raw)
            if shard is None:
                # Alignment guard sized against PER-SHARD rows (the
                # multiple below is per-shard x txn_shards).
                txn_multiple = (
                    self._txn_multiple(n_chunks, per_dev) * ctx.txn_shards
                )
                packed_np, f_pad = build_packed_bitmap_csr(
                    data.basket_indices,
                    data.basket_offsets,
                    f,
                    txn_multiple,
                    cfg.item_tile,
                )
                t_pad = packed_np.shape[0]
                w_digits_np, scales, heavy_b, heavy_w = (
                    self._split_weights(
                        data.weights, t_pad, data.basket_indices,
                        data.basket_offsets, f,
                    )
                )
                # Bit-packed transfer + on-device unpack: 8x less
                # host->device traffic (the dominant cost of this phase
                # on tunneled chips).
                bitmap = ctx.upload_packed(packed_np)
                w_digits = ctx.shard_weight_digits(w_digits_np)
                heavy = self._upload_heavy(heavy_b, heavy_w)
            else:
                # Multi-host sharded ingest: this process holds only its
                # shard's baskets; each process pads its rows to the SAME
                # local count (max over shards, aligned so per-device
                # rows split into n_chunks equal scan chunks) and the
                # global bitmap is assembled with zero cross-host data
                # movement.  Digit count is globally uniform (SPMD needs
                # identical static shapes on every process).
                from fastapriori_tpu.ops.bitmap import pad_axis

                n_proc = shard.num_processes
                assert ctx.txn_shards % n_proc == 0 and ctx.cand_shards == 1, (
                    "sharded ingest needs a 1-D txn mesh with devices "
                    f"divisible by processes (txn_shards={ctx.txn_shards}, "
                    f"cand={ctx.cand_shards}, processes={n_proc})"
                )
                local_devices = ctx.txn_shards // n_proc
                local_multiple = (
                    max(cfg.txn_tile, 32) * local_devices * n_chunks
                )
                local_pad = max(
                    pad_axis(c, local_multiple) for c in shard.local_counts
                )
                packed_np, f_pad = build_packed_bitmap_csr(
                    data.basket_indices,
                    data.basket_offsets,
                    f,
                    local_pad,  # every shard pads to the same row count
                    cfg.item_tile,
                )
                assert packed_np.shape[0] == local_pad, (
                    packed_np.shape, local_pad
                )
                t_pad = local_pad * n_proc
                n_digits = 1
                while 128**n_digits <= shard.max_weight:
                    n_digits += 1
                w_digits_np, scales = weight_digits(
                    data.weights, local_pad, min_digits=n_digits
                )
                bitmap = ctx.upload_packed_local(packed_np)
                w_digits = ctx.shard_weight_digits_local(w_digits_np)
                # Multi-host keeps the legacy multi-digit path (the
                # remainder arrays would need globally uniform shapes
                # and replicated cross-host assembly for little gain).
                heavy = None
            m.update(
                shape=[t_pad, f_pad],
                digits=len(scales),
                fast_f32=fast_f32,
                upload_bytes=packed_np.nbytes + w_digits_np.nbytes,
            )
        return self._level_loop(
            data, resume, bitmap, w_digits, scales, n_chunks,
            fast_f32, t_pad, heavy,
        )

    def _fast_f32(self, n_raw: int) -> bool:
        """CPU backends: ONE f32 matmul per phase (BLAS) instead of D
        int8 matmuls — XLA-CPU integer matmuls are orders slower.  Exact
        while every count < 2^24 (counts are bounded by the raw
        transaction total); TPU always keeps the int8 MXU path.  One
        definition for both ingest modes — the kernel choice must never
        depend on how the bitmap reached the device."""
        return self.context.platform == "cpu" and n_raw < 2**24

    def _level_loop(
        self,
        data: CompressedData,
        resume: Optional[list],
        bitmap,
        w_digits,
        scales,
        n_chunks: int,
        fast_f32: bool,
        t_pad: int,
        heavy: Optional[tuple] = None,
        try_fused: bool = False,
        pair_pre: Optional[dict] = None,
        vertical: bool = False,
    ) -> List[Tuple[np.ndarray, np.ndarray]]:
        """Elastic arm around :meth:`_level_loop_impl` (ISSUE 17): on
        ``PeerLost``/``MeshEpochAbort`` the survivors abort the
        in-flight level, re-rendezvous under an incremented mesh epoch
        (:func:`quorum.elastic_rejoin` — which re-raises classified
        when elastic continuation is disabled or the strict
        ``FA_EPOCH_RETRY_MAX`` budget exhausts), then re-enter the loop
        seeded from the last completed level boundary: the consensus
        sync at ``mine.start`` re-adopts floors for the shrunk member
        set, the engines re-resolve through those floors, and the
        ``exchange_spec`` + W_s shard-weight totals re-derive for the
        survivor topology (the wstotals cache/latch reset below) —
        bit-exact per level by the same associativity argument that
        proved the hierarchical exchange correct."""
        progress: list = []
        attempt_resume = resume
        while True:
            try:
                return self._level_loop_impl(
                    data, attempt_resume, bitmap, w_digits, scales,
                    n_chunks, fast_f32, t_pad, heavy,
                    try_fused=try_fused, pair_pre=pair_pre,
                    vertical=vertical, progress=progress,
                )
            except (quorum.PeerLost, quorum.MeshEpochAbort) as exc:
                quorum.elastic_rejoin(exc)
                from fastapriori_tpu.obs import flight

                # Survivor continuation: everything derived from the
                # OLD member set is re-derived on re-entry — the
                # exchanged W_s totals (cache + one-shot verify latch
                # reset here), the exchange_spec, the engine floors.
                self._wstotals_cache.clear()
                self._wstotals_verified = False
                done = [lv for lv in progress if lv[1] is not None]
                if done:
                    attempt_resume = done
                # The fused offer and the ingest-overlapped pair
                # program belong to the aborted epoch's dispatch
                # stream; the re-entered loop re-counts from the
                # boundary with the plain engines.
                try_fused = False
                pair_pre = None
                progress = []
                flight.note(
                    "mesh_epoch_reseed",
                    mesh_epoch=quorum.mesh_epoch(),
                    members=quorum.mesh_members(),
                    resume_from_k=(
                        int(done[-1][0].shape[1]) if done else None
                    ),
                    levels_kept=len(done),
                    # The survivor topology this epoch re-mines under
                    # (exchange_spec re-derives at the mine.start
                    # re-entry — this stamps the local mesh shape).
                    respec=self.context.respec_summary(),
                )

    def _level_loop_impl(
        self,
        data: CompressedData,
        resume: Optional[list],
        bitmap,
        w_digits,
        scales,
        n_chunks: int,
        fast_f32: bool,
        t_pad: int,
        heavy: Optional[tuple] = None,
        try_fused: bool = False,
        pair_pre: Optional[dict] = None,
        vertical: bool = False,
        progress: Optional[list] = None,
    ) -> List[Tuple[np.ndarray, np.ndarray]]:
        """The level-synchronous loop over a device-resident bitmap
        (levels 2..k; reference C6+C7+C8+C9).  ``try_fused``: the
        pipelined-ingest caller — offer the whole lattice to the fused
        engine first (:meth:`_fused_resident`, engine= "fused"/"auto"),
        over this same resident bitmap.  ``pair_pre``: in-flight
        ingest-overlapped pair outputs — both the engine auto-choice's
        sizing inputs (n2/census) and level 2 itself reduce to ONE host
        fetch of its packed survivor array.

        ``vertical``: ``bitmap`` is the tid-lane arena
        (``uint32[F_pad+1, NL]``, lanes sharded over txn) and
        ``w_digits``/``scales`` the weight bit-planes — the SAME loop
        drives the Eclat-style kernels (ops/vertical.py) so candidate
        generation, deferred counts, mid-mine drains, checkpointing and
        resume stay engine-independent; the fused offer, the
        heavy-weight split and the shallow-tail fold are bitmap-engine
        machinery and stay off.

        ``progress``: the elastic wrapper's live view of completed
        levels — the SAME list object the loop mutates in place, so an
        abort mid-level still leaves every completed boundary visible
        to the re-seed."""
        cfg = self.config
        ctx = self.context
        f = data.num_items
        min_count = data.min_count
        # Consensus exchange BEFORE any engine resolution (ISSUE 12):
        # adopt peers' cascade positions first, so every resolution
        # below starts at the domain's agreed floor and the first
        # dispatch is already lockstep.  No-op without a domain.
        quorum.sync("mine.start")
        # Count-reduction engine (ROADMAP item 2): sparse threshold
        # exchange on multi-device meshes, dense psum elsewhere — and
        # always available as the differential oracle / overflow
        # fallback.  Resolved once per mine; the per-shard prune
        # thresholds are static (shard weight totals).
        count_reduce, _cr_req = self._count_reduce_engine(data)
        # Exchange topology for every sparse collective this mine
        # issues (ISSUE 15): resolved once, installed on the context —
        # the kernel builders key their compiles on it, so a later
        # hier→flat clamp recompiles (and re-issues) flat collectives
        # from the next dispatch on.
        ctx.set_exchange_spec(
            self._exchange_spec() if count_reduce == "sparse" else None
        )
        sparse_thr = (
            self._sparse_thresholds(data, t_pad, heavy is not None)
            if count_reduce == "sparse"
            else None
        )
        self.metrics.emit(
            "count_reduce", engine=count_reduce, requested=_cr_req
        )
        # Frequent k-sets live as a lex-sorted int32 [M, k] matrix between
        # levels; frozensets are materialized ONCE at the end (the per-set
        # Python objects were the dominant cost on dense data).
        levels: List[Tuple[np.ndarray, np.ndarray]] = (
            [] if progress is None else progress
        )

        def pair_fetch():
            """Host values from the overlapped pair program (memoized —
            the fused auto-choice, level 2, and level 3 share one
            fetch, issued async at dispatch time)."""
            if "host" not in pair_pre:
                out = pair_pre.pop("fetch").result()
                cap = pair_pre["cap"]
                pair_pre["host"] = (
                    out[:cap],
                    out[cap : 2 * cap],
                    int(out[2 * cap]),
                    int(out[2 * cap + 1]),
                )
                if pair_pre.get("l3") is not None:
                    p3, cap3, _nc = pair_pre["l3"]
                    base = 2 * cap + 2
                    pair_pre["l3_host"] = (
                        out[base : base + cap3],
                        out[base + cap3 : base + 2 * cap3],
                        int(out[base + 2 * cap3]),
                        p3,
                        cap3,
                    )
            return pair_pre["host"]

        fused_ok = (
            not resume
            and try_fused
            and cfg.engine in ("fused", "auto")
            and not cfg.checkpoint_prefix  # no mid-points to checkpoint
            and ctx.cand_shards == 1
            and data.shard is None
            # Consensus floor: a peer already walked engine past fused.
            and quorum.stage_allowed("engine", "fused")
        )
        need_n2 = False
        if fused_ok:
            # Warm path: a recorded budget hint (or a recorded auto
            # choice) resolves the engine without any pair pre-pass —
            # repeat runs of a fused-able dataset go straight to the ONE
            # mining dispatch.
            lv, partial, need_n2 = self._fused_resident_safe(
                data, bitmap, n_chunks, t_pad
            )
            if lv is None and need_n2 and pair_pre is not None:
                # Cold path with the overlapped pair in flight: its
                # n2/census ARE the sizing pre-pass — no extra dispatch.
                _idx, _cnt, n2, tri = pair_fetch()
                lv, partial, _ = self._fused_resident_safe(
                    data, bitmap, n_chunks, t_pad, n2=n2, tri=tri
                )
                need_n2 = False
            if lv is not None:
                return lv
            if partial:
                self._fused_fallback(partial)
                resume = partial

        if resume:
            levels.extend(resume)
            cur = resume[-1][0]
            self.metrics.emit(
                "level_resume", from_k=int(cur.shape[1]) + 1
            )
        else:
            # Level 2 (C6): one Gram matmul, thresholded ON DEVICE — only
            # the surviving pairs are transferred (local_pair_gather).
            # With the ingest-overlapped pair program in flight, this
            # whole phase is a FETCH of its packed output (~2·cap·4
            # bytes), not a dispatch.
            with self.metrics.timed("level", k=2) as m:
                f_pad_p = (
                    bitmap.shape[0] - 1 if vertical else bitmap.shape[1]
                )
                rinfo = {
                    "reduce": "dense",
                    "psum_bytes": 4 * f_pad_p * f_pad_p,
                    "gather_bytes": 0,
                }
                if pair_pre is not None:
                    idx, cnt, n2, tri = pair_fetch()
                    cap = pair_pre["cap"]
                    # The pair dispatch rode the ingest shadow: the
                    # mining loop pays zero dispatches here (the ingest
                    # accounting carries it) unless the cap overflowed.
                    d_disp = 0
                    if n2 > cap:
                        ledger.record(
                            "pair_cap_overflow", n2=int(n2), cap=cap
                        )
                        cap = _next_pow2(n2)
                        idx, cnt, _ = ctx.pair_regather(
                            pair_pre["counts_dev"], min_count, f, cap
                        )
                        ctx.record_pair_cap(pair_pre["cap_key"], cap)
                        d_disp = 1
                    pair_pre["counts_dev"] = None  # free [F, F] promptly
                    d_eff = 1  # one exact f32 Gram inside the mega dispatch
                    m.update(overlapped=True, dispatches=d_disp)
                else:
                    # Start from the recorded budget when this profile
                    # overflowed before, so repeat runs never re-pay the
                    # retry's extra dispatch.
                    cap_key = ("pair_cap", t_pad, f, min_count)
                    cap = max(
                        cfg.pair_cap, ctx.pair_cap_hint(cap_key) or 0
                    )
                    hb, hw = heavy if heavy is not None else (None, None)
                    # Both engines reduce the same [F, F] space (the
                    # vertical pair runs per-plane Grams over the lane
                    # arena — ops/vertical.py); only the hint-key
                    # prefix differs so the two engines' overflow
                    # budgets never cross-pollinate.
                    sp_cap = None
                    spk = (
                        "sparse_vpair" if vertical else "sparse_pair",
                        t_pad, f, min_count,
                    )
                    if (
                        count_reduce == "sparse"
                        and f_pad_p * f_pad_p >= cfg.count_sparse_min
                    ):
                        sp_cap = self._sparse_cap(
                            f_pad_p * f_pad_p, hint_key=spk
                        )
                    def _pair_dispatch(sp_cap_, thr_):
                        if vertical:
                            return ctx.vertical_pair_gather(
                                bitmap, w_digits, scales, min_count, f,
                                cap, cfg.level_txn_chunk,
                                fast_f32=fast_f32,
                                sparse_cap=sp_cap_, sparse_thr=thr_,
                            )
                        return ctx.pair_gather(
                            bitmap, w_digits, scales, min_count, f,
                            cap,
                            heavy_b=hb, heavy_w=hw,
                            fast_f32=fast_f32,
                            sparse_cap=sp_cap_, sparse_thr=thr_,
                        )

                    try:
                        idx, cnt, n2, tri, counts_dev, rinfo = (
                            _pair_dispatch(sp_cap, sparse_thr)
                        )
                    except Exception as exc:
                        # Transient exhaustion at the SPARSE pair fetch
                        # walks the cascade like the level path
                        # (exchange hier→flat first, then count_reduce
                        # sparse→dense) and redoes the pair dense —
                        # exact either way; the dense fetch is its own
                        # audited site with a fresh retry budget.
                        # Dense-engine exhaustion has nowhere to walk
                        # and re-raises classified.
                        if sp_cap is None or not watchdog.transient(
                            exc
                        ):
                            raise
                        site_p = "vpair" if vertical else "pair"
                        if ctx.exchange_spec is not None:
                            watchdog.downgrade(
                                "exchange", "hier", "flat",
                                reason="transient_exhausted",
                                site=site_p,
                            )
                            ctx.set_exchange_spec(None)
                        watchdog.downgrade(
                            "count_reduce", "sparse", "dense",
                            reason="transient_exhausted", site=site_p,
                            error=f"{type(exc).__name__}: {exc}"[:200],
                        )
                        count_reduce, sparse_thr = "dense", None
                        idx, cnt, n2, tri, counts_dev, rinfo = (
                            _pair_dispatch(None, None)
                        )
                    if rinfo.get("fallback") == "sparse_overflow":
                        # Remember the true union size so repeat runs
                        # size the compaction right (pair_cap pattern).
                        ctx.record_pair_cap(
                            spk, _next_pow2(rinfo["n_union"])
                        )
                    d_disp = 1
                    if n2 > cap:
                        # Overflow: re-extract at the exact budget over
                        # the RESIDENT count matrix — no Gram re-run, no
                        # matmul compile (mesh.pair_regather).
                        ledger.record(
                            "pair_cap_overflow", n2=int(n2), cap=cap
                        )
                        cap = _next_pow2(n2)
                        idx, cnt, _ = ctx.pair_regather(
                            counts_dev, min_count, f, cap
                        )
                        ctx.record_pair_cap(cap_key, cap)
                        d_disp = 2
                    del counts_dev  # free the [F, F] matrix promptly
                    d_eff = 1 if fast_f32 else len(scales)
                    m.update(dispatches=d_disp)
                f_pad = f_pad_p if vertical else bitmap.shape[1]
                idx, cnt = idx[:n2], cnt[:n2]
                cur = np.stack([idx // f_pad, idx % f_pad], axis=1).astype(
                    np.int32
                )  # row-major upper triangle => already lex-sorted
                levels.append((cur, cnt.astype(np.int64)))
                if vertical:
                    # The vertical pair IS a matmul phase (per-plane
                    # Grams over the unpacked lane chunks): d_eff is
                    # the plane count (1 under fast_f32).
                    m.update(engine="vertical")
                m.update(macs=d_eff * t_pad * f_pad * f_pad)
                m.update(
                    candidates=f * (f - 1) // 2,
                    frequent=n2,
                    cand3=tri,
                    reduce=rinfo["reduce"],
                    psum_bytes=rinfo["psum_bytes"],
                    gather_bytes=rinfo["gather_bytes"],
                    **{
                        kf: rinfo[kf]
                        for kf in (
                            "exchange", "intra_bytes", "inter_bytes",
                            "exchange_groups",
                        )
                        if kf in rinfo
                    },
                )
            if need_n2:
                # Cold path: the pair gather above doubles as the fused
                # engine's sizing pre-pass (it IS level 2 if the choice
                # lands on the level engine — no wasted dispatch either
                # way).
                lv, partial, _ = self._fused_resident_safe(
                    data, bitmap, n_chunks, t_pad, n2=n2, tri=tri
                )
                if lv is not None:
                    return lv
                if partial:
                    # Salvaged complete levels include level 2 (bit-exact
                    # with the gather above — both are exact weighted
                    # counts over the same bitmap).
                    self._fused_fallback(partial)
                    levels[:] = partial
                    cur = partial[-1][0]
            self._checkpoint_levels(levels, data)
            # Level 3 from the SAME overlapped dispatch + fetch (the
            # dispatch fold): valid only when the true pair count fit
            # the static prefix budget and the survivors fit cap3 —
            # otherwise fall back to the classic level-3 dispatch below,
            # growing the recorded budgets so repeat runs fold.  Skipped
            # when a fused salvage already advanced past level 2.
            l3h = (
                pair_pre.get("l3_host") if pair_pre is not None else None
            )
            if (
                l3h is not None
                and len(levels) == 1
                and cur.shape[1] == 2
                and cur.shape[0] >= 3
            ):
                idx3, cnt3, n3, p3, cap3 = l3h
                n2_now = cur.shape[0]
                if n2_now <= p3 and n3 <= cap3:
                    with self.metrics.timed("level", k=3) as m:
                        f_pad3 = bitmap.shape[1]
                        idx3, cnt3 = idx3[:n3], cnt3[:n3]
                        # Row-major (pair_slot, z) extraction over a
                        # lex-sorted pair level => already lex-sorted.
                        nxt3 = np.concatenate(
                            [cur[idx3 // f_pad3], (idx3 % f_pad3)[:, None]],
                            axis=1,
                        ).astype(np.int32)
                        levels.append((nxt3, cnt3.astype(np.int64)))
                        cur = nxt3
                        m.update(
                            candidates=int(tri) if tri >= 0 else -1,
                            frequent=int(n3),
                            overlapped=True,
                            dispatches=0,
                            macs=0,  # counted under the ingest dispatch
                            psum_bytes=0,
                        )
                    self._checkpoint_levels(levels, data)
                else:
                    l3p_key, l3c_key = pair_pre["l3_keys"]
                    if n2_now > p3:
                        ctx.record_pair_cap(l3p_key, _next_pow2(n2_now))
                    if n3 > cap3:
                        ctx.record_pair_cap(l3c_key, _next_pow2(n3))
                    ledger.record(
                        "pair_l3_overflow",
                        n2=int(n2_now), p3=int(p3),
                        n3=int(n3), cap3=int(cap3),
                    )

        # Deferred count resolution (single-process): per-level fetches
        # carry only survivor bitmasks; counts resolve in ONE dispatch +
        # fetch after the loop — unless the retained [NB, C] tensors
        # outgrow the byte budget, in which case they DRAIN mid-mine
        # (one gather dispatch compacts the survivors and frees the big
        # tensors; the async fetch is consumed at end-of-mine — ADVICE
        # r5 #2).  Checkpointing forces eager counts — a durable level
        # must carry its counts, and deferring them would leave every
        # checkpoint one crash away from useless.
        pending_map: Dict[int, list] = {}
        drained: list = []  # [(per-level segment sizes, PendingCounts)]
        pending_bytes = [0]
        # Elastic domains force eager counts: a level whose counts are
        # still device-pending is not a boundary the survivors can
        # re-seed from (the pending tensors die with the aborted
        # dispatch stream).
        defer = (
            jax.process_count() == 1
            and not cfg.checkpoint_prefix
            and not quorum.elastic_enabled()
        )

        def note_pending(nxt_counts):
            pending_bytes[0] += sum(
                int(np.prod(c.shape)) * 4 for c, _ in nxt_counts
            )
            if pending_bytes[0] > cfg.pending_fetch_budget_bytes:
                self._drain_pending(pending_map, drained, data.n_raw)
                pending_bytes[0] = 0

        def finish(lvls):
            return self._resolve_pending_counts(
                lvls, pending_map, drained, n_raw=data.n_raw
            )

        # Levels >=3 (C7 + C8), reference termination rule
        # (FastApriori.scala:111).
        # Shrink evidence is an AUTO-mode heuristic only: an explicit
        # tail_fuse_rows forces folding whenever the seed fits it
        # (config.py documents the explicit value as platform-
        # independent and forcing).
        auto_tail = cfg.tail_fuse_rows is None
        tail_rows = cfg.tail_fuse_rows
        if tail_rows is None:
            # Auto: the fold amortizes the per-launch round-trip floor,
            # which cpu backends don't have (and every distinct seed
            # depth would pay a fresh while-loop compile there).  The
            # 64K ceiling is what the chunked candidate-gen +
            # descending-slot output admit (webdocs folds from the
            # 64,427-row k=9 level, absorbing k=10..13 in one
            # dispatch); seeds past the legacy 16K bar additionally
            # require SHRINKING evidence (see below) so a still-growing
            # mid-lattice never wastes a doomed fold dispatch.
            tail_rows = 0 if ctx.platform == "cpu" else 65536
        tail_ok = (
            tail_rows > 0
            and not vertical  # the fold is a bitmap-engine program
            and ctx.cand_shards == 1
            and data.shard is None
        )
        # Fused-engine checkpointing (ISSUE 9 tentpole a): with
        # engine="fused" under a checkpoint prefix the lattice mines in
        # SEGMENTS — seeded whole-loop dispatches of
        # ``checkpoint_every_levels`` depth (the tail program with 2x
        # row headroom and flat slot caps, ops/fused.py), a durable
        # checkpoint after each — so a fused mine kills-and-resumes
        # byte-identically at the segment boundary instead of
        # forfeiting the engine (the ROADMAP reliability residue).  A
        # segment whose first level outgrows its budget walks the
        # cascade to per-level dispatches until the lattice shrinks
        # back under the failed seed.
        fused_ckpt = (
            cfg.engine == "fused"
            and bool(cfg.checkpoint_prefix)
            and not vertical
            and ctx.cand_shards == 1
            and data.shard is None
        )
        k = cur.shape[1] + 1
        prev_rows = None  # previous level's row count (shrink signal)
        fold_attempts = 2  # an early incomplete fold keeps one retry
        last_fold_seed = None  # strict seed shrink between attempts
        while cur.shape[0] >= k:
            # Mid-mine consensus adoption (ISSUE 12): the boundary sync
            # in _checkpoint_levels may have adopted a peer's degraded
            # position since the last iteration — re-clamp the local
            # choices BEFORE this level's dispatch, so the very next
            # collective already matches the domain's agreed shape.
            if vertical and not quorum.stage_allowed(
                "mine_engine", "vertical"
            ):
                # PR-12 residue fix (ISSUE 17 satellite): mine_engine
                # adoption used to land at mine start only — a peer's
                # mid-lattice vertical→bitmap walk must clamp THIS
                # rank at the level boundary too, like count_reduce /
                # exchange below.  Control-flow raise: the vertical
                # loop cannot swap its arena for a bitmap in place, so
                # the completed levels ride up to _mine_vertical_safe,
                # which re-seeds the bitmap loop from this boundary.
                raise _MineEngineClamp(finish(levels), int(k))
            if count_reduce == "sparse" and not quorum.stage_allowed(
                "count_reduce", "sparse"
            ):
                ledger.record(
                    "count_reduce_fallback", once_key="quorum",
                    reason="quorum", k=int(k),
                )
                count_reduce, sparse_thr = "dense", None
            if ctx.exchange_spec is not None and not quorum.stage_allowed(
                "exchange", "hier"
            ):
                # A peer walked hier→flat: the very next sparse
                # dispatch must issue the FLAT collectives (the spec is
                # in every kernel cache key, so this re-clamp is a
                # recompile, not a silent shape mismatch).
                ledger.record(
                    "exchange_fallback", once_key="quorum",
                    reason="quorum", k=int(k),
                )
                ctx.set_exchange_spec(None)
            if fused_ckpt and not quorum.stage_allowed("engine", "fused"):
                fused_ckpt = False  # per-level (still checkpointed)
            if tail_ok and not quorum.stage_allowed("engine", "tail"):
                tail_ok = False
            # k > 3: never fold straight off the pair level — small
            # lattices that fit a whole-loop program are the fused
            # engine's job (the auto choice), and the fold's seed should
            # be a level the per-level engine already counted.  Fused
            # checkpoint segments are exempt from every heuristic gate:
            # the engine was FORCED, so segments run whenever the seed
            # fits memory and the last segment at this size didn't fail.
            if fused_ckpt:
                want_fold = (
                    last_fold_seed is None
                    or cur.shape[0] < last_fold_seed
                )
            else:
                want_fold = (
                    tail_ok
                    and fold_attempts > 0
                    and k > 3
                    and cur.shape[0] <= tail_rows
                    and self._tail_entry_ok(
                        auto_tail, cur.shape[0], prev_rows
                    )
                    and (
                        last_fold_seed is None
                        or cur.shape[0] < last_fold_seed
                    )
                )
            if want_fold:
                fold_err = False
                try:
                    tail, complete, dispatched = self._mine_tail(
                        data, bitmap, w_digits, scales, cur, n_chunks,
                        heavy,
                        pending_state=(
                            (pending_map, drained, pending_bytes)
                            if defer
                            else None
                        ),
                        count_reduce=count_reduce,
                        sparse_thr=sparse_thr,
                        l_max=(
                            cfg.checkpoint_every_levels
                            if fused_ckpt
                            else None
                        ),
                        segment=fused_ckpt,
                    )
                except Exception as exc:
                    # Repeated transients at the fold's fetch walk the
                    # cascade to per-level dispatches instead of
                    # killing the mine (the per-level fetches are their
                    # own audited sites with their own retry budgets).
                    if not watchdog.transient(exc):
                        raise
                    watchdog.downgrade(
                        "engine", "tail", "level",
                        reason="transient_exhausted",
                        error=f"{type(exc).__name__}: {exc}"[:200],
                    )
                    tail, complete, dispatched = [], False, True
                    fold_err = True
                if dispatched:
                    if not fused_ckpt:
                        fold_attempts -= 1
                    last_fold_seed = cur.shape[0]
                    if tail:
                        levels.extend(tail)
                        cur = tail[-1][0]
                        k = cur.shape[1] + 1
                        self._checkpoint_levels(levels, data)
                        if fused_ckpt:
                            # Progress: the next segment folds again
                            # regardless of the new seed's size.
                            last_fold_seed = None
                    if complete:
                        return finish(levels)
                    if fused_ckpt and not tail and not fold_err:
                        # Segment overflowed at its first level: walk
                        # the chain — per-level dispatches (each still
                        # checkpointed) carry the lattice until it
                        # shrinks back under the failed seed.
                        watchdog.downgrade(
                            "engine", "fused", "level",
                            reason="segment_overflow", k=int(k),
                            seed_rows=int(cur.shape[0]),
                        )
                    continue  # incomplete: per-level from last good level
                if fused_ckpt:
                    # Memory model rejected the segment seed outright:
                    # per-level (checkpointed) until it fits.
                    watchdog.downgrade(
                        "engine", "fused", "level",
                        reason="segment_memory",
                        seed_rows=int(cur.shape[0]),
                    )
                    last_fold_seed = cur.shape[0]
                # Not dispatched (memory model rejected this seed): fall
                # through to the per-level dispatch — a later, smaller
                # seed may fit where this one didn't.
            with self.metrics.timed("level", k=k) as m:
                nxt, nxt_counts, lvl_stats = self._count_level(
                    ctx,
                    bitmap,
                    w_digits,
                    scales,
                    cur,
                    gen_candidates_stream(cur),
                    min_count,
                    n_chunks,
                    fast_f32,
                    heavy,
                    defer_counts=defer,
                    count_reduce=count_reduce,
                    sparse_thr=sparse_thr,
                    vertical=vertical,
                )
                m.update(frequent=nxt.shape[0], **lvl_stats)
            if isinstance(nxt_counts, list):  # deferred (pending runs)
                pending_map[len(levels)] = nxt_counts
                note_pending(nxt_counts)
                nxt_counts = None
            elif nxt_counts is None:  # empty level
                nxt_counts = np.empty(0, dtype=np.int64)
            levels.append((nxt, nxt_counts))
            if nxt.shape[0]:
                self._checkpoint_levels(levels, data)
            prev_rows = cur.shape[0]
            cur = nxt
            k += 1
        return finish(levels)

    @staticmethod
    def _tail_entry_ok(
        auto_tail: bool, n0: int, prev_rows: Optional[int]
    ) -> bool:
        """AUTO-mode entry heuristic for the shallow-tail fold (explicit
        ``tail_fuse_rows`` always enters).  Seeds past the legacy 16K bar
        need evidence the fold won't immediately overflow its
        ``next_pow2(n0)`` row budget: SHRINKING rows, or (VERDICT r5
        next #2's lowered entry) NEAR-PEAK growth — a level grown <= 20%
        over its predecessor is at or next to the lattice peak, so the
        pow2 headroom covers the next level and k=8-9-class levels ride
        the fold instead of costing one dispatch each.  A still-doubling
        mid-lattice stays out (a doomed fold dispatch is pure waste)."""
        if not auto_tail or n0 <= 16384:
            return True
        if prev_rows is None:
            return False
        return n0 < prev_rows or n0 * 5 <= prev_rows * 6

    def _drain_pending(self, pending_map, drained, n_raw) -> None:
        """Byte-budgeted mid-mine drain of the deferred count tensors
        (ADVICE r5 #2): one gather dispatch compacts every pending
        level's survivors into a small device array, the [NB, C] int32
        tensors free (pending_map is cleared — the gather output is the
        only remaining reference), and the device→host copy is issued
        ASYNC — consumed at end-of-mine, so the transfer hides under the
        remaining levels' compute.  Deep lattices hold O(budget) extra
        HBM instead of O(levels)."""
        flat = []
        for idx in sorted(pending_map):
            for counts_dev, pos in pending_map[idx]:
                if pos.size:
                    flat.append((idx, counts_dev, pos))
        pending_map.clear()
        if not flat:
            return
        failpoints.fire("drain.counts")
        u24 = n_raw is not None and n_raw < 2**24
        n_out = sum(p.size for _, _, p in flat)
        with self.metrics.timed("counts_drain") as m:
            handle = self.context.gather_level_counts_start(
                [(c, p) for _, c, p in flat],
                u24=u24,
                site="counts_drain",
            )
            m.update(
                levels=len({i for i, _, _ in flat}),
                dispatches=1,
                fetch_bytes=(3 if u24 else 4) * n_out,
            )
        drained.append(([(i, p.size) for i, _, p in flat], handle))

    def _resolve_pending_counts(
        self, levels, pending_map, drained=None, n_raw=None
    ):
        """ONE dispatch + ONE fetch for every still-deferred level's
        survivor counts (the per-level transfers used to cross the slow
        tunnel down-link padded ~4 bytes/candidate; this crosses exactly
        4 bytes/SURVIVOR once), plus consumption of any mid-mine drains'
        in-flight async fetches (:meth:`_drain_pending`) — drains land
        first, in launch order, so each level's count segments
        concatenate in block order.  ``pending_map``: level index ->
        [(counts_dev, flat positions)] in row order."""
        if not pending_map and not drained:
            return levels
        per_level: Dict[int, list] = {}
        for seg_sizes, handle in drained or ():
            out = self.context.finish_level_counts(handle)
            off = 0
            for idx, size in seg_sizes:
                per_level.setdefault(idx, []).append(out[off : off + size])
                off += size
        flat = []  # (level idx, counts_dev, pos) in level-major order
        for idx in sorted(pending_map):
            for counts_dev, pos in pending_map[idx]:
                if pos.size:
                    flat.append((idx, counts_dev, pos))
        with self.metrics.timed("counts_resolve") as m:
            # Counts < 2^24 (weighted counts are bounded by n_raw) cross
            # the link as 3 bytes each — the down-link is the scarcest
            # resource and this is its single largest mining fetch.
            u24 = n_raw is not None and n_raw < 2**24
            out = (
                self.context.gather_level_counts(
                    [(c, p) for _, c, p in flat], u24=u24
                )
                if flat
                else np.empty(0, np.int64)
            )
            m.update(
                levels=len(pending_map),
                drains=len(drained or ()),
                # One real gather dispatch when anything was still
                # pending (bench reports it as resolve_dispatches,
                # SEPARATE from the mining-loop series — the r5 baseline
                # of 9 was measured without it, and folding it in would
                # reset the round-over-round comparison).
                dispatches=1 if flat else 0,
                fetch_bytes=(3 if u24 else 4) * int(out.size),
            )
        off = 0
        for idx, _c, p in flat:
            per_level.setdefault(idx, []).append(out[off : off + p.size])
            off += p.size
        resolved = []
        for i, (mat, cnts) in enumerate(levels):
            if cnts is None:
                parts = per_level.get(i, [])
                cnts = (
                    np.concatenate(parts)
                    if parts
                    else np.empty(0, np.int64)
                )
                assert cnts.size == mat.shape[0], (cnts.size, mat.shape)
            resolved.append((mat, cnts))
        return resolved

    def _mine_tail(
        self, data, bitmap, w_digits, scales, cur: np.ndarray,
        n_chunks: int, heavy: Optional[tuple],
        pending_state: Optional[tuple] = None,
        count_reduce: str = "dense",
        sparse_thr=None,
        l_max: Optional[int] = None,
        segment: bool = False,
    ) -> Tuple[list, bool, bool]:
        """Shallow-tail fold: mine every remaining level in ONE dispatch
        seeded from the current level matrix (ops/fused.py
        _tail_mine_local — the inverse of the fused→level salvage).
        Returns ``(complete tail levels, loop_finished, dispatched)``;
        ``dispatched=False`` means the memory model rejected the seed
        before any device work.  On overflow or depth bound the caller
        resumes per-level counting from the last complete level.

        ``pending_state`` = ``(pending_map, drained, pending_bytes)``
        from the deferred-count machinery: when given, the fold's ONE
        dispatch ALSO gathers every pending level's survivor counts
        (mesh.tail_miner_with_resolve — the ROADMAP counts_resolve fold),
        so a tail-finished mine pays ZERO extra resolve dispatches; the
        end-of-mine ``counts_resolve`` event then reports
        ``resolve_dispatches=0``, still as its own bench field.

        ``count_reduce="sparse"`` (with ``sparse_thr``) folds the
        threshold-sparse exchange into the tail's per-iteration
        [p_cap, F] count reduction (ops/fused.py — the PR-6 residue:
        this was the last counting path still dense); a union overflow
        marks the level invalid like a p_cap overflow and the host
        resumes per-level, recording the census so repeat runs size
        the budget right.

        ``segment`` (with ``l_max`` = the checkpoint cadence) is the
        fused-CHECKPOINT shape (ISSUE 9): the dispatch is one segment
        of an engine="fused" mine under checkpoint_prefix, so the seed
        may sit mid-lattice where levels still GROW — the row budget
        takes 2x headroom, the slot caps go flat (ops/fused.py
        tail_slot_caps), and the prefix budget is uncompacted (every
        seed row may extend)."""
        from fastapriori_tpu.ops import fused

        cfg = self.config
        ctx = self.context
        n0, k0 = cur.shape
        t_pad, f_pad = bitmap.shape
        if l_max is None:
            l_max = cfg.tail_fuse_l_max
        # No 2x headroom (unlike the fused engine's budget): in a
        # shrinking tail the SEED is the largest level, and the [m, m]
        # candidate-gen intermediates are the memory wall (8·m² bytes —
        # headroom at webdocs' 12042-row fold point is the difference
        # between 2.1 GB and an infeasible 8.6 GB).  A growing tail
        # overflows the budget and falls back per-level, exact either
        # way.  Checkpoint SEGMENTS take the headroom: their seeds sit
        # mid-lattice where growth is the common case, and the cadence
        # keeps them shallow.
        m_cap = max(
            _next_pow2(2 * n0 if segment else n0),
            cfg.min_prefix_bucket,
            _next_pow2(l_max + 2),
        )
        # The memory model is the fused engine's (conservative: the tail
        # counts over p_cap rows, not m_cap) — skip the fold rather than
        # compile a program that could OOM.  The search cap is the
        # tail's own need, NOT fused_m_cap_max (an unrelated knob).
        if m_cap > _fused_m_cap_memory_limit(
            cfg, ctx, t_pad, f_pad, n_chunks, unpacked_resident=True,
            cap=m_cap, tail_chunked=True,
        ):
            return [], False, False
        # Prefix budget scales with LARGE seeds: a 64K-row fold's first
        # level can have ~10K prefixes with extensions — the configured
        # cap (tuned for the legacy 16K regime) would trip the in-kernel
        # abort on every run.  At or below 16K the knob keeps its exact
        # configured meaning (tests force tiny caps to drive the abort
        # path).  Checkpoint segments skip the compaction gamble
        # entirely (p_cap = m_cap): a mid-lattice level can extend from
        # every row, and a tripped prefix abort would waste the whole
        # segment dispatch.
        if segment:
            p_cap = m_cap
        else:
            p_cap = cfg.tail_fuse_p_cap
            if m_cap > 16384:
                p_cap = max(p_cap, m_cap // 8)
            p_cap = min(p_cap, m_cap)
        # The level engine's chunk count bounds a [t_c, P] intermediate
        # sized for its own prefix caps; the tail's [t_c, p_cap] is
        # narrower, so consolidate chunks (fewer scan steps per
        # iteration — at webdocs scale 104 steps of per-step scan
        # overhead were ~40% of the fold's wall).
        tail_chunks = n_chunks
        per_dev = t_pad // max(ctx.txn_shards, 1)
        while (
            tail_chunks % 2 == 0
            and (per_dev // (tail_chunks // 2)) * p_cap * 4 <= (768 << 20)
        ):
            tail_chunks //= 2
        seed = np.zeros((m_cap, k0), np.int32)
        seed[:n0] = cur
        hb, hw = heavy if heavy is not None else (None, None)
        # Count-reduction engine for the fold's per-iteration [p_cap, F]
        # psum (PR-6 residue): sparse only above the candidate-space
        # floor, budget grown by any previously recorded overflow.
        sp_cap = None
        sp_key = ("sparse_tail", t_pad, f_pad, int(data.min_count))
        if count_reduce == "sparse" and sparse_thr is not None:
            if p_cap * f_pad >= cfg.count_sparse_min:
                sp_cap = self._sparse_cap(p_cap * f_pad, hint_key=sp_key)
            else:
                ledger.record(
                    "count_reduce_fallback", once_key="tiny_tail",
                    reason="tiny_candidate_set", site="tail",
                    p_cap=p_cap,
                )
        # Pending-count resolve folded into the SAME dispatch (the
        # ROADMAP counts_resolve follow-up): flatten the deferred levels
        # exactly like a mid-mine drain; the fold's program gathers them
        # alongside the tail mine and the async fetch is consumed at
        # end-of-mine (_resolve_pending_counts reads it from `drained`).
        resolve_flat = []
        if pending_state is not None:
            pending_map, drained, pending_bytes = pending_state
            for idx in sorted(pending_map):
                for counts_dev, pos in pending_map[idx]:
                    if pos.size:
                        resolve_flat.append((idx, counts_dev, pos))
        # The resolve-fold build below does not thread flat_caps, so a
        # checkpoint SEGMENT must never carry deferred counts — today
        # guaranteed because checkpointing forces eager fetches (defer
        # is off under checkpoint_prefix); if that gate ever changes,
        # fail loudly here instead of unpacking with mismatched slot
        # offsets.
        assert not (segment and resolve_flat), (
            "fused-checkpoint segment with deferred counts: "
            "tail_miner_with_resolve lacks flat_caps"
        )
        with self.metrics.timed(
            "tail_fuse", k0=k0, m_cap=m_cap, p_cap=p_cap,
            n_chunks=tail_chunks, l_max=l_max,
            checkpoint_segment=segment,
        ) as met:
            args = [
                bitmap, w_digits, ctx.replicate(seed), jnp.int32(n0),
                jnp.int32(data.min_count),
            ]
            if sp_cap is not None:
                args += [jnp.asarray(sparse_thr, dtype=jnp.int32)]
            if heavy is not None:
                args += [hb, hw]
            if resolve_flat:
                from fastapriori_tpu.parallel.mesh import (
                    PendingCounts,
                    _pad_positions,
                )

                u24 = data.n_raw < 2**24
                padded = [_pad_positions(p) for _, _, p in resolve_flat]
                counts_t = tuple(c for _, c, _ in resolve_flat)
                pos_t = tuple(jnp.asarray(p) for p in padded)
                fn = ctx.tail_miner_with_resolve(
                    scales, k0, m_cap, p_cap, l_max,
                    tail_chunks, heavy is not None,
                    tuple(c.shape for c in counts_t)
                    + tuple(p.size for p in padded),
                    u24,
                    sparse_cap=sp_cap,
                )
                packed_dev, gathered = fn(tuple(args), counts_t, pos_t)
                handle = PendingCounts(
                    retry.fetch_async(gathered, "counts_resolve"),
                    [int(p.size) for _, _, p in resolve_flat],
                    [p.size for p in padded],
                    u24,
                )
                drained.append(
                    ([(i, p.size) for i, _, p in resolve_flat], handle)
                )
                pending_map.clear()
                pending_bytes[0] = 0
                met.update(
                    resolve_levels=len({i for i, _, _ in resolve_flat}),
                    resolve_folded=True,
                )
                # lint: fetch-site -- the tail fold's single audited fetch, retry-wrapped; lint: waive G013 -- same logical site as the no-resolve branch below: exactly one of the two exclusive dispatch shapes runs per mine
                packed_out = retry.fetch(
                    lambda: np.asarray(packed_dev), "tail"
                )
            else:
                fn = ctx.tail_miner(
                    scales, k0, m_cap, p_cap, l_max,
                    tail_chunks, heavy is not None, sparse_cap=sp_cap,
                    flat_caps=segment,
                )
                # lint: fetch-site -- the tail fold's single audited fetch, retry-wrapped; lint: waive G013 -- same logical site as the resolve-fold branch above: exactly one of the two exclusive dispatch shapes runs per mine
                packed_out = retry.fetch(
                    lambda: np.asarray(fn(*args)), "tail"
                )
            rows, cols, counts, n_lvl, incomplete, snu = (
                fused.unpack_tail_result(
                    packed_out, m_cap, l_max, flat=segment
                )
            )
            if sp_cap is not None and snu > sp_cap:
                # Union compaction overflowed at some tail level: that
                # level carried the bad sentinel (the host resumes
                # per-level from the last complete one — exact either
                # way); memoize the true census so repeat runs size
                # the budget right (the pair-cap-hint pattern).
                ledger.record(
                    "count_sparse_overflow", site="tail",
                    n_union=int(snu), cap=sp_cap,
                )
                watchdog.downgrade(
                    "count_reduce", "sparse", "dense",
                    reason="union_overflow", site="tail",
                )
                ctx.record_pair_cap(sp_key, _next_pow2(int(snu)))
            # MACs: per stored level, candidate gen (two [m_cap, m_cap]
            # f32 matmuls) + membership/counting over the compacted
            # [p_cap] prefix rows.
            n_iters = max(int(np.count_nonzero(n_lvl)), 1)
            d_eff = len(scales)
            if sp_cap is not None:
                from fastapriori_tpu.ops.count import (
                    sparse_psum_bytes,
                    sparse_stage_bytes,
                )

                xspec = ctx.exchange_spec
                g_b, p_b = sparse_psum_bytes(
                    p_cap * f_pad, sp_cap, ctx.txn_shards, xspec
                )
                i_b, e_b = sparse_stage_bytes(
                    p_cap * f_pad, sp_cap, ctx.txn_shards, xspec
                )
                psum_b = n_iters * p_b
                gather_b = n_iters * g_b
                met.update(
                    intra_bytes=n_iters * i_b,
                    inter_bytes=n_iters * e_b,
                    exchange="hier" if xspec is not None else "flat",
                )
            else:
                psum_b = n_iters * 4 * p_cap * f_pad
                gather_b = 0
            met.update(
                levels=int(np.count_nonzero(n_lvl)),
                dispatches=1,
                incomplete=bool(incomplete),
                reduce="sparse" if sp_cap is not None else "dense",
                macs=n_iters
                * (
                    2 * m_cap * m_cap * f_pad
                    + (1 + d_eff) * t_pad * p_cap * f_pad
                ),
                psum_bytes=psum_b,
                gather_bytes=gather_b,
                upload_bytes=seed.nbytes * ctx.n_devices,
            )
        lvls = fused.decode_level_matrices(
            rows, cols, counts, n_lvl,
            max_rows=fused.tail_slot_caps(m_cap, l_max, flat=segment),
            prev=cur,
        )
        return lvls, not bool(incomplete), True

    def _count_level(
        self,
        ctx: DeviceContext,
        bitmap,
        w_digits,
        scales,
        level: np.ndarray,
        cand_blocks,
        min_count: int,
        n_chunks: int,
        fast_f32: bool = False,
        heavy: Optional[tuple] = None,
        defer_counts: bool = True,
        count_reduce: str = "dense",
        sparse_thr=None,
        vertical: bool = False,
    ) -> Tuple[np.ndarray, object, dict]:
        """C8 for one level, transfer-minimal: greedy chunks of at most
        P_CAP prefixes / C_CAP candidates go through the compiled-once
        gather kernel (ops/count.py local_level_gather); only each
        candidate's survivor BIT comes back per level — the counts stay
        device-resident and resolve in one end-of-mine gather
        (``defer_counts``; the second return is then the pending list,
        otherwise the eager int64 counts).

        ``count_reduce="sparse"`` (with ``sparse_thr``, the [S]
        per-shard prune thresholds) runs each dispatch's candidate
        reduction as the threshold-sparse exchange; blocks under the
        ``count_sparse_min`` floor stay dense, and a union-compaction
        overflow discards the level and recounts it dense (ledger
        event + grown budget hint for repeat runs) — bit-exact either
        way.

        ``cand_blocks`` is an ITERATOR of ``(x_idx, ys)`` blocks in
        global ``(x_idx, y)`` order (candidates.gen_candidates_stream).
        The native generator emits ONE block (its early-exit prune is
        fast enough to run ahead of the first dispatch); the numpy
        fallback streams blocks, and each block's chunks are dispatched
        (async) before the next block is pulled so its join+prune
        overlaps device counting.  Results are fetched only after every
        block is dispatched.  Returns the next level's lex-sorted
        matrix, its counts, and a stats dict (candidate count, kernel
        dispatches, MAC count, psum bytes) for the per-level metrics.

        ``vertical``: ``bitmap`` is the tid-lane arena and ``w_digits``
        the weight bit-planes (ops/vertical.py) — the SAME block/chunk
        machinery feeds the AND+popcount kernel instead of the matmuls
        (identical padding discipline: the zero column keeps padded
        candidate counts at 0; the kernel remaps padded PREFIX entries
        to its all-ones AND-identity row)."""
        cfg = self.config
        s = level.shape[1]
        if vertical:
            f_pad = bitmap.shape[0] - 1  # arena carries the identity row
            t_pad = bitmap.shape[1] * 32
        else:
            f_pad = bitmap.shape[1]
            t_pad = bitmap.shape[0]
        zcol = f_pad - 1  # guaranteed all-zero column (ops/bitmap.py)
        # Per-cand-shard capacities: the prefix rows and the candidate
        # gather are sharded over the mesh's cand axis (mesh.level_gather),
        # so each shard gets a contiguous block of prefix runs.  A single
        # prefix can have up to F-1 extensions, and blocks take whole
        # per-prefix runs — each shard's budget must fit at least one run.
        # With cand_shards == 1 this is exactly the old single-block path.
        n_cs = ctx.cand_shards
        c_cap_max = max(cfg.level_cand_cap // n_cs, f_pad)
        # Prefix width in buckets of 8 (at most ceil(level_k_max/8)
        # compiled shapes): the host->device prefix table is the per-
        # dispatch upload that dominates fixed dispatch cost on tunneled
        # chips, so a shallow level must not pay a level_k_max-wide row.
        k_pad = min(((s + 7) // 8) * 8, max(cfg.level_k_max, 8))
        if s > k_pad:  # deeper than the padded width: widen (recompiles)
            k_pad = ((s + 7) // 8) * 8
        # Compact dtype for the same reason (half the bytes) — int32 only
        # when the padded item axis outgrows int16.
        cols_dt = np.int16 if f_pad <= (1 << 15) else np.int32
        d_eff = 1 if fast_f32 else len(scales)
        stats = {
            "candidates": 0, "dispatches": 0, "macs": 0, "psum_bytes": 0,
            "gather_bytes": 0,
            "reduce": "dense",
        }
        sp_hint_key = (
            ("sparse_vlevel" if vertical else "sparse_level"),
            t_pad, f_pad, min_count,
        )
        inflight = []  # (placed, device out, counts buffer, sparse cap)
        blocks = []  # (x_idx, ys, counts buffer)
        for x_idx, ys in cand_blocks:
            if x_idx.size == 0:
                continue
            stats["candidates"] += int(x_idx.size)
            keep_blk = np.empty(x_idx.size, dtype=bool)
            blocks.append((x_idx, ys, keep_blk))
            # x_idx is sorted, so each unique prefix's candidates are one
            # contiguous run; chunks take whole runs.
            uniq_x, run_start = np.unique(x_idx, return_index=True)
            run_end = np.concatenate([run_start[1:], [x_idx.size]])
            # Right-size the prefix budget to THIS block's actual prefix
            # count, in power-of-two buckets (compiles stay bounded) up
            # to the level_prefix_cap transfer-amortization cap.  A fixed
            # cap-wide budget made every small level pay the full padded
            # [T, P] membership matmul — ~145 GMAC for a 1-candidate
            # level at T10I4D100K scale, the whole CPU-fallback
            # regression.  The cap itself is large (2^14) because each
            # extra dispatch costs ~100+ ms of fixed launch latency on
            # tunneled chips — big levels want FEW dispatches.
            p_sh = min(
                max(
                    _next_pow2(-(-uniq_x.size // n_cs)),
                    max(cfg.min_prefix_bucket // n_cs, 1),
                ),
                max(cfg.level_prefix_cap // n_cs, 1),
            )
            p_cap = p_sh * n_cs
            # Chunk boundaries first (pass 1), array materialization
            # second — the candidate budget must be sized from the MAX
            # PER-CHUNK candidate count, not the whole block's: with a
            # 16K-prefix chunk and ~2 extensions/prefix, sizing from the
            # block total shipped a 1 MB cand_idx per chunk of which
            # ~87% was padding (multi-MB per big level on the host
            # link).  Boundaries are computed against the configured
            # ceiling (c_cap_max >= f_pad by construction, so any single
            # prefix run — < F extensions — fits).
            c_bound = c_cap_max
            chunk_descs = []  # per chunk: list of (start, end, base, n_c)
            start = 0  # index into uniq_x
            while start < uniq_x.size:
                shards = []
                for sh in range(n_cs):
                    if start >= uniq_x.size:
                        break
                    hi = min(start + p_sh, uniq_x.size)
                    base = run_start[start]
                    end = int(
                        np.searchsorted(
                            run_end[start:hi] - base, c_bound, side="right"
                        )
                    )
                    end = start + max(end, 1)
                    shards.append(
                        (start, end, base, int(run_end[end - 1] - base))
                    )
                    start = end
                chunk_descs.append(shards)
            c_sh = min(
                max(
                    _next_pow2(
                        max(n_c for sh_l in chunk_descs for *_, n_c in sh_l)
                    ),
                    f_pad,
                ),
                c_cap_max,
            )
            c_cap = c_sh * n_cs
            pcs = []  # per-block-chunk compact prefix tables
            cis = []  # per-block-chunk flat candidate indexes
            placed_all = []  # per-block-chunk placement lists
            for shards in chunk_descs:
                prefix_cols = np.full((p_cap, k_pad), zcol, dtype=cols_dt)
                # Padded candidate slots gather the guaranteed-zero
                # column's count (0) rather than slot 0's real count —
                # under the sparse reduction a hot slot-0 count would
                # drag every padding slot into the union.
                cand_idx = np.full(c_cap, zcol, dtype=np.int32)
                placed = []  # (counts slice, offset in cand_idx, length)
                for sh, (c_start, c_end, base, n_c) in enumerate(shards):
                    n_p = c_end - c_start
                    prefix_cols[sh * p_sh : sh * p_sh + n_p, :s] = level[
                        uniq_x[c_start:c_end]
                    ]
                    ci = slice(base, base + n_c)
                    # Row indexes are LOCAL to the shard's prefix block —
                    # each cand shard sees only its own [p_sh, F] counts.
                    row_of_cand = (
                        np.searchsorted(uniq_x, x_idx[ci]) - c_start
                    ).astype(np.int64)
                    cand_idx[sh * c_sh : sh * c_sh + n_c] = (
                        row_of_cand * f_pad + ys[ci]
                    )
                    placed.append((ci, sh * c_sh, n_c))
                pcs.append(prefix_cols)
                cis.append(cand_idx)
                placed_all.append(placed)
            # ONE launch for the whole generator block: launches carry
            # ~100+ ms of fixed round-trip cost on tunneled backends (the
            # runtime does not pipeline them), so the chunks ride a
            # device-side scan instead of separate dispatches.  The block
            # axis pads to a BUCKET — pow2 up to 16, then multiples of 8:
            # dummy chunks run the full-size matmuls (a scan step cannot
            # be skipped), so pure pow2 buckets wasted up to ~2x device
            # work on big levels, while finer buckets would multiply the
            # distinct compiled scan shapes (each a multi-second XLA
            # compile on a tunneled backend).  Multiples of 8 cap the
            # waste at 7 chunks with at most a handful of shapes.
            nb = len(pcs)
            nb_pad = _next_pow2(nb) if nb <= 16 else -(-nb // 8) * 8
            for _ in range(nb_pad - nb):
                pcs.append(np.full((p_cap, k_pad), zcol, dtype=cols_dt))
                cis.append(np.full(c_cap, zcol, dtype=np.int32))
            hb, hw = heavy if heavy is not None else (None, None)
            # Per-dispatch reduction engine: the sparse exchange only
            # beats the dense psum above the candidate-count floor.
            sp_cap = None
            if count_reduce == "sparse":
                if c_cap >= self.config.count_sparse_min:
                    sp_cap = self._sparse_cap(c_cap, hint_key=sp_hint_key)
                elif stats["dispatches"] == 0:
                    # The mine selected sparse but this level runs
                    # dense (config.py's tiny-candidate-set fallback
                    # contract): one ledger event per level, so a
                    # record shows WHICH reduction each level ran.
                    ledger.record(
                        "count_reduce_fallback",
                        once_key="tiny_level",
                        reason="tiny_candidate_set",
                        site="level", k=s + 1, c_cap=c_cap,
                    )
            if vertical:
                bits, counts_out = ctx.vertical_level_gather_batch(
                    bitmap,
                    w_digits,
                    scales,
                    np.stack(pcs),
                    min_count,
                    np.stack(cis),
                    self._vertical_chunk(c_cap),
                    sparse_cap=sp_cap,
                    sparse_thr=sparse_thr,
                    lane_tile=self._vertical_lane_tile(),
                )
            else:
                bits, counts_out = ctx.level_gather_batch(
                    bitmap,
                    w_digits,
                    scales,
                    np.stack(pcs),
                    s,
                    min_count,
                    np.stack(cis),
                    n_chunks,
                    heavy_b=hb,
                    heavy_w=hw,
                    fast_f32=fast_f32,
                    sparse_cap=sp_cap,
                    sparse_thr=sparse_thr,
                )
            # Audited fetch issued NON-BLOCKING at dispatch time
            # (reliability/retry.py fetch_async): the ~C/8-byte survivor
            # mask crosses the link while the host preps the next block
            # (and, for the last block, while it runs the collect loop
            # below) — a congested link stalls the copy, not the host.
            # Distinct labels per reduction engine AND per mining
            # engine: the sparse payload carries the union censuses
            # too, and each site's failpoint must be armable
            # independently (G013).
            if vertical and sp_cap is not None:
                bits_fu = retry.fetch_async(bits, "vlevel_bits_sparse")
            elif vertical:
                bits_fu = retry.fetch_async(bits, "vlevel_bits")
            elif sp_cap is not None:
                bits_fu = retry.fetch_async(bits, "level_bits_sparse")
            else:
                bits_fu = retry.fetch_async(bits, "level_bits")
            inflight.append((placed_all, bits_fu, counts_out, sp_cap))
            # Per-launch cost model (metrics/MFU): membership matmul
            # [T, P_cap] + counting matmuls [P_cap, F] over padded
            # global shapes per scanned chunk — including the padding
            # chunks, which execute the full-size matmuls (the MFU
            # figure must reflect what the device actually ran); the
            # reduction moves either the dense 4·C psum payload or the
            # sparse mask-gather + compact-psum payloads per chunk.
            stats["dispatches"] += 1
            if vertical:
                from fastapriori_tpu.ops.vertical import (
                    vertical_level_word_ops,
                )

                stats["engine"] = "vertical"
                stats["vops"] = stats.get(
                    "vops", 0
                ) + vertical_level_word_ops(
                    nb_pad, p_cap, k_pad, c_cap, len(scales), t_pad // 32
                )
                # HBM-traffic model for the Pallas tier: the [P_cap, NL]
                # prefix-AND write+read the VMEM-resident kernel never
                # pays (bench --engine-compare's member_bytes_saved).
                from fastapriori_tpu.ops.vertical import (
                    vertical_member_bytes,
                )

                stats["member_bytes_saved"] = stats.get(
                    "member_bytes_saved", 0
                ) + vertical_member_bytes(nb_pad, p_cap, t_pad // 32)
            else:
                stats["macs"] += (
                    nb_pad * (1 + d_eff) * t_pad * p_cap * f_pad
                )
            if sp_cap is not None:
                from fastapriori_tpu.ops.count import (
                    sparse_psum_bytes,
                    sparse_stage_bytes,
                )

                xspec = ctx.exchange_spec
                g_b, p_b = sparse_psum_bytes(
                    c_cap, sp_cap, ctx.txn_shards, xspec
                )
                i_b, e_b = sparse_stage_bytes(
                    c_cap, sp_cap, ctx.txn_shards, xspec
                )
                stats["psum_bytes"] += nb_pad * p_b
                stats["gather_bytes"] += nb_pad * g_b
                stats["intra_bytes"] = (
                    stats.get("intra_bytes", 0) + nb_pad * i_b
                )
                stats["inter_bytes"] = (
                    stats.get("inter_bytes", 0) + nb_pad * e_b
                )
                stats["reduce"] = "sparse"
                stats["exchange"] = (
                    "hier" if xspec is not None else "flat"
                )
            else:
                stats["psum_bytes"] += nb_pad * 4 * c_cap
        empty = (
            np.empty((0, s + 1), dtype=np.int32),
            None,
            stats,
        )
        if not blocks:
            return empty
        # Collect: only the survivor BITMASK crosses the link per level
        # (C/8 bytes; the padded [NB, C] int32 fetch was 1-4 MB over a
        # ~11-38 MB/s tunnel down-link — often more wall than the
        # level's device time).  Counts stay device-resident; survivors'
        # flat positions are recorded for the ONE end-of-mine gather
        # (_resolve_pending_counts).  The collect wall (mask consumption
        # + any eager count fetch) is attributed separately as fetch_ms
        # so multi-process scaling records decompose into compute vs
        # link terms (VERDICT r5 next #7 remainder).
        t_collect0 = time.perf_counter()
        # Consume every async fetch first and decode the sparse blocks'
        # trailing union censuses: an overflowed union truncated the
        # compaction, so that dispatch's counts (and mask) silently MISS
        # candidates — the whole level must recount dense before any
        # survivor state is built from it.
        fetched = []
        max_nu = 0
        recount = None
        try:
            for placed_all, bits_fu, counts_out, sp_cap in inflight:
                mask = bits_fu.result()  # consume the async fetch (retried)
                if sp_cap is not None:
                    nus = mask[:, -4:].astype(np.int64)
                    nus = (
                        nus[:, 0]
                        | (nus[:, 1] << 8)
                        | (nus[:, 2] << 16)
                        | (nus[:, 3] << 24)
                    )
                    if nus.size and int(nus.max()) > sp_cap:
                        max_nu = max(max_nu, int(nus.max()))
                    mask = mask[:, :-4]
                fetched.append((placed_all, mask, counts_out))
        except Exception as exc:
            # Transient exhaustion on a SPARSE-engine fetch walks the
            # cascade: recount the whole level dense (its fetch is a
            # separate audited site with a fresh retry budget) instead
            # of killing the mine.  Dense-engine exhaustion has nowhere
            # further to walk and re-raises classified.  A hierarchical
            # exchange ALSO walks its own chain first (hier→flat — the
            # two-level collectives are the newest moving part, and the
            # flat exchange is the cheaper exact fallback), so the
            # dense recount below and every later sparse dispatch run
            # flat.
            # A vertical level that ran the Pallas kernel tier walks
            # vertical_kernel pallas→xla FIRST (the kernel is the
            # newest moving part; the XLA vertical path is exact by
            # construction) — sticky local disable + quorum proposal,
            # so every later dispatch (and the recount below) compiles
            # the XLA body.
            pallas_walk = (
                vertical
                and ctx.vertical_pallas_active()
                and watchdog.transient(exc)
            )
            if not pallas_walk and (
                count_reduce != "sparse" or not watchdog.transient(exc)
            ):
                raise
            if pallas_walk:
                watchdog.downgrade(
                    "vertical_kernel", "pallas", "xla",
                    reason="transient_exhausted", site="vlevel",
                    k=s + 1,
                    error=f"{type(exc).__name__}: {exc}"[:200],
                )
                ctx.disable_vertical_pallas()
            if count_reduce == "sparse":
                if ctx.exchange_spec is not None:
                    watchdog.downgrade(
                        "exchange", "hier", "flat",
                        reason="transient_exhausted",
                        site="vlevel" if vertical else "level", k=s + 1,
                    )
                    ctx.set_exchange_spec(None)
                watchdog.downgrade(
                    "count_reduce", "sparse", "dense",
                    reason="transient_exhausted",
                    site="vlevel" if vertical else "level", k=s + 1,
                    error=f"{type(exc).__name__}: {exc}"[:200],
                )
            recount = "transient_exhausted"
        if max_nu:
            recount = "union_overflow"
            ledger.record(
                "count_sparse_overflow",
                site="vlevel" if vertical else "level", k=s + 1,
                n_union=max_nu,
            )
            watchdog.downgrade(
                "count_reduce", "sparse", "dense",
                reason="union_overflow",
                site="vlevel" if vertical else "level", k=s + 1,
            )
            ctx.record_pair_cap(sp_hint_key, _next_pow2(max_nu))
        if recount:
            nxt_d, cnts_d, stats_d = self._count_level(
                ctx, bitmap, w_digits, scales, level,
                gen_candidates_stream(level), min_count, n_chunks,
                fast_f32, heavy, defer_counts=defer_counts,
                count_reduce="dense", vertical=vertical,
            )
            # The wasted sparse dispatches still ran (and their bytes
            # still crossed the mesh) — account them on top of the
            # dense recount's own figures.
            stats_d["dispatches"] += stats["dispatches"]
            stats_d["macs"] += stats["macs"]
            if stats.get("vops"):
                stats_d["vops"] = stats_d.get("vops", 0) + stats["vops"]
            if stats.get("member_bytes_saved"):
                stats_d["member_bytes_saved"] = (
                    stats_d.get("member_bytes_saved", 0)
                    + stats["member_bytes_saved"]
                )
            stats_d["psum_bytes"] += stats["psum_bytes"]
            stats_d["gather_bytes"] = (
                stats_d.get("gather_bytes", 0) + stats["gather_bytes"]
            )
            stats_d["candidates"] = stats["candidates"]
            if max_nu:
                stats_d["sparse_overflow"] = max_nu
            return nxt_d, cnts_d, stats_d
        pending = []  # (counts_dev [NB, C], flat positions int64[n])
        for (placed_all, mask, counts_out), blk in zip(fetched, blocks):
            arr = np.unpackbits(mask, axis=1)  # [NB, C]
            c_tot = arr.shape[1]
            keep_blk = blk[2]
            pos_parts = []
            for bi, placed in enumerate(placed_all):
                for ci, off, n_c in placed:
                    kb = arr[bi, off : off + n_c].astype(bool)
                    keep_blk[ci] = kb
                    if kb.any():
                        pos_parts.append(
                            np.int64(bi) * c_tot
                            + off
                            + np.flatnonzero(kb)
                        )
            pos = (
                np.concatenate(pos_parts)
                if pos_parts
                else np.empty(0, np.int64)
            )
            pending.append((counts_out, pos))
        stats["fetch_ms"] = round(
            (time.perf_counter() - t_collect0) * 1e3, 1
        )
        x_idx = np.concatenate([b[0] for b in blocks])
        ys = np.concatenate([b[1] for b in blocks])
        keep = np.concatenate([b[2] for b in blocks])
        if not keep.any():
            return empty
        nxt = np.concatenate(
            [level[x_idx[keep]], ys[keep, None]], axis=1
        ).astype(np.int32)
        if not defer_counts:
            # Multi-process SPMD (and checkpointing runs): the deferred
            # device gather would mix global and process-local arrays;
            # fetch this level's count arrays now and slice on host (the
            # pre-deferral behavior).
            t_eager0 = time.perf_counter()
            parts = [
                # lint: fetch-site -- eager per-level count fetch (defer off), retry-wrapped
                retry.fetch(lambda c=c: np.asarray(c), "level_counts")
                .reshape(-1)[p]
                for c, p in pending
                if p.size
            ]
            counts = (
                np.concatenate(parts) if parts else np.empty(0, np.int64)
            ).astype(np.int64)
            stats["fetch_ms"] = round(
                stats["fetch_ms"]
                + (time.perf_counter() - t_eager0) * 1e3,
                1,
            )
            return nxt, counts, stats
        # Blocks arrive in (x_idx, y) order and level is lex-sorted, so
        # nxt is already lex-sorted — the invariant the next join needs;
        # the pending positions are collected in the same order, so the
        # resolved counts align row-for-row with nxt.
        return nxt, pending, stats
