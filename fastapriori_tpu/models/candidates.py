"""Apriori candidate generation (reference C7, FastApriori.scala:167-193).

Host-side: the candidate table is tiny next to counting (SURVEY.md §2 C7).

The reference enumerates every rank in ``max(x)+1 .. F-1`` per frequent
set and prunes by hashed subset lookups — O(M·F·k).  Here the same
candidate set is produced by the classic prefix join: two frequent
(k-1)-sets sharing their first k-2 sorted elements join into a candidate
``c = x ∪ {y}`` (``x`` = c minus its largest element, ``y = max(c)``), and
the remaining k-2 subsets of ``c`` are verified by hash lookup —
O(M·log M + candidates·k).

Equivalence to the reference's rule (:176-188): a pair ``(x, y)`` with
``y > max(x)`` survives the reference's prune iff every (k-1)-subset of
``x ∪ {y}`` is frequent.  The join supplies two of those subsets
(``c - y = x`` and ``c - e`` where e is x's largest element) and the
explicit checks cover the rest, so the surviving set is identical.  The
per-prefix extension lists are returned sorted ascending; prefixes with no
surviving extension are dropped (:190).
"""

from __future__ import annotations

from collections import defaultdict
from typing import Dict, FrozenSet, List, Sequence, Tuple

Prefix = Tuple[int, ...]  # sorted ranks


def gen_candidates(
    k_items: Sequence[FrozenSet[int]], num_items: int
) -> List[Tuple[Prefix, List[int]]]:
    """Return ``(sorted prefix, sorted surviving extensions)`` per prefix."""
    if not k_items:
        return []
    tuples = sorted(tuple(sorted(x)) for x in k_items)
    k_set = set(tuples)
    s = len(tuples[0])  # = k-1

    by_prefix: Dict[Prefix, List[Tuple[int, ...]]] = defaultdict(list)
    for t in tuples:
        by_prefix[t[:-1]].append(t)

    out: Dict[Prefix, List[int]] = defaultdict(list)
    for shared, group in by_prefix.items():
        # group is sorted by last element (tuples were globally sorted).
        n = len(group)
        for i in range(n - 1):
            x = group[i]
            for j in range(i + 1, n):
                y = group[j][-1]
                c = x + (y,)
                # Verify the k-2 subsets dropping a shared-prefix element
                # (dropping x's last element gives group[j], frequent by
                # construction; dropping y gives x itself).
                ok = True
                for d in range(s - 1):
                    if c[:d] + c[d + 1 :] not in k_set:
                        ok = False
                        break
                if ok:
                    out[x].append(y)
    return [(x, ys) for x, ys in out.items()]  # ys ascending by construction
