"""Apriori candidate generation (reference C7, FastApriori.scala:167-193).

Host-side: the candidate table is tiny next to counting (SURVEY.md §2 C7).
Semantics reproduced exactly:

- extensions of a frequent (k-1)-set ``x`` are drawn from ranks
  ``max(x)+1 .. F-1`` not in ``x`` (ordered-extension dedup, :176-177);
- classic Apriori prune: extension ``y`` survives iff for EVERY element
  ``e`` of ``x``, ``(x - {e}) ∪ {y}`` is a frequent (k-1)-set (:181-188 —
  the reference's early exit when the candidate set empties does not change
  the result, the prune conditions are order-independent);
- prefixes with no surviving extension are dropped (:190).
"""

from __future__ import annotations

from typing import FrozenSet, List, Sequence, Tuple

Prefix = Tuple[int, ...]  # sorted ranks


def gen_candidates(
    k_items: Sequence[FrozenSet[int]], num_items: int
) -> List[Tuple[Prefix, List[int]]]:
    """Return ``(sorted prefix, sorted surviving extensions)`` per prefix."""
    k_set = frozenset(k_items)
    out: List[Tuple[Prefix, List[int]]] = []
    for x in k_items:
        cands = set(range(max(x) + 1, num_items)) - x
        for elem in x:
            if not cands:
                break
            sub = x - {elem}
            cands = {y for y in cands if (sub | {y}) in k_set}
        if cands:
            out.append((tuple(sorted(x)), sorted(cands)))
    return out
