"""Apriori candidate generation (reference C7, FastApriori.scala:167-193).

Host-side: the candidate table is tiny next to counting (SURVEY.md §2 C7).

The reference enumerates every rank in ``max(x)+1 .. F-1`` per frequent
set and prunes by hashed subset lookups — O(M·F·k).  Here the same
candidate set is produced by the classic prefix join: two frequent
(k-1)-sets sharing their first k-2 sorted elements join into a candidate
``c = x ∪ {y}`` (``x`` = c minus its largest element, ``y = max(c)``), and
the remaining k-2 subsets of ``c`` are verified by hash lookup —
O(M·log M + candidates·k).

Equivalence to the reference's rule (:176-188): a pair ``(x, y)`` with
``y > max(x)`` survives the reference's prune iff every (k-1)-subset of
``x ∪ {y}`` is frequent.  The join supplies two of those subsets
(``c - y = x`` and ``c - e`` where e is x's largest element) and the
explicit checks cover the rest, so the surviving set is identical.  The
per-prefix extension lists are returned sorted ascending; prefixes with no
surviving extension are dropped (:190).
"""

from __future__ import annotations

from collections import defaultdict
from typing import Dict, FrozenSet, List, Sequence, Tuple

import numpy as np

Prefix = Tuple[int, ...]  # sorted ranks


def gen_candidates(
    k_items: Sequence[FrozenSet[int]], num_items: int
) -> List[Tuple[Prefix, List[int]]]:
    """Return ``(sorted prefix, sorted surviving extensions)`` per prefix."""
    if not k_items:
        return []
    tuples = sorted(tuple(sorted(x)) for x in k_items)
    k_set = set(tuples)
    s = len(tuples[0])  # = k-1

    by_prefix: Dict[Prefix, List[Tuple[int, ...]]] = defaultdict(list)
    for t in tuples:
        by_prefix[t[:-1]].append(t)

    out: Dict[Prefix, List[int]] = defaultdict(list)
    for shared, group in by_prefix.items():
        # group is sorted by last element (tuples were globally sorted).
        n = len(group)
        for i in range(n - 1):
            x = group[i]
            for j in range(i + 1, n):
                y = group[j][-1]
                c = x + (y,)
                # Verify the k-2 subsets dropping a shared-prefix element
                # (dropping x's last element gives group[j], frequent by
                # construction; dropping y gives x itself).
                ok = True
                for d in range(s - 1):
                    if c[:d] + c[d + 1 :] not in k_set:
                        ok = False
                        break
                if ok:
                    out[x].append(y)
    return [(x, ys) for x, ys in out.items()]  # ys ascending by construction


# ----------------------------------------------------------------------
# Vectorized form used by the level engine on large levels.  Same
# candidate set as :func:`gen_candidates` (tested for equality), but the
# frequent sets stay a lex-sorted int32 matrix end-to-end — no Python
# tuples or per-candidate hash probes on the hot path.


def _encode_rows(a: np.ndarray) -> np.ndarray:
    """Encode int rows as fixed-width big-endian byte strings: memcmp
    order == lexicographic row order, and (keys being equal length)
    byte-equality == row equality, so a lex-sorted matrix encodes to a
    sorted key array ready for ``np.searchsorted``."""
    a = np.ascontiguousarray(a.astype(">u4"))
    return a.view("S%d" % (4 * a.shape[1])).ravel()


def _keys_member(qk: np.ndarray, table_keys: np.ndarray) -> np.ndarray:
    pos = np.searchsorted(table_keys, qk)
    ok = pos < table_keys.shape[0]
    ok[ok] = table_keys[pos[ok]] == qk[ok]
    return ok


def gen_candidates_blocks(level: np.ndarray, pair_budget: int = 1 << 21):
    """Prefix-join + Apriori subset prune, vectorized, streamed in blocks
    of at most ~``pair_budget`` pre-prune join pairs.

    ``level``: lex-sorted int32 ``[M, s]`` matrix of the frequent
    (k-1)-sets (``s = k-1``, rows sorted ascending within and across).
    Yields ``(x_idx, y)`` blocks in global ``(x_idx, y)`` order: each
    candidate is ``level[x_idx] ∪ {y}`` with ``y > max(level[x_idx])`` —
    the same ordered-extension semantics as the reference's prune
    (FastApriori.scala:176-188).

    Blocks cut on x-row boundaries (a pair belongs to its x row; y rows
    may extend past the block — the table is global), so the mining
    engine can DISPATCH counting for one block while this generator
    prunes the next on the host (this numpy prune is ~4.5 s of host
    work at Webdocs scale; the native generator in
    :func:`gen_candidates_stream` replaces it at ~6x and emits one
    block).
    """
    m, s = level.shape
    if m < 2:
        return
    # Rows joinable when they share their first s-1 elements; since the
    # matrix is lex-sorted, each join group is a contiguous row range.
    if s == 1:
        group_of_row = np.zeros(m, dtype=np.int64)
        group_end = np.full(1, m, dtype=np.int64)
    else:
        new_group = np.any(level[1:, :-1] != level[:-1, :-1], axis=1)
        group_of_row = np.concatenate(
            [[0], np.cumsum(new_group)]
        ).astype(np.int64)
        group_end = np.zeros(int(group_of_row[-1]) + 1, dtype=np.int64)
        np.maximum.at(group_end, group_of_row, np.arange(m) + 1)
    # Pair (x, y_row) for every x < y_row inside a group: x repeats once
    # per later row in its group.
    reps = group_end[group_of_row] - np.arange(m) - 1
    cum = np.concatenate([[0], np.cumsum(reps)])  # [m+1]
    if cum[-1] == 0:
        return
    table_keys = _encode_rows(level)
    lo = 0
    while lo < m:
        hi = int(np.searchsorted(cum, cum[lo] + pair_budget, side="left"))
        hi = min(max(hi, lo + 1), m)
        yield _join_prune_rows(
            level, s, reps, cum, table_keys, lo, hi
        )
        lo = hi


def _join_prune_rows(level, s, reps, cum, table_keys, lo, hi):
    """Join + prune for x rows in [lo, hi) against the GLOBAL table."""
    reps_blk = reps[lo:hi]
    total = int(cum[hi] - cum[lo])
    if total == 0:
        return (
            np.empty(0, dtype=np.int64),
            np.empty(0, dtype=np.int32),
        )
    x_idx = np.repeat(np.arange(lo, hi, dtype=np.int64), reps_blk)
    offs = np.concatenate([[0], np.cumsum(reps_blk)[:-1]])
    y_row = x_idx + 1 + (np.arange(total) - offs[x_idx - lo])
    y = level[y_row, -1].astype(np.int32)

    # Apriori prune: every (k-1)-subset of the candidate obtained by
    # dropping one of the shared-prefix positions must be frequent.
    # (Dropping y gives level[x_idx]; dropping x's last element gives
    # level[y_row] — both frequent by construction.)
    ok = np.ones(total, dtype=bool)
    for d in range(s - 1):
        live = np.flatnonzero(ok)
        if live.size == 0:
            break
        xi = x_idx[live]
        sub = np.empty((live.size, s), dtype=level.dtype)
        sub[:, :d] = level[xi, :d]
        sub[:, d:s - 1] = level[xi, d + 1:]
        sub[:, s - 1] = y[live]
        ok[live] = _keys_member(_encode_rows(sub), table_keys)
    return x_idx[ok], y[ok]


def gen_candidates_stream(level: np.ndarray, pair_budget: int = 1 << 21):
    """Best-available candidate stream for the mining engine: the native
    C++ join+prune (native/preprocess.cc fa_gen_candidates — early-exit
    prune with narrowed search ranges; ~10x the numpy passes) as a single
    block when built, else the numpy blocks.  Identical candidates in
    identical global (x_idx, y) order either way (tested)."""
    if level.shape[0] >= 2:
        native = None
        try:
            from fastapriori_tpu.native import native_available
            from fastapriori_tpu.native.loader import gen_candidates_native

            if native_available():
                native = gen_candidates_native
        except (ImportError, RuntimeError):  # pragma: no cover - env
            native = None
        if native is not None:
            try:
                x_idx, y = native(level)
            except RuntimeError:  # stale .so without the entry point
                x_idx = None
            if x_idx is not None:
                if x_idx.size:
                    yield (x_idx, y)
                return
    yield from gen_candidates_blocks(level, pair_budget=pair_budget)


def gen_candidates_arrays(
    level: np.ndarray,
) -> Tuple[np.ndarray, np.ndarray]:
    """One-shot form of :func:`gen_candidates_blocks`: the whole level's
    ``(x_idx, y)`` in global order."""
    xs, ys = [], []
    for x_idx, y in gen_candidates_blocks(level, pair_budget=1 << 62):
        xs.append(x_idx)
        ys.append(y)
    if not xs:
        return (np.empty(0, dtype=np.int64), np.empty(0, dtype=np.int32))
    return np.concatenate(xs), np.concatenate(ys)
