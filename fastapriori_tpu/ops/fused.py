"""Fully-fused on-device Apriori: the entire level loop as ONE XLA program
(reference C6+C7+C8+C9 — FastApriori.scala:88-241 — without any per-level
host round trip).

The level-synchronous loop runs as a ``lax.while_loop`` on device.  Each
iteration mines level k from the frequent (k-1)-set matrix
``S ∈ {0,1}^{M_cap×F}`` (one row per frequent set, padded to a static row
budget) using only matmuls:

- **candidate generation as matmuls** (replaces the reference's driver-side
  set algebra, FastApriori.scala:167-193): a pair ``(x, y)`` with
  ``y > max(x)`` is a candidate iff ALL k-1 of the (k-1)-subsets of
  ``x ∪ {y}`` containing y are frequent.  Those subsets are exactly the
  frequent rows r with ``|r ∩ x| = k-2`` and ``y ∈ r``, so with
  ``D = S Sᵀ`` and ``E = (D == k-2)``:  ``cand_cnt = E S`` counts them and
  ``cand[x,y] = (cand_cnt[x,y] == k-1)``;
- **support counting as matmuls** (replaces the per-candidate Boolean scans,
  FastApriori.scala:140-157): ``common = (B Sᵀ == k-1)`` marks baskets
  containing each prefix, ``counts = Σ_d 128^d (common ⊙ w_d)ᵀ B`` the
  weighted supports of every extension, ``psum`` over the transaction mesh
  axis;
- **compaction**: survivors ``(row, col)`` via size-bounded ``jnp.nonzero``
  into the next level's S.  The program returns only (row, col, count)
  triples per level — the host reconstructs itemsets by chaining rows
  through levels, so the device→host transfer is a few MB regardless of
  bitmap size.

The bitmap crosses host→device bit-packed (uint8, 8 items/byte — an 8x
transfer saving) and is unpacked on device.

Static row budget ``m_cap`` bounds the per-level frequent-set count; if a
level overflows (or the loop exceeds ``l_max`` levels), the program reports
it and the caller falls back (larger m_cap or the chunked level-at-a-time
engine).  Termination rule is the reference's ``while (kItems.length >= k)``
(FastApriori.scala:111).
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from fastapriori_tpu import compat

AXIS = "txn"


def pack_bitmap(bitmap: np.ndarray) -> np.ndarray:
    """Host-side bit packing along the item axis (MSB-first, matching
    jnp unpack in ``_unpack``).  F must be a multiple of 8."""
    assert bitmap.shape[1] % 8 == 0
    return np.packbits(bitmap.astype(bool), axis=1)


def _unpack(packed: jnp.ndarray) -> jnp.ndarray:
    """[T, F//8] uint8 -> [T, F] int8 (MSB-first per byte)."""
    shifts = jnp.arange(7, -1, -1, dtype=jnp.uint8)
    bits = (packed[:, :, None] >> shifts[None, None, :]) & 1
    return bits.reshape(packed.shape[0], packed.shape[1] * 8).astype(jnp.int8)


def _gen_candidates_matmul(s, k, col_ids, valid_row, row_chunks: int = 1):
    """Candidate generation as matmuls (module docstring): from the
    frequent (k-1)-set one-hot matrix ``s`` [M, F], the Boolean [M, F]
    candidate mask — ``cand[x, y]`` iff every (k-1)-subset of x∪{y}
    containing y is frequent AND y > max(x).  float32 on purpose: every
    value is an intersection size bounded by F (< 2^24), so f32
    accumulation is exact — and f32 matmuls hit the fast path on every
    backend (MXU on TPU, BLAS on the CPU fallback; XLA-CPU integer
    matmuls are orders slower).  Shared by the whole-loop miner and the
    shallow-tail miner so the two can never drift.

    ``row_chunks``: process the [M, M] intersection matrix in row
    blocks of M/row_chunks via lax.scan — the peak intermediate drops
    from 8·M² bytes to 8·M²/row_chunks, which is what lets the
    shallow-tail fold take 64K-row seeds (8·65536² = 34 GB unchunked)."""
    s_f = s.astype(jnp.float32)
    rowmax = jnp.max(jnp.where(s > 0, col_ids[None, :], -1), axis=1)

    def blk(s_blk):
        # lint: f32-gate -- intersection sizes bounded by F < 2^24 (docstring)
        d_blk = lax.dot_general(
            s_blk, s_f, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [mb, M] pairwise intersection sizes
        e_blk = (d_blk == (k - 2).astype(jnp.float32)).astype(jnp.float32)
        # lint: f32-gate -- subset-prune vote counts bounded by F < 2^24
        return lax.dot_general(
            e_blk, s_f, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        ).astype(jnp.int32)  # [mb, F]

    if row_chunks <= 1:
        cand_cnt = blk(s_f)
    else:
        m, f = s_f.shape
        assert m % row_chunks == 0, (m, row_chunks)
        sb = s_f.reshape(row_chunks, m // row_chunks, f)
        _, parts = lax.scan(lambda c, xb: (c, blk(xb)), jnp.int32(0), sb)
        cand_cnt = parts.reshape(m, f)
    return (
        (cand_cnt == (k - 1))
        & (col_ids[None, :] > rowmax[:, None])
        & valid_row
    )


def _weighted_counts(common, bitmap, w, n_digits: int, fast_f32: bool):
    """counts[m, f] = Σ_t w_t common[t, m] bitmap[t, f] via base-128 digit
    matmuls (ops/bitmap.py weight_digits, but on device).

    ``fast_f32`` runs the matmuls in float32 — exact only when the caller
    has proven every partial sum fits f32's integer range (engine checks
    ``127 · T_pad < 2^24``); used on CPU backends where XLA integer
    matmuls are orders of magnitude slower than BLAS."""
    dtype = jnp.float32 if fast_f32 else jnp.int8
    acc = jnp.float32 if fast_f32 else jnp.int32
    total = None
    for d in range(n_digits):
        w_d = ((w // (128**d)) % 128).astype(dtype)
        # Weights scale the F-wide bitmap side, not the M-wide common
        # side: a scaled [T_c, M] operand per digit was the dominant HBM
        # intermediate at large row budgets (same regrouping as
        # ops/count.py _weighted_matmul; integer arithmetic, exact).
        part = lax.dot_general(
            common.astype(dtype),
            bitmap.astype(dtype) * w_d[:, None],
            (((0,), (0,)), ((), ())),
            preferred_element_type=acc,
        )
        part = part.astype(jnp.int32)
        part = part if d == 0 else part * jnp.int32(128**d)
        total = part if total is None else total + part
    return total


def _fused_mine_local(
    packed,  # [T_local, F//8] uint8 — or [T_local, F] int8 (packed_input=False)
    w,  # [T_local] int32
    min_count,  # scalar int32
    sparse_thr=None,  # [S] int32 per-shard prune thresholds (sparse only)
    *,
    m_cap: int,
    l_max: int,
    n_digits: int,
    n_chunks: int,
    fast_f32: bool,
    axis_name: Optional[str],
    packed_input: bool = True,
    sparse_caps: Optional[Tuple[int, int]] = None,  # (pair, level) budgets
    groups: Optional[Tuple[int, int]] = None,  # two-level exchange grid
):
    f = packed.shape[1] * 8 if packed_input else packed.shape[1]
    t_local = packed.shape[0]
    assert t_local % n_chunks == 0, (t_local, n_chunks)
    t_c = t_local // n_chunks
    # Transaction chunking bounds the [T_c, M] `common` intermediate so
    # HBM never holds a full [T, M] matrix at Webdocs scale; the scan
    # accumulates the int32 count matrix across chunks.  With
    # ``packed_input`` the bitmap stays bit-packed in HBM — each chunk is
    # unpacked transiently on the VPU, an 8x resident-memory saving;
    # without it the engine hands over the ALREADY-resident unpacked int8
    # bitmap (the pipelined-ingest path shares one device bitmap between
    # both engines instead of paying a second upload).
    packed_c = packed.reshape(n_chunks, t_c, packed.shape[1])
    w_c = w.reshape(n_chunks, t_c)
    col_ids = jnp.arange(f, dtype=jnp.int32)

    def psum(x):
        return lax.psum(x, axis_name) if axis_name is not None else x

    def reduce_counts(counts, cand_mask, cap):
        """The per-level count reduction: dense psum, or the
        threshold-sparse exchange (ops/count.py local_sparse_psum — the
        same local-prune/union-gather/compact-sum the level engine
        runs) restricted to the level's candidate mask.  Returns
        ``(global counts, union census)``; a census above ``cap``
        makes the level's counts unusable — the overflow flag AND the
        census ride the meta row so the host re-runs the attempt with
        the dense reduction and memoizes the grown budget."""
        if sparse_caps is None or axis_name is None:
            return psum(counts), jnp.int32(0)
        from fastapriori_tpu.ops.count import local_sparse_psum

        thr = sparse_thr[lax.axis_index(axis_name)]
        out, nu = local_sparse_psum(
            counts, thr, cap, axis_name, valid=cand_mask, groups=groups
        )
        return out, nu

    def scan_counts(project, out_dim):
        """Σ over chunks of _weighted_counts(project(B_chunk), B_chunk)."""

        def step(acc, xs):
            pk, wk = xs
            b = _unpack(pk) if packed_input else pk
            return (
                acc + _weighted_counts(project(b), b, wk, n_digits, fast_f32),
                None,
            )

        acc0 = jnp.zeros((out_dim, f), dtype=jnp.int32)
        if axis_name is not None:
            # Mark the carry as device-varying over the mesh axis (each
            # shard accumulates its own partial sums; psum comes later).
            acc0 = compat.pcast(acc0, (axis_name,), to="varying")
        acc, _ = lax.scan(step, acc0, (packed_c, w_c))
        return acc

    # ---- level 2: weighted Gram matmul (C6) ---------------------------
    upper2 = col_ids[None, :] > col_ids[:, None]
    cap2 = sparse_caps[0] if sparse_caps else 0
    pair, nu2 = reduce_counts(
        scan_counts(lambda b: b, f), upper2, cap2
    )  # [F, F] int32
    sparse_nu = nu2
    sparse_ovf = nu2 > jnp.int32(cap2)
    mask2 = (pair >= min_count) & upper2
    n2 = jnp.sum(mask2, dtype=jnp.int32)
    r2, c2 = jnp.nonzero(mask2, size=m_cap, fill_value=0)
    valid2 = (jnp.arange(m_cap, dtype=jnp.int32) < n2)[:, None]
    s2 = (
        (jax.nn.one_hot(r2, f, dtype=jnp.int8)
         | jax.nn.one_hot(c2, f, dtype=jnp.int8))
        * valid2.astype(jnp.int8)
    )
    counts2 = pair[r2, c2] * valid2[:, 0].astype(jnp.int32)

    out_rows = jnp.zeros((l_max, m_cap), dtype=jnp.int32).at[0].set(r2)
    out_cols = jnp.zeros((l_max, m_cap), dtype=jnp.int32).at[0].set(c2)
    out_counts = jnp.zeros((l_max, m_cap), dtype=jnp.int32).at[0].set(counts2)
    out_n = jnp.zeros((l_max,), dtype=jnp.int32).at[0].set(n2)
    overflow = n2 > m_cap

    # ---- levels >= 3 (C7 + C8 + C9) -----------------------------------
    capk = sparse_caps[1] if sparse_caps else 0

    def cond(state):
        s, m, k, *_rest, ovf, sovf, _snu = state
        return (~ovf) & (~sovf) & (m >= k) & (k <= l_max + 1)

    def body(state):
        s, m, k, o_rows, o_cols, o_counts, o_n, ovf, sovf, snu = state
        valid_row = (jnp.arange(m_cap, dtype=jnp.int32) < m)[:, None]
        cand = _gen_candidates_matmul(s, k, col_ids, valid_row)

        # Support counting: common = (B Sᵀ == k-1); weighted matmul; psum.
        def contains_prefix(b):
            dt = jnp.float32 if fast_f32 else jnp.int8
            # int path: int8 output — intersection sizes are bounded by
            # the set size k-1 <= l_max, exact while l_max <= 127; an
            # l_max past that widens the accumulator to int32 (ADVICE r5
            # #1 — int8 would silently wrap) at 4x the [T_c, M]
            # intermediate's HBM traffic, which is what bounds this
            # phase.
            acc = (
                jnp.float32
                if fast_f32
                else (jnp.int32 if l_max >= 128 else jnp.int8)
            )
            overlap = lax.dot_general(
                b.astype(dt), s.astype(dt), (((1,), (1,)), ((), ())),
                preferred_element_type=acc,
            )  # [T_c, M] intersection sizes (bounded by F: f32-exact)
            return (overlap == (k - 1).astype(acc)).astype(jnp.int8)

        counts, lvl_nu = reduce_counts(
            scan_counts(contains_prefix, m_cap), cand, capk
        )

        surv = cand & (counts >= min_count)
        n = jnp.sum(surv, dtype=jnp.int32)
        rows, cols = jnp.nonzero(surv, size=m_cap, fill_value=0)
        valid = (jnp.arange(m_cap, dtype=jnp.int32) < n)[:, None]
        s_next = (
            (s[rows] | jax.nn.one_hot(cols, f, dtype=jnp.int8))
            * valid.astype(jnp.int8)
        )
        level_counts = counts[rows, cols] * valid[:, 0].astype(jnp.int32)

        idx = k - 2  # level k stored at slot k-2 (level 2 is slot 0)
        o_rows = o_rows.at[idx].set(rows)
        o_cols = o_cols.at[idx].set(cols)
        o_counts = o_counts.at[idx].set(level_counts)
        o_n = o_n.at[idx].set(n)
        ovf = ovf | (n > m_cap)
        return (
            s_next, n, k + 1, o_rows, o_cols, o_counts, o_n, ovf,
            sovf | (lvl_nu > jnp.int32(capk)),
            jnp.maximum(snu, lvl_nu),
        )

    state = (
        s2,
        n2,
        jnp.int32(3),
        out_rows,
        out_cols,
        out_counts,
        out_n,
        overflow,
        sparse_ovf,
        sparse_nu,
    )
    (
        s, m, k, out_rows, out_cols, out_counts, out_n, overflow,
        sparse_ovf, sparse_nu,
    ) = lax.while_loop(cond, body, state)
    # incomplete: loop stopped by the l_max bound while still converging.
    incomplete = overflow | ((m >= k) & (k > l_max + 1))
    # Pack everything into ONE int32 array so the host needs a single
    # device->host transfer (each blocking fetch costs a full round trip
    # on tunneled backends): rows | cols | counts stacked level-major,
    # then a meta row holding per-level survivor counts, the incomplete
    # flag at slot l_max, and the overflow flags at slot l_max+1
    # (m_cap > l_max+1 is asserted by the builders).  Overflow is
    # reported separately because the host's responses differ: overflow
    # retries with a budget sized from the true survivor counts (out_n
    # is the pre-cap sum, so the overflowing level's need is exact),
    # while an l_max-bound stop can't be fixed by more rows at all.
    # Bit 1 of the overflow slot is the sparse-reduction union overflow
    # (reduce_counts): the host re-runs the SAME budget with the dense
    # reduction — sharing the slot keeps the meta layout (and every
    # dense build's bytes) unchanged.  The max union census rides slot
    # l_max+2 when the row has room (m_cap == l_max+2 skips it — the
    # host just loses the budget memo, never correctness) so repeat
    # runs size the compaction right instead of re-paying the wasted
    # sparse dispatch.
    meta = (
        jnp.zeros((m_cap,), dtype=jnp.int32)
        .at[:l_max]
        .set(out_n)
        .at[l_max]
        .set(incomplete.astype(jnp.int32))
        .at[l_max + 1]
        .set(
            overflow.astype(jnp.int32)
            + 2 * sparse_ovf.astype(jnp.int32)
        )
    )
    if m_cap > l_max + 2:
        meta = meta.at[l_max + 2].set(sparse_nu)
    return jnp.concatenate(
        [out_rows, out_cols, out_counts, meta[None, :]], axis=0
    )


def make_pair_counter(
    mesh: Optional[Mesh],
    n_digits: int,
    n_chunks: int = 1,
    fast_f32: bool = False,
):
    """Cheap pre-pass over the same device-resident packed bitmap:
    ``(n2, tri)`` — the number of frequent pairs (level-2 survivors) and
    the level-3 candidate census (ops/count.py ``_pair_triangles``; -1
    when F exceeds its matmul bound).  The engine sizes the fused
    program's row budget from n2 and reads tri for the auto engine
    choice."""
    from fastapriori_tpu.ops.count import TRI_F_CAP, _pair_triangles

    def local(packed, w, min_count):
        f = packed.shape[1] * 8
        t_local = packed.shape[0]
        t_c = t_local // n_chunks
        packed_c = packed.reshape(n_chunks, t_c, packed.shape[1])
        w_c = w.reshape(n_chunks, t_c)

        def step(acc, xs):
            pk, wk = xs
            b = _unpack(pk)
            return acc + _weighted_counts(b, b, wk, n_digits, fast_f32), None

        acc0 = jnp.zeros((f, f), dtype=jnp.int32)
        if mesh is not None:
            acc0 = compat.pcast(acc0, (AXIS,), to="varying")
        pair, _ = lax.scan(step, acc0, (packed_c, w_c))
        if mesh is not None:
            pair = lax.psum(pair, AXIS)
        col = jnp.arange(f, dtype=jnp.int32)
        # Padded item columns have zero counts, so min_count >= 1 keeps
        # them out of the mask (and out of the triangle census).
        mask = (pair >= min_count) & (col[None, :] > col[:, None])
        n2 = jnp.sum(mask, dtype=jnp.int32)
        tri = _pair_triangles(mask) if f <= TRI_F_CAP else jnp.int32(-1)
        return n2, tri

    if mesh is None:
        return jax.jit(local)
    return jax.jit(
        compat.shard_map(
            local,
            mesh=mesh,
            in_specs=(P(AXIS, None), P(AXIS), P()),
            out_specs=(P(), P()),
        )
    )


def make_fused_miner(
    mesh: Optional[Mesh],
    m_cap: int,
    l_max: int,
    n_digits: int,
    n_chunks: int = 1,
    fast_f32: bool = False,
    packed_input: bool = True,
    sparse_caps: Optional[Tuple[int, int]] = None,
    groups: Optional[Tuple[int, int]] = None,
):
    """Build the jitted fused mining program.  With a mesh, the bitmap and
    weights are sharded over the txn axis inside shard_map (psum
    reductions); without one, a plain single-device jit.  Returns the
    packed [3*l_max+1, m_cap] int32 result (see _fused_mine_local).
    ``packed_input=False`` takes the level engine's resident unpacked
    int8 bitmap instead of the uint8 bit-packed form (pipelined-ingest
    sharing).  ``sparse_caps=(pair_cap, level_cap)`` switches both
    count reductions to the threshold-sparse exchange; the program then
    takes a fourth argument — the replicated [S] per-shard prune
    thresholds (weighted pigeonhole over the static shard weights)."""
    assert m_cap > l_max + 1, (m_cap, l_max)  # meta row layout requirement
    kernel = functools.partial(
        _fused_mine_local,
        m_cap=m_cap,
        l_max=l_max,
        n_digits=n_digits,
        n_chunks=n_chunks,
        fast_f32=fast_f32,
        axis_name=AXIS if mesh is not None else None,
        packed_input=packed_input,
        sparse_caps=sparse_caps if mesh is not None else None,
        groups=groups if mesh is not None else None,
    )
    if mesh is None:
        return jax.jit(kernel)
    in_specs = (P(AXIS, None), P(AXIS), P()) + (
        (P(None),) if sparse_caps is not None else ()
    )
    return jax.jit(
        compat.shard_map(
            kernel,
            mesh=mesh,
            in_specs=in_specs,
            out_specs=P(),
        )
    )


def _tail_mine_local(
    bitmap,  # [T_local, F] int8 — the level engine's resident bitmap
    w_digits,  # [D, T_local] int8 base-128 weight digits
    seed_cols,  # [m_cap, K0] int32 — current level's member matrix, padded
    n0,  # () int32 — real seed rows
    min_count,  # () int32
    heavy_b,  # [Th, F] int8 or None
    heavy_w,  # [Th] int32 or None
    sparse_thr=None,  # [S] int32 per-shard prune thresholds (sparse only)
    *,
    scales: Tuple[int, ...],
    k0: int,  # seed level depth (static: the compiled program is per-depth)
    m_cap: int,
    p_cap: int,
    l_max: int,
    n_chunks: int,
    axis_name: Optional[str],
    slot_caps: Tuple[int, ...],  # per-tail-level row caps (static)
    cand_row_chunks: int = 1,
    sparse_cap: Optional[int] = None,  # [p_cap, F] union slot budget
    groups: Optional[Tuple[int, int]] = None,  # two-level exchange grid
):
    """Shallow-tail fold (VERDICT r3 task 4): once the level engine's
    survivor count drops under the fold threshold, the REMAINING level
    loop runs as one device program seeded from the current level —
    the inverse of the fused→level salvage.  Each per-level launch on a
    tunneled chip costs a fixed ~110 ms round trip regardless of its
    (tiny) device math, so a 3-level tail pays ~330 ms of pure floor;
    this program pays it once (FastApriori.scala:111-121 is the loop
    being folded).

    Differences from :func:`_fused_mine_local`:

    - seeded: candidate generation starts from the uploaded seed matrix
      (a few-hundred-KB [m_cap, K0] index table, NOT the multi-MB
      one-hot) instead of from level 2;
    - prefix COMPACTION: candidates at tail depth live in few prefix
      rows, so the counting matmul gathers the ≤ p_cap rows that have
      any candidate extension instead of running all m_cap rows over
      the bitmap — the difference between ~14 TGMAC and ~1 TGMAC per
      level at webdocs scale (this is what makes the fold cheaper than
      the per-level engine rather than slower);
    - counting uses the level engine's weighted form (base-128 digit
      matmuls + the heavy-row int32 correction, ops/count.py) over the
      ALREADY-resident arrays — no raw-weight upload;
    - no overflow retry: p_cap/slot-cap/l_max overflow marks the level
      invalid (survivor-count sentinel > its slot cap) and the host
      resumes the per-level engine from the last complete level;
    - DESCENDING per-slot output caps (``slot_caps``): a fold's levels
      shrink, so slot i only reserves (and the host only FETCHES)
      ``slot_caps[i]`` rows — at m_cap=65536 a flat l_max x m_cap
      layout would be a 6 MB fetch over a tunnel down-link measured as
      low as 6.8 MB/s this round, vs ~1.6 MB compacted;
    - ``cand_row_chunks`` chunks the [M, M] candidate-gen intermediate
      (see _gen_candidates_matmul), which is what admits 64K-row seeds;
    - ``sparse_cap`` (with ``sparse_thr``) runs each iteration's
      [p_cap, F] count reduction as the threshold-sparse exchange
      (ops/count.py local_sparse_psum, validity = the iteration's
      candidate mask restricted to the compacted prefix rows) instead
      of the dense psum — the PR-6 residue: the fold was the last
      counting path still dense-psumming its per-iteration counts.  A
      union-compaction overflow marks the level invalid exactly like a
      p_cap overflow (the host resumes per-level and the max census
      rides the output so repeat runs size the budget right).

    Returns a 1-D int32 array: per slot i the compacted
    ``rows[:cap_i] | cols[:cap_i] | counts[:cap_i]`` runs, then
    ``n_per_level[l_max] | incomplete | max_union_census``
    (unpack_tail_result; the census slot reads 0 on dense builds)."""
    from fastapriori_tpu.ops.count import (
        _weighted_matmul,
        heavy_level_correction,
    )

    f = bitmap.shape[1]
    t_local = bitmap.shape[0]
    t_c = t_local // n_chunks
    bm = bitmap.reshape(n_chunks, t_c, f)
    d = w_digits.shape[0]
    wd = w_digits.reshape(d, n_chunks, t_c).transpose(1, 0, 2)
    col_ids = jnp.arange(f, dtype=jnp.int32)

    def psum(x):
        return lax.psum(x, axis_name) if axis_name is not None else x

    # Seed one-hot [m_cap, F] from the index table; padded rows zeroed.
    row_valid0 = (jnp.arange(m_cap, dtype=jnp.int32) < n0)[:, None]
    s0 = (
        jnp.zeros((m_cap, f), jnp.int8)
        .at[jnp.arange(m_cap)[:, None], seed_cols]
        .set(1)
        * row_valid0.astype(jnp.int8)
    )

    out_rows = jnp.zeros((l_max, m_cap), dtype=jnp.int32)
    out_cols = jnp.zeros((l_max, m_cap), dtype=jnp.int32)
    out_counts = jnp.zeros((l_max, m_cap), dtype=jnp.int32)
    out_n = jnp.zeros((l_max,), dtype=jnp.int32)

    def cond(state):
        s, m, k, *_rest, stop = state
        return (~stop) & (m >= k) & (k <= k0 + l_max)

    slot_caps_arr = jnp.asarray(slot_caps, dtype=jnp.int32)

    def body(state):
        s, m, k, o_rows, o_cols, o_counts, o_n, snu, stop = state
        valid_row = (jnp.arange(m_cap, dtype=jnp.int32) < m)[:, None]
        cand = _gen_candidates_matmul(
            s, k, col_ids, valid_row, row_chunks=cand_row_chunks
        )

        # Prefix compaction: only rows with >= 1 candidate extension go
        # through the counting matmul.
        has = jnp.any(cand, axis=1)
        n_pref = jnp.sum(has, dtype=jnp.int32)
        (pr,) = jnp.nonzero(has, size=p_cap, fill_value=0)
        valid_p = (jnp.arange(p_cap, dtype=jnp.int32) < n_pref)[:, None]
        s_p = s[pr] * valid_p.astype(jnp.int8)  # padded rows all-zero

        def step(acc, xs):
            b_chunk, wd_chunk = xs
            # int8 membership: values bounded by k-1 <= k0+l_max-1, and
            # the [t_c, p_cap] intermediate's HBM traffic bounds the
            # phase.  A tail reaching depth >= 129 widens to int32
            # rather than wrapping (ADVICE r5 #1); the static bound is
            # known at build time, so shallow tails pay nothing.
            member_dt = (
                jnp.int32 if k0 + l_max - 1 >= 128 else jnp.int8
            )
            member = lax.dot_general(
                b_chunk, s_p, (((1,), (1,)), ((), ())),
                preferred_element_type=member_dt,
            )  # [t_c, p_cap]
            common = (member == (k - 1).astype(member_dt)).astype(jnp.int8)
            return acc + _weighted_matmul(common, b_chunk, wd_chunk, scales), None

        acc0 = jnp.zeros((p_cap, f), dtype=jnp.int32)
        if axis_name is not None:
            acc0 = compat.pcast(acc0, (axis_name,), to="varying")
        counts_p, _ = lax.scan(step, acc0, (bm, wd))
        if heavy_b is not None:
            counts_p = counts_p + heavy_level_correction(
                s_p, (k - 1).astype(jnp.int32), heavy_b, heavy_w, axis_name
            )
        if sparse_cap is not None and axis_name is not None:
            # Threshold-sparse exchange over the compacted [p_cap, F]
            # counts (the PR-6 residue fold): validity restricted to
            # the iteration's candidate extensions so dead (prefix,
            # item) cells never enter the union.
            from fastapriori_tpu.ops.count import local_sparse_psum

            thr_s = sparse_thr[lax.axis_index(axis_name)]
            counts_p, lvl_nu = local_sparse_psum(
                counts_p, thr_s, sparse_cap, axis_name,
                valid=cand[pr] & valid_p, groups=groups,
            )
        else:
            counts_p = psum(counts_p)
            lvl_nu = jnp.int32(0)

        surv = cand[pr] & (counts_p >= min_count) & valid_p
        n = jnp.sum(surv, dtype=jnp.int32)
        rows_p, cols = jnp.nonzero(surv, size=m_cap, fill_value=0)
        valid = (jnp.arange(m_cap, dtype=jnp.int32) < n)[:, None]
        rows = pr[rows_p]
        s_next = (
            (s[rows] | jax.nn.one_hot(cols, f, dtype=jnp.int8))
            * valid.astype(jnp.int8)
        )
        level_counts = counts_p[rows_p, cols] * valid[:, 0].astype(jnp.int32)

        # Overflow: compaction, this slot's row cap, or the sparse
        # union budget exceeded -> this level's output is unusable;
        # store a sentinel survivor count above m_cap so the host's
        # decode stops before it.
        idx = k - k0 - 1  # tail level k0+1+i at slot i
        bad = (n_pref > p_cap) | (n > slot_caps_arr[idx])
        if sparse_cap is not None:
            bad = bad | (lvl_nu > jnp.int32(sparse_cap))
        o_rows = o_rows.at[idx].set(rows)
        o_cols = o_cols.at[idx].set(cols)
        o_counts = o_counts.at[idx].set(level_counts)
        o_n = o_n.at[idx].set(jnp.where(bad, jnp.int32(m_cap + 1), n))
        return (
            s_next, n, k + 1, o_rows, o_cols, o_counts, o_n,
            jnp.maximum(snu, lvl_nu), stop | bad,
        )

    state = (
        s0,
        n0,
        jnp.int32(k0 + 1),
        out_rows,
        out_cols,
        out_counts,
        out_n,
        jnp.int32(0),
        jnp.bool_(False),
    )
    (
        s, m, k, out_rows, out_cols, out_counts, out_n, snu, stop
    ) = lax.while_loop(cond, body, state)
    # incomplete: a bad level, or the l_max bound stopped a live loop —
    # either way the host resumes the per-level engine from the last
    # complete level.
    incomplete = stop | ((m >= k) & (k > k0 + l_max))
    parts = []
    for i, c in enumerate(slot_caps):
        parts += [out_rows[i, :c], out_cols[i, :c], out_counts[i, :c]]
    parts.append(out_n)
    parts.append(incomplete.astype(jnp.int32)[None])
    parts.append(snu[None])
    return jnp.concatenate(parts)


def tail_slot_caps(
    m_cap: int, l_max: int, flat: bool = False
) -> Tuple[int, ...]:
    """Descending per-tail-level row caps: slot i reserves m_cap >> i
    rows (floor 4096, never above m_cap) — a fold's levels shrink, and
    the compact output keeps the host fetch ~1.6 MB even at 64K-row
    seeds.  A level that violates the assumption trips the in-kernel
    ``bad`` sentinel and the host resumes per-level (exact either
    way).

    ``flat``: every slot reserves the full m_cap — the fused-checkpoint
    SEGMENT shape (models/apriori.py, ISSUE 9): a segment seeded
    mid-lattice can grow level over level, so the descending-caps
    assumption would trip the bad sentinel on perfectly minable levels;
    segments are shallow (the checkpoint cadence) and their seeds
    modest, so the flat fetch stays small."""
    if flat:
        return tuple(m_cap for _ in range(l_max))
    return tuple(
        min(m_cap, max(m_cap >> i, 4096)) for i in range(l_max)
    )


def tail_cand_row_chunks(m_cap: int) -> int:
    """Chunk count for the fold's [M, M] candidate-gen intermediates:
    smallest power of two keeping the per-chunk f32 block under
    ~512 MB."""
    rc = 1
    while 8 * m_cap * (m_cap // rc) > (512 << 20):
        rc *= 2
    return rc


def make_tail_miner(
    mesh: Optional[Mesh],
    scales: Tuple[int, ...],
    k0: int,
    m_cap: int,
    p_cap: int,
    l_max: int,
    n_chunks: int,
    has_heavy: bool,
    sparse_cap: Optional[int] = None,
    flat_caps: bool = False,
    groups: Optional[Tuple[int, int]] = None,
):
    """Build the jitted shallow-tail program (see _tail_mine_local).
    Sharded over the txn mesh axis like the level kernels; the seed
    table and outputs are replicated.  ``sparse_cap`` switches the
    per-iteration [p_cap, F] count reduction to the threshold-sparse
    exchange; the program then takes the replicated [S] per-shard
    prune-threshold array after ``min_count`` (before the heavy
    arrays).  ``flat_caps`` reserves the full m_cap per slot (the
    fused-checkpoint segment shape — see :func:`tail_slot_caps`)."""
    assert m_cap > l_max + 1, (m_cap, l_max)
    if mesh is None:
        sparse_cap = None  # the exchange is a mesh collective
    kernel = functools.partial(
        _tail_mine_local,
        scales=tuple(scales),
        k0=k0,
        m_cap=m_cap,
        p_cap=p_cap,
        l_max=l_max,
        n_chunks=n_chunks,
        axis_name=AXIS if mesh is not None else None,
        slot_caps=tail_slot_caps(m_cap, l_max, flat=flat_caps),
        cand_row_chunks=tail_cand_row_chunks(m_cap),
        sparse_cap=sparse_cap,
        groups=groups if mesh is not None else None,
    )

    def wrapped(bitmap, w_digits, seed_cols, n0, min_count, *rest):
        rest = list(rest)
        thr = rest.pop(0) if sparse_cap is not None else None
        hb, hw = rest if rest else (None, None)
        return kernel(
            bitmap, w_digits, seed_cols, n0, min_count, hb, hw, thr
        )

    if mesh is None:
        return jax.jit(wrapped)
    in_specs = (
        (P(AXIS, None), P(None, AXIS), P(None, None), P(), P())
        + ((P(None),) if sparse_cap is not None else ())
        + ((P(None, None), P(None)) if has_heavy else ())
    )
    return jax.jit(
        compat.shard_map(
            wrapped,
            mesh=mesh,
            in_specs=in_specs,
            out_specs=P(None),
        )
    )


def unpack_tail_result(
    packed: np.ndarray, m_cap: int, l_max: int, flat: bool = False
):
    """Split the tail miner's compact 1-D result (see _tail_mine_local)
    into (rows_list, cols_list, counts_list, n_per_level, incomplete,
    max_union_census) — the lists are per-slot 1-D arrays sized by
    :func:`tail_slot_caps` (``flat`` must match the build), consumable
    by decode_level_matrices with ``max_rows=slot_caps``.  The census
    is 0 for dense-reduction builds; under the sparse reduction a
    census above the build's cap names the overflowing union size (the
    host records it so repeat runs size the compaction right)."""
    caps = tail_slot_caps(m_cap, l_max, flat=flat)
    rows, cols, counts = [], [], []
    off = 0
    for c in caps:
        rows.append(packed[off : off + c]); off += c
        cols.append(packed[off : off + c]); off += c
        counts.append(packed[off : off + c]); off += c
    n_lvl = packed[off : off + l_max]
    incomplete = bool(packed[off + l_max])
    snu = (
        int(packed[off + l_max + 1])
        if packed.shape[0] > off + l_max + 1
        else 0
    )
    return rows, cols, counts, n_lvl, incomplete, snu


def unpack_fused_result(
    packed: np.ndarray, l_max: int
) -> Tuple[
    np.ndarray, np.ndarray, np.ndarray, np.ndarray, bool, bool, bool, int
]:
    """Split the packed [3*l_max+1, m_cap] device result into
    (rows, cols, counts, n_per_level, incomplete, overflow, sparse_ovf,
    sparse_nu).  ``sparse_ovf`` (bit 1 of the overflow slot) means the
    sparse count reduction's union compaction overflowed: every level's
    counts are unusable and the attempt must re-run with the dense
    reduction — checked BEFORE incomplete/overflow, which are undefined
    then.  ``sparse_nu`` is the max union census (slot l_max+2; 0 when
    the meta row had no room or the build was dense) — the budget the
    host memoizes so repeat runs never re-pay the overflow."""
    rows = packed[:l_max]
    cols = packed[l_max : 2 * l_max]
    counts = packed[2 * l_max : 3 * l_max]
    meta = packed[3 * l_max]
    return (
        rows,
        cols,
        counts,
        meta[:l_max],
        bool(meta[l_max]),
        bool(meta[l_max + 1] & 1),
        bool(meta[l_max + 1] >> 1),
        int(meta[l_max + 2]) if meta.shape[0] > l_max + 2 else 0,
    )


def decode_level_matrices(
    out_rows: np.ndarray,
    out_cols: np.ndarray,
    out_counts: np.ndarray,
    out_n: np.ndarray,
    max_rows: Optional[int] = None,
    prev: Optional[np.ndarray] = None,
) -> list:
    """Chain complete levels into ``[(member matrix int32[N, k],
    counts int64[N]), ...]`` — the level engine's inter-level
    representation, lex-sorted by construction (survivor extraction is
    row-major over a lex-ordered previous level via one gather per level
    — 1.35M itemsets at Webdocs scale made a per-set Python loop the
    decode bottleneck — and the extension column is always the largest
    member).

    ``max_rows`` (the attempt's row budget — a scalar, or the tail
    miner's per-slot cap sequence) stops BEFORE the first level whose
    true survivor count exceeded it: such a level's stored rows are
    truncated and must never be decoded.  Pass it when salvaging a
    failed attempt for the level engine to resume from; a successful
    attempt needs no cap.

    ``prev``: seed member matrix for slot 0's row indexes (the tail
    miner's output chains from the level the host handed it, not from
    level 2)."""
    out = []
    for lvl in range(len(out_n)):
        n = int(out_n[lvl])
        cap = (
            max_rows[lvl]
            if isinstance(max_rows, (list, tuple))
            else max_rows
        )
        if n == 0 or (cap is not None and n > cap):
            break
        rows = np.asarray(out_rows[lvl][:n], dtype=np.int32)
        cols = np.asarray(out_cols[lvl][:n], dtype=np.int32)
        if prev is None:
            cur = np.stack([rows, cols], axis=1)
        else:
            cur = np.concatenate([prev[rows], cols[:, None]], axis=1)
        out.append((cur, out_counts[lvl][:n].astype(np.int64)))
        prev = cur
    return out
