"""Vertical (Eclat-style) mining kernels: per-item tid-lists as packed
uint32 lanes, level-k support by lane-wise AND + popcount (ROADMAP item 3;
*RDD-Eclat*, arxiv 1912.06415, with the packed-lane set-intersection
layout of *A New Data Layout For Set Intersection on GPUs*, arxiv
1102.1003, adapted to uint32 lanes).

The horizontal bitmap-matmul engine (ops/count.py) counts a level as
``(1+D) · T · P · F`` MXU MACs — every transaction column scanned for
every possible extension, even when an itemset touches a few hundred
tids (BENCH r3-r5: 0.2-0.8% MFU at k=2 on sparse long-tail corpora).
The vertical engine inverts the layout: item ``f`` owns the packed
bitset of the transactions containing it (``uint32[NL]``, 32 tids per
lane, ``NL = T'/32``), a candidate's support is the popcount of the AND
of its members' lanes, and only the ACTUAL candidates are counted —
``(k·P + C·(1+B)) · NL`` word ops per level, a ``~32·F/k`` op reduction
against the matmul form on wide-item corpora.  Levels k >= 3 run that
AND+popcount form; k=2 — where EVERY pair is a candidate and
per-candidate gathers degenerate — runs as per-plane Gram matmuls over
lane chunks unpacked on the fly (RDD-Eclat likewise computes F2 from
the horizontal layout before verticalizing).

**Weighted counts via weight bit-planes.**  Multiplicity weights enter
as base-2 bit-planes packed along the tid axis (``w_t = Σ_b 2^b·bit_b``,
``planes uint32[B, NL]``), so a weighted support is
``Σ_b 2^b · popcount(inter & plane_b)`` — exact integer arithmetic for
any weight (no int8 saturation bound: unlike the matmul engines the
vertical path needs neither the base-128 digit split nor the heavy-row
correction, and stays exact at ANY lattice depth — there is no
``wide_member`` analog).  Deduplicated corpora (all weights 1) have
exactly one all-ones plane and the count is a pure popcount.

**Layout (the arxiv 1102.1003 adaptation).**  The device-resident arena
is dense ``uint32[F_pad+1, NL]`` (row ``F_pad`` is the all-ones AND
identity for padded prefix positions; the guaranteed-zero column
``F_pad-1`` of the horizontal bitmap keeps its role for padded
CANDIDATE slots).  The tid-space is a sequence of dense 32-bit
segments; an item's tids cluster into few of them on sparse corpora, so
the HOST→DEVICE form is index-compressed: per item, the (segment index,
segment word) pairs of its non-empty lanes, pow2-bucketed by active-
segment count so a handful of static shapes serve every item
(:func:`compress_arena`); one device dispatch scatters the buckets into
the dense arena (parallel/mesh.py ``upload_tid_arena``).  Sharding is
over the LANE axis — lane block ``s`` holds tids ``[s·T'/S, (s+1)·T'/S)``,
the same contiguous transaction split as the horizontal engine's row
sharding, so the weighted-pigeonhole shard thresholds of the sparse
count reduction (models/apriori.py ``_sparse_thresholds``) apply
unchanged and :func:`~fastapriori_tpu.ops.count.local_sparse_psum` is
reused verbatim for the cross-shard reduction.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np
from jax import lax

from fastapriori_tpu.ops.bitmap import next_pow2, pad_axis
from fastapriori_tpu.ops.count import (
    TRI_F_CAP,
    local_sparse_psum,
    pair_threshold_pack,
)

ONES_WORD = np.uint32(0xFFFFFFFF)


# ---------------------------------------------------------------------------
# host-side arena construction


def weight_bit_planes(
    weights: np.ndarray, t_pad: int, min_planes: int = 1
) -> Tuple[np.ndarray, List[int]]:
    """Base-2 bit-planes of the multiplicity weights, packed along the
    tid axis into uint32 lanes (LSB-first within each lane — the same
    bit order :func:`build_tid_arena_csr` uses for item lanes, which is
    the only thing that matters: AND/popcount never unpacks).

    Returns ``(planes uint32[B, t_pad//32], scales)`` with
    ``weights == Σ_b scales[b] · bit_b`` and ``scales[b] = 2**b``; B is
    data-dependent but static per compilation (1 for fully-deduplicated
    or weightless corpora, where plane 0 is the row-validity mask).
    ``min_planes`` forces a floor on B — multi-process lane sharding
    needs a GLOBALLY uniform plane count (SPMD static shapes), derived
    from the ingest-exchanged global max weight (ShardInfo.max_weight);
    the extra planes are all-zero and contribute 0 to every count."""
    assert t_pad % 32 == 0, t_pad
    w = np.zeros(t_pad, dtype=np.int64)
    w[: len(weights)] = weights
    b_planes = max(int(w.max()).bit_length(), 1, int(min_planes))
    shifts = np.arange(32, dtype=np.uint32)
    planes = np.zeros((b_planes, t_pad // 32), dtype=np.uint32)
    for b in range(b_planes):
        bits = ((w >> b) & 1).astype(np.uint32).reshape(-1, 32)
        planes[b] = (bits << shifts[None, :]).sum(axis=1, dtype=np.uint64)
    return planes, [1 << b for b in range(b_planes)]


# Below this many (item, lane) runs the thread-pool split of the
# reduceat pass costs more in pool setup than it saves.
_ARENA_THREAD_MIN_RUNS = 1 << 16


def build_tid_arena_csr(
    indices: np.ndarray,
    offsets: np.ndarray,
    num_items: int,
    txn_multiple: int = 32,
    item_multiple: int = 128,
    n_threads: int = 1,
) -> Tuple[np.ndarray, int, int]:
    """Build the dense tid-lane arena from the basket CSR: returns
    ``(arena uint32[f_pad+1, NL], f_pad, t_pad)`` with
    ``t_pad = pad_axis(T, lcm(txn_multiple, 32))`` and row ``f_pad`` the
    all-ones AND identity.  One sorted segment-reduce builds every
    item's lanes (``np.bitwise_or.reduceat`` over the (item, lane) runs
    — C speed, no per-basket Python loop).

    ``n_threads > 1`` splits the reduceat pass over the same host
    thread pool the pipelined ingest's segmented pass-1 scan uses
    (FA_INGEST_THREADS, models/apriori.py): runs are independent and
    write disjoint arena slots, so the split is a run-aligned partition
    of the sorted stream — identical output (OR is associative and each
    run stays whole), the PR-7 "single-threaded arena build" residue."""
    import math

    t = len(offsets) - 1
    mult = txn_multiple * 32 // math.gcd(txn_multiple, 32)
    t_pad = pad_axis(t, mult)
    f_pad = pad_axis(num_items + 1, item_multiple)
    nl = t_pad // 32
    arena = np.zeros((f_pad + 1, nl), dtype=np.uint32)
    if t > 0 and len(indices) > 0:
        rows = np.repeat(
            np.arange(t, dtype=np.int64), np.diff(offsets).astype(np.int64)
        )
        word = rows // 32
        bit = (np.uint32(1) << (rows % 32).astype(np.uint32)).astype(
            np.uint32
        )
        key = indices.astype(np.int64) * nl + word
        order = np.argsort(key, kind="stable")
        skey = key[order]
        bit_sorted = bit[order]
        uniq, start = np.unique(skey, return_index=True)
        flat = arena.reshape(-1)
        n_runs = len(uniq)
        if n_threads > 1 and n_runs >= _ARENA_THREAD_MIN_RUNS:
            from concurrent.futures import ThreadPoolExecutor

            # Run-aligned partition: thread j owns runs [lo, hi) — its
            # reduceat sees every element of its runs (the next
            # thread's first run starts at start[hi]) and its scatter
            # targets are disjoint uniq slots, so threads never race.
            bounds = [
                (n_runs * j) // n_threads for j in range(n_threads + 1)
            ]
            end = np.concatenate(
                [start[1:], np.asarray([len(skey)], dtype=start.dtype)]
            )

            def _reduce(j):
                lo, hi = bounds[j], bounds[j + 1]
                if lo >= hi:
                    return
                base = start[lo]
                flat[uniq[lo:hi]] = np.bitwise_or.reduceat(
                    bit_sorted[base : end[hi - 1]], start[lo:hi] - base
                )

            with ThreadPoolExecutor(n_threads) as pool:
                list(pool.map(_reduce, range(n_threads)))
        else:
            flat[uniq] = np.bitwise_or.reduceat(bit_sorted, start)
    arena[f_pad, :] = ONES_WORD
    return arena, f_pad, t_pad


def compress_arena(
    arena: np.ndarray, f_pad: int, build: bool = True
) -> Tuple[list, int, dict]:
    """Index-compressed, pow2-bucketed form of the arena's item rows
    (the arxiv 1102.1003 host→device layout): items are grouped by the
    pow2 bucket of their NON-EMPTY lane count, each bucket carrying
    ``(item_ids int32[nb'], seg_idx int32[nb', S_b], words
    uint32[nb', S_b])`` with ``nb'`` itself pow2-padded (padding rows
    target the AND-identity row ``f_pad`` at segment 0 with word 0 —
    absorbed by the scatter).  Returns ``(buckets, payload_bytes,
    stats)``; ``payload_bytes`` is the host→device transfer the
    compressed upload pays, versus the dense arena's ``4·F·NL``
    (``stats['occupancy']`` = active lanes / total — the density signal
    the engine auto-choice reads).  ``build=False`` returns the payload
    estimate and stats WITHOUT materializing the buckets (the census is
    vectorized numpy; the bucket fill is a per-item host loop) — the
    caller decides dense-vs-compressed first and only pays the fill
    when the compressed upload wins."""
    nl = arena.shape[1]
    if build:
        items, segs = np.nonzero(arena[:f_pad])
        counts = np.bincount(items, minlength=f_pad)
        n_active = int(items.size)
    else:
        # Census-only pass: one vectorized reduction over the arena —
        # no (item, seg) index materialization.
        counts = np.count_nonzero(arena[:f_pad], axis=1)
        n_active = int(counts.sum())
    stats = {
        "active_lanes": n_active,
        "occupancy": round(float(n_active) / max(f_pad * nl, 1), 6),
        "max_item_lanes": int(counts.max()) if counts.size else 0,
    }
    buckets = []
    active = np.flatnonzero(counts)
    if active.size == 0:
        return buckets, 0, stats
    pows = np.array([next_pow2(int(c)) for c in counts[active]])
    sizes = sorted(set(pows.tolist()))
    # Per bucket: nb' int32 ids + nb'·S_b (int32 seg_idx + uint32 word).
    payload = sum(
        next_pow2(int((pows == s_b).sum())) * (4 + 8 * s_b)
        for s_b in sizes
    )
    if not build:
        return buckets, payload, stats
    run_start = np.concatenate([[0], np.cumsum(counts[active])[:-1]])
    for s_b in sizes:
        sel = np.flatnonzero(pows == s_b)
        nb = next_pow2(sel.size)
        ids = np.full(nb, f_pad, dtype=np.int32)
        seg_idx = np.zeros((nb, s_b), dtype=np.int32)
        words = np.zeros((nb, s_b), dtype=np.uint32)
        for j, ai in enumerate(sel):
            item = int(active[ai])
            lo = run_start[ai]
            n = counts[item]
            ids[j] = item
            seg_idx[j, :n] = segs[lo : lo + n]
            words[j, :n] = arena[item, segs[lo : lo + n]]
        buckets.append((ids, seg_idx, words))
    return buckets, payload, stats


def assemble_arena(buckets, f_pad: int, nl: int) -> jnp.ndarray:
    """Device-side inverse of :func:`compress_arena`: scatter the
    compressed buckets into the dense ``uint32[f_pad+1, NL]`` arena.
    Each real (item, segment) pair appears exactly once, so a max-
    scatter over the zero-initialized arena lands every word exactly
    (bucket padding rows target the identity row with word 0 — a no-op
    under max, and the identity row is overwritten to all-ones last)."""
    arena = jnp.zeros((f_pad + 1, nl), dtype=jnp.uint32)
    for ids, seg_idx, words in buckets:
        arena = arena.at[ids[:, None], seg_idx].max(words)
    return arena.at[f_pad].set(jnp.uint32(0xFFFFFFFF))


# ---------------------------------------------------------------------------
# device kernels


def _popcount_weighted(
    inter: jnp.ndarray,  # [C, NL] uint32 intersection lanes
    w_planes: jnp.ndarray,  # [B, NL] uint32 weight bit-planes
    scales: Sequence[int],  # python ints, len B (static)
) -> jnp.ndarray:
    """``counts[c] = Σ_t w_t · [t ∈ inter_c]`` via per-plane popcounts
    (int32; exact for any weight — popcounts are bounded by 32·NL and
    the plane scales reassemble the integer weight exactly)."""
    total = None
    for b, scale in enumerate(scales):
        pc = lax.population_count(inter & w_planes[b][None, :])
        part = jnp.sum(pc.astype(jnp.int32), axis=1)
        part = part if scale == 1 else part * jnp.int32(scale)
        total = part if total is None else total + part
    return total


def _prefix_and(
    arena: jnp.ndarray,  # [f_pad+1, NL] uint32
    prefix_cols: jnp.ndarray,  # [P, K] int (padding -> zero column)
) -> jnp.ndarray:
    """AND of each prefix row's member lanes ([P, NL] uint32).  The
    dispatch layer pads prefix positions (and whole padded rows) with
    the horizontal engine's guaranteed-zero column ``f_pad - 1``; for
    the AND that must be the IDENTITY, so those entries remap to the
    all-ones row ``f_pad`` (the zero column is never a real item rank:
    ``f_pad >= num_items + 1``).  Padded prefix ROWS therefore AND to
    all-ones — harmless, because their candidate slots point at the
    zero column as the EXTENSION and gather a 0 count."""
    f_pad = arena.shape[0] - 1
    cols = prefix_cols.astype(jnp.int32)
    cols = jnp.where(cols == f_pad - 1, f_pad, cols)
    acc = jnp.take(arena, cols[:, 0], axis=0)
    for i in range(1, cols.shape[1]):
        acc = acc & jnp.take(arena, cols[:, i], axis=0)
    return acc


def _chunked_candidate_counts(
    pref: jnp.ndarray,  # [P, NL] uint32 prefix lanes (or the arena itself)
    arena: jnp.ndarray,  # [f_pad+1, NL] uint32
    w_planes: jnp.ndarray,
    scales: Sequence[int],
    cand_idx: jnp.ndarray,  # [C] int32 flat row·f_pad + y
    cand_chunk: int,
) -> jnp.ndarray:
    """Per-candidate intersection counts, scanned in ``cand_chunk``
    blocks so the [chunk, NL] gathered intermediates stay bounded in
    HBM regardless of the candidate count.  Returns int32[C] local
    (per-shard) counts."""
    f_pad = arena.shape[0] - 1
    c = cand_idx.shape[0]
    assert c % cand_chunk == 0, (c, cand_chunk)

    def step(carry, ix):
        row = ix // f_pad
        y = ix % f_pad
        inter = jnp.take(pref, row, axis=0) & jnp.take(arena, y, axis=0)
        return carry, _popcount_weighted(inter, w_planes, scales)

    _, parts = lax.scan(
        step, jnp.int32(0), cand_idx.reshape(c // cand_chunk, cand_chunk)
    )
    return parts.reshape(-1)


def _lane_tiled_counts(
    arena: jnp.ndarray,  # [f_pad+1, NL] uint32
    w_planes: jnp.ndarray,
    scales: Sequence[int],
    prefix_cols: jnp.ndarray,  # [P, K]
    cand_idx: jnp.ndarray,  # [C] int32
    cand_chunk: int,
    lane_tile: int,
    axis_name: Optional[str] = None,
) -> jnp.ndarray:
    """Lane-streamed form of the level-k count: ``lax.scan`` over
    ``lane_tile``-wide slabs of the arena, each step the plain
    prefix-AND + candidate-intersection body on a ``[P, lane_tile]``
    slice — the prefix intermediate is bounded by the tile regardless
    of T (the ~50K-lane ceiling the unstreamed ``[P, NL]`` form hits).
    Bit-exact vs the single-slab form: int32 addition is associative
    and the zero-lane padding of the last slab contributes 0 to every
    popcount (the vertical_pair_local padding argument — padded member
    and plane lanes are all zero)."""
    nl = arena.shape[1]
    nt = -(-nl // lane_tile)
    pad = nt * lane_tile - nl
    a = jnp.pad(arena, ((0, 0), (0, pad))) if pad else arena
    w = jnp.pad(w_planes, ((0, 0), (0, pad))) if pad else w_planes
    a_t = a.reshape(a.shape[0], nt, lane_tile).transpose(1, 0, 2)
    w_t = w.reshape(w.shape[0], nt, lane_tile).transpose(1, 0, 2)

    def step(acc, xs):
        at, wt = xs  # [f_pad+1, LT] uint32, [B, LT] uint32
        pref = _prefix_and(at, prefix_cols)
        part = _chunked_candidate_counts(
            pref, at, wt, scales, cand_idx, cand_chunk
        )
        return acc + part, None

    acc0 = jnp.zeros((cand_idx.shape[0],), jnp.int32)
    if axis_name is not None:
        from fastapriori_tpu import compat

        acc0 = compat.pcast(acc0, (axis_name,), to="varying")
    local, _ = lax.scan(step, acc0, (a_t, w_t))
    return local


def _unpack_lanes(lanes: jnp.ndarray) -> jnp.ndarray:
    """uint32 [..., L] -> int8 [..., L*32] (LSB-first per lane — the
    arena/plane bit order)."""
    shifts = jnp.arange(32, dtype=jnp.uint32)
    bits = (lanes[..., :, None] >> shifts) & jnp.uint32(1)
    return bits.reshape(*lanes.shape[:-1], lanes.shape[-1] * 32).astype(
        jnp.int8
    )


def vertical_pair_local(
    arena: jnp.ndarray,  # [f_pad+1, NL_local] uint32 (lanes sharded)
    w_planes: jnp.ndarray,  # [B, NL_local] uint32
    scales: Sequence[int],
    min_count: jnp.ndarray,  # () int32 (traced)
    num_items: jnp.ndarray,  # () int32 (traced)
    cap: int,
    n_chunks: int,
    axis_name: Optional[str] = None,
    fast_f32: bool = False,
    sparse_thr: Optional[jnp.ndarray] = None,  # () int32 per-shard prune
    sparse_cap: Optional[int] = None,
    groups: Optional[tuple] = None,  # two-level exchange grid (hier.py)
) -> tuple:
    """C6, vertical-arena form.  At k=2 EVERY pair is a candidate, so
    per-candidate lane intersections degenerate to ``F²/2`` redundant
    row gathers and lose to the MXU/BLAS Gram (measured 6x slower on
    the sparse bench corpus) — RDD-Eclat itself computes F2 from the
    horizontal layout before verticalizing (arxiv 1912.06415 §4).  So
    the pair phase runs as per-PLANE Gram matmuls over lane chunks
    unpacked on the fly: ``G = Σ_b 2^b · (A ⊙ plane_b) Aᵀ`` with ``A``
    the arena's bit matrix — int8×int8→int32 (exact for any count), or
    ONE f32 matmul with the reassembled weights folded in under
    ``fast_f32`` (callers prove ``n_raw < 2^24``: entries are weighted
    counts bounded by the raw transaction total).  The vertical win
    starts at k=3, where only ACTUAL candidates are counted
    (:func:`vertical_level_local`).

    The counts land in the same ``[F, F]`` matrix the horizontal engine
    produces, so everything downstream — ``pair_threshold_pack``, the
    level-3 census, the resident-matrix overflow regather — is reused
    verbatim and the engines cannot drift.  Returns
    ``(packed, counts_mat)`` exactly like ``local_pair_gather`` (packed
    gains the trailing union census under the sparse reduction)."""
    f_pad = arena.shape[0] - 1
    nl = arena.shape[1]
    # Lane counts are not generally multiples of the chunk count (a
    # prime local lane count must not degrade to per-lane scan steps):
    # pad the scan axis with zero lanes — zero bits contribute nothing
    # to any Gram entry, so the padded chunks are exact.
    lc = -(-nl // n_chunks)
    lanes = arena[:f_pad]
    planes = w_planes
    if lc * n_chunks > nl:
        pad = lc * n_chunks - nl
        lanes = jnp.pad(lanes, ((0, 0), (0, pad)))
        planes = jnp.pad(planes, ((0, 0), (0, pad)))
    lanes_c = lanes.reshape(f_pad, n_chunks, lc).transpose(1, 0, 2)
    planes_c = planes.reshape(
        planes.shape[0], n_chunks, lc
    ).transpose(1, 0, 2)
    def step(acc, xs):
        lane_c, plane_c = xs  # [f_pad, lc] uint32, [B, lc] uint32
        if fast_f32:
            # ONE matmul with the reassembled f32 weights folded into
            # the scaled side (the bitmap engine's _weights_f32 trick)
            # — exact under the caller's n_raw < 2^24 gate (weighted
            # counts are bounded by the raw transaction total).
            bits = _unpack_lanes(lane_c).astype(jnp.float32)
            w = None
            for b, scale in enumerate(scales):
                part = _unpack_lanes(plane_c[b]).astype(jnp.float32)
                part = part if scale == 1 else part * jnp.float32(scale)
                w = part if w is None else w + part
            # lint: f32-gate -- fast_f32 callers prove n_raw < 2^24 (weighted counts bounded by the raw total)
            part = lax.dot_general(
                bits * w[None, :],
                bits,
                (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            ).astype(jnp.int32)
            return acc + part, None
        # Integer path (TPU / counts past 2^24): one int8 matmul per
        # weight bit-plane, int32 accumulation — exact for any count.
        bits = _unpack_lanes(lane_c)  # int8
        total = acc
        for b, scale in enumerate(scales):
            wb = _unpack_lanes(plane_c[b])  # [lc*32] int8
            part = lax.dot_general(
                bits * wb[None, :],
                bits,
                (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.int32,
            )
            part = part if scale == 1 else part * jnp.int32(scale)
            total = total + part
        return total, None

    acc0 = jnp.zeros((f_pad, f_pad), jnp.int32)
    if axis_name is not None:
        from fastapriori_tpu import compat

        acc0 = compat.pcast(acc0, (axis_name,), to="varying")
    local, _ = lax.scan(step, acc0, (lanes_c, planes_c))
    nu = None
    if sparse_cap is not None and axis_name is not None:
        iu = jnp.arange(f_pad)
        cand = (iu[None, :] > iu[:, None]) & (iu[None, :] < num_items)
        counts_mat, nu = local_sparse_psum(
            local, sparse_thr, sparse_cap, axis_name, valid=cand,
            groups=groups,
        )
    elif axis_name is not None:
        counts_mat = lax.psum(local, axis_name)
    else:
        counts_mat = local
    packed = pair_threshold_pack(
        counts_mat, min_count, num_items, cap, census=f_pad <= TRI_F_CAP
    )
    if nu is not None:
        packed = jnp.concatenate([packed, nu[None]])
    return packed, counts_mat


def vertical_level_local(
    arena: jnp.ndarray,  # [f_pad+1, NL_local] uint32
    w_planes: jnp.ndarray,  # [B, NL_local] uint32
    scales: Sequence[int],
    prefix_cols: jnp.ndarray,  # [P, K] int; padding -> zero column
    cand_idx: jnp.ndarray,  # [C] int32 flat row·f_pad + y
    cand_chunk: int,
    axis_name: Optional[str] = None,
    sparse_thr: Optional[jnp.ndarray] = None,
    sparse_cap: Optional[int] = None,
    groups: Optional[tuple] = None,
    lane_tile: int = 0,
    pallas: Optional[tuple] = None,  # (cand_tile, lane_tile, interpret)
):
    """C8, vertical form: one AND-reduction per prefix row, then per-
    candidate lane intersections with the extension items — only the
    ACTUAL candidates are counted (the matmul engine counts all P·F
    possible extensions).  Same dispatch-layer contract as
    ``local_level_gather``: padded prefix positions/rows and padded
    candidate slots all resolve to zero counts; the prefix width K is
    static per bucket but needs NO traced ``k1`` (the AND identity
    handles padding, and popcounts are exact at any depth — no int8
    membership bound, no ``wide_member`` widen).  ``lane_tile`` streams
    the lane axis in tiles (0 = single slab, exact either way);
    ``pallas`` swaps the local body for the VMEM-resident kernel
    (ops/pallas_vertical.py) — the cross-shard reduction below is
    shared by all three forms, so the tiers cannot drift.  Returns
    int32[C] reduced counts, or ``(counts, n_union)`` under
    ``sparse_cap``."""
    if pallas is not None:
        from fastapriori_tpu.ops.pallas_vertical import (
            vertical_counts_pallas,
        )

        ct, lt, interp = pallas
        local = vertical_counts_pallas(
            arena, w_planes, prefix_cols, cand_idx,
            tuple(scales), ct, lt, interp,
        )
    elif lane_tile and arena.shape[1] > lane_tile:
        local = _lane_tiled_counts(
            arena, w_planes, scales, prefix_cols, cand_idx,
            cand_chunk, lane_tile, axis_name=axis_name,
        )
    else:
        pref = _prefix_and(arena, prefix_cols)
        local = _chunked_candidate_counts(
            pref, arena, w_planes, scales, cand_idx, cand_chunk
        )
    if sparse_cap is not None and axis_name is not None:
        return local_sparse_psum(
            local, sparse_thr, sparse_cap, axis_name, groups=groups
        )
    if axis_name is not None:
        return lax.psum(local, axis_name)
    return local


def vertical_level_batch(
    arena: jnp.ndarray,
    w_planes: jnp.ndarray,
    scales: Sequence[int],
    prefix_stack: jnp.ndarray,  # [NB, P, K]
    cand_stack: jnp.ndarray,  # [NB, C]
    cand_chunk: int,
    axis_name: Optional[str] = None,
    sparse_thr: Optional[jnp.ndarray] = None,
    sparse_cap: Optional[int] = None,
    groups: Optional[tuple] = None,
    lane_tile: int = 0,
    pallas: Optional[tuple] = None,
):
    """A whole level's prefix blocks in ONE launch (the vertical twin of
    ``local_level_gather_batch``): ``lax.scan`` over the stacked blocks,
    each step one :func:`vertical_level_local`.  Returns ``[NB, C]``
    counts — or ``([NB, C], [NB])`` union censuses under the sparse
    reduction."""

    def step(carry, xs):
        pc, ci = xs
        out = vertical_level_local(
            arena, w_planes, scales, pc, ci, cand_chunk,
            axis_name=axis_name, sparse_thr=sparse_thr,
            sparse_cap=sparse_cap, groups=groups,
            lane_tile=lane_tile, pallas=pallas,
        )
        return carry, out

    _, outs = lax.scan(step, jnp.int32(0), (prefix_stack, cand_stack))
    return outs


def vertical_level_word_ops(
    nb: int, p_cap: int, k_pad: int, c_cap: int, n_planes: int, nl: int
) -> int:
    """uint32 word-op model of one vertical level launch (the metrics
    analog of the matmul engines' ``macs`` — NOT MXU MACs, so it rides
    the separate ``vops`` field and never inflates an MFU claim):
    per block, K gather-ANDs over the [P, NL] prefix lanes plus
    ``(1 + B)`` AND+popcount passes over the [C, NL] candidate
    intersections."""
    return nb * (k_pad * p_cap + (1 + n_planes) * c_cap) * nl


def vertical_member_bytes(nb: int, p_cap: int, nl: int) -> int:
    """HBM bytes of the ``[P_cap, NL]`` prefix-AND intermediate per
    level launch (one uint32 write + one read) — the traffic the Pallas
    tier (ops/pallas_vertical.py) keeps in VMEM.  Rides the metrics
    ``member_bytes_saved`` field: bench --engine-compare's per-level
    HBM-traffic model for the pallas flavor."""
    return nb * 2 * 4 * p_cap * nl
