from fastapriori_tpu.ops.bitmap import (  # noqa: F401
    build_bitmap,
    pad_axis,
    weight_digits,
)
from fastapriori_tpu.ops.count import (  # noqa: F401
    local_level_counts,
    local_pair_counts,
)
