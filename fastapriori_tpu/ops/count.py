"""Support-counting kernels (reference C6/C8) as MXU matmuls.

The reference counts candidate support by scanning Boolean arrays per
candidate on Spark executors (the hot loops at FastApriori.scala:145,
149-151, 233-235).  Here every level is a handful of int8×int8→int32
matmuls:

- pair counts (C6):   ``C2[f,g] = Σ_t w_t B[t,f] B[t,g]`` — one matmul
  replaces all of genTwoFreqItems (FastApriori.scala:212-241);
- level-k counts (C8): per candidate prefix S (a frequent (k-1)-set),
  ``common[t,p] = Π_{i∈S_p} B[t,i]`` (k-1 gathers + elementwise products),
  then ``counts[p,f] = Σ_t w_t common[t,p] B[t,f]`` for ALL possible
  extensions f at once — one (P×T)·(T×F) matmul replaces
  genNextFreqItemsets (FastApriori.scala:132-160).

These functions compute *local* (per-shard) partial counts over the
transaction axis and finish with ``lax.psum`` over the mesh axis when one
is given — the TPU-native replacement for the reference's
``reduceByKey``+``collect`` (SURVEY.md C15).  Weights enter via base-128
int8 digits (see ops/bitmap.py) so the MXU path stays int8.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
from jax import lax


def _psum_if(x: jnp.ndarray, axis_name: Optional[str]) -> jnp.ndarray:
    return lax.psum(x, axis_name) if axis_name is not None else x


def _weighted_matmul(
    lhs_int8: jnp.ndarray,  # [T, P] int8 (0/1)
    bitmap: jnp.ndarray,  # [T, F] int8 (0/1)
    w_digits: jnp.ndarray,  # [D, T] int8
    scales: Sequence[int],  # python ints, len D (static)
) -> jnp.ndarray:
    """``out[p,f] = Σ_t w_t lhs[t,p] bitmap[t,f]`` via per-digit int8
    matmuls with int32 accumulation (exact for counts < 2^31)."""
    total = None
    for d, scale in enumerate(scales):
        scaled = lhs_int8 * w_digits[d][:, None]  # int8 in [0,127]
        part = lax.dot_general(
            scaled,
            bitmap,
            (((0,), (0,)), ((), ())),  # contract over T
            preferred_element_type=jnp.int32,
        )
        part = part if scale == 1 else part * jnp.int32(scale)
        total = part if total is None else total + part
    return total


def local_pair_counts(
    bitmap: jnp.ndarray,  # [T_local, F] int8
    w_digits: jnp.ndarray,  # [D, T_local] int8
    scales: Sequence[int],
    axis_name: Optional[str] = None,
) -> jnp.ndarray:
    """C6: weighted co-occurrence counts for all item pairs.

    Returns int32[F, F]; entry (f, g) is the weighted number of distinct
    baskets containing both f and g (diagonal = weighted item support over
    size>=2 baskets; callers read the upper triangle).
    """
    counts = _weighted_matmul(bitmap, bitmap, w_digits, scales)
    return _psum_if(counts, axis_name)


def local_level_counts(
    bitmap: jnp.ndarray,  # [T_local, F] int8
    w_digits: jnp.ndarray,  # [D, T_local] int8
    scales: Sequence[int],
    prefix_cols: jnp.ndarray,  # [P, K] int32 column indexes (K = k-1, static)
    axis_name: Optional[str] = None,
) -> jnp.ndarray:
    """C8: weighted support of (prefix ∪ {f}) for every prefix row and every
    item f simultaneously.

    ``prefix_cols`` rows are the k-1 item ranks of each candidate prefix;
    padding rows must point at an all-zero padded column so their counts
    are 0.  Returns int32[P, F].
    """
    k = prefix_cols.shape[1]
    common = jnp.take(bitmap, prefix_cols[:, 0], axis=1)  # [T, P] int8
    for i in range(1, k):
        common = common * jnp.take(bitmap, prefix_cols[:, i], axis=1)
    counts = _weighted_matmul(common, bitmap, w_digits, scales)
    return _psum_if(counts, axis_name)


def local_item_supports(
    bitmap: jnp.ndarray,  # [T_local, F] int8
    w_digits: jnp.ndarray,  # [D, T_local] int8
    scales: Sequence[int],
    axis_name: Optional[str] = None,
) -> jnp.ndarray:
    """Weighted per-item support over the compressed baskets (int32[F]).

    Not a reference component (the reference's 1-item counts are raw
    occurrence counts from C3) — used by tests and diagnostics."""
    total = None
    for d, scale in enumerate(scales):
        part = jnp.sum(
            bitmap.astype(jnp.int32) * w_digits[d].astype(jnp.int32)[:, None],
            axis=0,
        )
        part = part if scale == 1 else part * jnp.int32(scale)
        total = part if total is None else total + part
    return _psum_if(total, axis_name)
