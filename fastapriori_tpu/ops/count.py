"""Support-counting kernels (reference C6/C8) as MXU matmuls.

The reference counts candidate support by scanning Boolean arrays per
candidate on Spark executors (the hot loops at FastApriori.scala:145,
149-151, 233-235).  Here every level is a handful of int8×int8→int32
matmuls:

- pair counts (C6):   ``C2[f,g] = Σ_t w_t B[t,f] B[t,g]`` — one matmul
  replaces all of genTwoFreqItems (FastApriori.scala:212-241);
- level-k counts (C8): per candidate prefix S (a frequent (k-1)-set),
  ``common[t,p] = Π_{i∈S_p} B[t,i]`` (k-1 gathers + elementwise products),
  then ``counts[p,f] = Σ_t w_t common[t,p] B[t,f]`` for ALL possible
  extensions f at once — one (P×T)·(T×F) matmul replaces
  genNextFreqItemsets (FastApriori.scala:132-160).

These functions compute *local* (per-shard) partial counts over the
transaction axis and finish with ``lax.psum`` over the mesh axis when one
is given — the TPU-native replacement for the reference's
``reduceByKey``+``collect`` (SURVEY.md C15).  Weights enter via base-128
int8 digits (see ops/bitmap.py) so the MXU path stays int8.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
from jax import lax

from fastapriori_tpu import compat


def _psum_if(x: jnp.ndarray, axis_name: Optional[str]) -> jnp.ndarray:
    return lax.psum(x, axis_name) if axis_name is not None else x


def sparse_union_cap(n_valid: int, override: Optional[int] = None) -> int:
    """Union-compaction slot budget for :func:`local_sparse_psum`:
    ``n_valid/16`` rounded to a pow2 bucket (floor 1024, never above the
    candidate count's own bucket) — at that size the sparse exchange's
    bytes (S·n/8 mask gather + 4·cap compact psum) stay under 25% of the
    dense 4·n psum on a 4-shard mesh.  ``override``
    (config.count_sparse_cap / FA_COUNT_SPARSE_CAP) is pow2-bucketed and
    clamped the same way, so every compiled compaction shape stays in
    the bucket family (G011)."""
    from fastapriori_tpu.ops.bitmap import next_pow2

    ceiling = next_pow2(max(n_valid, 8))
    if override is not None and override > 0:
        return min(next_pow2(override), ceiling)
    return min(next_pow2(max(n_valid // 16, 1024)), ceiling)


def _unpack_bits_msb(packed: jnp.ndarray) -> jnp.ndarray:
    """uint8 [..., N//8] -> bool [..., N] (MSB-first, the inverse of
    :func:`pack_bits_msb`)."""
    shifts = jnp.arange(7, -1, -1, dtype=jnp.uint8)
    bits = (packed[..., :, None] >> shifts) & 1
    return bits.reshape(
        *packed.shape[:-1], packed.shape[-1] * 8
    ).astype(jnp.bool_)


def local_sparse_psum(
    local: jnp.ndarray,  # int32 partial counts (any shape, size % 8 == 0)
    thr: jnp.ndarray,  # () int32 — THIS shard's local-prune threshold
    cap: int,  # static union-compaction slot budget (pow2)
    axis_name: str,
    valid: Optional[jnp.ndarray] = None,  # bool, same shape: candidate mask
    groups: Optional[tuple] = None,  # (groups, per_group) two-level grid
) -> tuple:
    """Threshold-sparse replacement for the dense ``lax.psum`` over the
    txn mesh axis (ROADMAP item 2; *Sparse Allreduce*, arxiv 1312.3020):
    candidate supports are power-law — most candidates die at
    min_count — yet the dense reduction moves every partial count over
    ICI/DCN.  Three steps, all inside the one counting dispatch:

    1. **local prune**: each shard keeps candidates with local count
       >= ``thr``, its weighted-pigeonhole threshold
       ``max(1, ceil(min_count · W_s / W))`` (W_s = the shard's static
       total transaction weight).  Any candidate below EVERY shard's
       threshold sums below min_count globally, so the union of the
       per-shard survivor sets is a superset of the frequent set —
       pruning loses nothing.
    2. **union exchange**: the survivor masks cross the axis bit-packed
       (``all_gather`` of N/8 bytes per shard vs the dense psum's 4·N);
       OR-ing them gives every shard the identical union.
    3. **compact segment-sum**: each shard gathers its OWN local counts
       at the first ``cap`` union positions and one compact [cap] psum
       produces the EXACT global sums (every shard contributes at every
       union position — including sub-threshold contributions — so
       surviving counts are bit-exact vs the dense path); the sums
       scatter back so callers see the same [N]-shaped tensor, zero at
       provably-infrequent positions.

    ``groups``: a ``(groups, per_group)`` grid over the axis routes
    both exchanges through the two-level hierarchy
    (parallel/hier.py — intra-group union/sum, then one inter-group
    exchange over the grid columns): mask-gather bytes drop from
    ``S·n/8`` to ``(per_group + groups)·n/8`` per shard, bit-exact by
    associativity.  None keeps the flat single-level exchange (the
    differential oracle and the ``hier→flat`` cascade fallback).

    Returns ``(counts, n_union)``; ``n_union > cap`` means the
    compaction truncated and the result is UNUSABLE — callers must
    detect it and fall back to the dense reduction (they get the true
    union size to resize with)."""
    flat = local.reshape(-1)
    n = flat.shape[0]
    assert n % 8 == 0, n
    promising = flat >= thr
    if valid is not None:
        promising = promising & valid.reshape(-1)
    packed = pack_bits_msb(promising)  # [n//8] uint8
    if groups is not None:
        from fastapriori_tpu.parallel.hier import hier_union_packed

        union_packed = hier_union_packed(packed, axis_name, groups)
    else:
        gathered = lax.all_gather(packed, axis_name)  # [S, n//8]
        union_packed = lax.reduce(
            gathered, jnp.uint8(0), lax.bitwise_or, (0,)
        )
    union = _unpack_bits_msb(union_packed)  # [n] bool, identical per shard
    nu = jnp.sum(union, dtype=jnp.int32)
    (upos,) = jnp.nonzero(union, size=cap, fill_value=0)
    upos = upos.astype(jnp.int32)
    slot_ok = jnp.arange(cap, dtype=jnp.int32) < nu
    comp = jnp.where(slot_ok, jnp.take(flat, upos), 0)
    if groups is not None:
        from fastapriori_tpu.parallel.hier import hier_psum

        summed = hier_psum(comp, axis_name, groups)
    else:
        summed = lax.psum(comp, axis_name)
    # Scatter-ADD onto zeros: overflow fill slots point at position 0,
    # but their contribution is masked to 0, so a real union member at
    # position 0 still lands its exact sum.
    counts = (
        jnp.zeros_like(flat)
        .at[upos]
        .add(jnp.where(slot_ok, summed, 0))
    )
    return counts.reshape(local.shape), nu


def sparse_psum_bytes(
    n_valid: int, cap: int, n_shards: int, groups: Optional[tuple] = None
) -> tuple:
    """(gather_bytes, psum_bytes) payload model of one
    :func:`local_sparse_psum` call — the per-engine comms accounting
    bench records next to the dense ``4·n`` psum figure.  The mask
    gather lands S·n/8 bytes per shard — or ``(per_group + groups)·n/8``
    under the hierarchical exchange (``groups``; parallel/hier.py) —
    and the compact psum payload is 4·cap (+4 for the union census
    riding the survivor fetch; its per-hop payload is
    topology-independent — the hierarchy restages the reduction, it
    does not grow the summed tensor)."""
    if groups is not None:
        g, per = groups
        return (g + per) * (n_valid // 8), 4 * cap + 4
    return n_shards * (n_valid // 8), 4 * cap + 4


def sparse_stage_bytes(
    n_valid: int, cap: int, n_shards: int, groups: Optional[tuple] = None
) -> tuple:
    """Per-shard ``(intra_bytes, inter_bytes)`` attribution of the SAME
    payload :func:`sparse_psum_bytes` totals — the per-stage fields the
    scaling bench and the trace counter tracks record (flat: the whole
    exchange is the single slow tier; hierarchical: the intra stage
    moves ``per_group`` mask payloads over the fast tier, the inter
    stage ``groups`` group aggregates plus the compact psum)."""
    from fastapriori_tpu.parallel.hier import union_stage_bytes

    intra, inter = union_stage_bytes(n_valid // 8, n_shards, groups)
    return intra, inter + 4 * cap + 4


# Item-axis bound for the in-kernel level-3 candidate census: the extra
# [F, F] matmul is ~2·F³ flops (sub-ms on the MXU at 4096, but F³ grows
# fast and sparse-item datasets — the ones with F in the tens of
# thousands — never need the signal; their pair graphs are sparse and the
# fused engine fits them anyway).
TRI_F_CAP = 4096


def _pair_triangles(mask: jnp.ndarray) -> jnp.ndarray:
    """Level-3 candidate census from the frequent-pair mask: the number
    of ordered triples ``x < y < z`` whose three pairs are all frequent —
    exactly the k=3 Apriori candidate count after the full subset prune
    (models/candidates.py), i.e. the triangles of the pair graph.

    With ``U`` the upper-triangle adjacency, ``(U Uᵀ)[x, y]`` counts the
    common larger neighbors ``z`` of x and y (``U[y, z]`` forces
    ``z > y > x``), so the census is ``Σ_{(x,y) frequent} (U Uᵀ)[x, y]``
    — one [F, F] matmul on the already-resident mask.  The engine's
    auto-choice (models/apriori.py) uses it to predict the mid-lattice
    blowup that the level-2 survivor count alone cannot see (a dense
    217-item corpus and a sparse 1000-item basket set can have similar
    pair counts but 20x different level-3 fan-outs).  f32 is exact for
    the per-entry counts (bounded by F < 2^24); the total saturates at
    2^30 — callers only compare it against row budgets ≤ 2^15.

    Returns int32; callers with F above :data:`TRI_F_CAP` skip the
    matmul and pass -1 ("not computed") instead."""
    u = mask.astype(jnp.float32)
    # lint: f32-gate -- entries bounded by F < 2^24; total clamped at 2^30
    paths = lax.dot_general(
        u, u, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    total = jnp.sum(jnp.where(mask, paths, 0.0))
    return jnp.minimum(total, jnp.float32(2**30)).astype(jnp.int32)


def _weighted_matmul(
    lhs_int8: jnp.ndarray,  # [T, P] int8 (0/1)
    bitmap: jnp.ndarray,  # [T, F] int8 (0/1)
    w_digits: jnp.ndarray,  # [D, T] int8
    scales: Sequence[int],  # python ints, len D (static)
) -> jnp.ndarray:
    """``out[p,f] = Σ_t w_t lhs[t,p] bitmap[t,f]`` via per-digit int8
    matmuls with int32 accumulation (exact for counts < 2^31).

    The weights scale the F-wide ``bitmap`` side, NOT the P-wide lhs:
    at level shapes (P up to 16K, F fixed at a few hundred) a scaled
    [T, P] operand is a multi-GB HBM intermediate written and re-read
    per digit — the membership phase was bandwidth-bound on exactly
    that traffic — while ``w ⊙ B`` is [T, F], ~2% of the bytes.
    Integer arithmetic, so the regrouping is exact."""
    total = None
    for d, scale in enumerate(scales):
        scaled = bitmap * w_digits[d][:, None]  # int8 in [0,127]
        part = lax.dot_general(
            lhs_int8,
            scaled,
            (((0,), (0,)), ((), ())),  # contract over T
            preferred_element_type=jnp.int32,
        )
        part = part if scale == 1 else part * jnp.int32(scale)
        total = part if total is None else total + part
    return total


def local_pair_counts(
    bitmap: jnp.ndarray,  # [T_local, F] int8
    w_digits: jnp.ndarray,  # [D, T_local] int8
    scales: Sequence[int],
    axis_name: Optional[str] = None,
) -> jnp.ndarray:
    """C6: weighted co-occurrence counts for all item pairs.

    Returns int32[F, F]; entry (f, g) is the weighted number of distinct
    baskets containing both f and g (diagonal = weighted item support over
    size>=2 baskets; callers read the upper triangle).
    """
    counts = _weighted_matmul(bitmap, bitmap, w_digits, scales)
    return _psum_if(counts, axis_name)


def local_level_counts(
    bitmap: jnp.ndarray,  # [T_local, F] int8
    w_digits: jnp.ndarray,  # [D, T_local] int8
    scales: Sequence[int],
    prefix_cols: jnp.ndarray,  # [P, K] int32 column indexes (K = k-1, static)
    axis_name: Optional[str] = None,
) -> jnp.ndarray:
    """C8: weighted support of (prefix ∪ {f}) for every prefix row and every
    item f simultaneously.

    ``prefix_cols`` rows are the k-1 item ranks of each candidate prefix;
    padding rows must point at an all-zero padded column so their counts
    are 0.  Returns int32[P, F].
    """
    k = prefix_cols.shape[1]
    common = jnp.take(bitmap, prefix_cols[:, 0], axis=1)  # [T, P] int8
    for i in range(1, k):
        common = common * jnp.take(bitmap, prefix_cols[:, i], axis=1)
    counts = _weighted_matmul(common, bitmap, w_digits, scales)
    return _psum_if(counts, axis_name)


def _weights_f32(w_digits: jnp.ndarray, scales: Sequence[int]) -> jnp.ndarray:
    """Reassemble the per-transaction weights from their base-128 digits as
    float32 (exact: callers gate the f32 path on total counts < 2^24)."""
    w = None
    for d, scale in enumerate(scales):
        part = w_digits[d].astype(jnp.float32)
        part = part if scale == 1 else part * jnp.float32(scale)
        w = part if w is None else w + part
    return w


def _heavy_gate(corr: jnp.ndarray, axis_name: Optional[str]) -> jnp.ndarray:
    """Heavy-row corrections are computed from REPLICATED arrays; under a
    txn mesh only shard 0 may add them or the psum would multiply the
    contribution by the shard count."""
    if axis_name is None:
        return corr
    return jnp.where(lax.axis_index(axis_name) == 0, corr, 0)


def heavy_pair_correction(
    heavy_b: jnp.ndarray,  # [Th, F] int8 (zero rows when unused)
    heavy_w: jnp.ndarray,  # [Th] int32 = w - (w % 128) (0 on padding)
    axis_name: Optional[str] = None,
) -> jnp.ndarray:
    """The heavy rows' contribution to the pair Gram matrix.

    The engine runs the MAIN kernels with the single low digit
    ``w % 128`` for EVERY row (one int8 matmul per phase instead of D)
    and adds this exact remainder term — ``w = w%128 + (w - w%128)`` —
    over the few rows with multiplicity >= 128 (int32 arithmetic, no
    digit bound).  Tiny: Th is capped by the engine."""
    scaled = heavy_b.astype(jnp.int32) * heavy_w[:, None]
    corr = lax.dot_general(
        scaled,
        heavy_b.astype(jnp.int32),
        (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.int32,
    )
    return _heavy_gate(corr, axis_name)


def heavy_level_correction(
    onehot,  # [P, F] prefix one-hot (int8 or f32)
    k1: jnp.ndarray,  # () int32
    heavy_b: jnp.ndarray,  # [Th, F] int8
    heavy_w: jnp.ndarray,  # [Th] int32
    axis_name: Optional[str] = None,
) -> jnp.ndarray:
    """Heavy rows' contribution to one level's [P, F] count matrix (see
    :func:`heavy_pair_correction`): membership + weighted counting over
    just the heavy rows, int32 throughout."""
    member = lax.dot_general(
        heavy_b.astype(jnp.int32),
        onehot.astype(jnp.int32),
        (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.int32,
    )  # [Th, P]
    common = (member == k1).astype(jnp.int32) * heavy_w[:, None]
    corr = lax.dot_general(
        common,
        heavy_b.astype(jnp.int32),
        (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.int32,
    )  # [P, F]
    return _heavy_gate(corr, axis_name)


def frequent_pair_mask(
    counts: jnp.ndarray,  # [F, F] int32 — psum'd pair-count matrix
    min_count: jnp.ndarray,
    num_items: jnp.ndarray,
) -> jnp.ndarray:
    """The ONE definition of the frequent-pair mask (upper triangle,
    real-item columns, count threshold) — shared by the pair packing,
    the overflow regather, and the level-3 fold's candidate prune
    (parallel/mesh.py ingest_pair_miner), which indexes pair survivor
    SLOTS extracted from this same mask: a second inline copy could
    silently desynchronize the level-3 candidate set from the slots it
    is keyed to."""
    iu = jnp.arange(counts.shape[0])
    upper = (iu[None, :] > iu[:, None]) & (iu[None, :] < num_items)
    return upper & (counts >= min_count)


def pair_threshold_pack(
    counts: jnp.ndarray,  # [F, F] int32 — psum'd pair-count matrix
    min_count: jnp.ndarray,
    num_items: jnp.ndarray,
    cap: int,
    census: bool,
) -> jnp.ndarray:
    """The pair phase's on-device tail, shared by every Gram flavor
    (:func:`local_pair_gather` and the ingest-overlapped program,
    parallel/mesh.py ingest_pair_miner): upper-triangle threshold,
    survivor extraction at ``cap``, level-3 census.  One definition so
    the two paths can never drift in masking or packing layout.
    Returns the packed host-bound array
    ``[flat_idx[cap] | counts[cap] | n2 | tri]`` (tri = -1 when the
    census is skipped)."""
    mask = frequent_pair_mask(counts, min_count, num_items)
    n2 = jnp.sum(mask, dtype=jnp.int32)
    tri = _pair_triangles(mask) if census else jnp.int32(-1)
    (flat_idx,) = jnp.nonzero(mask.reshape(-1), size=cap, fill_value=0)
    flat_idx = flat_idx.astype(jnp.int32)
    return jnp.concatenate(
        [flat_idx, jnp.take(counts.reshape(-1), flat_idx),
         jnp.stack([n2, tri])]
    )


def l3_threshold_pack(
    bitmap: jnp.ndarray,  # [T, F] int8 — resident unpacked bitmap
    w_f: jnp.ndarray,  # [T] float32 raw weights (exact: counts < 2^24)
    mask: jnp.ndarray,  # [F, F] bool — frequent-pair upper-triangle mask
    flat_idx: jnp.ndarray,  # [cap] int32 pair survivors, row-major order
    n2: jnp.ndarray,  # () int32 true survivor count
    min_count: jnp.ndarray,
    num_items: jnp.ndarray,
    p3: int,  # static prefix-row budget (pairs counted for extensions)
    cap3: int,  # static level-3 survivor budget
    n_chunks: int,
) -> jnp.ndarray:
    """Level 3 counted INSIDE the pair dispatch (VERDICT r5 next #2): the
    pair mask already encodes the full k=3 Apriori candidate set — the
    triangles :func:`_pair_triangles` censuses — so counting them here
    removes one mining-loop dispatch and one fetch.  For each surviving
    pair (x, y) (one prefix row, same row-major order as ``flat_idx``)
    the chunked membership+count matmuls produce weighted supports of
    (x, y, z) for every extension z at once; candidates require z > y
    and both (x, z), (y, z) frequent — exactly the prefix join + subset
    prune.  Row-major ``(pair_slot, z)`` extraction keeps the output in
    lex (x, y, z) order, the invariant the k=4 join needs.

    f32 throughout (one BLAS/MXU-fast matmul per chunk); exact under the
    caller's ``n_raw < 2^24`` gate (membership values are bounded by 2).
    Returns ``[flat3[cap3] | counts3[cap3] | n3]`` where
    ``flat3 = pair_slot * F + z``; the section is only valid when
    ``n2 <= p3`` and ``n3 <= cap3`` — the HOST checks both and falls
    back to the classic level-3 dispatch otherwise (exact either way)."""
    t, f = bitmap.shape
    tc = t // n_chunks
    idx = flat_idx[:p3]
    x, y = idx // f, idx % f
    slot_valid = jnp.arange(p3, dtype=jnp.int32) < n2
    # Pair one-hot [p3, F]: padded slots (>= n2) zero out, so their
    # membership count never reaches 2 and they survive nothing.
    s2 = (
        (jax.nn.one_hot(x, f, dtype=jnp.float32)
         + jax.nn.one_hot(y, f, dtype=jnp.float32))
        * slot_valid[:, None].astype(jnp.float32)
    )
    bm = bitmap.reshape(n_chunks, tc, f)
    wc = w_f.reshape(n_chunks, tc)

    def step(acc, xs):
        b_chunk, w_chunk = xs
        b_f = b_chunk.astype(jnp.float32)
        # lint: f32-gate -- membership values bounded by 2; counts < 2^24 (caller's n_raw gate)
        member = lax.dot_general(
            b_f, s2, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [tc, p3]
        common = (member == 2.0).astype(jnp.float32)
        # lint: f32-gate -- weighted counts bounded by n_raw < 2^24 (caller's gate)
        part = lax.dot_general(
            common, b_f * w_chunk[:, None],
            (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [p3, F]
        return acc + part, None

    counts3_f, _ = lax.scan(
        step, jnp.zeros((p3, f), jnp.float32), (bm, wc)
    )
    counts3 = counts3_f.astype(jnp.int32)
    col = jnp.arange(f, dtype=jnp.int32)
    cand = (
        jnp.take(mask, x, axis=0)  # (x, z) frequent — x < y < z
        & jnp.take(mask, y, axis=0)  # (y, z) frequent
        & (col[None, :] > y[:, None])
        & (col[None, :] < num_items)
        & slot_valid[:, None]
    )
    surv = cand & (counts3 >= min_count)
    n3 = jnp.sum(surv, dtype=jnp.int32)
    (flat3,) = jnp.nonzero(surv.reshape(-1), size=cap3, fill_value=0)
    flat3 = flat3.astype(jnp.int32)
    return jnp.concatenate(
        [flat3, jnp.take(counts3.reshape(-1), flat3), n3[None]]
    )


def local_pair_gather(
    bitmap: jnp.ndarray,  # [T_local, F] int8
    w_digits: jnp.ndarray,  # [D, T_local] int8
    scales: Sequence[int],
    min_count: jnp.ndarray,  # () int32 (traced)
    num_items: jnp.ndarray,  # () int32 (traced) — real F before padding
    cap: int,
    heavy_b: Optional[jnp.ndarray] = None,  # [Th, F] int8
    heavy_w: Optional[jnp.ndarray] = None,  # [Th] int32
    axis_name: Optional[str] = None,
    fast_f32: bool = False,
    sparse_thr: Optional[jnp.ndarray] = None,  # () int32 per-shard prune
    sparse_cap: Optional[int] = None,  # static union slot budget
    groups: Optional[tuple] = None,  # two-level exchange grid (hier.py)
) -> tuple:
    """C6, transfer-minimal form: the pair Gram matmul PLUS the threshold,
    on device.  Only surviving pairs leave the chip: returns
    ``(packed, counts_mat)`` where ``packed`` is
    :func:`pair_threshold_pack`'s host-bound
    ``[flat_idx[cap] | counts[cap] | n2 | tri]`` array (upper-triangle
    survivors in row-major order, ``i = idx // F``, ``j = idx % F``;
    ``tri`` = level-3 census, -1 when F > TRI_F_CAP) and ``counts_mat``
    is the full psum'd count matrix — callers keep it DEVICE-RESIDENT
    (never fetched) so an ``n2 > cap`` overflow re-extracts survivors
    via :func:`local_pair_regather` without re-running the Gram.
    Replaces transferring the full [F, F] table (16 MB at F=2048) with
    ~2·cap·4 bytes.

    ``fast_f32``: run the Gram matmul as ONE float32 matmul (BLAS path on
    CPU backends, where XLA int8 matmuls are orders slower).  Exact only
    when the caller has proven every count < 2^24.

    ``sparse_cap`` (with ``sparse_thr``) replaces the dense [F, F] psum
    with the threshold-sparse exchange (:func:`local_sparse_psum`,
    validity = the upper-triangle real-item candidate set): the
    returned counts matrix then holds exact global counts at every
    union position and zeros at provably-infrequent ones — identical
    survivor extraction — and ``packed`` gains one trailing slot with
    the union census, ``[... | n2 | tri | n_union]``, so the host can
    detect compaction overflow (results unusable; redo dense).
    """
    f = bitmap.shape[1]
    if fast_f32:
        b_f = bitmap.astype(jnp.float32)
        scaled = b_f * _weights_f32(w_digits, scales)[:, None]
        # lint: f32-gate -- fast_f32 callers prove every count < 2^24 first
        counts = lax.dot_general(
            scaled,
            b_f,
            (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        ).astype(jnp.int32)
    else:
        counts = _weighted_matmul(bitmap, bitmap, w_digits, scales)
    if heavy_b is not None:
        counts = counts + heavy_pair_correction(heavy_b, heavy_w, axis_name)
    nu = None
    if sparse_cap is not None:
        iu = jnp.arange(f)
        cand = (iu[None, :] > iu[:, None]) & (iu[None, :] < num_items)
        counts, nu = local_sparse_psum(
            counts, sparse_thr, sparse_cap, axis_name, valid=cand,
            groups=groups,
        )
    else:
        counts = _psum_if(counts, axis_name)
    packed = pair_threshold_pack(
        counts, min_count, num_items, cap, census=f <= TRI_F_CAP
    )
    if nu is not None:
        packed = jnp.concatenate([packed, nu[None]])
    return packed, counts


def local_pair_regather(
    counts: jnp.ndarray,  # [F, F] int32 — resident psum'd pair counts
    min_count: jnp.ndarray,
    num_items: jnp.ndarray,
    cap: int,
) -> tuple:
    """Survivor re-extraction at a larger ``cap`` over the ALREADY
    computed (device-resident) pair-count matrix: the overflow retry of
    :func:`local_pair_gather` must not re-run the Gram matmul, and —
    since this kernel has no matmul — its one-off XLA compile is cheap
    too (re-compiling the full gather at a new static cap cost seconds,
    to save a one-time payload).  Returns ``(flat_idx, counts, n2)``."""
    mask = frequent_pair_mask(counts, min_count, num_items)
    n2 = jnp.sum(mask, dtype=jnp.int32)
    (flat_idx,) = jnp.nonzero(mask.reshape(-1), size=cap, fill_value=0)
    flat_idx = flat_idx.astype(jnp.int32)
    return flat_idx, jnp.take(counts.reshape(-1), flat_idx), n2


def local_level_gather(
    bitmap: jnp.ndarray,  # [T_local, F] int8
    w_digits: jnp.ndarray,  # [D, T_local] int8
    scales: Sequence[int],
    prefix_cols: jnp.ndarray,  # [P, K_MAX] int32; padding -> zero column
    k1: jnp.ndarray,  # () int32 — real prefix width (traced, not static)
    cand_idx: jnp.ndarray,  # [C] int32 flat indexes row*F + y
    n_chunks: int,
    heavy_b: Optional[jnp.ndarray] = None,  # [Th, F] int8
    heavy_w: Optional[jnp.ndarray] = None,  # [Th] int32
    axis_name: Optional[str] = None,
    cand_axis_name: Optional[str] = None,
    fast_f32: bool = False,
    pallas_tiles: Optional[tuple] = None,
    wide_member: bool = False,
    sparse_thr: Optional[jnp.ndarray] = None,  # () int32 per-shard prune
    sparse_cap: Optional[int] = None,  # static union slot budget
    groups: Optional[tuple] = None,  # two-level exchange grid (hier.py)
) -> jnp.ndarray:
    """C8, transfer-minimal form: one compilation serves EVERY level.

    Differences from :func:`local_level_counts` (both kept — this one is
    the mining engine's path, that one the simple/test path):

    - prefix membership via a one-hot matmul ``(B @ onehotᵀ) == k1``
      instead of per-column gathers — k1 enters as a *traced* scalar and
      ``prefix_cols`` has a fixed padded width, so changing level depth
      does not recompile (the reference recompiles nothing per level
      either; its per-level cost is pure re-execution,
      FastApriori.scala:111-121);
    - the transaction axis is processed in ``n_chunks`` scan steps so the
      [tc, P] intermediates stay bounded in HBM at Webdocs scale;
    - only the candidates' own counts leave the device: a [C] gather is
      ``psum``-reduced instead of the full [P, F] table (device->host
      bandwidth is the scarcest resource on a tunneled or PCIe-attached
      chip, and C << P·F).

    Padding discipline: padded prefix *positions* and padded prefix *rows*
    both point at the guaranteed all-zero bitmap column, so padded
    positions add 0 to the membership count and padded rows match only a
    k1 of 0 (never used: k1 >= 2).  Padded ``cand_idx`` entries gather a
    garbage count that callers slice off.

    ``fast_f32``: both matmuls run in float32 (BLAS on CPU backends) with
    the weights folded into the membership mask — ONE counting matmul
    instead of D digit matmuls.  Exact only when counts < 2^24 (caller's
    guard); intersection sizes are bounded by F, also f32-exact.

    ``pallas_tiles``: ``(t_tile, m_tile)`` — run the fused Pallas kernel
    (ops/pallas_level.py) instead of the chunked scan: the [tc, P]
    membership intermediate stays in VMEM tile-by-tile, removing the HBM
    write+read that bounds this phase on real chips.  TPU path only;
    the caller (parallel/mesh.py level_gather_batch) picks tiles that
    divide the local shapes or passes None.

    ``wide_member``: int32 membership accumulation.  The int8 fast path
    is exact only while the intersection size is bounded by ``k1 <= 127``
    (int8 saturates/wraps past that, silently matching or missing
    prefixes — ADVICE r5 #1); dispatch sites set this for levels with
    ``k1 >= 128`` instead of miscounting.  4x the [tc, P] intermediate
    bytes, paid only on absurdly deep lattices.

    ``sparse_cap`` (with ``sparse_thr``): the final [C] candidate-gather
    reduction runs as the threshold-sparse exchange
    (:func:`local_sparse_psum`) instead of the dense psum; the return
    becomes ``(counts, n_union)``.  The dispatch layer fills padded
    ``cand_idx`` slots with a guaranteed-zero-count position so padding
    never enters the union.
    """
    t_loc, f_pad = bitmap.shape
    p = prefix_cols.shape[0]
    d = w_digits.shape[0]
    onehot_dt = jnp.float32 if fast_f32 else jnp.int8
    # prefix_cols may arrive int16 (compact host-link form); widen on
    # device for the scatter.
    onehot = (
        jnp.zeros((p, f_pad), onehot_dt)
        .at[jnp.arange(p)[:, None], prefix_cols.astype(jnp.int32)]
        .set(1)
    )
    if pallas_tiles is not None and not fast_f32:
        from fastapriori_tpu.ops.pallas_level import level_counts_pallas

        # Caller gates on the single LOW digit; a scaled single digit
        # (scale != 1) would be silently dropped below, so reject it.
        assert tuple(scales) == (1,), scales
        # The Pallas kernel shares the int8 membership bound; dispatch
        # sites route k1 >= 128 levels to the XLA wide path instead.
        assert not wide_member, "wide_member has no Pallas path"
        tt, mt = pallas_tiles
        # w ⊙ B computed here (XLA, one [T, F] int8 elementwise): it is
        # loop-invariant across the NB-block scan above, so XLA hoists
        # it to once per launch.
        wb = bitmap * w_digits[0][:, None]
        counts = level_counts_pallas(
            bitmap, wb, onehot, k1, t_tile=tt, m_tile=mt
        )
        if heavy_b is not None:
            counts = counts + heavy_level_correction(
                onehot, k1, heavy_b, heavy_w, axis_name
            )
        local = jnp.take(counts.reshape(-1), cand_idx)
        if sparse_cap is not None:
            return local_sparse_psum(
                local, sparse_thr, sparse_cap, axis_name, groups=groups
            )
        return _psum_if(local, axis_name)

    tc = t_loc // n_chunks
    bm = bitmap.reshape(n_chunks, tc, f_pad)
    wd = w_digits.reshape(d, n_chunks, tc).transpose(1, 0, 2)

    def body(acc, xs):
        # HBM discipline (the membership phase is bandwidth-bound, not
        # MXU-bound, at level shapes): the [tc, P] membership
        # intermediate stays int8 (counts are bounded by k1 <= K_MAX,
        # far under 127, so int8 accumulation is exact), and the weights
        # scale the F-wide bitmap side — ``commonᵀ @ (w ⊙ B)`` — so no
        # scaled [tc, P] operand is ever materialized.  Same exact
        # integer result; ~5x fewer intermediate bytes per chunk.
        b_chunk, wd_chunk = xs  # [tc, F] int8, [D, tc] int8
        if fast_f32:
            b_f = b_chunk.astype(jnp.float32)
            # lint: f32-gate -- intersection sizes bounded by k1 <= K_MAX << 2^24
            member = lax.dot_general(
                b_f,
                onehot,
                (((1,), (1,)), ((), ())),  # contract over F -> [tc, P]
                preferred_element_type=jnp.float32,
            )
            common = (member == k1.astype(jnp.float32)).astype(
                jnp.float32
            )
            w_f = _weights_f32(wd_chunk, scales)  # [tc]
            # lint: f32-gate -- fast_f32 callers prove every count < 2^24 first
            total = lax.dot_general(
                common,
                b_f * w_f[:, None],
                (((0,), (0,)), ((), ())),  # contract over tc -> [P, F]
                preferred_element_type=jnp.float32,
            ).astype(jnp.int32)
            return acc + total, None
        # int8 accumulation is exact only for k1 <= 127 (docstring);
        # wide_member dispatches widen to int32 rather than miscount.
        member_dt = jnp.int32 if wide_member else jnp.int8
        member = lax.dot_general(
            b_chunk,
            onehot,
            (((1,), (1,)), ((), ())),  # contract over F -> [tc, P]
            preferred_element_type=member_dt,
        )
        common = (member == k1.astype(member_dt)).astype(jnp.int8)
        total = None
        for di, scale in enumerate(scales):
            part = lax.dot_general(
                common,
                b_chunk * wd_chunk[di][:, None],
                (((0,), (0,)), ((), ())),  # contract over tc -> [P, F]
                preferred_element_type=jnp.int32,
            )
            part = part if scale == 1 else part * jnp.int32(scale)
            total = part if total is None else total + part
        return acc + total, None

    init = jnp.zeros((p, f_pad), jnp.int32)
    # The per-shard accumulator varies over every sharded mesh axis (its
    # txn rows AND, on a 2-D mesh, its cand slice of the prefix rows);
    # mark the initial carry accordingly.
    varying = tuple(a for a in (axis_name, cand_axis_name) if a is not None)
    if varying:
        init = compat.pcast(init, varying, to="varying")
    counts, _ = lax.scan(body, init, (bm, wd))
    if heavy_b is not None:
        counts = counts + heavy_level_correction(
            onehot, k1, heavy_b, heavy_w, axis_name
        )
    local = jnp.take(counts.reshape(-1), cand_idx)
    if sparse_cap is not None:
        return local_sparse_psum(
            local, sparse_thr, sparse_cap, axis_name, groups=groups
        )
    return _psum_if(local, axis_name)


def local_level_gather_batch(
    bitmap: jnp.ndarray,  # [T_local, F] int8
    w_digits: jnp.ndarray,  # [D, T_local] int8
    scales: Sequence[int],
    prefix_stack: jnp.ndarray,  # [NB, P, K] compact prefix blocks
    k1: jnp.ndarray,  # () int32 (traced)
    cand_stack: jnp.ndarray,  # [NB, C] flat candidate indexes per block
    n_chunks: int,
    heavy_b: Optional[jnp.ndarray] = None,  # [Th, F] int8
    heavy_w: Optional[jnp.ndarray] = None,  # [Th] int32
    axis_name: Optional[str] = None,
    cand_axis_name: Optional[str] = None,
    fast_f32: bool = False,
    pallas_tiles: Optional[tuple] = None,
    wide_member: bool = False,
    sparse_thr: Optional[jnp.ndarray] = None,
    sparse_cap: Optional[int] = None,
    groups: Optional[tuple] = None,
) -> jnp.ndarray:
    """A whole level's prefix blocks in ONE launch: ``lax.scan`` over the
    stacked blocks, each step = :func:`local_level_gather`.  Kernel
    launches carry a large fixed cost on remote/tunneled backends (the
    runtime round-trips per launch instead of pipelining), so a level
    with NB blocks pays it once instead of NB times.  Returns
    ``[NB, C]`` gathered candidate counts — or, with ``sparse_cap``
    (the threshold-sparse reduction), ``([NB, C] counts, [NB] union
    censuses)``."""

    def step(carry, xs):
        pc, ci = xs
        out = local_level_gather(
            bitmap,
            w_digits,
            scales,
            pc,
            k1,
            ci,
            n_chunks,
            heavy_b=heavy_b,
            heavy_w=heavy_w,
            axis_name=axis_name,
            cand_axis_name=cand_axis_name,
            fast_f32=fast_f32,
            pallas_tiles=pallas_tiles,
            wide_member=wide_member,
            sparse_thr=sparse_thr,
            sparse_cap=sparse_cap,
            groups=groups,
        )
        return carry, out

    _, outs = lax.scan(step, jnp.int32(0), (prefix_stack, cand_stack))
    return outs


def pack_bits_msb(mask: jnp.ndarray) -> jnp.ndarray:
    """Bool [..., C] -> uint8 [..., C//8], MSB-first (numpy.packbits
    layout, so the host side unpacks with np.unpackbits)."""
    shifts = jnp.arange(7, -1, -1, dtype=jnp.uint8)
    b = mask.reshape(*mask.shape[:-1], -1, 8).astype(jnp.uint8)
    return jnp.sum(b << shifts, axis=-1).astype(jnp.uint8)


def keep_bits(counts: jnp.ndarray, min_count: jnp.ndarray) -> jnp.ndarray:
    """Survivor bitmask of a gathered count array — the ONLY per-level
    host fetch (VERDICT r4 weak #6 follow-through: the [NB, C] int32
    fetch was 1-4 MB per level over a ~11-38 MB/s tunnel down-link,
    often exceeding the level's device time; the mask is C/8 bytes and
    the counts stay device-resident for one packed end-of-mine gather,
    models/apriori.py _resolve_pending_counts)."""
    return pack_bits_msb(counts >= min_count)


def keep_bits_with_census(
    counts: jnp.ndarray,  # [NB, C] int32
    min_count: jnp.ndarray,
    nus: jnp.ndarray,  # [NB] int32 union censuses
) -> jnp.ndarray:
    """:func:`keep_bits` with the per-block union censuses appended as
    4 little-endian trailing bytes per block — the ONE definition of
    the sparse-engine bits payload (both mining engines' batch kernels
    emit it and the collect loop in models/apriori.py decodes it; a
    second fetch would cost a full link round trip just to carry NB
    ints, and a second inline copy of the layout could silently
    desynchronize the decode)."""
    nu_bytes = jnp.stack(
        [((nus >> s) & 0xFF).astype(jnp.uint8) for s in (0, 8, 16, 24)],
        axis=1,
    )
    return jnp.concatenate(
        [keep_bits(counts, min_count), nu_bytes], axis=1
    )


def local_item_supports(
    bitmap: jnp.ndarray,  # [T_local, F] int8
    w_digits: jnp.ndarray,  # [D, T_local] int8
    scales: Sequence[int],
    axis_name: Optional[str] = None,
) -> jnp.ndarray:
    """Weighted per-item support over the compressed baskets (int32[F]).

    Not a reference component (the reference's 1-item counts are raw
    occurrence counts from C3) — used by tests and diagnostics."""
    total = None
    for d, scale in enumerate(scales):
        part = jnp.sum(
            bitmap.astype(jnp.int32) * w_digits[d].astype(jnp.int32)[:, None],
            axis=0,
        )
        part = part if scale == 1 else part * jnp.int32(scale)
        total = part if total is None else total + part
    return _psum_if(total, axis_name)
