"""Vertical-bitmap construction and padding discipline (reference C5).

The reference builds its Boolean item->transactions table with one full
Spark scan per item (FastApriori.scala:195-210 — O(F) jobs, its worst
inefficiency) and then broadcasts the whole table to every executor.  Here
the bitmap is built in a single host pass as a dense ``B ∈ {0,1}^{T'×F}``
int8 matrix and *sharded over the transaction axis* across the device mesh —
inverting the reference's replicate-bitmap / shard-candidates layout
(SURVEY.md §7).

Weighted counting stays on the int8 MXU path via base-128 digit
decomposition of the multiplicity weights: ``w = Σ_d 128^d · w_d`` with
``w_d ∈ [0, 128)``, so ``B ⊙ w_d`` still fits in int8 and every support
count is a sum of int8×int8→int32 matmuls scaled by ``128^d``.  Real
datasets almost always need a single digit (most baskets are unique).
"""

from __future__ import annotations

from typing import List, Sequence, Tuple

import numpy as np


def pad_axis(n: int, multiple: int) -> int:
    """Smallest multiple of ``multiple`` that is >= max(n, 1)."""
    n = max(n, 1)
    return ((n + multiple - 1) // multiple) * multiple


def next_pow2(n: int) -> int:
    """Smallest power of two >= max(n, 1) — the ONE definition of the
    shape-bucketing helper (compiled shapes round to pow2 buckets
    across the engines; four private copies had grown by PR 4)."""
    p = 1
    while p < n:
        p *= 2
    return p


def build_bitmap(
    baskets: Sequence[np.ndarray],
    num_items: int,
    txn_multiple: int = 8,
    item_multiple: int = 128,
) -> np.ndarray:
    """Build the dense transaction×item bitmap, padded to device-friendly
    tiles.  Padding rows/columns are all-zero, so they contribute nothing to
    any count (a padded column's support is 0 < minCount; a padded row has
    weight 0).

    One vectorized pass over the ragged baskets replaces the reference's
    per-item filter jobs (FastApriori.scala:199-200).

    The item axis is padded to fit at least one all-zero column beyond the
    real items (``f_pad >= num_items + 1``): padded candidate-prefix rows
    point their column indexes at it, making their counts exactly 0.
    """
    t = len(baskets)
    t_pad = pad_axis(t, txn_multiple)
    f_pad = pad_axis(num_items + 1, item_multiple)
    if t == 0:
        return np.zeros((t_pad, f_pad), dtype=np.int8)
    lens = np.fromiter((len(b) for b in baskets), dtype=np.int64, count=t)
    rows = np.repeat(np.arange(t, dtype=np.int64), lens)
    cols = np.concatenate(baskets) if len(baskets) else np.empty(0, np.int64)
    b = np.zeros((t_pad, f_pad), dtype=np.int8)
    b[rows, cols] = 1
    return b


def build_bitmap_csr(
    indices: np.ndarray,
    offsets: np.ndarray,
    num_items: int,
    txn_multiple: int = 8,
    item_multiple: int = 128,
) -> np.ndarray:
    """CSR variant of :func:`build_bitmap` (basket ``i`` =
    ``indices[offsets[i]:offsets[i+1]]``) — the zero-copy path from the
    native preprocessor."""
    t = len(offsets) - 1
    t_pad = pad_axis(t, txn_multiple)
    f_pad = pad_axis(num_items + 1, item_multiple)
    b = np.zeros((t_pad, f_pad), dtype=np.int8)
    if t > 0 and len(indices) > 0:
        rows = np.repeat(
            np.arange(t, dtype=np.int64), np.diff(offsets).astype(np.int64)
        )
        b[rows, indices] = 1
    return b


def build_packed_bitmap_csr(
    indices: np.ndarray,
    offsets: np.ndarray,
    num_items: int,
    txn_multiple: int = 8,
    item_multiple: int = 128,
) -> Tuple[np.ndarray, int]:
    """Bit-packed variant of :func:`build_bitmap_csr`: returns
    ``(packed uint8[t_pad, f_pad//8], f_pad)`` with the same MSB-first
    byte layout as ``numpy.packbits`` / ``ops.fused.pack_bitmap``.

    The native scanner fills the bits straight from the CSR arrays when
    available, skipping the dense ``[T, F]`` int8 intermediate and the
    ``packbits`` pass (~0.5 GB of host traffic at Webdocs scale); the
    numpy fallback materializes the dense bitmap and packs it.
    """
    t = len(offsets) - 1
    t_pad = pad_axis(t, txn_multiple)
    f_pad = pad_axis(num_items + 1, item_multiple)
    assert f_pad % 8 == 0
    packed = np.zeros((t_pad, f_pad // 8), dtype=np.uint8)
    if t > 0 and len(indices) > 0:
        from fastapriori_tpu.native.loader import fill_packed_bitmap

        if not fill_packed_bitmap(indices, offsets, packed):
            dense = build_bitmap_csr(
                indices, offsets, num_items, txn_multiple, item_multiple
            )
            packed = np.packbits(dense.astype(bool), axis=1)
    return packed, f_pad


def pad_weights(weights: np.ndarray, txn_pad: int) -> np.ndarray:
    """Zero-pad the multiplicity vector to the padded transaction count."""
    out = np.zeros(txn_pad, dtype=np.int32)
    out[: len(weights)] = weights
    return out


def weight_digits(
    weights: np.ndarray, txn_pad: int, min_digits: int = 1
) -> Tuple[np.ndarray, List[int]]:
    """Decompose int32 weights into base-128 int8 digits.

    Returns ``(digits int8[D, T_pad], scales)`` with
    ``weights == Σ_d scales[d] * digits[d]`` and ``scales[d] = 128**d``.
    D is data-dependent but tiny (1 unless some basket repeats >= 128
    times), and static per compilation.  ``min_digits`` pads D with zero
    digits — multi-host shards must agree on D even when only one shard
    holds a heavy basket (SPMD requires identical static shapes).
    """
    w = pad_weights(weights, txn_pad).astype(np.int64)
    digits: List[np.ndarray] = []
    scales: List[int] = []
    scale = 1
    while True:
        digits.append((w % 128).astype(np.int8))
        scales.append(scale)
        w //= 128
        scale *= 128
        if not (w > 0).any() and len(digits) >= min_digits:
            break
    return np.stack(digits, axis=0), scales
