"""First-match rule containment kernel (reference C12's hot loop,
AssociationRules.scala:88-102) as chunked matmuls + a running argmin.

The reference scans the confidence-sorted rule list per user basket until
the first rule whose antecedent is a subset of the basket fires (:95-102).
On TPU, for a batch of (deduplicated) baskets U ∈ {0,1}^{Nb×F} and rule
antecedents A ∈ {0,1}^{R×F} sorted by priority:

- containment:  ``U · Aᵀ == |antecedent|``  (int8 matmul, int32 acc);
- eligibility:  ``|antecedent| <= |basket|`` and consequent ∉ basket
  (:90 — the reference pre-filters, we mask);
- first match:  argmin over rule index with ineligible rows mapped to R.

Baskets are sharded over the mesh axis (data parallelism over users —
each device answers its own slice; no reduction needed); the rule tables
are replicated, the analog of the reference's rule broadcast (:76-78).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from fastapriori_tpu import compat

AXIS = "txn"


# "No rule yet" sentinel in `best`.  A plain Python int, cast inside the
# traced kernels — a module-scope jnp scalar would initialize the JAX
# backend at import time (imports must stay backend-free so the CLI can
# fail gracefully when the accelerator tunnel is down).
NO_MATCH = 2**31 - 1


def local_first_match_chunk(
    baskets: jnp.ndarray,  # [Nb_local, F] int8
    basket_len: jnp.ndarray,  # [Nb_local] int32
    ant_cols: jnp.ndarray,  # [Rc, K] int32 — ONE priority chunk's
    #   antecedent item ranks; padding positions point at the guaranteed
    #   all-zero bitmap column (F_pad - 1), padding ROWS are all-padding
    ant_size: jnp.ndarray,  # [Rc] int32
    consequent: jnp.ndarray,  # [Rc] int32
    base: jnp.ndarray,  # () int32 — global index of this chunk's first rule
    best: jnp.ndarray,  # [Nb_local] int32 — running best global rule index
) -> jnp.ndarray:
    """Fold one rule chunk into the running first-match.

    The reference's per-user scan stops at the first hit (:95-102); the
    batch analog processes rules in priority-ordered chunks and keeps a
    running minimum, so the caller can stop dispatching chunks once every
    basket has matched — and the [Nb, R] eligibility matrix never exists
    at full R, only [Nb, Rc] per step.

    Antecedents arrive COMPACT ([Rc, K] column indexes, like the level
    engine's prefix_cols) and expand to the one-hot [Rc, F] form on
    device: the dense form was ~13 MB per chunk over the host link at
    movielens scale (f_pad ~1.7K) vs ~400 KB compact — chunk uploads,
    not compute, dominated the scan on tunneled chips.  The expansion
    is a broadcast compare-and-sum, NOT a scatter: TPU scatters cost
    ~200 ns per index (40 s across a webdocs-scale 16M-rule no-match
    scan), while the [Rc, K, F] compare tree is plain VPU work that
    XLA fuses into the matmul's operand."""
    rc = ant_cols.shape[0]
    f = baskets.shape[1]
    # [Rc, F]; pad positions all point at the guaranteed all-zero bitmap
    # column, whose duplicate count contributes 0 to every overlap.
    antecedents = jnp.sum(
        (
            ant_cols[:, :, None]
            == jnp.arange(f, dtype=ant_cols.dtype)[None, None, :]
        ).astype(jnp.int8),
        axis=1,
        dtype=jnp.int8,
    )
    overlap = lax.dot_general(
        baskets,
        antecedents,
        (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.int32,
    )  # [Nb, Rc]
    contained = overlap == ant_size[None, :]
    size_ok = ant_size[None, :] <= basket_len[:, None]
    cons_in_basket = jnp.take(baskets, consequent, axis=1) > 0
    eligible = contained & size_ok & ~cons_in_basket
    idx = jnp.where(
        eligible,
        jnp.arange(rc, dtype=jnp.int32)[None, :] + base,
        jnp.int32(NO_MATCH),
    )
    return jnp.minimum(best, jnp.min(idx, axis=1))


def local_first_match_scan(
    baskets: jnp.ndarray,  # [Nb_local, F] int8
    basket_len: jnp.ndarray,  # [Nb_local] int32 (0 on padding rows)
    ant_cols: jnp.ndarray,  # [R_pad, K] int32 — the FULL resident table
    ant_size: jnp.ndarray,  # [R_pad] int32 (padding rows: > F, never hit)
    consequent: jnp.ndarray,  # [R_pad] int32
    *,
    chunk: int,
    axis_name=None,
):
    """The whole priority scan as ONE device program: a ``lax.while_loop``
    over rule chunks with the early exit ON DEVICE (stop as soon as every
    real local basket has a match — padding rows, ``basket_len == 0``,
    are excluded or they would pin the loop to full length).

    Replaces the host-driven chunk loop whose per-chunk uploads and
    lagged early-exit fetches were link-bound on tunneled chips
    (VERDICT weak #4): the rule table is resident (uploaded once per
    recommender instance), each dispatch costs only the basket upload +
    one [Nb_local] result fetch.  Exactness: later chunks hold only
    larger rule indices, so stopping once every real row is below
    NO_MATCH cannot change the running minimum.

    Returns ``(best [Nb_local] int32, chunks_run () int32)`` —
    ``chunks_run`` (the max across shards when meshed) feeds the MAC
    accounting that the mining phases already have."""
    r_pad = ant_cols.shape[0]
    n_chunks = r_pad // chunk
    real = basket_len > 0

    def cond(state):
        c, best = state
        return (c < n_chunks) & jnp.any(real & (best == jnp.int32(NO_MATCH)))

    def body(state):
        c, best = state
        base = c * chunk
        best = local_first_match_chunk(
            baskets,
            basket_len,
            lax.dynamic_slice_in_dim(ant_cols, base, chunk, 0),
            lax.dynamic_slice_in_dim(ant_size, base, chunk, 0),
            lax.dynamic_slice_in_dim(consequent, base, chunk, 0),
            base,
            best,
        )
        return c + 1, best

    best0 = jnp.full(baskets.shape[0], NO_MATCH, dtype=jnp.int32)
    if axis_name is not None:
        # The carry varies over the mesh axis (it is derived from the
        # sharded baskets); mark the initial value to match.
        best0 = compat.pcast(best0, (axis_name,), to="varying")
    c, best = lax.while_loop(cond, body, (jnp.int32(0), best0))
    if axis_name is not None:
        # Shards may exit at different chunks (no collectives inside the
        # loop); report the deepest scan for the cost model.
        c = lax.pmax(c, axis_name)
    return best, c


def make_sharded_first_match_scan(mesh: Mesh, chunk: int):
    """shard_map-wrapped, jitted resident-table scan: baskets and the
    result sharded over the mesh axis, rule tables replicated (the
    reference's rule broadcast, AssociationRules.scala:76-78)."""
    import functools

    return jax.jit(
        compat.shard_map(
            functools.partial(
                local_first_match_scan, chunk=chunk, axis_name=AXIS
            ),
            mesh=mesh,
            in_specs=(
                P(AXIS, None),
                P(AXIS),
                P(None, None),
                P(None),
                P(None),
            ),
            out_specs=(P(AXIS), P()),
        )
    )


# ---------------------------------------------------------------------------
# Device-resident rule generation (reference C11's level-wise subset joins,
# AssociationRules.scala:122-188, reformulated as packed-key layouts and
# batched sorted-key gathers — the transposition "A New Data Layout For Set
# Intersection on GPUs" applies to set containment, PAPERS.md).
#
# The host formulation (rules/gen.py) joins each k-itemset's k deleted-column
# antecedents against the sorted (k-1)-itemset key table with numpy
# searchsorted — 13.6-19.3 s of host wall for 16.34M rules at webdocs scale
# (VERDICT r5 weak #8).  Here the same join runs on device: row keys pack
# into uint32 LANES (no 64-bit device dtypes — jax_enable_x64 stays off, the
# repo-wide G004 contract), the parent table is sorted once per level with
# `lax.sort` (multi-operand lexicographic), all k column deletions of a level
# batch into ONE dispatch, and the dominance prune's confidence comparisons
# run as exact 48-bit rational compares (see `frac_less24`).


def rule_key_bits(f: int) -> int:
    """Bits per item rank in the packed row keys (rules/gen.py `_row_keys`
    uses the same widths for its uint64 host keys)."""
    return 8 if f <= 256 else (16 if f <= 65536 else 32)


def pack_rank_keys(mat: jnp.ndarray, bits: int) -> list:
    """Pack int32 [N, w] sorted-row ranks into ``ceil(w*bits/32)`` uint32
    key columns, left-aligned so lexicographic order over the column tuple
    equals lexicographic row order (the host packs the same fields into
    one uint64; the device splits them across 32-bit lanes because 64-bit
    dtypes silently downcast while jax_enable_x64 is off)."""
    n, w = mat.shape
    per = 32 // bits
    m = mat.astype(jnp.uint32)
    cols = []
    for ci in range(-(-w // per)):
        acc = None
        for j in range(per):
            pos = ci * per + j
            if pos >= w:
                break
            part = m[:, pos] << ((per - 1 - j) * bits)
            acc = part if acc is None else acc | part
        cols.append(acc)
    return cols


def lex_searchsorted(
    sorted_cols, n_real: jnp.ndarray, query_cols, n_iters: int
) -> jnp.ndarray:
    """Left insertion point of each query row in a lexicographically
    sorted multi-column uint32 key table — a vectorized binary search
    (``n_iters`` static gather/compare rounds over all queries at once),
    bounded by the TRACED real row count so pow2-padded tables need no
    sentinel discipline."""
    m = query_cols[0].shape[0]
    lo0 = jnp.zeros(m, jnp.int32)
    hi0 = jnp.broadcast_to(n_real.astype(jnp.int32), (m,))

    def body(_, state):
        lo, hi = state
        mid = (lo + hi) // 2
        lt = jnp.zeros(m, bool)
        eq = jnp.ones(m, bool)
        for sc, qc in zip(sorted_cols, query_cols):
            v = jnp.take(sc, mid)
            lt = lt | (eq & (v < qc))
            eq = eq & (v == qc)
        active = lo < hi
        lo = jnp.where(active & lt, mid + 1, lo)
        hi = jnp.where(active & ~lt, mid, hi)
        return lo, hi

    lo, _ = lax.fori_loop(0, n_iters, body, (lo0, hi0))
    return lo


def _mul24_wide(a: jnp.ndarray, b: jnp.ndarray):
    """Exact 48-bit product of two uint32 values < 2^24 as a (hi, lo)
    uint32 pair — 16-bit-limb schoolbook multiply (no 64-bit dtypes on
    device).  Bounds: a0,b0 < 2^16 and a1,b1 < 2^8, so every partial
    product and the limb sum fit uint32 exactly; only the final lo add
    can wrap, and its carry is recovered by comparison."""
    a0, a1 = a & 0xFFFF, a >> 16
    b0, b1 = b & 0xFFFF, b >> 16
    p00 = a0 * b0
    mid = a0 * b1 + a1 * b0  # < 2^25: no wrap
    t = (mid & 0xFFFF) << 16
    lo = p00 + t
    carry = (lo < p00).astype(jnp.uint32)
    hi = a1 * b1 + (mid >> 16) + carry
    return hi, lo


def frac_less24(pn, pd, cn, cd) -> jnp.ndarray:
    """``pn/pd < cn/cd`` for positive int counts < 2^24, EXACTLY matching
    the host's IEEE-double comparison (rules/gen.py compares f64
    confidences, like the reference's JVM doubles).  Equivalence: with
    denominators < 2^24 two distinct rationals in (0, 1] differ by at
    least 1/(pd·cd) > 2^-48, while doubles at or below 1.0 are spaced at
    most 2^-53 — distinct rationals therefore round to distinct doubles
    and the double order IS the rational order, so the exact cross
    product compare (48-bit, `_mul24_wide`) reproduces it bit-for-bit.
    Callers gate the device path on counts < 2^24 (rules/gen.py)."""
    h1, l1 = _mul24_wide(pn.astype(jnp.uint32), cd.astype(jnp.uint32))
    h2, l2 = _mul24_wide(cn.astype(jnp.uint32), pd.astype(jnp.uint32))
    return (h1 < h2) | ((h1 == h2) & (l1 < l2))


def rule_level_kernel(
    mat: jnp.ndarray,  # [N_pad, k] int32 lex-sorted k-itemset rows
    cnts: jnp.ndarray,  # [N_pad] int32 itemset counts (< 2^24, gated)
    n_real: jnp.ndarray,  # () int32 — real row count (pow2 row padding)
    psorted,  # tuple of [Np_pad] uint32 — parent sorted key columns
    porder: jnp.ndarray,  # [Np_pad] int32 — parent sort order (row ids)
    pcnts: jnp.ndarray,  # [Np_pad] int32 — (k-1)-itemset counts
    np_real: jnp.ndarray,  # () int32 — real parent rows
    prev_surv: jnp.ndarray,  # [(k-1)*Np_pad] bool — parent-RULE survival
    prev_d: jnp.ndarray,  # [(k-1)*Np_pad] int32 — parent-rule denominators
    *,
    k: int,
    bits: int,
    first: bool,
):
    """One level's raw rule generation + dominance prune in ONE dispatch
    (all k column deletions batched): the k→(k-1) antecedent lookups as
    packed-key binary searches over the resident sorted parent table,
    then the reference's "cut leaves" prune (AssociationRules.scala:
    147-182) as flat gathers into the previous level's device-resident
    survival/denominator arrays — rule (S-{e}→S[j]) survives iff each
    parent rule (S-{e,x}→S[j]) survived with strictly lower confidence,
    compared exactly (`frac_less24`).

    ``first`` statically marks the k=2 base level: its parents are the
    1-itemsets (an identity table — the deleted single-column rows ARE
    the parent row indexes, no search), and every found rule survives
    (the reference's base case, :173).

    Returns ``(packed, skeys, order, d_flat, surv_flat)``: ``packed`` is
    the ONE host-bound array — the j-major survivor bitmask plus a
    4-byte little-endian count of unmatched antecedents (downward-
    closure violations; the host raises InputError) — while ``skeys``/
    ``order`` (this table's sorted keys, the next level's parent) and
    ``d_flat``/``surv_flat`` (this level's rule denominators/survival,
    the next level's prune inputs) stay device-resident."""
    from fastapriori_tpu.ops.count import pack_bits_msb

    n_pad = mat.shape[0]
    valid = jnp.arange(n_pad, dtype=jnp.int32) < n_real.astype(jnp.int32)
    if first:
        # k == 2: parent table is the 1-itemset arange — delete column j
        # and the remaining rank IS the parent row index.
        rows = jnp.stack([mat[:, 1], mat[:, 0]])
        found = jnp.broadcast_to(valid[None, :], (k, n_pad))
    else:
        np_pad = porder.shape[0]
        dels = [
            jnp.concatenate([mat[:, :j], mat[:, j + 1 :]], axis=1)
            for j in range(k)
        ]
        packed_q = [pack_rank_keys(d, bits) for d in dels]
        n_cols = len(packed_q[0])
        flat_q = [
            jnp.stack([packed_q[j][ci] for j in range(k)]).reshape(-1)
            for ci in range(n_cols)
        ]
        # np_pad is a static Python shape int, so the iteration count is
        # compile-time constant.
        pos = lex_searchsorted(
            psorted, np_real, flat_q, np_pad.bit_length() + 1
        )
        safe = jnp.clip(pos, 0, jnp.maximum(np_real - 1, 0))
        eq = pos < np_real
        for sc, qc in zip(psorted, flat_q):
            eq = eq & (jnp.take(sc, safe) == qc)
        found = eq.reshape(k, n_pad) & valid[None, :]
        rows = jnp.take(porder, safe).reshape(k, n_pad)
    # Denominators: count(S - {e}) per deleted column — ALSO each parent
    # rule's numerator (the prune below reuses the same gather).
    d = jnp.take(pcnts, rows.reshape(-1)).reshape(k, n_pad)
    miss = jnp.sum(valid[None, :] & ~found, dtype=jnp.int32)
    if first:
        ok = found  # base case: every min-size rule survives (:173)
    else:
        np_pad = porder.shape[0]
        oks = []
        for j in range(k):
            ok_j = found[j]
            for e in range(k):
                if e == j:
                    continue
                # Parent rule (S-{e}) -> S[j]: the consequent position
                # shifts down when the deleted column precedes it
                # (rules/gen.py uses the same flat addressing).
                jp = j - (e < j)
                pidx = jp * np_pad + rows[e]
                ok_j = (
                    ok_j
                    & jnp.take(prev_surv, pidx)
                    & frac_less24(d[e], jnp.take(prev_d, pidx), cnts, d[j])
                )
            oks.append(ok_j)
        ok = jnp.stack(oks)
    surv_flat = ok.reshape(-1)
    d_flat = d.reshape(-1)
    miss_u = miss.astype(jnp.uint32)
    packed = jnp.concatenate(
        [
            pack_bits_msb(surv_flat),
            jnp.stack(
                [(miss_u >> (8 * i)) & 0xFF for i in range(4)]
            ).astype(jnp.uint8),
        ]
    )
    # This table's sorted keys feed the NEXT level's search; pow2 row
    # padding sorts to the tail via the all-ones sentinel (real keys can
    # never be all-ones: ranks within a row strictly increase, and
    # left-aligned packing zero-fills any unused low bits).
    scols = [
        jnp.where(valid, c, jnp.uint32(0xFFFFFFFF))
        for c in pack_rank_keys(mat, bits)
    ]
    srt = lax.sort(
        tuple(scols) + (jnp.arange(n_pad, dtype=jnp.int32),),
        num_keys=len(scols),
    )
    return packed, tuple(srt[:-1]), srt[-1], d_flat, surv_flat
